"""ClusterController: elected leader that drives master recovery.

Reference: fdbserver/ClusterController.actor.cpp (worker registry, recruitment
:383, ServerDBInfo broadcast) + fdbserver/masterserver.actor.cpp (masterCore
:1160, recoverFrom :759) + fdbserver/TagPartitionedLogSystem.actor.cpp
(epochEnd :398-417). The reference splits the recovery driver into a recruited
master role babysat by the CC; here the CC runs the recovery state machine
itself and recruits the *version-allocator* master as a worker role — the
fitness/preemption machinery (betterMasterExists :799) is not modeled yet.

Recovery states (RecoveryState.h:30):
  READING_CSTATE  — quorum-read the coordinated state (prior log system)
  LOCKING_CSTATE  — lock the old TLog generation; compute the recovery version
  RECRUITING      — instantiate a whole new transaction subsystem on workers
  WRITING_CSTATE  — publish the new log-system config through the coordinators
  ACCEPTING_COMMITS — broadcast DBInfo + SetLogSystem; monitor for failure

The transaction subsystem is disposable: ANY master/proxy/resolver/TLog
failure triggers a fresh recovery with a new epoch; storage servers survive
across epochs and roll back to the recovery version (storageserver rollback
:2211 via SetLogSystemRequest).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from foundationdb_tpu.core.future import Future, settle_failed
from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.server.coordination import (
    CandidacyRequest, CoordinatedStateClient, CoordToken, quorum_wait)
from foundationdb_tpu.server.interfaces import (
    AddShardRequest, DBInfo, GetStorageMetricsRequest, InitRoleRequest,
    LogEpoch, RegisterWorkerRequest, SetLogSystemRequest, SetShardsRequest,
    TLogLockRequest, Token)
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.types import Mutation, MutationType
from foundationdb_tpu.utils.keys import partition_boundaries as _partition_boundaries
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.stats import CounterCollection, trace_counters_loop
from foundationdb_tpu.utils.trace import TraceEvent


@dataclass
class ClusterConfig:
    n_proxies: int = 1
    # dedicated GRV proxies (grv_proxy/commit_proxy split): 0 keeps the
    # combined shape where commit proxies also serve read versions
    n_grv_proxies: int = 0
    n_resolvers: int = 1
    n_tlogs: int = 1
    n_storage: int = 1  # number of SHARDS
    n_replicas: int = 1  # storage team size per shard (replication factor)
    # -- two-region (the reference's region configuration,
    # DatabaseConfiguration.h regions + TagPartitionedLogSystem satellite
    # log sets + LogRouter.actor.cpp) --
    # region_dcs: dc ids in failover-priority order; recovery recruits the
    # txn subsystem in the first listed dc with enough live workers, so
    # killing the whole primary region fails over to the next.
    region_dcs: tuple | None = None
    satellite_dc: str | None = None  # hosts the synchronous satellite logs
    n_satellites: int = 0
    # usable_regions=2: the standby region keeps full storage replicas fed
    # asynchronously through log routers (its tags still route through the
    # primary log system; the routers pull each tag across the WAN once)
    usable_regions: int = 1
    n_log_routers: int = 1


# ProcessClass fitness per role (fdbrpc/Locality.h ProcessClass::machineClassFitness,
# used by getWorkerForRoleInDatacenter ClusterController.actor.cpp:383): lower
# is better; recruitment picks the best-ranked alive workers.
_FITNESS = {
    # role kind -> {process_class: rank}
    "stateless": {"stateless": 0, "unset": 1, "transaction": 2, "storage": 3},
    "tlog": {"transaction": 0, "unset": 1, "stateless": 2, "storage": 3},
    "storage": {"storage": 0, "unset": 1, "transaction": 2, "stateless": 2},
}


def role_fitness(kind: str, process_class: str) -> int:
    return _FITNESS[kind].get(process_class, 1)


@dataclass
class _Registry:
    """Known workers: address -> (capabilities, process_class, last_seen),
    plus each worker's LocalityData for policy-driven placement."""

    workers: dict = field(default_factory=dict)
    localities: dict = field(default_factory=dict)

    def register(self, req: RegisterWorkerRequest, now: float):
        from foundationdb_tpu.server.replication import LocalityData
        self.workers[req.address] = (
            list(req.roles), getattr(req, "process_class", "unset"), now)
        self.localities[req.address] = LocalityData(
            process_id=req.address,
            zone_id=getattr(req, "zone_id", "") or req.address,
            machine_id=getattr(req, "machine_id", "") or req.address,
            dc_id=getattr(req, "dc_id", ""))

    def alive(self, capability: str, now: float, max_age: float = 3.0) -> list[str]:
        """Alive workers with `capability`, best-fitness first (ties by
        address for determinism) — recruitment takes from the front."""
        fit = _FITNESS.get(capability, _FITNESS["stateless"])
        return sorted(
            (a for a, (caps, _cls, seen) in self.workers.items()
             if capability in caps and now - seen <= max_age),
            key=lambda a: (fit.get(self.workers[a][1], 1), a))

    def class_of(self, address: str) -> str:
        entry = self.workers.get(address)
        return entry[1] if entry else "unset"

    def locality_of(self, address: str):
        from foundationdb_tpu.server.replication import LocalityData
        return self.localities.get(
            address, LocalityData(process_id=address, zone_id=address,
                                  machine_id=address))


class ClusterController:
    def __init__(self, process: SimProcess, coordinators: list[str],
                 config: ClusterConfig):
        self.process = process
        self.net = process.net
        self.loop = process.net.loop
        self.coordinators = coordinators
        self.config = config
        self.registry = _Registry()
        self.cstate = CoordinatedStateClient(process, coordinators)
        self.dbinfo = DBInfo(version=0, epoch=0, master=None, proxies=[],
                             resolvers=[], log_epochs=[], storages=[],
                             shard_boundaries=[], recovery_state="unrecovered")
        self.deposed = False
        self._need_recovery = Future()
        self._watchers: list = []
        self._incarnations: dict[str, int] = {}
        self._attempt = 0
        self.counters = CounterCollection("ClusterController",
                                          str(process.address))
        self._c_registrations = self.counters.counter("WorkerRegistrations")
        self._c_recoveries = self.counters.counter("RecoveriesCompleted")
        self._c_status_reqs = self.counters.counter("StatusRequests")
        self._counters_task = trace_counters_loop(process, self.counters)
        process.register(Token.CC_REGISTER_WORKER, self._on_register)
        process.register(Token.CC_GET_DBINFO, self._on_get_dbinfo)
        process.register(Token.CC_GET_STATUS, self._on_get_status)

    def _on_register(self, req: RegisterWorkerRequest, reply):
        self._c_registrations.increment()
        self.registry.register(req, self.loop.now())
        reply.send(None)
        # stand-down: a storage worker that hosts no referenced tag (healed
        # away while it was partitioned/clogged — never actually dead) must
        # stop serving its stale ranges, or clients with stale layouts would
        # read data missing every post-heal write. Delivered on the worker's
        # own heartbeat, so it reaches exactly the ones that came back.
        info = self.dbinfo
        if ("storage" in req.roles
                and info.recovery_state == "accepting_commits"
                and getattr(self, "_initial_meta_done", False)
                and req.address not in {a for a, _t in info.storages}):
            self.net.one_way(self.process,
                             Endpoint(req.address, Token.STORAGE_SET_SHARDS),
                             SetShardsRequest(shard_ranges=[],
                                              layout_version=(info.epoch,
                                                              info.version)))

    def _on_get_dbinfo(self, req, reply):
        reply.send(self.dbinfo)

    def _on_get_status(self, req, reply):
        self.process.spawn(self._get_status(reply), "clusterGetStatus")

    def _metrics_targets(self, info) -> list[tuple[str, str, int]]:
        """(role, address, metrics token) for every live role in the
        published generation — the workerEventsFetcher fan-out set."""
        targets: list[tuple[str, str, int]] = []
        if info.master:
            targets.append(("master", info.master, Token.MASTER_METRICS))
        for a in info.proxies:
            targets.append(("proxy", a, Token.PROXY_METRICS))
        for a in info.grv_proxies:
            targets.append(("grv_proxy", a, Token.PROXY_METRICS))
        for a in info.resolvers:
            targets.append(("resolver", a, Token.RESOLVER_METRICS))
        last_ep = info.log_epochs[-1] if info.log_epochs else None
        for a in (last_ep.addrs if last_ep else []):
            targets.append(("log", a, Token.TLOG_METRICS))
        for a in sorted({a for a, _t in info.storages}):
            targets.append(("storage", a, Token.STORAGE_METRICS))
        if info.ratekeeper:
            targets.append(("ratekeeper", info.ratekeeper, Token.RK_METRICS))
        return targets

    async def _fetch_metrics(self, addr: str, token: int):
        """One role's counter snapshot; None when the role is unreachable
        (a dead role must not wedge the whole status request)."""
        try:
            return await self.loop.timeout(self.net.request(
                self.process, Endpoint(addr, token), None), 1.0)
        except FDBError as e:
            if e.name == "operation_cancelled":
                raise
            return None

    async def _get_status(self, reply):
        """Status JSON assembled by the CC from every role
        (fdbserver/Status.actor.cpp:1698 clusterGetStatus, schema shape from
        fdbclient/Schemas.cpp — trimmed to what this cluster models)."""
        self._c_status_reqs.increment()
        info = self.dbinfo
        now = self.loop.now()
        status = {
            "cluster": {
                "recovery_state": {"name": info.recovery_state,
                                   "epoch": info.epoch},
                "generation": info.epoch,
                "cluster_controller": self.process.address,
                "coordinators": list(self.coordinators),
                "workers": {
                    a: {"roles": caps, "class": cls,
                        "stale_seconds": round(now - seen, 2)}
                    for a, (caps, cls, seen)
                    in sorted(self.registry.workers.items())
                },
                "layers": {"master": info.master,
                           "proxies": list(info.proxies),
                           "grv_proxies": list(info.grv_proxies),
                           "resolvers": list(info.resolvers),
                           "ratekeeper": info.ratekeeper,
                           "logs": [{"epoch": ep.epoch, "begin": ep.begin,
                                     "end": ep.end, "addrs": list(ep.addrs)}
                                    for ep in info.log_epochs],
                           "storages": [{"address": a, "tag": t}
                                        for a, t in info.storages]},
                "data": {"shard_boundaries": [b.hex() for b in
                                              info.shard_boundaries],
                         "shard_teams": info.shard_tags},
            },
        }
        # roles: per-role counter snapshots, fetched CONCURRENTLY — a
        # sequential sweep with 1s timeouts would make status O(roles)
        # seconds exactly when parts of the cluster are dead
        targets = self._metrics_targets(info)
        futs = [self.loop.spawn(self._fetch_metrics(a, tok), "statusMetrics")
                for _role, a, tok in targets]
        roles = [{"role": "cluster_controller",
                  "address": self.process.address,
                  "counters": self.counters.as_dict()}]
        try:
            for (role, addr, _tok), f in zip(targets, futs):
                snap = await f
                entry = {"role": role, "address": addr}
                if snap is None:
                    entry["unreachable"] = True
                else:
                    entry["counters"] = dict(snap)
                roles.append(entry)
        except FDBError as e:
            # CC displaced (or a fetch died) mid-status: settle before
            # propagating, or the status client waits out the full RPC
            # timeout (protolint PROTO002)
            for f in futs:
                f.cancel()
            settle_failed(reply, e)
            raise
        status["cluster"]["roles"] = roles
        # workload: cluster-wide commit traffic summed over the proxy fleet
        # (Status's workload.transactions/bytes section)
        workload = {"transactions_started": 0, "transactions_committed": 0,
                    "transactions_conflicted": 0, "commit_batches": 0,
                    "mutation_bytes": 0}
        for entry in roles:
            if (entry["role"] not in ("proxy", "grv_proxy")
                    or "counters" not in entry):
                continue
            c = entry["counters"]
            workload["transactions_started"] += c.get("GRVIn", 0)
            workload["transactions_committed"] += c.get("TxnCommitted", 0)
            workload["transactions_conflicted"] += c.get("TxnConflicts", 0)
            workload["commit_batches"] += c.get("CommitBatches", 0)
            workload["mutation_bytes"] += c.get("MutationBytes", 0)
        status["cluster"]["workload"] = workload
        # qos: live ratekeeper view (Status's qos section)
        if info.ratekeeper:
            try:
                r = await self.loop.timeout(self.net.request(
                    self.process, Endpoint(info.ratekeeper, Token.RK_GET_RATE),
                    1), 1.0)
                status["cluster"]["qos"] = {
                    "transactions_per_second_limit": round(r.tps, 1)}
            except FDBError as e:
                if e.name == "operation_cancelled":
                    settle_failed(reply, e)
                    raise
                status["cluster"]["qos"] = {"unreachable": True}
        reply.send(status)

    # -- leadership maintenance (tryBecomeLeader's nominee refresh) --

    async def _hold_leadership(self):
        quorum = len(self.coordinators) // 2 + 1
        while True:
            votes = 0
            for addr in self.coordinators:
                try:
                    r = await self.loop.timeout(self.net.request(
                        self.process, Endpoint(addr, CoordToken.CANDIDACY),
                        CandidacyRequest(address=self.process.address,
                                         priority=1)), 1.0)
                    if r.leader == self.process.address:
                        votes += 1
                except FDBError as e:
                    if e.name == "operation_cancelled":
                        raise
            if votes < quorum:
                self.deposed = True
                if not self._need_recovery.is_ready():
                    self._need_recovery._set("deposed")
                return
            await self.loop.delay(1.0)

    # -- role failure detection (waitFailureClient analogue) --

    async def _watch_role(self, address: str, what: str, incarnation: int):
        """A role is dead when its worker stops answering OR answers with a
        newer incarnation (the worker rebooted: the process is back but the
        roles recruited on it died with the old incarnation)."""
        misses = 0
        while True:
            try:
                inc = await self.loop.timeout(self.net.request(
                    self.process, Endpoint(address, Token.WORKER_PING), None),
                    1.0)
                if inc != incarnation:
                    misses = 2
                else:
                    misses = 0
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
                misses += 1
            if misses >= 2:
                TraceEvent("CCRoleFailed", self.process.address) \
                    .detail("Role", what).detail("Address", address).log()
                if not self._need_recovery.is_ready():
                    self._need_recovery._set(f"{what}@{address}")
                return
            await self.loop.delay(0.5)

    async def _watch_epoch_role(self, address: str, token: int, epoch: int,
                                what: str):
        """Worker pings can't see a ROLE stomped by a competing recovery
        attempt on the same worker (the process never rebooted), a master
        that self-deposed, or a proxy that died because its commit pipeline
        kept failing — watch the role's own epoch-answering endpoint."""
        misses = 0
        while True:
            try:
                got = await self.loop.timeout(self.net.request(
                    self.process, Endpoint(address, token), None), 1.0)
                misses = 0 if got == epoch else 2
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
                misses += 1
            if misses >= 2:
                TraceEvent("CCEpochRoleFailed", self.process.address) \
                    .detail("What", what).detail("Address", address) \
                    .detail("Epoch", epoch).log()
                if not self._need_recovery.is_ready():
                    self._need_recovery._set(f"{what}@{address}")
                return
            await self.loop.delay(0.5)

    # -- the recovery state machine --

    async def run(self):
        """Drive recoveries until deposed (clusterControllerCore)."""
        hold = self.process.spawn(self._hold_leadership(), "holdLeadership")
        try:
            while not self.deposed:
                try:
                    await self._recover_once()
                except FDBError as e:
                    if e.name == "operation_cancelled":
                        raise
                    TraceEvent("CCRecoveryFailed", self.process.address) \
                        .detail("Error", e.name).detail("Detail", e.detail).log()
                    await self.loop.delay(0.5)
                    continue
                # recovered: wait for a role failure or deposition
                reason = await self._need_recovery
                self._need_recovery = Future()
                TraceEvent("CCRecoveryTriggered", self.process.address) \
                    .detail("Reason", str(reason)).log()
        finally:
            hold.cancel()
            for w in self._watchers:  # a deposed CC stops babysitting
                w.cancel()
            self._watchers = []

    async def _recover_once(self):
        cfg = self.config
        # stop babysitting the generation being replaced (a locked old TLog
        # dying later must not trigger a spurious recovery)
        for w in self._watchers:
            w.cancel()
        self._watchers = []
        self._incarnations: dict[str, int] = {}
        # ---- READING_CSTATE ----
        self.dbinfo.recovery_state = "reading_cstate"
        prior, _gen = await self.cstate.read()

        # ---- LOCKING_CSTATE: epoch end over the old generation ----
        self.dbinfo.recovery_state = "locking_cstate"
        if prior is None:
            epoch = 1
            recovery_version = 0
            old_epochs: list[LogEpoch] = []
            storages: list[tuple[str, int]] = []
            boundaries = _partition_boundaries(cfg.n_storage)
        else:
            epoch = prior["epoch"] + 1
            old_epochs = list(prior["log_epochs"])
            storages = list(prior["storages"])
            boundaries = list(prior["shard_boundaries"])
            # configure-commanded txn-subsystem shape (ManagementAPI):
            # recruit the new generation with the configured counts
            cc_conf = prior.get("conf") or {}
            from dataclasses import replace as _dc_replace
            cfg = _dc_replace(cfg, **{
                k: int(v) for k, v in cc_conf.items()
                if k in ("n_proxies", "n_grv_proxies", "n_resolvers",
                         "n_tlogs", "n_replicas")})
            recovery_version = await self._lock_old_generation(old_epochs[-1])
            # close the old generation at the recovery version
            old_epochs[-1] = LogEpoch(begin=old_epochs[-1].begin,
                                      end=recovery_version,
                                      addrs=old_epochs[-1].addrs,
                                      epoch=old_epochs[-1].epoch,
                                      uids=old_epochs[-1].uids)

        # the new generation starts above anything any process can have seen
        # in flight (masterserver.actor.cpp:858 bump)
        start_version = recovery_version + KNOBS.MAX_VERSIONS_IN_FLIGHT

        # ---- RECRUITING ----
        self.dbinfo.recovery_state = "recruiting"
        now = self.loop.now()
        # excluded servers (ManagementAPI) never receive new roles; the
        # exclusion list is mirrored into the cstate since the database is
        # unreadable during recovery
        excluded = set(((prior or {}).get("conf") or {}).get("excluded") or [])
        stateless_all = [a for a in self.registry.alive("stateless", now)
                         if a not in excluded]
        log_workers_all = [a for a in self.registry.alive("tlog", now)
                           if a not in excluded]

        def dc_of(a: str) -> str:
            return self.registry.locality_of(a).dc_id

        # region selection: the first dc in priority order with enough live
        # workers hosts the txn subsystem — so a dead primary REGION makes
        # recovery recruit in the next region (the failover path the
        # reference drives through its region priority config)
        primary_dc = None
        if cfg.region_dcs:
            for dc in cfg.region_dcs:
                sl = [a for a in stateless_all if dc_of(a) == dc]
                lw = [a for a in log_workers_all if dc_of(a) == dc]
                if (len(sl) >= max(1, cfg.n_proxies + cfg.n_grv_proxies,
                                   cfg.n_resolvers)
                        and len(lw) >= cfg.n_tlogs):
                    primary_dc = dc
                    stateless, log_workers = sl, lw
                    break
            if primary_dc is None:
                raise FDBError("recruitment_failed",
                               "no region has enough workers")
        else:
            stateless, log_workers = stateless_all, log_workers_all
        # one resolver/proxy per worker: co-locating two same-keyed roles on
        # one process would silently displace the first (single endpoint
        # token per role kind per process). GRV proxies count against the
        # same stateless pool — they own the GRV token a co-located commit
        # proxy would also register.
        if (len(stateless) < max(1, cfg.n_proxies + cfg.n_grv_proxies,
                                 cfg.n_resolvers)
                or len(log_workers) < cfg.n_tlogs):
            raise FDBError("recruitment_failed", "not enough workers")

        # new TLog generation: fresh instances with UNIQUE ids (and uid-named
        # files), so neither an old locked generation nor a racing recovery
        # attempt can ever be stomped on a shared host
        self._attempt += 1
        uids = [f"e{epoch}-{self.process.address}-a{self._attempt}-t{i}"
                for i in range(cfg.n_tlogs)]
        tlog_addrs = await self._recruit_many(
            log_workers, cfg.n_tlogs, "tlog",
            lambda i: {"uid": uids[i], "recovery_version": start_version})
        # satellite log set: synchronously quorumed OUTSIDE the primary dc
        # (TagPartitionedLogSystem satellite tLogs), so losing the whole
        # primary region loses no acked commit. Folded into the epoch's
        # addr list after the n_primary split: peeks/pops/locks treat every
        # member uniformly, only the proxy's push quorum is per set.
        sat_addrs: list[str] = []
        sat_uids: list[str] = []
        if cfg.region_dcs and cfg.n_satellites:
            if KNOBS.TLOG_QUORUM_ANTIQUORUM:
                raise FDBError("recruitment_failed",
                               "satellite logs require antiquorum 0")
            sat_workers = [a for a in log_workers_all
                           if dc_of(a) == cfg.satellite_dc]
            if len(sat_workers) < cfg.n_satellites:
                raise FDBError("recruitment_failed",
                               "not enough satellite log workers")
            sat_uids = [f"e{epoch}-{self.process.address}"
                        f"-a{self._attempt}-s{i}"
                        for i in range(cfg.n_satellites)]
            sat_addrs = await self._recruit_many(
                sat_workers, cfg.n_satellites, "tlog",
                lambda i: {"uid": sat_uids[i],
                           "recovery_version": start_version})
        new_epochs = old_epochs + [LogEpoch(begin=recovery_version, end=None,
                                            addrs=tlog_addrs + sat_addrs,
                                            epoch=epoch,
                                            uids=uids + sat_uids,
                                            n_primary=len(tlog_addrs))]

        # each resolver is told its slice of the outer key split so a
        # sharded conflict engine can cut the mesh INSIDE its range
        resolver_bounds = _partition_boundaries(cfg.n_resolvers)
        resolver_addrs = await self._recruit_many(
            stateless, cfg.n_resolvers, "resolver",
            lambda i: {"recovery_version": start_version,
                       "n_proxies": cfg.n_proxies,
                       "key_range_begin": resolver_bounds[i],
                       "key_range_end": (resolver_bounds[i + 1]
                                         if i + 1 < len(resolver_bounds)
                                         else None)})
        master_addr = (await self._recruit_many(
            stateless, 1, "master",
            lambda i: {"recovery_version": start_version, "epoch": epoch,
                       "coordinators": list(self.coordinators)}))[0]

        remote_dc = None
        if cfg.region_dcs and cfg.usable_regions >= 2:
            remotes = [d for d in cfg.region_dcs if d != primary_dc]
            remote_dc = remotes[0] if remotes else None
        if prior is None:
            storage_workers = [a for a in self.registry.alive("storage", now)
                               if a not in excluded]
            if primary_dc is not None:
                storage_workers = [a for a in storage_workers
                                   if dc_of(a) == primary_dc]
            # one storage role per worker (a process has one set of STORAGE_*
            # endpoints, so co-located roles would displace each other —
            # also the reference's normal deployment shape)
            if len(storage_workers) < cfg.n_storage * cfg.n_replicas:
                raise FDBError("recruitment_failed", "not enough storage workers")
            # teams (DDTeamCollection :515): every shard gets n_replicas
            # storage servers on DISTINCT workers, each with its OWN tag; the
            # proxy routes each mutation to every team member's tag, so
            # replication happens through the log, not server-to-server.
            # Placement honors the replication POLICY (ReplicationPolicy.h:
            # Across(n, zoneid) for double/triple) when worker localities
            # allow; otherwise it degrades to distinct workers with a trace.
            from foundationdb_tpu.server.replication import (
                policy_for_replication, select_replicas)
            policy = policy_for_replication(cfg.n_replicas)
            storages = []
            shard_tags: list[list[int]] = []
            # each worker hosts at most ONE storage role (a process has one
            # set of STORAGE_* endpoint tokens), so picked workers leave the
            # pool; the count guard above ensures it never runs dry
            pool = list(storage_workers)
            for i in range(cfg.n_storage):
                srange = (boundaries[i],
                          boundaries[i + 1] if i + 1 < len(boundaries) else None)
                # balance zone consumption across shards: offer candidates
                # from the zones with the MOST remaining workers first
                # (stable, so fitness order survives within a zone) — a
                # plain greedy strands small zones and forces later shards
                # into same-zone teams that a global assignment avoids
                zone_left: dict[str, int] = {}
                for a in pool:
                    z = self.registry.locality_of(a).zone_id
                    zone_left[z] = zone_left.get(z, 0) + 1
                ordered = sorted(
                    pool, key=lambda a: -zone_left[
                        self.registry.locality_of(a).zone_id])
                cands = [(a, self.registry.locality_of(a)) for a in ordered]
                picked = select_replicas(policy, cands)
                if picked is None or len(picked) < cfg.n_replicas:
                    TraceEvent("CCPolicyUnsatisfiable", self.process.address,
                               severity=30) \
                        .detail("Policy", str(policy)).detail("Shard", i).log()
                    picked = ordered[:cfg.n_replicas]
                team = []
                for r, w in enumerate(picked[:cfg.n_replicas]):
                    tag = i * cfg.n_replicas + r
                    addr = (await self._recruit_many(
                        [w], 1, "storage",
                        lambda _i, tag=tag, srange=srange: {
                            "tag": tag, "log_epochs": list(new_epochs),
                            "recovery_count": epoch,
                            "shard_ranges": [srange],
                            "engine": ((prior or {}).get("conf") or {})
                            .get("storage_engine")}))[0]
                    storages.append((addr, tag))
                    team.append(tag)
                pool = [a for a in pool if a not in picked]
                shard_tags.append(team)
            router_of: dict[int, tuple[str, str]] = {}
            if remote_dc is not None:
                # remote-region replica set (usable_regions=2): every shard
                # gets n_replicas more storages in the standby region with
                # their OWN tags — mutations route to those tags through
                # the primary log system, and the region's log routers pull
                # each tag across the WAN once to feed them
                remote_pool = [a for a in self.registry.alive("storage", now)
                               if a not in excluded and dc_of(a) == remote_dc]
                if len(remote_pool) < cfg.n_storage * cfg.n_replicas:
                    raise FDBError("recruitment_failed",
                                   "not enough remote-region storage workers")
                base = cfg.n_storage * cfg.n_replicas
                remote_tags_all = [base + i * cfg.n_replicas + r
                                   for i in range(cfg.n_storage)
                                   for r in range(cfg.n_replicas)]
                router_of = await self._recruit_log_routers(
                    remote_dc, remote_tags_all, new_epochs,
                    recovery_version, epoch, excluded, now)
                rp = list(remote_pool)
                for i in range(cfg.n_storage):
                    srange = (boundaries[i],
                              boundaries[i + 1] if i + 1 < len(boundaries)
                              else None)
                    for r in range(cfg.n_replicas):
                        tag = base + i * cfg.n_replicas + r
                        w = rp.pop(0)
                        ep_view = self._router_epochs(new_epochs, router_of,
                                                      tag)
                        addr = (await self._recruit_many(
                            [w], 1, "storage",
                            lambda _i, tag=tag, srange=srange,
                            ep_view=ep_view: {
                                "tag": tag, "log_epochs": ep_view,
                                "recovery_count": epoch,
                                "shard_ranges": [srange]}))[0]
                        storages.append((addr, tag))
                        shard_tags[i].append(tag)
        else:
            shard_tags = list(prior.get("shard_tags")
                              or [[t] for _a, t in storages])
            # refresh the standby region's log routers for the new
            # generation (they pull the NEW epoch list); best effort — with
            # the remote region's workers gone (or after a failover into
            # it) its storages just bind the primary view directly
            router_of = {}
            if remote_dc is not None:
                remote_tags_all = sorted(
                    t for a, t in storages if dc_of(a) == remote_dc)
                if remote_tags_all:
                    try:
                        router_of = await self._recruit_log_routers(
                            remote_dc, remote_tags_all, new_epochs,
                            recovery_version, epoch, excluded, now)
                    except FDBError as e:
                        if e.name == "operation_cancelled":
                            raise
                        router_of = {}

        # admission control alongside the new generation (Ratekeeper runs
        # with the master in the reference)
        rk_addr = (await self._recruit_many(
            stateless, 1, "ratekeeper",
            lambda i: {"tlogs": list(tlog_addrs),
                       "storages": [a for a, _t in storages],
                       "resolvers": list(resolver_addrs)}))[0]

        from foundationdb_tpu.server import systemdata
        from foundationdb_tpu.server.proxy import ResolverMap
        # seed every proxy's txnStateStore with the \xff snapshot derived
        # from the coordinated checkpoint (the recovery transaction /
        # sendInitialCommitToResolvers analogue, masterserver.actor.cpp:690)
        system_snapshot = systemdata.build_keyservers_snapshot(
            boundaries, shard_tags)
        resolver_map = ResolverMap(
            boundaries=resolver_bounds,
            endpoints=[Endpoint(a, Token.RESOLVER_RESOLVE)
                       for a in resolver_addrs])
        # worker address == role address, so the cross-proxy GRV confirmation
        # set (getLiveCommittedVersion :935) is known before recruitment
        proxy_addrs = [stateless[i % len(stateless)]
                       for i in range(cfg.n_proxies)]
        for i in range(cfg.n_proxies):
            await self._recruit_many(
                [proxy_addrs[i]], 1, "proxy",
                lambda _i, i=i: {
                    "proxy_id": i,
                    "master": Endpoint(master_addr, Token.MASTER_GET_COMMIT_VERSION),
                    "resolvers": resolver_map,
                    "tlogs": [Endpoint(a, Token.TLOG_COMMIT) for a in tlog_addrs],
                    "tlog_uids": list(uids),
                    "satellites": [Endpoint(a, Token.TLOG_COMMIT)
                                   for a in sat_addrs],
                    "satellite_uids": list(sat_uids),
                    "system_snapshot": list(system_snapshot),
                    "storages": list(storages),
                    "recovery_version": start_version,
                    "epoch": epoch,
                    "other_proxies": [a for a in proxy_addrs
                                      if a != proxy_addrs[i]],
                    "ratekeeper": rk_addr,
                    "n_proxies": cfg.n_proxies,
                    "die_on_failure": True,
                })
        # dedicated GRV proxies on workers AFTER the commit proxies (they
        # register the same GRV/ping/metrics tokens, so sharing a worker
        # with a commit proxy would displace its handlers). They confirm
        # read versions against the COMMIT proxies' committed versions and
        # report their own pool size to the ratekeeper, so the GRV budget
        # divides over the pool actually serving GRVs.
        grv_addrs = [stateless[(cfg.n_proxies + i) % len(stateless)]
                     for i in range(cfg.n_grv_proxies)]
        for i in range(cfg.n_grv_proxies):
            await self._recruit_many(
                [grv_addrs[i]], 1, "grv_proxy",
                lambda _i, i=i: {
                    "proxy_id": cfg.n_proxies + i,
                    "master": Endpoint(master_addr,
                                       Token.MASTER_GET_COMMIT_VERSION),
                    "recovery_version": start_version,
                    "epoch": epoch,
                    "other_proxies": list(proxy_addrs),
                    "ratekeeper": rk_addr,
                    "n_proxies": max(1, cfg.n_grv_proxies),
                    "die_on_failure": True,
                })

        # ---- WRITING_CSTATE: fencing point for competing recoveries ----
        self.dbinfo.recovery_state = "writing_cstate"
        await self.cstate.write({
            "epoch": epoch,
            "master": master_addr,
            "log_epochs": new_epochs,
            "storages": storages,
            "shard_tags": shard_tags,
            "shard_boundaries": boundaries,
            "recovery_version": recovery_version,
            # configure-commanded overrides survive further recoveries
            "conf": (prior.get("conf") if prior else None) or {},
        })
        self._cstate_conf = (prior.get("conf") if prior else None) or {}

        # ---- ACCEPTING_COMMITS: rebind storages, publish DBInfo ----
        for addr, tag in storages:
            # standby-region storages bind the open generation via their
            # tag's log router; everyone else binds the primary view
            eps = self._router_epochs(new_epochs, router_of, tag)
            self.net.one_way(self.process,
                             Endpoint(addr, Token.STORAGE_SET_LOGSYSTEM),
                             SetLogSystemRequest(epochs=eps,
                                                 rollback_to=recovery_version,
                                                 recovery_count=epoch))
        if prior is not None:
            # fence the old generation's read versions before clients can see
            # (and commit through) the new one. Fast path: depose the old
            # master directly. Backstop for partitions: the old master's own
            # cstate lease fails within MASTER_CSTATE_LEASE once the cstate
            # has moved (or its coordinator quorum is gone), and its proxies'
            # GRV leases drain within PROXY_MASTER_LEASE after that — so wait
            # out both before publishing DBInfo (the reference gets this from
            # the old master's cstate writes failing + proxy failure
            # monitoring; strict serializability needs no old-generation GRV
            # after the first new-generation commit).
            old_master = prior.get("master")
            if old_master:
                self.net.one_way(self.process,
                                 Endpoint(old_master, Token.MASTER_DEPOSE),
                                 epoch)
            await self.loop.delay(1.5 * KNOBS.MASTER_CSTATE_LEASE_SECONDS
                                  + KNOBS.PROXY_MASTER_LEASE_SECONDS)
        # wire the DD's client to the new generation (DBInfo publishes just
        # below; the background recovery txn and DD both use this handle)
        self._initial_meta_done = False
        addr_of_tag = {tag: addr for addr, tag in storages}
        pre_db = self._dd_database()
        pre_db.proxies = list(proxy_addrs)
        pre_db.locations.update(
            boundaries, [[addr_of_tag[t] for t in team]
                         for team in shard_tags])
        self.dbinfo = DBInfo(
            version=self.dbinfo.version + 1, epoch=epoch, master=master_addr,
            proxies=proxy_addrs, resolvers=resolver_addrs,
            log_epochs=new_epochs, storages=storages,
            shard_boundaries=boundaries, recovery_state="accepting_commits",
            ratekeeper=rk_addr, shard_tags=shard_tags,
            grv_proxies=grv_addrs)
        self._c_recoveries.increment()
        TraceEvent("CCRecovered", self.process.address) \
            .detail("Epoch", epoch).detail("RecoveryVersion", recovery_version) \
            .detail("Proxies", len(proxy_addrs)).detail("TLogs", len(tlog_addrs)).log()

        # recovery transaction (the reference's recovery txn +
        # sendInitialCommitToResolvers, masterserver.actor.cpp:597-690),
        # run in the BACKGROUND and retried until it lands or the generation
        # dies: it writes the keyServers snapshot INTO the database so DD's
        # read-modify-write layout txns have rows to read (DD waits on
        # _initial_meta_done). Blocking the publish on it would make
        # recovery fragile under sustained clogging; the one thing that
        # genuinely cannot wait — an in-flight backup's mutation-log tee —
        # is instead self-seeded by each proxy from durable storage before
        # it accepts any commit (Proxy._seed_backup_ranges), so no client
        # write can land in an un-teed gap.
        self._watchers.append(self.process.spawn(
            self._write_initial_metadata(system_snapshot), "recoveryTxn"))

        # shard tracker / relocator (DataDistribution.actor.cpp:2260 runs
        # alongside the master; here it runs with the CC and survives until
        # the next recovery replaces it)
        self._watchers.append(
            self.process.spawn(self._data_distribution(), "dataDistribution"))
        # fitness preemption (betterMasterExists, ClusterController.actor.cpp
        # :799): when strictly better-class workers become available for the
        # txn subsystem, one recovery migrates the roles onto them
        self._watchers.append(self.process.spawn(
            self._preemption_watch(epoch), "betterMasterExists"))
        # babysit the new generation (role stomps by racing recoveries,
        # self-deposed masters, and self-killed proxies are caught by the
        # epoch watchers; worker deaths by the incarnation pings)
        self._watchers.append(self.process.spawn(
            self._watch_epoch_role(master_addr, Token.MASTER_PING, epoch,
                                   "master"), "watchMaster"))
        for pa in proxy_addrs:
            self._watchers.append(self.process.spawn(
                self._watch_epoch_role(pa, Token.PROXY_PING, epoch, "proxy"),
                "watchProxy"))
        for ga in grv_addrs:
            self._watchers.append(self.process.spawn(
                self._watch_epoch_role(ga, Token.PROXY_PING, epoch,
                                       "grv_proxy"), "watchGrvProxy"))
        router_addrs = sorted({a for a, _u in router_of.values()})
        for addr in sorted(set([master_addr] + proxy_addrs + grv_addrs
                               + resolver_addrs + tlog_addrs + sat_addrs
                               + router_addrs + [rk_addr])):
            self._watchers.append(self.process.spawn(
                self._watch_role(addr, "txn",
                                 self._incarnations.get(addr, 0)),
                "watchRole"))

    async def _recruit_log_routers(self, remote_dc: str, tags: list[int],
                                   epochs: list[LogEpoch], begin: int,
                                   epoch: int, excluded: set,
                                   now: float) -> dict:
        """Recruit the standby region's log routers (LogRouter.actor.cpp):
        tags are partitioned round-robin over n_log_routers routers hosted
        on the region's tlog-capable workers; each router pulls its tags
        from the primary log system once and re-serves them locally.
        Returns {tag: (router_addr, router_uid)}."""
        cfg = self.config
        workers = [a for a in self.registry.alive("tlog", now)
                   if a not in excluded
                   and self.registry.locality_of(a).dc_id == remote_dc]
        if not workers:
            raise FDBError("recruitment_failed",
                           "no remote-region log-router workers")
        n = max(1, min(cfg.n_log_routers, len(workers)))
        router_of: dict[int, tuple[str, str]] = {}
        for j in range(n):
            uid = (f"e{epoch}-{self.process.address}"
                   f"-a{self._attempt}-lr{j}")
            tags_j = [t for k, t in enumerate(tags) if k % n == j]
            if not tags_j:
                continue
            addr = (await self._recruit_many(
                [workers[j % len(workers)]], 1, "logrouter",
                lambda _i, uid=uid, tags_j=tags_j: {
                    "uid": uid, "tags": tags_j,
                    "epochs": list(epochs), "begin": begin}))[0]
            for t in tags_j:
                router_of[t] = (addr, uid)
        return router_of

    @staticmethod
    def _router_epochs(epochs: list[LogEpoch], router_of: dict,
                       tag: int) -> list[LogEpoch]:
        """A remote storage's epoch view: the OPEN generation routes through
        the tag's log router; closed generations stay direct (their data is
        already applied locally or reachable with peek failover — including
        the satellite members folded into each epoch's addr list)."""
        if tag not in router_of:
            return list(epochs)
        addr, uid = router_of[tag]
        last = epochs[-1]
        return list(epochs[:-1]) + [LogEpoch(
            begin=last.begin, end=last.end, addrs=[addr], epoch=last.epoch,
            uids=[uid], n_primary=1)]

    async def _lock_old_generation(self, old: LogEpoch) -> int:
        """epochEnd (TagPartitionedLogSystem:398-417): lock enough old TLogs
        that no old-generation commit can reach quorum again, then choose the
        recovery version.

        With commit quorum N - a (antiquorum a), locking a+1 logs fences the
        generation. For the recovery version we use the (s-a)-th highest
        durable version over the s locked logs: any acknowledged commit is
        durable on >= N-a logs, so at least s-a locked logs hold it and the
        (s-a)-th highest durable version is >= every acked commit. With the
        default a=0 this is min-over-locked, which every locked log holds in
        full (so the data for every recovered version is reachable)."""
        # the SAME antiquorum the proxies commit with (proxy.py quorum =
        # len(tlogs) - TLOG_QUORUM_ANTIQUORUM): the fencing and recovery-
        # version math below is only sound against the real commit quorum
        a = KNOBS.TLOG_QUORUM_ANTIQUORUM
        futures = [self.loop.timeout(self.net.request(
            self.process, Endpoint(addr, Token.TLOG_LOCK),
            TLogLockRequest(epoch=old.epoch + 1, uid=old.uid_of(i))), 2.0)
            for i, addr in enumerate(old.addrs)]
        # a+1 locked logs fence the old generation (the alive unlocked
        # remainder is below the N-a commit quorum) and suffice for safety:
        # any acked commit is durable on >= N-a logs, so >= s-a of any s
        # locked logs hold it. Locking MORE when available only improves the
        # data's reachability, so collect every answer (bounded by the
        # per-request timeouts already attached).
        need = a + 1
        replies = []
        for f in futures:
            try:
                replies.append(await f)
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
        if len(replies) < need:
            raise FDBError("master_tlog_failed",
                           "cannot lock enough old TLogs")
        durables = sorted((r.durable_version for r in replies), reverse=True)
        s = len(durables)
        recovery_version = durables[max(0, s - a - 1)]
        return recovery_version

    async def _recruit_many(self, workers: list[str], n: int, role: str,
                            make_args) -> list[str]:
        if self.deposed:
            # a deposed CC must stop recruiting immediately: its half-built
            # generation would stomp the new leader's roles on shared workers
            raise FDBError("recruitment_failed", "deposed")
        addrs = []
        for i in range(n):
            addr = workers[i % len(workers)]
            try:
                r = await self.loop.timeout(self.net.request(
                    self.process, Endpoint(addr, Token.WORKER_INIT_ROLE),
                    InitRoleRequest(role=role, args=make_args(i))), 2.0)
                addrs.append(r.address)
                self._incarnations[r.address] = r.incarnation
            except FDBError as e:
                raise FDBError("recruitment_failed",
                               f"{role} on {addr}: {e.name}") from None
        return addrs

    # -- data distribution (shard tracker + relocator) --

    async def _data_distribution(self):
        """shardSplitter (DataDistributionTracker.actor.cpp:314) + a
        least-loaded relocation policy (DataDistributionQueue :849) +
        MoveKeys-style execution: split an oversized shard at its sampled
        median and hand the upper half to the team currently serving the
        fewest shards. Every step is fenced so no mutation is lost:
          1. swap every proxy's shard map (dual-routes the moving range)
          2. take a version fence from the master (all later commits use
             the new routing)
          3. destination team fetches the range (storage _add_shard)
          4. publish the new layout (cstate + DBInfo); source drops the range
        """
        while True:
            await self.loop.delay(KNOBS.DD_INTERVAL_SECONDS)
            info = self.dbinfo
            if self.deposed or info.recovery_state != "accepting_commits" \
                    or not getattr(self, "_initial_meta_done", False):
                continue
            try:
                # QuietDatabase's "data distribution idle" signal: a checker
                # must not race an in-flight relocation's splice/publish
                self._dd_moving = True
                await self._dd_once()
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
                TraceEvent("DDRoundFailed", self.process.address) \
                    .detail("Error", e.name).log()
            finally:
                self._dd_moving = False

    async def _dd_once(self):
        info = self.dbinfo
        # live configuration from \xff/conf (ManagementAPI changeConfig):
        # replication/exclusions apply through the healing machinery below;
        # txn-subsystem shape changes trigger a recovery that re-recruits
        # with the new counts
        conf = await self._read_db_conf()
        if conf is None:
            return  # conf unreadable this round: do nothing rather than
                    # act on boot-time defaults
        if await self._apply_conf_shape(info, conf):
            return
        # reconcile next: a failed round can leave the live \xff/keyServers
        # mid-transition (e.g. dual-routed) while dbinfo/cstate still hold
        # the last PUBLISHED layout. Published state is the authority (an
        # unpublished move is by definition not final and its dual-route
        # window is safe to revert), and without this the expected-value
        # guards in every later layout txn would wedge forever.
        if await self._reconcile_keyservers(info):
            return
        # redundancy healing next (the relocation queue's highest priority,
        # DataDistributionQueue.actor.cpp PRIORITY_TEAM_UNHEALTHY)
        if await self._heal_once(info, conf):
            return
        b = list(info.shard_boundaries)
        teams = [list(t) for t in info.teams()]
        addr_of_tag = {t: a for a, t in info.storages}
        # conflict-hotspot feed (docs/contention.md): the resolver sketch
        # gives DD a second split trigger — sustained write contention on a
        # shard splits it even when its byte count is small
        from foundationdb_tpu.server.hotspot import overlaps
        hot_ranges = await self._poll_hot_ranges(info)
        streaks = getattr(self, "_hot_streaks", None)
        if streaks is None:
            streaks = self._hot_streaks = {}
        hot_shards: set[bytes] = set()  # shard begin keys hot THIS round
        hot_split: tuple[int, bytes] | None = None
        # sample every shard from one replica
        sizes: list[int] = []
        for i, team in enumerate(teams):
            lo = b[i]
            hi = b[i + 1] if i + 1 < len(b) else None
            owner = addr_of_tag[team[0]]
            metrics = await self.loop.timeout(self.net.request(
                self.process, Endpoint(owner, Token.STORAGE_GET_METRICS),
                GetStorageMetricsRequest(ranges=[(lo, hi)])), 2.0)
            m = metrics[0]
            sizes.append(m.bytes)
            rate = sum(hr.rate for hr in hot_ranges
                       if overlaps(hr.begin, hr.end, lo, hi))
            if rate >= KNOBS.DD_SHARD_SPLIT_CONFLICT_RATE:
                hot_shards.add(lo)
                streaks[lo] = streaks.get(lo, 0) + 1
                if (hot_split is None and m.split_key is not None
                        and streaks[lo] >= KNOBS.DD_HOT_SHARD_ROUNDS):
                    hot_split = (i, m.split_key)
            else:
                streaks.pop(lo, None)
            if m.bytes <= KNOBS.DD_SHARD_SPLIT_BYTES or m.split_key is None:
                continue
            await self._split_and_move(i, m.split_key)
            return  # one relocation per round
        if hot_split is not None:
            i, split_key = hot_split
            streaks.pop(b[i], None)  # the streak acted; children start fresh
            TraceEvent("DDConflictSplit", self.process.address) \
                .detail("Shard", b[i].hex()) \
                .detail("SplitKey", split_key.hex()).log()
            await self._split_and_move(i, split_key)
            return
        # shardMerger (:379): two adjacent small shards on the SAME team
        # collapse back into one — metadata-only (no data moves). Skip pairs
        # touching a currently-hot shard: re-merging what the conflict
        # trigger just split would make the two triggers fight forever.
        for i in range(len(teams) - 1):
            if b[i] in hot_shards or b[i + 1] in hot_shards:
                continue
            if (teams[i] == teams[i + 1]
                    and sizes[i] + sizes[i + 1] < KNOBS.DD_SHARD_MERGE_BYTES):
                await self._merge(i)
                return

    async def _poll_hot_ranges(self, info) -> list:
        """Merged conflict-hotspot snapshot across the live resolvers (the
        DD side of the contention loop; ratekeeper polls independently for
        throttling). A dead resolver costs one bounded timeout and is
        skipped — DD must keep distributing through resolver failures."""
        if not KNOBS.CONTENTION_THROTTLE_ENABLED or not info.resolvers:
            return []
        out = []
        for a in info.resolvers:
            try:
                r = await self.loop.timeout(self.net.request(
                    self.process, Endpoint(a, Token.RESOLVER_HOT_RANGES),
                    KNOBS.HOTSPOT_TOP_K), 1.0)
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
                continue
            out.extend(r.ranges)
        return out

    async def _write_initial_metadata(self, snapshot):
        """Persist the recovery's \\xff snapshot through the pipeline
        (idempotent: re-writes the cstate-derived layout; a ghost from a
        deposed generation dies at its locked TLogs). DD mutations wait on
        this."""
        from foundationdb_tpu.server import systemdata
        db = self._dd_database()  # pre-wired by the recovery
        while not self.deposed and not self._need_recovery.is_ready():
            try:
                tr = db.create_transaction()
                tr.clear_range(systemdata.KEY_SERVERS_PREFIX,
                               systemdata.KEY_SERVERS_END)
                for k, v in snapshot:
                    tr.set(k, v)
                await tr.commit()  # RPCs inside are individually bounded
                self._initial_meta_done = True
                return
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
                await self.loop.delay(1.0)

    def _dd_database(self):
        """Client handle the DD uses to run layout transactions (the
        reference's DD runs its moveKeys transactions through NativeAPI,
        DataDistribution.actor.cpp; MoveKeys.actor.cpp)."""
        if getattr(self, "_dd_db", None) is None:
            from foundationdb_tpu.client.database import Database
            self._dd_db = Database(self.process,
                                   coordinators=list(self.coordinators))
        return self._dd_db

    async def _commit_metadata_txn(self, info, expected: dict, mutations) -> int:
        """Run a layout metadata transaction through the commit pipeline (the
        moveKeys-transaction analogue, MoveKeys.actor.cpp): resolved by every
        resolver, applied to every proxy's txnStateStore in version order.

        `expected` maps each touched \\xff key to the value this round
        believes is current; the transaction READS those keys (conflict
        ranges at its snapshot) and aborts if they moved. This makes a ghost
        commit — an RPC the CC timed out on that delivers later — harmless:
        either the keyspace is unchanged (the ghost re-writes the same
        values) or something advanced it and the ghost CONFLICTS. A timeout
        here fails the DD round; the next round re-reads the live layout.

        Returns the commit version — by the pipeline's ordering guarantee,
        every batch with a later version routes with the new map, so the
        returned version IS the routing fence."""
        db = self._dd_database()
        await db.refresh(max_wait=5.0)
        tr = db.create_transaction()
        try:
            for k, want in expected.items():
                cur = await tr.get(k)
                if cur != want:
                    raise FDBError("operation_failed",
                                   f"layout moved under DD: {k!r}")
            for m in mutations:
                if m.type == MutationType.CLEAR_RANGE:
                    tr.clear_range(m.param1, m.param2)
                else:
                    tr.set(m.param1, m.param2)
            await tr.commit()
            return tr.committed_version
        except FDBError as e:
            if e.name == "operation_cancelled":
                raise
            raise FDBError("operation_failed",
                           f"metadata txn failed: {e.name}") from None

    async def _preemption_watch(self, epoch: int):
        """Trigger ONE recovery when the current generation's txn roles
        could be placed on strictly better-fitness workers (a degraded-but-
        alive generation is otherwise never improved). The candidate must
        look better across two consecutive checks so a worker mid-reboot
        doesn't cause churn."""
        better_streak = 0
        while True:
            await self.loop.delay(KNOBS.CC_PREEMPT_INTERVAL_SECONDS)
            info = self.dbinfo
            if (self.deposed or info.epoch != epoch
                    or info.recovery_state != "accepting_commits"):
                return
            now = self.loop.now()

            def current_cost(addrs, kind):
                return sum(role_fitness(kind, self.registry.class_of(a))
                           for a in addrs)

            # recruitment skips excluded workers; a better-looking placement
            # that needs one would churn recoveries forever
            excluded = set(
                (getattr(self, "_cstate_conf", None) or {}).get("excluded")
                or [])

            def best_cost(kind, families):
                # mirror recruitment's placement exactly: each role FAMILY
                # takes workers from the front of the fitness-ranked list
                # independently (proxies from ranked[0..], resolvers from
                # ranked[0..], ...), excluded workers removed
                ranked = [a for a in self.registry.alive(
                    "stateless" if kind == "stateless" else kind, now)
                    if a not in excluded]
                if not ranked:
                    return None  # can't even re-recruit: no preemption
                return sum(
                    role_fitness(kind, self.registry.class_of(
                        ranked[i % len(ranked)]))
                    for size in families for i in range(size))

            stateless_addrs = ([info.master] + list(info.proxies)
                               + list(info.resolvers)
                               + ([info.ratekeeper] if info.ratekeeper else []))
            last_ep = info.log_epochs[-1] if info.log_epochs else None
            tlog_addrs = (last_ep.addrs[:last_ep.n_primary or len(last_ep.addrs)]
                          if last_ep else [])
            cur = (current_cost(stateless_addrs, "stateless")
                   + current_cost(tlog_addrs, "tlog"))
            b_s = best_cost("stateless", [1, len(info.proxies),
                                          len(info.resolvers),
                                          1 if info.ratekeeper else 0])
            b_t = best_cost("tlog", [len(tlog_addrs)])
            if b_s is None or b_t is None or b_s + b_t >= cur:
                better_streak = 0
                continue
            better_streak += 1
            if better_streak < 2:
                continue
            TraceEvent("CCBetterMasterExists", self.process.address) \
                .detail("Current", cur).detail("Best", b_s + b_t).log()
            if not self._need_recovery.is_ready():
                self._need_recovery._set("betterMasterExists")
            return

    async def _read_db_conf(self) -> dict | None:
        """Live \\xff/conf contents (ManagementAPI surface); None when the
        read failed — callers must SKIP the round, not act on boot defaults
        (falling back would e.g. shrink-team a `configure double` cluster
        on any transient read blip)."""
        from foundationdb_tpu.client import management
        db = self._dd_database()
        try:
            return await management.get_configuration(db)
        except FDBError as e:
            if e.name == "operation_cancelled":
                raise
            return None

    async def _apply_conf_shape(self, info, conf: dict) -> bool:
        """Txn-subsystem shape changes (n_proxies/n_resolvers/n_tlogs):
        persist to the cstate and trigger a recovery that re-recruits with
        the new counts (the reference equally restarts the transaction
        subsystem on such configure commands). Exclusions are synced into
        the cstate too, so recruitment (which runs while the database is
        unreadable) honors them. Returns True if a recovery was triggered."""
        now = self.loop.now()
        excluded = sorted(conf.get("excluded") or [])
        shape = {}
        cur = {"n_proxies": len(info.proxies),
               "n_grv_proxies": len(info.grv_proxies),
               "n_resolvers": len(info.resolvers),
               "n_tlogs": len(info.log_epochs[-1].addrs[
                   :info.log_epochs[-1].n_primary
                   or len(info.log_epochs[-1].addrs)])
               if info.log_epochs else 0}
        for k in ("n_proxies", "n_grv_proxies", "n_resolvers", "n_tlogs"):
            if k in conf and conf[k] != cur[k]:
                shape[k] = conf[k]
        want_conf = {k: v for k, v in conf.items() if k != "excluded"}
        want_conf["excluded"] = excluded
        if not shape and want_conf == getattr(self, "_cstate_conf", None):
            return False
        if shape:
            # feasibility: a shape the registry cannot satisfy would brick
            # the cluster (recovery fails forever; the corrective configure
            # can never commit while recovery holds the database down).
            # Mirror recruitment exactly: excluded workers don't count.
            ex = set(excluded)
            n_stateless = len([a for a in self.registry.alive(
                "stateless", now) if a not in ex])
            avail = {
                "n_proxies": n_stateless,
                "n_grv_proxies": n_stateless,
                "n_resolvers": n_stateless,
                "n_tlogs": len([a for a in self.registry.alive("tlog", now)
                                if a not in ex])}
            bad = {k: v for k, v in shape.items() if v > avail[k]}
            # commit + GRV proxies each need their own stateless worker
            want_px = (shape.get("n_proxies", cur["n_proxies"])
                       + shape.get("n_grv_proxies", cur["n_grv_proxies"]))
            if want_px > n_stateless:
                bad.setdefault("n_proxies+n_grv_proxies", want_px)
            if bad:
                TraceEvent("CCConfigureInfeasible", self.process.address,
                           severity=30).detail("Requested", bad) \
                    .detail("Available", avail).log()
                return False
        prior, _gen = await self.cstate.read()
        if prior is None or prior.get("epoch") != info.epoch or self.deposed:
            return False
        prior["conf"] = want_conf
        await self.cstate.write(prior)
        self._cstate_conf = want_conf
        if not shape:
            return False  # exclusion sync only: no recovery needed
        TraceEvent("CCConfigureRecovery", self.process.address) \
            .detail("Shape", shape).log()
        if not self._need_recovery.is_ready():
            self._need_recovery._set(f"configure {shape}")
        return True

    async def _reconcile_keyservers(self, info) -> bool:
        """Compare the live \\xff/keyServers rows with the published layout;
        if they differ, write the published layout back (expected = the live
        values just read, so a delayed ghost of this txn conflicts unless
        nothing changed). Returns True if a corrective txn ran."""
        from foundationdb_tpu.server import systemdata
        db = self._dd_database()
        await db.refresh(max_wait=5.0)
        tr = db.create_transaction()
        try:
            live = await tr.get_range(systemdata.KEY_SERVERS_PREFIX,
                                      systemdata.KEY_SERVERS_END)
            want = systemdata.build_keyservers_snapshot(
                list(info.shard_boundaries), [list(t) for t in info.teams()])
            if list(live) == want:
                return False
            TraceEvent("DDReconcileLayout", self.process.address) \
                .detail("Live", len(live)).detail("Want", len(want)).log()
            tr.clear_range(systemdata.KEY_SERVERS_PREFIX,
                           systemdata.KEY_SERVERS_END)
            for k, v in want:
                tr.set(k, v)
            await tr.commit()
            return True
        except FDBError as e:
            if e.name == "operation_cancelled":
                raise
            raise FDBError("operation_failed",
                           f"reconcile failed: {e.name}") from None

    # -- redundancy healing (teamTracker DataDistribution.actor.cpp:1373 +
    # storageServerTracker :1730): a storage server silent past the failure
    # timeout is permanently failed; every shard it served is re-replicated
    # onto a replacement via the normal dual-route + fetchKeys move --

    async def _heal_once(self, info, conf: dict | None = None) -> bool:
        from foundationdb_tpu.server import systemdata
        conf = conf or {}
        now = self.loop.now()
        alive = set(self.registry.alive(
            "storage", now, max_age=KNOBS.DD_STORAGE_FAILURE_SECONDS))
        # excluded servers are drained exactly like failed ones
        # (ManagementAPI excludeServers -> DD moves every shard off them)
        excluded = set(conf.get("excluded") or [])
        alive -= excluded
        addr_of_tag = {t: a for a, t in info.storages}
        dead_tags = {t for a, t in info.storages if a not in alive}
        teams = [list(t) for t in info.teams()]
        b = list(info.shard_boundaries)
        # a team needs healing if it references a dead/excluded tag OR is
        # off the replication target (below: top up one replacement per
        # round; above after `configure single`: shrink one per round)
        want = int(conf.get("n_replicas", self.config.n_replicas))
        over = [(i, t) for i, t in enumerate(teams)
                if not any(x in dead_tags for x in t) and len(t) > want]
        if over:
            return await self._shrink_team(info, over[0][0], want)
        affected = [(i, t) for i, t in enumerate(teams)
                    if any(x in dead_tags for x in t)
                    or len([x for x in t if x not in dead_tags]) < want]
        if not affected:
            # GC: a dead tag referenced by NO team can be dropped — pop it
            # on every TLog so the queue can truncate, and forget the server
            gone = [t for t in dead_tags
                    if not any(t in team for team in teams)]
            if gone:
                await self._forget_tags(info, gone)
                return True
            return False
        i, team = affected[0]
        alive_in_team = [t for t in team if t not in dead_tags]
        if not alive_in_team:
            TraceEvent("DDShardUnrecoverable", self.process.address,
                       severity=40).detail("Shard", i).log()
            return False  # every replica lost: nothing to copy from
        lo = b[i]
        hi = b[i + 1] if i + 1 < len(b) else None

        # replacement: a spare alive storage worker (no live tag), else an
        # alive server not already in this team. Among spares, prefer one
        # that keeps the team satisfying the replication policy (a zone the
        # surviving members don't cover, ReplicationPolicy Across semantics).
        from foundationdb_tpu.server.replication import (
            policy_for_replication, select_replicas)
        used = {addr_of_tag[t] for t in addr_of_tag
                if t not in dead_tags}
        spare = sorted(a for a in alive if a not in used)
        if len(spare) > 1:
            policy = policy_for_replication(want)
            surviving = [(addr_of_tag[t], self.registry.locality_of(
                addr_of_tag[t])) for t in alive_in_team]
            best = select_replicas(
                policy, [(a, self.registry.locality_of(a)) for a in spare],
                already=surviving)
            if best:
                spare = best + [a for a in spare if a not in best]
        new_storages = list(info.storages)
        if spare:
            new_tag = max((t for _a, t in info.storages), default=-1) + 1
            epoch0 = info.log_epochs[-1].begin if info.log_epochs else 0
            addr = (await self._recruit_many(
                [spare[0]], 1, "storage",
                lambda _i: {"tag": new_tag,
                            "log_epochs": list(info.log_epochs),
                            "recovery_count": info.epoch,
                            "recovery_version": epoch0,
                            "shard_ranges": [],
                            "engine": conf.get("storage_engine")}))[0]
            new_storages.append((addr, new_tag))
            addr_of_tag[new_tag] = addr
        else:
            candidates = [t for _a, t in info.storages
                          if t not in dead_tags and t not in team]
            if not candidates:
                TraceEvent("DDHealNoReplacement", self.process.address) \
                    .detail("Shard", i).log()
                return False
            new_tag = candidates[0]
        TraceEvent("DDHealShard", self.process.address) \
            .detail("Shard", i) \
            .detail("DeadTags", sorted(set(team) - set(alive_in_team))) \
            .detail("NewTag", new_tag).log()

        # dual-route (mutations flow to the replacement from the fence on),
        # copy from an alive replica, then finalize the team without the
        # dead tag — the same fenced move shards use
        fence = await self._commit_metadata_txn(
            info,
            {systemdata.keyservers_key(lo): systemdata.encode_tags(team)},
            [Mutation(MutationType.SET_VALUE, systemdata.keyservers_key(lo),
                      systemdata.encode_tags(sorted(set(team) | {new_tag})))])
        src = addr_of_tag[alive_in_team[0]]
        await self.loop.timeout(self.net.request(
            self.process, Endpoint(addr_of_tag[new_tag],
                                   Token.STORAGE_ADD_SHARD),
            AddShardRequest(begin=lo, end=hi, source=src,
                            fence_version=fence)), 30.0)
        new_team = sorted(set(alive_in_team) | {new_tag})
        done = await self._commit_metadata_txn(
            info,
            {systemdata.keyservers_key(lo):
                 systemdata.encode_tags(sorted(set(team) | {new_tag}))},
            [Mutation(MutationType.SET_VALUE, systemdata.keyservers_key(lo),
                      systemdata.encode_tags(new_team))])
        new_teams = [list(t) for t in teams]
        new_teams[i] = new_team
        await self._publish_layout(b, new_teams, storages=new_storages)
        # serving ranges for every OLD member too, not just the new team: a
        # drained-but-alive member (exclusion heals look exactly like dead-
        # server heals) must drop the range, or a later move back onto it
        # would look like a duplicate and skip the re-fetch — serving every
        # write since the drain from a stale replica
        self._push_team_ranges(sorted(set(team) | {new_tag}), b, new_teams,
                               addr_of_tag, as_of_version=done)
        return True

    async def _shrink_team(self, info, i: int, want: int) -> bool:
        """Drop one member from an over-replicated team (configure down):
        metadata txn, publish, updated serving ranges. The dropped member's
        tag is GC'd by _forget_tags once no team references it."""
        from foundationdb_tpu.server import systemdata
        from foundationdb_tpu.server.replication import (
            policy_for_replication, select_replicas)
        teams = [list(t) for t in info.teams()]
        b = list(info.shard_boundaries)
        team = teams[i]
        addr_of_tag = {t: a for a, t in info.storages}
        # retain a subset that still satisfies the replication policy at the
        # new size (dropping by tag order alone can keep two same-zone
        # replicas and drop the only one in a distinct zone)
        policy = policy_for_replication(want)
        tag_of_addr = {a: t for a, t in info.storages}
        cands = [(addr_of_tag[t], self.registry.locality_of(addr_of_tag[t]))
                 for t in sorted(team) if t in addr_of_tag]
        picked = select_replicas(policy, cands)
        if picked is not None and len(picked) == want:
            new_team = sorted(tag_of_addr[a] for a in picked)
        else:
            new_team = sorted(team)[:want]
            TraceEvent("DDShrinkTeamNoPolicySubset", self.process.address) \
                .detail("Shard", i).detail("Policy", str(policy)).log()
        TraceEvent("DDShrinkTeam", self.process.address) \
            .detail("Shard", i).detail("From", team).detail("To", new_team).log()
        done = await self._commit_metadata_txn(
            info,
            {systemdata.keyservers_key(b[i]): systemdata.encode_tags(team)},
            [Mutation(MutationType.SET_VALUE, systemdata.keyservers_key(b[i]),
                      systemdata.encode_tags(new_team))])
        new_teams = [list(t) for t in teams]
        new_teams[i] = new_team
        await self._publish_layout(b, new_teams)
        # every old member (dropped ones included) gets its remaining
        # assignments pushed — possibly empty (new_team is a subset of team)
        self._push_team_ranges(sorted(set(team)), b, new_teams, addr_of_tag,
                               as_of_version=done)
        return True

    async def _forget_tags(self, info, tags: list[int]):
        """Drop fully-unreferenced dead tags: final TLog pops (so disk
        queues can truncate past their backlog) + remove from the server
        list."""
        from foundationdb_tpu.server.interfaces import TLogPopRequest
        for ep in info.log_epochs:
            for j, addr in enumerate(ep.addrs):
                for t in tags:
                    self.net.one_way(
                        self.process, Endpoint(addr, Token.TLOG_POP),
                        TLogPopRequest(tag=t, version=1 << 60,
                                       uid=ep.uid_of(j)))
        new_storages = [(a, t) for a, t in info.storages if t not in tags]
        TraceEvent("DDForgetTags", self.process.address) \
            .detail("Tags", list(tags)).log()
        await self._publish_layout(list(info.shard_boundaries),
                                   [list(t) for t in info.teams()],
                                   storages=new_storages)

    async def _merge(self, i: int):
        """Drop the boundary between shards i and i+1 (same team): one
        metadata transaction clears its \\xff/keyServers entry (every proxy
        applies it in version order), then publish through the cstate and
        DBInfo. Stale layouts stay correct — the union of the halves is
        exactly the merged shard on the same servers."""
        from foundationdb_tpu.server import systemdata
        info = self.dbinfo
        b = list(info.shard_boundaries)
        teams = [list(t) for t in info.teams()]
        new_b = b[:i + 1] + b[i + 2:]
        new_teams = teams[:i + 1] + teams[i + 2:]
        TraceEvent("DDMergeShards", self.process.address) \
            .detail("At", b[i + 1].hex()).log()
        k = systemdata.keyservers_key(b[i + 1])
        done = await self._commit_metadata_txn(
            info,
            {k: systemdata.encode_tags(teams[i + 1]),
             systemdata.keyservers_key(b[i]): systemdata.encode_tags(teams[i])},
            [Mutation(MutationType.CLEAR_RANGE, k, k + b"\x00")])
        await self._publish_layout(new_b, new_teams)
        # the merged team's storage servers must coalesce their served
        # ranges too: _owns_range requires a request to fit ONE entry, so a
        # post-merge range read spanning the former boundary would get
        # wrong_shard_server forever from a team with explicit shard_ranges
        addr_of_tag = {t: a for a, t in info.storages}
        self._push_team_ranges(teams[i], new_b, new_teams, addr_of_tag,
                               as_of_version=done)

    def _tag_ranges(self, tag, boundaries, teams):
        """EVERY range `tag` serves — the union over all shards whose team
        contains it. Teams may overlap (healing/configure reuse servers), so
        a per-team list would clobber a member's other assignments."""
        return [(boundaries[j],
                 boundaries[j + 1] if j + 1 < len(boundaries) else None)
                for j, t in enumerate(teams) if tag in t]

    def _push_team_ranges(self, team, boundaries, teams, addr_of_tag,
                          as_of_version=None):
        lv = (self.dbinfo.epoch, self.dbinfo.version)
        for tag in team:
            if addr_of_tag.get(tag) is None:
                continue
            self.net.one_way(
                self.process,
                Endpoint(addr_of_tag[tag], Token.STORAGE_SET_SHARDS),
                SetShardsRequest(
                    shard_ranges=self._tag_ranges(tag, boundaries, teams),
                    layout_version=lv, as_of_version=as_of_version))

    async def _publish_layout(self, new_b, new_teams, storages=None):
        """Shared publish step for every DD layout change: the coordinated
        state FIRST (a racing recovery must see a consistent layout), then
        DBInfo for clients. Aborts if the epoch moved or we were deposed."""
        info = self.dbinfo
        if storages is None:
            storages = info.storages
        prior, _gen = await self.cstate.read()
        if prior is None or prior.get("epoch") != info.epoch or self.deposed:
            raise FDBError("coordinators_changed", "layout changed under DD")
        prior["shard_boundaries"] = new_b
        prior["shard_tags"] = new_teams
        prior["storages"] = [list(s) for s in storages]
        await self.cstate.write(prior)
        self.dbinfo = DBInfo(
            version=info.version + 1, epoch=info.epoch, master=info.master,
            proxies=info.proxies, resolvers=info.resolvers,
            log_epochs=info.log_epochs, storages=[tuple(s) for s in storages],
            shard_boundaries=new_b, recovery_state="accepting_commits",
            ratekeeper=info.ratekeeper, shard_tags=new_teams)

    async def _split_and_move(self, i: int, split_key: bytes):
        info = self.dbinfo
        b = list(info.shard_boundaries)
        teams = [list(t) for t in info.teams()]
        addr_of_tag = {t: a for a, t in info.storages}
        old_team = teams[i]
        hi = b[i + 1] if i + 1 < len(b) else None
        # destination: the team serving the fewest shards (itself included:
        # a pure split happens when the source team is least loaded)
        uniq: list[list[int]] = []
        for t in teams:
            if t not in uniq:
                uniq.append(t)
        counts = {tuple(t): sum(1 for x in teams if x == t) for t in uniq}
        dest = min(uniq, key=lambda t: (counts[tuple(t)], tuple(t)))
        new_b = b[:i + 1] + [split_key] + b[i + 1:]
        new_teams = teams[:i + 1] + [dest] + teams[i + 1:]
        # during the handoff the moving range is DUAL-ROUTED to source and
        # destination tags: the source keeps serving (and seeing) acked
        # writes until the layout is published, and a CC crash mid-move
        # leaves the old cstate layout fully correct (the source missed
        # nothing; the destination's partial copy is simply never served)
        from foundationdb_tpu.server import systemdata
        both = sorted(set(old_team) | set(dest))
        TraceEvent("DDSplitShard", self.process.address) \
            .detail("At", split_key.hex()).detail("Move", dest != old_team).log()

        # 1. dual-route via a metadata transaction: \xff/keyServers/<split>
        # = union team flows through the pipeline; every proxy applies it in
        # version order BEFORE routing any later batch, so the txn's commit
        # version IS the fence — every mutation with a later version is
        # routed to both teams (the moveKeys startMoveKeys analogue). The
        # expected-value reads abort the txn (or any delayed ghost of an
        # earlier round) if the layout moved.
        fence = await self._commit_metadata_txn(
            info,
            {systemdata.keyservers_key(b[i]): systemdata.encode_tags(old_team),
             systemdata.keyservers_key(split_key): None},
            [Mutation(MutationType.SET_VALUE,
                      systemdata.keyservers_key(split_key),
                      systemdata.encode_tags(both))])
        # 2. destination fetches at/above the fence (no-op when the team
        # keeps the shard)
        if dest != old_team:
            src = addr_of_tag[old_team[0]]
            for tag in dest:
                await self.loop.timeout(self.net.request(
                    self.process,
                    Endpoint(addr_of_tag[tag], Token.STORAGE_ADD_SHARD),
                    AddShardRequest(begin=split_key, end=hi, source=src,
                                    fence_version=fence)), 30.0)
        # 3. publish: cstate first (a concurrent recovery must see the new
        # layout), then DBInfo for clients; finally shrink the source
        await self._publish_layout(new_b, new_teams)
        # 4. end the dual-route window (finishMoveKeys analogue): final
        # single-team entry, then the source stops serving the moved range
        # (stale clients get wrong_shard_server and re-resolve through the
        # published layout)
        done = await self._commit_metadata_txn(
            info,
            {systemdata.keyservers_key(split_key):
                 systemdata.encode_tags(both)},
            [Mutation(MutationType.SET_VALUE,
                      systemdata.keyservers_key(split_key),
                      systemdata.encode_tags(dest))])
        if dest != old_team:
            self._push_team_ranges(old_team, new_b, new_teams, addr_of_tag,
                                   as_of_version=done)
