"""Server roles: master, proxy, resolver, tlog, storage, and cluster wiring.

Reference layer 3 (fdbserver/). Each role is a plain class bound to a
SimProcess; request handlers register on well-known endpoint tokens
(fdbserver/WorkerInterface.h pattern).
"""
