"""Ratekeeper: cluster-wide admission control.

Reference: fdbserver/Ratekeeper.actor.cpp — updateRate (:250) computes a
transactions-per-second budget from smoothed storage durability lag and TLog
queue depth; proxies fetch it with GetRateInfoRequest (rateKeeper :508 /
MasterProxyServer getRate :86) and gate read-version handouts with it, which
throttles ingest at the front door instead of letting server queues grow
without bound.

Here the worst storage lag (latest applied version - durable version) and the
worst TLog in-memory queue depth each scale the budget down proportionally
when they exceed their targets; the final rate is the min of the two,
exponentially smoothed.
"""

from __future__ import annotations

from dataclasses import dataclass

from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.server.interfaces import Token
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.stats import CounterCollection, trace_counters_loop


@dataclass
class RateInfoReply:
    tps: float  # transaction starts per second this proxy may grant


@dataclass
class QueueStatsReply:
    """TLog/storage health sample (TLogQueuingMetrics / StorageQueuingMetrics)."""

    queue_bytes: int = 0  # TLog: un-popped in-memory bytes
    lag_versions: int = 0  # storage: version - durable_version


class Ratekeeper:
    def __init__(self, process: SimProcess,
                 tlogs: list[str] | None = None,
                 storages: list[str] | None = None):
        self.process = process
        self.loop = process.net.loop
        self.tlogs = list(tlogs or [])
        self.storages = list(storages or [])
        self.tps = KNOBS.RK_BASE_TPS
        self.stats = {"worst_tlog_bytes": 0, "worst_storage_lag": 0}
        self.counters = CounterCollection("Ratekeeper", str(process.address))
        self._c_rate_reqs = self.counters.counter("RateRequests")
        self._c_updates = self.counters.counter("UpdateRounds")
        # control-loop gauges (set, not incremented): the last sampled worsts
        # and the current budget
        self._g_tps = self.counters.counter("TPS")
        self._g_worst_log = self.counters.counter("WorstTLogBytes")
        self._g_worst_lag = self.counters.counter("WorstStorageLag")
        self._g_tps.set(self.tps)
        process.register(Token.RK_GET_RATE, self._on_get_rate)
        process.register(Token.RK_METRICS, self._on_metrics)
        self._task = process.spawn(self._update_loop(), "rateKeeper")
        self._counters_task = trace_counters_loop(process, self.counters)

    def shutdown(self):
        self._task.cancel()
        self._counters_task.cancel()

    def _on_metrics(self, req, reply):
        reply.send(self.counters.as_dict())

    def _on_get_rate(self, req, reply):
        n = max(1, req if isinstance(req, int) else 1)  # proxies share the budget
        self._c_rate_reqs.increment()
        reply.send(RateInfoReply(tps=self.tps / n))

    async def _sample(self, addr: str) -> QueueStatsReply | None:
        try:
            return await self.loop.timeout(self.process.net.request(
                self.process, Endpoint(addr, Token.QUEUE_STATS), None), 1.0)
        except FDBError as e:
            if e.name == "operation_cancelled":
                raise
            return None

    async def _update_loop(self):
        smoothing = KNOBS.RK_SMOOTHING
        while True:
            # sample everyone concurrently: sequential 1s timeouts would slow
            # the control loop to O(n) seconds exactly when servers are dead
            log_f = [self.loop.spawn(self._sample(a), "rkSample")
                     for a in self.tlogs]
            lag_f = [self.loop.spawn(self._sample(a), "rkSample")
                     for a in self.storages]
            worst_log = 0
            for f in log_f:
                s = await f
                if s is not None:
                    worst_log = max(worst_log, s.queue_bytes)
            worst_lag = 0
            for f in lag_f:
                s = await f
                if s is not None:
                    worst_lag = max(worst_lag, s.lag_versions)
            self.stats["worst_tlog_bytes"] = worst_log
            self.stats["worst_storage_lag"] = worst_lag
            self._c_updates.increment()
            # Counter gauges, not promise gates: nothing awaits them,
            # so no settle discipline applies
            self._g_worst_log.set(worst_log)  # flowlint: ignore[FLOW002]
            self._g_worst_lag.set(worst_lag)  # flowlint: ignore[FLOW002]

            scale = 1.0
            if worst_log > KNOBS.RK_TARGET_TLOG_BYTES:
                scale = min(scale, KNOBS.RK_TARGET_TLOG_BYTES / worst_log)
            if worst_lag > KNOBS.RK_TARGET_STORAGE_LAG_VERSIONS:
                scale = min(scale,
                            KNOBS.RK_TARGET_STORAGE_LAG_VERSIONS / worst_lag)
            target = KNOBS.RK_BASE_TPS * scale
            self.tps = (1 - smoothing) * self.tps + smoothing * target
            self._g_tps.set(round(self.tps, 2))  # flowlint: ignore[FLOW002]
            await self.loop.delay(KNOBS.RK_UPDATE_INTERVAL)
