"""Ratekeeper: cluster-wide admission control.

Reference: fdbserver/Ratekeeper.actor.cpp — updateRate (:250) computes a
transactions-per-second budget from smoothed storage durability lag and TLog
queue depth; proxies fetch it with GetRateInfoRequest (rateKeeper :508 /
MasterProxyServer getRate :86) and gate read-version handouts with it, which
throttles ingest at the front door instead of letting server queues grow
without bound.

Here the worst storage lag (latest applied version - durable version) and the
worst TLog in-memory queue depth each scale the budget down proportionally
when they exceed their targets; the final rate is the min of the two,
exponentially smoothed.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.server.hotspot import HotRangeSketch, ThrottleEntry
from foundationdb_tpu.server.interfaces import Token
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.stats import CounterCollection, trace_counters_loop
from foundationdb_tpu.utils.trace import TraceEvent


@dataclass
class RateInfoReply:
    tps: float  # transaction starts per second this proxy may grant
    # hot ranges each proxy must gate commits against (ThrottleEntry list;
    # trailing + defaulted so the extension is wire-compatible with peers
    # that still send/expect the bare-tps schema)
    throttles: list = field(default_factory=list)


@dataclass
class QueueStatsReply:
    """TLog/storage health sample (TLogQueuingMetrics / StorageQueuingMetrics)."""

    queue_bytes: int = 0  # TLog: un-popped in-memory bytes
    lag_versions: int = 0  # storage: version - durable_version


class Ratekeeper:
    def __init__(self, process: SimProcess,
                 tlogs: list[str] | None = None,
                 storages: list[str] | None = None,
                 resolvers: list[str] | None = None):
        self.process = process
        self.loop = process.net.loop
        self.tlogs = list(tlogs or [])
        self.storages = list(storages or [])
        self.resolvers = list(resolvers or [])
        self.tps = KNOBS.RK_BASE_TPS
        # ThrottleEntry list recomputed each update round from the merged
        # resolver hot-range snapshots (docs/contention.md)
        self.throttles: list[ThrottleEntry] = []
        self.stats = {"worst_tlog_bytes": 0, "worst_storage_lag": 0,
                      "hot_total_rate": 0.0}
        self.counters = CounterCollection("Ratekeeper", str(process.address))
        self._c_rate_reqs = self.counters.counter("RateRequests")
        self._c_updates = self.counters.counter("UpdateRounds")
        # control-loop gauges (set, not incremented): the last sampled worsts
        # and the current budget
        self._g_tps = self.counters.counter("TPS")
        self._g_worst_log = self.counters.counter("WorstTLogBytes")
        self._g_worst_lag = self.counters.counter("WorstStorageLag")
        self._g_throttled = self.counters.counter("ThrottledRanges")
        self._g_hot_rate = self.counters.counter("HotConflictRate")
        self._g_tps.set(self.tps)
        process.register(Token.RK_GET_RATE, self._on_get_rate)
        process.register(Token.RK_METRICS, self._on_metrics)
        self._task = process.spawn(self._update_loop(), "rateKeeper")
        self._counters_task = trace_counters_loop(process, self.counters)

    def shutdown(self):
        self._task.cancel()
        self._counters_task.cancel()

    def _on_metrics(self, req, reply):
        from foundationdb_tpu.utils.stats import fold_transport_counters
        reply.send(fold_transport_counters(self.process,
                                           self.counters.as_dict()))

    def _on_get_rate(self, req, reply):
        n = max(1, req if isinstance(req, int) else 1)  # proxies share the budget
        self._c_rate_reqs.increment()
        # the throttle release budget is fleet-wide: each proxy gets 1/n of it
        throttles = [ThrottleEntry(begin=t.begin, end=t.end,
                                   release_tps=t.release_tps / n,
                                   backoff=t.backoff)
                     for t in self.throttles]
        reply.send(RateInfoReply(tps=self.tps / n, throttles=throttles))

    async def _sample(self, addr: str) -> QueueStatsReply | None:
        try:
            return await self.loop.timeout(self.process.net.request(
                self.process, Endpoint(addr, Token.QUEUE_STATS), None), 1.0)
        except FDBError as e:
            if e.name == "operation_cancelled":
                raise
            return None

    async def _sample_hot(self, addr: str):
        try:
            return await self.loop.timeout(self.process.net.request(
                self.process, Endpoint(addr, Token.RESOLVER_HOT_RANGES),
                KNOBS.HOTSPOT_TOP_K), 1.0)
        except FDBError as e:
            if e.name == "operation_cancelled":
                raise
            return None

    def _compute_throttles(self, hot_replies: list) -> list[ThrottleEntry]:
        """Merge per-resolver hot-range snapshots and throttle every range
        whose summed conflict rate clears RK_THROTTLE_CONFLICT_RATE. The
        advised backoff scales with how far over the threshold the range is
        (hotter range -> longer advised wait), capped at the knob ceiling.
        Deterministic: merge keys are exact ranges, output sorted hottest
        first with (begin, end) tie-breaks — same snapshots, same list."""
        merged: dict[tuple[bytes, bytes], float] = {}
        total = 0.0
        for r in hot_replies:
            if r is None:
                continue
            total += r.total_rate
            for hr in r.ranges:
                key = (hr.begin, hr.end)
                merged[key] = merged.get(key, 0.0) + hr.rate
        self.stats["hot_total_rate"] = total
        threshold = KNOBS.RK_THROTTLE_CONFLICT_RATE
        hot = []
        for (begin, end), rate in merged.items():
            if rate < threshold:
                continue
            backoff = min(KNOBS.RK_THROTTLE_MAX_BACKOFF,
                          KNOBS.RK_THROTTLE_BACKOFF * rate / threshold)
            hot.append((rate, ThrottleEntry(
                begin=begin, end=end,
                release_tps=KNOBS.RK_THROTTLE_RELEASE_TPS, backoff=backoff)))
        hot.sort(key=lambda rt: (-rt[0], rt[1].begin, rt[1].end))
        return [t for _rate, t in hot]

    async def _update_loop(self):
        smoothing = KNOBS.RK_SMOOTHING
        while True:
            # sample everyone concurrently: sequential 1s timeouts would slow
            # the control loop to O(n) seconds exactly when servers are dead
            log_f = [self.loop.spawn(self._sample(a), "rkSample")
                     for a in self.tlogs]
            lag_f = [self.loop.spawn(self._sample(a), "rkSample")
                     for a in self.storages]
            hot_f = ([self.loop.spawn(self._sample_hot(a), "rkHotSample")
                      for a in self.resolvers]
                     if KNOBS.CONTENTION_THROTTLE_ENABLED else [])
            worst_log = 0
            for f in log_f:
                s = await f
                if s is not None:
                    worst_log = max(worst_log, s.queue_bytes)
            worst_lag = 0
            for f in lag_f:
                s = await f
                if s is not None:
                    worst_lag = max(worst_lag, s.lag_versions)
            hot_replies = [await f for f in hot_f]
            self.throttles = (self._compute_throttles(hot_replies)
                              if KNOBS.CONTENTION_THROTTLE_ENABLED else [])
            if self.throttles:
                TraceEvent("RkThrottleList", self.process.address) \
                    .detail("Ranges", len(self.throttles)) \
                    .detail("Hottest", self.throttles[0].begin.hex()) \
                    .detail("Backoff", round(self.throttles[0].backoff, 3)) \
                    .log()
            self.stats["worst_tlog_bytes"] = worst_log
            self.stats["worst_storage_lag"] = worst_lag
            self._c_updates.increment()
            # Counter gauges, not promise gates: nothing awaits them,
            # so no settle discipline applies
            self._g_worst_log.set(worst_log)  # flowlint: ignore[FLOW002]
            self._g_worst_lag.set(worst_lag)  # flowlint: ignore[FLOW002]
            self._g_throttled.set(len(self.throttles))  # flowlint: ignore[FLOW002]
            self._g_hot_rate.set(  # flowlint: ignore[FLOW002]
                round(self.stats["hot_total_rate"], 2))

            scale = 1.0
            if worst_log > KNOBS.RK_TARGET_TLOG_BYTES:
                scale = min(scale, KNOBS.RK_TARGET_TLOG_BYTES / worst_log)
            if worst_lag > KNOBS.RK_TARGET_STORAGE_LAG_VERSIONS:
                scale = min(scale,
                            KNOBS.RK_TARGET_STORAGE_LAG_VERSIONS / worst_lag)
            target = KNOBS.RK_BASE_TPS * scale
            self.tps = (1 - smoothing) * self.tps + smoothing * target
            self._g_tps.set(round(self.tps, 2))  # flowlint: ignore[FLOW002]
            await self.loop.delay(KNOBS.RK_UPDATE_INTERVAL)
