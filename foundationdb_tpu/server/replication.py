"""Replication policy engine: team placement across failure domains.

Reference: fdbrpc/ReplicationPolicy.h:99-127 (PolicyOne / PolicyAcross /
PolicyAnd over locality attributes), fdbrpc/Locality.h (LocalityData:
processid/zoneid/machineid/dcid), fdbrpc/ReplicationUtils.cpp
(selectReplicas / validate). FDB's standard configurations are instances:
`triple` = Across(3, "zoneid", One()), `double` = Across(2, "zoneid", One()).

The engine answers two questions for the data distributor:
  validate(team)   — does this team satisfy the policy?
  select_replicas  — pick n candidates satisfying it (greedy over the
                     rarest attribute values first, the shape of the
                     reference's deep-first selection)
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class LocalityData:
    """fdbrpc/Locality.h LocalityData's standard keys."""

    process_id: str = ""
    zone_id: str = ""
    machine_id: str = ""
    dc_id: str = ""

    def get(self, attrib: str) -> str:
        return {"processid": self.process_id, "zoneid": self.zone_id,
                "machineid": self.machine_id, "dcid": self.dc_id}[attrib]


class Policy:
    def n_required(self) -> int:
        raise NotImplementedError

    def validate(self, localities: list[LocalityData]) -> bool:
        raise NotImplementedError


@dataclass(frozen=True)
class PolicyOne(Policy):
    """ReplicationPolicy.h PolicyOne: any single replica."""

    def n_required(self) -> int:
        return 1

    def validate(self, localities) -> bool:
        return len(localities) >= 1

    def __str__(self):
        return "One()"


@dataclass(frozen=True)
class PolicyAcross(Policy):
    """ReplicationPolicy.h:99 PolicyAcross(count, attrib, sub): `count`
    distinct values of `attrib`, each internally satisfying `sub`."""

    count: int
    attrib: str
    sub: Policy = field(default_factory=PolicyOne)

    def n_required(self) -> int:
        return self.count * self.sub.n_required()

    def validate(self, localities) -> bool:
        groups: dict[str, list[LocalityData]] = {}
        for loc in localities:
            groups.setdefault(loc.get(self.attrib), []).append(loc)
        ok = sum(1 for g in groups.values() if self.sub.validate(g))
        return ok >= self.count

    def __str__(self):
        return f"Across({self.count}, {self.attrib}, {self.sub})"


@dataclass(frozen=True)
class PolicyAnd(Policy):
    """ReplicationPolicy.h PolicyAnd: every sub-policy must hold."""

    subs: tuple

    def n_required(self) -> int:
        return max(s.n_required() for s in self.subs)

    def validate(self, localities) -> bool:
        return all(s.validate(localities) for s in self.subs)

    def __str__(self):
        return "And(" + ", ".join(str(s) for s in self.subs) + ")"


def select_replicas(policy: Policy,
                    candidates: list[tuple[str, LocalityData]],
                    already: list[tuple[str, LocalityData]] | None = None,
                    ) -> list[str] | None:
    """Pick addresses so that `already + picks` satisfies `policy`, using as
    few picks as possible; None when impossible (ReplicationUtils
    selectReplicas). Greedy: prefer candidates contributing a NEW value of
    the policy's discriminating attribute, rarest values first (keeps
    future choices open, like the reference's deep-first search)."""
    already = list(already or [])
    locs = [l for _a, l in already]
    if policy.validate(locs):
        return []
    picks: list[str] = []
    pool = [(i, a, l) for i, (a, l) in enumerate(candidates)
            if a not in {a2 for a2, _l in already}]
    for _ in range(policy.n_required() + len(already) + 1):
        best = None
        for idx, addr, loc in pool:
            trial = locs + [loc]
            # score: does this pick move validation forward for any Across?
            gain = _coverage(policy, trial) - _coverage(policy, locs)
            rarity = sum(1 for _i2, _a2, l2 in pool
                         if _discr_values(policy, l2) == _discr_values(policy, loc))
            # final tiebreak = INPUT ORDER: callers pass candidates ranked
            # (e.g. by ProcessClass fitness), and that ranking must survive
            # the policy selection
            cand = (-gain, rarity, idx)
            if gain > 0 and (best is None or cand < best[0]):
                best = (cand, addr, loc)
        if best is None:
            return None  # no candidate makes progress: impossible
        _, addr, loc = best
        picks.append(addr)
        locs.append(loc)
        pool = [c for c in pool if c[1] != addr]
        if policy.validate(locs):
            return picks
    return None


def _discr_values(policy: Policy, loc: LocalityData) -> tuple:
    if isinstance(policy, PolicyAcross):
        return (loc.get(policy.attrib),) + _discr_values(policy.sub, loc)
    if isinstance(policy, PolicyAnd):
        return tuple(v for s in policy.subs for v in _discr_values(s, loc))
    return ()


_BIG = 10**6


def _coverage(policy: Policy, localities: list[LocalityData]) -> int:
    """How 'satisfied' the policy is — strictly increases whenever a replica
    moves validation forward at any level (a full group outweighs any sum
    of partial ones, and only the best `count` groups score, so surplus
    replicas in an already-full group never mask missing groups)."""
    if isinstance(policy, PolicyAcross):
        groups: dict[str, list[LocalityData]] = {}
        for loc in localities:
            groups.setdefault(loc.get(policy.attrib), []).append(loc)
        scores = sorted(
            (_BIG if policy.sub.validate(g)
             else min(_coverage(policy.sub, g), _BIG - 1)
             for g in groups.values()),
            reverse=True)
        return sum(scores[:policy.count])
    if isinstance(policy, PolicyAnd):
        return sum(_coverage(s, localities) for s in policy.subs)
    return _BIG if localities else 0


def policy_for_replication(n_replicas: int) -> Policy:
    """FDB's standard configs: single/double/triple = Across(n, zoneid, One)."""
    if n_replicas <= 1:
        return PolicyOne()
    return PolicyAcross(n_replicas, "zoneid")
