"""Request/reply structs and well-known endpoint tokens.

Reference: the *Interface.h headers — MasterInterface.h (GetCommitVersion),
ResolverInterface.h:83-91 (ResolveTransactionBatchRequest),
TLogInterface.h (TLogCommitRequest, TLogPeekRequest, TLogPopRequest),
StorageServerInterface.h (GetValueRequest, GetKeyValuesRequest, WatchValue),
MasterProxyInterface.h (CommitTransactionRequest, GetReadVersionRequest).

Payloads are plain dataclasses: the simulator delivers them by reference (the
real transport will serialize; see core/sim.py). Every request that expects a
reply carries it via the sim's reply-promise mechanism, not a field here.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from foundationdb_tpu.utils.types import Mutation


# Well-known endpoint tokens (fdbrpc/FlowTransport.h WLTOKEN_* pattern).
# Every token here must be BOTH registered by a role and reachable from a
# send site (protolint PROTO001); dead declarations were removed — their
# integers stay retired so a revived token cannot collide with frames from
# a mixed-version peer (4, 12, 15, 43, 97, 98 are burned).
class Token:
    MASTER_GET_COMMIT_VERSION = 1
    MASTER_PING = 2
    MASTER_DEPOSE = 3
    PROXY_COMMIT = 10
    PROXY_GET_READ_VERSION = 11
    PROXY_GET_COMMITTED_VERSION = 13
    PROXY_PING = 14
    RESOLVER_RESOLVE = 20
    RESOLVER_HOT_RANGES = 22  # conflict-hotspot snapshot (ratekeeper/DD poll)
    TLOG_COMMIT = 30
    TLOG_PEEK = 31
    TLOG_POP = 32
    STORAGE_GET_VALUE = 40
    STORAGE_GET_KEY_VALUES = 41
    STORAGE_GET_VALUES = 48  # batched point reads
    STORAGE_WATCH_VALUE = 42
    TLOG_LOCK = 33
    STORAGE_SET_LOGSYSTEM = 44
    STORAGE_GET_METRICS = 45
    STORAGE_ADD_SHARD = 46
    STORAGE_SET_SHARDS = 47
    RK_GET_RATE = 80
    QUEUE_STATS = 81
    WORKER_PING = 90
    WORKER_INIT_ROLE = 91
    CC_REGISTER_WORKER = 95
    CC_GET_DBINFO = 96
    CC_GET_STATUS = 99
    # Per-role counter snapshots for status aggregation (Status.actor.cpp's
    # workerEventsFetcher analogue): reply is a plain dict of counter
    # values. Each lives in its role's decade block, skipping burned ints.
    MASTER_METRICS = 5
    PROXY_METRICS = 16
    RESOLVER_METRICS = 21
    TLOG_METRICS = 34
    STORAGE_METRICS = 49
    RK_METRICS = 82


_TOKEN_NAMES_CACHE: dict[int, str] | None = None


def token_name(value: int) -> str:
    """Reverse lookup for diagnostics: 30 -> "TLOG_COMMIT". Covers
    CoordToken too; unknown values format as "token:<n>" so log lines stay
    greppable either way."""
    global _TOKEN_NAMES_CACHE
    names = _TOKEN_NAMES_CACHE
    if names is None:
        names = {v: k for k, v in vars(Token).items()
                 if not k.startswith("_") and isinstance(v, int)}
        from foundationdb_tpu.server.coordination import CoordToken
        for k, v in vars(CoordToken).items():
            if not k.startswith("_") and isinstance(v, int):
                names.setdefault(v, k)
        _TOKEN_NAMES_CACHE = names
    return names.get(value, f"token:{value}")


# --- master ---

@dataclass
class GetCommitVersionRequest:
    """masterserver.actor.cpp:822 getVersion. requestNum dedupes retransmits.

    epoch fences deposed generations: well-known tokens are re-registered at
    the same address by each recruitment, so without the fence a zombie
    proxy could consume versions from the NEW master's chain and push them
    only to its own LOCKED TLogs — a permanent gap that wedges every
    later batch of the new generation (the reference avoids this with
    per-recruitment interface UIDs)."""

    proxy_id: int
    request_num: int
    epoch: int = 0


@dataclass
class GetCommitVersionReply:
    version: int
    prev_version: int


# --- proxy ---

@dataclass
class CommitTransactionRequest:
    """CommitTransaction.h:89-121 CommitTransactionRef + request wrapper."""

    read_snapshot: int
    read_conflict_ranges: list[tuple[bytes, bytes]] = field(default_factory=list)
    write_conflict_ranges: list[tuple[bytes, bytes]] = field(default_factory=list)
    mutations: list[Mutation] = field(default_factory=list)
    # Client-side span id for TraceBatch stitching (NativeAPI's
    # debugTransaction). Trailing + defaulted: wire-compatible with older
    # peers (utils/wire.py fills missing trailing fields from defaults).
    debug_id: str | None = None


@dataclass
class CommitReply:
    """CommitID on success; errors travel as FDBError through the reply."""

    version: int


@dataclass
class GetReadVersionRequest:
    """MasterProxyInterface.h GetReadVersionRequest (flags/priority subset)."""

    priority: int = 0
    debug_id: str | None = None  # client span id (trailing: wire-compatible)
    # how many client transactions this (batched) request stands for: the
    # client's GRV batcher coalesces N concurrent waiters into ONE wire
    # request, and the proxy both spends N ratekeeper tokens and counts N
    # GRVs served — the reference's transactionCount on
    # GetReadVersionRequest. Trailing-defaulted: wire-compatible with
    # older encoders (decoders fill 1).
    count: int = 1


@dataclass
class GetReadVersionReply:
    version: int


# --- resolver ---

@dataclass
class ResolveTransactionBatchRequest:
    """ResolverInterface.h:83-91. (prev_version -> version) chains batches
    into a total order per resolver across all proxies.

    State (metadata) transactions — those with mutations on the \\xff
    system keyspace — are registered with EVERY resolver via
    `state_txn_indices` (indices into `transactions`); their mutations ride
    only in resolver 0's request (`state_txn_mutations`, parallel to the
    indices; empty lists elsewhere), mirroring
    MasterProxyServer.actor.cpp:307-311 / ResolutionRequestBuilder."""

    prev_version: int
    version: int
    last_receive_version: int  # this proxy's own previous batch version
    transactions: list  # list[TxnConflictInfo]
    proxy_id: int = 0
    state_txn_indices: list = None  # list[int] | None
    state_txn_mutations: list = None  # list[list[Mutation]] | None


@dataclass
class ResolveTransactionBatchReply:
    committed: list[int]  # per-txn {CONFLICT, TOO_OLD, COMMITTED}
    # state txns from versions in (last_receive_version, version) — other
    # proxies' batches this proxy hasn't seen (Resolver.actor.cpp:170-190):
    # [(version, [(locally_committed, mutations), ...]), ...] version-sorted.
    # A proxy ANDs `locally_committed` across ALL resolvers' replies for the
    # global verdict (MasterProxyServer.actor.cpp:452-489).
    state_mutations: list = None


# --- tlog ---

@dataclass
class TLogCommitRequest:
    """TLogInterface.h TLogCommitRequest: version-ordered mutation push.
    `epoch` routes to the right generation on a shared TLog host."""

    prev_version: int
    version: int
    messages: dict[int, list[Mutation]]  # tag -> mutations for that tag
    known_committed_version: int = 0
    uid: str = ""


@dataclass
class TLogCommitReply:
    version: int


@dataclass
class TLogPeekRequest:
    """Pull messages for `tag` with version >= begin (ILogSystem::peek)."""

    tag: int
    begin: int
    uid: str = ""  # generation to peek on a shared TLog host


@dataclass
class TLogPeekReply:
    messages: list[tuple[int, list[Mutation]]]  # [(version, mutations)]
    end: int  # exclusive: peeker has everything < end for this tag
    popped: int
    # highest fully-acknowledged commit the pushers reported; storage caps
    # engine durability here so an unacked mutation can never outlive a
    # recovery rollback (storageserver updateStorage / kcv semantics)
    known_committed_version: int = 0


@dataclass
class TLogPopRequest:
    """Advance the durable point: messages for tag below `version` may go."""

    tag: int
    version: int
    uid: str = ""  # generation to pop on a shared TLog host


# --- storage ---

@dataclass
class GetValueRequest:
    key: bytes
    version: int


@dataclass
class GetValueReply:
    value: bytes | None
    version: int


@dataclass
class GetValuesRequest:
    """Batched point reads: the client-side read batcher coalesces every
    concurrent `get` bound for one storage team into a single RPC (the
    readVersionBatcher pattern of NativeAPI.actor.cpp:2709 applied to the
    data path — amortizing per-message cost is what lets a Python host
    approach the reference's per-core read rates)."""

    reads: list  # [(key, version), ...]


@dataclass
class GetValuesReply:
    """Parallel to request.reads: (0, value-or-None) | (1, error name).
    Per-key errors (wrong_shard_server on a moved key, transaction_too_old)
    must not fail the whole batch."""

    results: list


@dataclass
class KeySelector:
    """FDBTypes.h KeySelectorRef: resolves to a key by (base, or_equal, offset).

    first_greater_or_equal(k)  = (k, False, 1)
    first_greater_than(k)      = (k, True, 1)
    last_less_or_equal(k)      = (k, True, 0)
    last_less_than(k)          = (k, False, 0)
    """

    key: bytes
    or_equal: bool
    offset: int

    @staticmethod
    def first_greater_or_equal(key: bytes) -> "KeySelector":
        return KeySelector(key, False, 1)

    @staticmethod
    def first_greater_than(key: bytes) -> "KeySelector":
        return KeySelector(key, True, 1)

    @staticmethod
    def last_less_or_equal(key: bytes) -> "KeySelector":
        return KeySelector(key, True, 0)

    @staticmethod
    def last_less_than(key: bytes) -> "KeySelector":
        return KeySelector(key, False, 0)


@dataclass
class GetKeyValuesRequest:
    """storageserver.actor.cpp:1210 getKeyValues (selectors resolved server-side)."""

    begin: KeySelector
    end: KeySelector
    version: int
    limit: int = 0  # 0 = unlimited (subject to byte limit)
    limit_bytes: int = 0  # 0 = knob default
    reverse: bool = False


@dataclass
class GetKeyValuesReply:
    data: list[tuple[bytes, bytes]]
    more: bool
    version: int


@dataclass
class WatchValueRequest:
    """storageserver.actor.cpp:842 watchValueQ: resolve when value != expected."""

    key: bytes
    value: bytes | None  # value the client last saw
    version: int


# --- recovery / recruitment (WorkerInterface.h Initialize*Request family) ---

@dataclass
class TLogLockRequest:
    """Epoch end (ILogSystem::epochEnd): stop accepting commits; report how
    far this log got. masterserver recoverFrom locks the old generation."""

    epoch: int  # the NEW generation doing the locking (fence marker)
    uid: str = ""  # generation being locked (routing on a shared host)


@dataclass
class TLogLockReply:
    known_committed_version: int
    durable_version: int


@dataclass
class LogEpoch:
    """One generation of the log system (LogSystemConfig.h oldTLogs entry):
    versions in (begin, end] are served by these TLogs (end None = current).
    `uids` (parallel to addrs) are the per-instance generation ids that route
    requests on shared TLog hosts — UNIQUE per recovery attempt, so racing
    recoveries can never collide on a host (the reference's TLog UIDs in
    LogSystemConfig). `epoch` is the generation number."""

    begin: int
    end: int | None
    addrs: list[str]
    epoch: int = 0
    uids: list[str] | None = None  # None -> [""] per addr (direct clusters)
    # two-region: the first n_primary addrs are the primary-region TLogs,
    # the rest are SATELLITE TLogs (synchronously quorumed outside the
    # primary DC, TagPartitionedLogSystem's satellite log set). Peeks, pops
    # and locks treat them uniformly — every member holds every tag — but
    # the proxy's push quorum is per set, rebuilt from this split. None =
    # single-region epoch (all addrs primary).
    n_primary: int | None = None

    def uid_of(self, i: int) -> str:
        return self.uids[i] if self.uids else ""


@dataclass
class SetLogSystemRequest:
    """Master -> storage after recovery: new epoch list + rollback point
    (storageserver rollback :2211 discards versions the new log system does
    not know)."""

    epochs: list  # list[LogEpoch]
    rollback_to: int
    recovery_count: int


@dataclass
class GetStorageMetricsRequest:
    """StorageMetrics sampling (fdbserver/StorageMetrics.actor.h): byte
    counts + a split-point candidate per queried range, for the data
    distributor's shard tracker."""

    ranges: list  # list[(begin, end|None)]


@dataclass
class ShardMetrics:
    bytes: int
    split_key: bytes | None  # median key, None if too few rows


@dataclass
class AddShardRequest:
    """MoveKeys destination half (fetchKeys, storageserver.actor.cpp:1775):
    pause ingestion, snapshot [begin, end) from `source` at the current
    applied version, splice it in, extend the served ranges, resume. The
    fence version proves every mutation after it is dual-routed to this
    server's tag."""

    begin: bytes
    end: bytes | None
    source: str  # storage address to fetch the snapshot from
    fence_version: int


@dataclass
class SetShardsRequest:
    """Replace the served ranges (MoveKeys source side after the handoff).

    layout_version orders pushes: SET_SHARDS travels one_way, and a clogged
    link delays (and can reorder) packets — a stale assignment arriving after
    a newer one must not resurrect ranges the server no longer receives
    mutations for. None (direct tests) always applies."""

    shard_ranges: list  # list[(begin, end|None)]
    layout_version: tuple | None = None  # (epoch, DBInfo.version) at push
    # commit version of the metadata txn this layout reflects: the server
    # drops shard revocations fenced at/below it (the layout accounts for
    # those moves), while a delayed stale push — carrying an older version —
    # can never lift a newer fence. None (legacy/tests) lifts nothing.
    as_of_version: int | None = None


@dataclass
class UpdateShardsRequest:
    """RETIRED: shard-map changes now flow as \\xff/keyServers metadata
    transactions through the commit pipeline (systemdata.py). Kept only to
    pin wire id 32 (the registry is append-only)."""

    boundaries: list
    tags: list  # list[list[int]]


@dataclass
class InitRoleRequest:
    """worker.actor.cpp:694-794 InitializeTLog/Storage/Proxy/ResolverRequest,
    collapsed into one parameterized request."""

    role: str  # "tlog" | "storage" | "proxy" | "resolver" | "master"
    args: dict


@dataclass
class InitRoleReply:
    address: str
    incarnation: int = 0  # worker reboot count at recruit time


@dataclass
class RegisterWorkerRequest:
    address: str
    roles: list[str]
    # ProcessClass (fdbrpc/Locality.h): ranks this worker's fitness for each
    # role during recruitment ("stateless" | "transaction" | "storage" |
    # "unset")
    process_class: str = "unset"
    # LocalityData attributes (zone/machine default to the process itself)
    zone_id: str = ""
    machine_id: str = ""
    dc_id: str = ""


@dataclass
class DBInfo:
    """ServerDBInfo: everything a worker/client needs to find the cluster.
    Broadcast by the CC (ClusterController.actor.cpp ServerDBInfo)."""

    version: int
    epoch: int
    master: str | None
    proxies: list[str]
    resolvers: list[str]
    log_epochs: list  # list[LogEpoch]
    storages: list[tuple[str, int]]  # (address, tag)
    shard_boundaries: list[bytes]
    recovery_state: str = "unrecovered"
    ratekeeper: str | None = None
    # team per shard: the tags of the replicas serving shard i
    # (DDTeamCollection's server teams, DataDistribution.actor.cpp:515)
    shard_tags: list[list[int]] | None = None
    # dedicated GRV proxies (the grv_proxy/commit_proxy role split): clients
    # route read-version requests here when non-empty, commits to `proxies`.
    # Trailing-defaulted for wire compatibility with older encoders.
    grv_proxies: list[str] = field(default_factory=list)

    def teams(self) -> list[list[int]]:
        """shard -> replica tags, defaulting to the single-replica identity
        layout — THE source of truth for every consumer (client location
        cache, worker storage restore, consistency checker)."""
        return self.shard_tags or [[i] for i in
                                   range(len(self.shard_boundaries))]
