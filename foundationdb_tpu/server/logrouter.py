"""LogRouter role: the remote region's window into the primary log system.

Reference: fdbserver/LogRouter.actor.cpp — a log router pulls its tags from
the primary region's log system ONCE across the WAN (pullAsyncData) and
re-serves them to the remote region's storage servers through the ordinary
TLog peek/pop surface (logRouterPeekMessages :283, logRouterPop :372), so N
remote replicas cost one WAN stream per tag instead of N. Pops forward
upstream (:392) once the remote consumer has made the data durable, which is
what lets the primary TLogs (and satellites) truncate for remote tags.

Here the router is an entry in the worker's TLogHost (uid-routed, exactly
like a TLog generation): remote storage servers are recruited with
log_epochs whose last entry points at router addresses, and the rest of the
storage/cursor machinery works unchanged — the IPeekCursor seam's promise
that a log router is "just another peek source".
"""

from __future__ import annotations

from collections import deque

from foundationdb_tpu.core.notified import NotifiedVersion
from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.server.interfaces import (
    LogEpoch, TLogPeekReply, TLogPopRequest, Token)
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS


class LogRouter:
    """Pulls `tags` from the primary log system epochs and re-serves them.

    Buffering is bounded by consumption: pulling pauses once the un-popped
    buffer for a tag exceeds LOG_ROUTER_BUFFER_VERSIONS of versions ahead of
    its pop floor (the reference bounds by bytes with
    LOG_ROUTER_MAX_SEARCH_MEMORY; versions are the sim's natural unit).
    """

    def __init__(self, process: SimProcess, uid: str, tags: list[int],
                 epochs: list[LogEpoch], begin: int = 0):
        self.process = process
        self.uid = uid
        self.tags = list(tags)
        self.epochs = list(epochs)
        # per-tag: buffered pages, covered-through watermark, pop floor
        self.buffers: dict[int, deque] = {t: deque() for t in self.tags}
        self.covered: dict[int, NotifiedVersion] = {
            t: NotifiedVersion(begin) for t in self.tags}
        self.popped: dict[int, int] = {t: begin for t in self.tags}
        self.known_committed = begin
        self._begin = {t: begin for t in self.tags}
        self._tasks = [process.spawn(self._pull(t), f"lrPull{t}")
                       for t in self.tags]

    def shutdown(self):
        for t in self._tasks:
            t.cancel()

    async def _pull(self, tag: int):
        from foundationdb_tpu.server.logsystem import PeekCursor
        loop = self.process.net.loop
        cursor = PeekCursor(self.process, self.epochs, tag, self._begin[tag],
                            refresh=lambda t=tag: (self.epochs,
                                                   self._begin[t]))
        while True:
            # flow control: don't run unboundedly ahead of the consumer
            while (self._begin[tag] - self.popped[tag]
                   > KNOBS.LOG_ROUTER_BUFFER_VERSIONS):
                await loop.delay(0.2)
            epoch, reply = await cursor.get_more()
            if reply is None:
                continue
            self.known_committed = max(self.known_committed,
                                       reply.known_committed_version)
            buf = self.buffers[tag]
            for version, muts in reply.messages:
                if version <= self._begin[tag]:
                    continue
                if epoch.end is not None and version > epoch.end:
                    break
                buf.append((version, muts))
                self._begin[tag] = version
            end_v = reply.end - 1
            if epoch.end is not None:
                end_v = min(end_v, epoch.end)
            if end_v > self._begin[tag]:
                self._begin[tag] = end_v
            if self._begin[tag] > self.covered[tag].get():
                self.covered[tag].set(self._begin[tag])

    # -- the TLog surface (TLogHost routes by uid) --

    def _on_peek(self, req, reply):
        self.process.spawn(self._peek(req, reply), "lrPeek")

    async def _peek(self, req, reply):
        if req.tag not in self.buffers:
            reply.send_error(FDBError("tlog_stopped",
                                      f"tag {req.tag} not routed here"))
            return
        # long-poll like the TLog: block until the router covers `begin`
        await self.covered[req.tag].when_at_least(req.begin)
        budget = KNOBS.TLOG_PEEK_REPLY_BYTES
        out: list[tuple[int, list]] = []
        last_v = req.begin - 1
        for v, muts in self.buffers[req.tag]:
            if v < req.begin:
                continue
            out.append((v, list(muts)))
            budget -= sum(m.weight() for m in muts)
            last_v = v
            if budget <= 0:
                break
        end = (last_v + 1) if budget <= 0 else self.covered[req.tag].get() + 1
        reply.send(TLogPeekReply(
            messages=out, end=end, popped=self.popped.get(req.tag, 0),
            known_committed_version=self.known_committed))

    def _on_pop(self, req: TLogPopRequest, reply):
        """Drop the local buffer and FORWARD the pop to the primary log
        system (LogRouter.actor.cpp:392): the remote consumer made the data
        durable, so every upstream holder of this tag may truncate."""
        if req.tag in self.popped:
            self.popped[req.tag] = max(self.popped[req.tag], req.version)
            buf = self.buffers[req.tag]
            while buf and buf[0][0] < req.version:
                buf.popleft()
            sent: set[tuple[str, str]] = set()
            for ep in self.epochs:
                for i, addr in enumerate(ep.addrs):
                    key = (addr, ep.uid_of(i))
                    if key in sent:
                        continue
                    sent.add(key)
                    self.process.net.one_way(
                        self.process, Endpoint(addr, Token.TLOG_POP),
                        TLogPopRequest(tag=req.tag, version=req.version,
                                       uid=ep.uid_of(i)))
        reply.send(None)

    def _on_commit(self, req, reply):
        reply.send_error(FDBError("tlog_stopped", "log router takes no commits"))

    def _on_lock(self, req, reply):
        reply.send_error(FDBError("tlog_stopped", "log router takes no locks"))
