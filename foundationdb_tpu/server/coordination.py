"""Coordination: replicated generation register, coordinated state, election.

Reference: fdbserver/Coordination.actor.cpp (localGenerationReg :125) — each
coordinator is a disk-backed single-key register versioned by generations;
fdbserver/CoordinatedState.actor.cpp layers a disk-paxos-flavored quorum
read/write over the registers; fdbserver/LeaderElection.actor.cpp
(tryBecomeLeaderInternal :78) elects the cluster controller by candidacy
polling against the same coordinators; clients find the leader through
fdbclient/MonitorLeader.actor.cpp.

Generations are (batch, sequence)-free here: a single int64 drawn uniquely by
each client attempt (ballot). Register semantics per coordinator:

  read(gen):  rgen = max(rgen, gen); return (value, vgen, rgen)
  write(value, gen): ok iff gen >= rgen and gen > vgen; then value/vgen := gen

A CoordinatedState client reads with a fresh ballot from a quorum (taking the
value with the highest vgen) and writes through a quorum; any interleaved
competing ballot forces a retry, which is exactly enough to serialize master
recoveries (the reference's usage: the cstate holds the log-system config).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Any

from foundationdb_tpu.core.sim import Endpoint, SimProcess
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils import wire


class CoordToken:
    GENERATION_READ = 60
    GENERATION_WRITE = 61
    CANDIDACY = 62
    GET_LEADER = 63
    GENERATION_PEEK = 64  # read-only: no rgen promotion, no ballot needed


@dataclass
class GenReadRequest:
    key: str
    gen: int


@dataclass
class GenReadReply:
    value: Any
    vgen: int
    rgen: int


@dataclass
class GenWriteRequest:
    key: str
    value: Any
    gen: int


@dataclass
class GenWriteReply:
    ok: bool
    rgen: int
    vgen: int


@dataclass
class CandidacyRequest:
    """LeaderElection: a candidate advertises itself; the coordinator nominates
    the best (highest priority, then lowest address) candidate with a fresh
    lease and replies with its current nominee."""

    address: str
    priority: int
    lease_seconds: float = 4.0


@dataclass
class LeaderReply:
    leader: str | None
    priority: int


def quorum_wait(futures, need: int, max_errors: int):
    """Future of the first `need` successful replies; errors beyond
    max_errors fail the whole quorum (the reference's quorum() actor)."""
    from foundationdb_tpu.core.future import Future

    out = Future()
    replies: list = []
    state = {"errors": 0}

    def on_done(f):
        if out.is_ready():
            return
        if f.is_error():
            state["errors"] += 1
            if state["errors"] > max_errors:
                out._set_error(FDBError("coordinators_changed",
                                        "quorum unreachable"))
        else:
            replies.append(f._result)
            if len(replies) >= need:
                out._set(list(replies))

    for f in futures:
        f.add_callback(on_done)
    return out


class Coordinator:
    """One coordinator process: generation registers + election arbiter.

    Registers persist to a kvstore file on the process, so a rebooted
    coordinator keeps its promises (OnDemandStore in the reference).
    """

    def __init__(self, process: SimProcess):
        from foundationdb_tpu.storage.kvstore import MemoryKeyValueStore

        self.process = process
        self.store = MemoryKeyValueStore(
            process.net.open_file(process, "coord.0"),
            process.net.open_file(process, "coord.1"))
        self.store.recover()
        self._regs: dict[str, tuple[Any, int, int]] = {}  # key -> (value, vgen, rgen)
        raw = self.store.get_metadata("regs")
        if raw:
            try:
                self._regs = wire.loads(raw)
            except wire.WireError as e:
                raise FDBError("file_corrupt", f"coordinator regs undecodable: {e}")
        self.nominee: str | None = None
        self.nominee_priority = -1
        self.nominee_expiry = 0.0
        process.register(CoordToken.GENERATION_READ, self._on_read)
        process.register(CoordToken.GENERATION_WRITE, self._on_write)
        process.register(CoordToken.CANDIDACY, self._on_candidacy)
        process.register(CoordToken.GET_LEADER, self._on_get_leader)
        process.register(CoordToken.GENERATION_PEEK, self._on_peek)

    def _persist(self):
        self.store.set_metadata("regs", wire.dumps(self._regs))
        self.store.commit()

    def _on_peek(self, req: GenReadRequest, reply):
        """Read-only register peek: observers (e.g. a master checking whether
        its generation is still current) must not promote rgen, or they would
        force live CoordinatedState writers into ballot retries."""
        value, vgen, rgen = self._regs.get(req.key, (None, 0, 0))
        reply.send(GenReadReply(value=value, vgen=vgen, rgen=rgen))

    def _on_read(self, req: GenReadRequest, reply):
        value, vgen, rgen = self._regs.get(req.key, (None, 0, 0))
        rgen = max(rgen, req.gen)
        self._regs[req.key] = (value, vgen, rgen)
        self._persist()
        reply.send(GenReadReply(value=value, vgen=vgen, rgen=rgen))

    def _on_write(self, req: GenWriteRequest, reply):
        value, vgen, rgen = self._regs.get(req.key, (None, 0, 0))
        if req.gen >= rgen and req.gen > vgen:
            self._regs[req.key] = (req.value, req.gen, max(rgen, req.gen))
            self._persist()
            reply.send(GenWriteReply(ok=True, rgen=max(rgen, req.gen), vgen=req.gen))
        else:
            reply.send(GenWriteReply(ok=False, rgen=rgen, vgen=vgen))

    # -- election --

    def _on_candidacy(self, req: CandidacyRequest, reply):
        now = self.process.net.loop.now()
        expired = now >= self.nominee_expiry
        better = (req.priority, req.address) > (self.nominee_priority, self.nominee or "")
        if self.nominee is None or expired or better or req.address == self.nominee:
            self.nominee = req.address
            self.nominee_priority = req.priority
            self.nominee_expiry = now + req.lease_seconds
        reply.send(LeaderReply(leader=self.nominee, priority=self.nominee_priority))

    def _on_get_leader(self, req, reply):
        now = self.process.net.loop.now()
        if self.nominee is not None and now < self.nominee_expiry:
            reply.send(LeaderReply(leader=self.nominee, priority=self.nominee_priority))
        else:
            reply.send(LeaderReply(leader=None, priority=-1))


class CoordinatedStateClient:
    """Quorum read/write over the coordinators' generation registers
    (CoordinatedState.actor.cpp semantics; serializes master recoveries)."""

    def __init__(self, process: SimProcess, coordinators: list[str],
                 key: str = "cstate"):
        self.process = process
        self.coordinators = coordinators
        self.key = key
        self._ballot = 0

    @property
    def quorum(self) -> int:
        return len(self.coordinators) // 2 + 1

    def _next_ballot(self, floor: int = 0) -> int:
        # unique per (process, attempt): high bits attempt counter, low bits
        # a stable per-process tag derived from the address hash
        self._ballot = max(self._ballot + 1, floor + 1)
        # stable across interpreters (str hash is PYTHONHASHSEED-salted, which
        # would break deterministic simulation) and well-spread over the tag
        # space to avoid ballot collisions between processes
        tag = zlib.crc32(self.process.address.encode()) % 1000
        return self._ballot * 1000 + tag

    async def _quorum_call(self, token: int, make_req) -> list:
        futures = [self.process.net.request(
            self.process, Endpoint(addr, token), make_req())
            for addr in self.coordinators]
        return await quorum_wait(futures, self.quorum,
                                 len(self.coordinators) - self.quorum)

    async def read(self) -> tuple[Any, int]:
        """Returns (value, write-generation). Retries ballots until clean."""
        for _ in range(20):
            gen = self._next_ballot()
            replies = await self._quorum_call(
                CoordToken.GENERATION_READ,
                lambda: GenReadRequest(key=self.key, gen=gen))
            best = max(replies, key=lambda r: r.vgen)
            max_rgen = max(r.rgen for r in replies)
            if max_rgen > gen:
                self._ballot = max(self._ballot, max_rgen // 1000)
                continue  # a competing ballot intervened; retry higher
            return best.value, best.vgen
        raise FDBError("coordinators_changed", "read ballot contention")

    async def write(self, value: Any) -> int:
        """Write value with a fresh ballot through a quorum; returns the
        generation. Raises if beaten by a competing recovery."""
        for _ in range(20):
            gen = self._next_ballot()
            replies = await self._quorum_call(
                CoordToken.GENERATION_WRITE,
                lambda: GenWriteRequest(key=self.key, value=value, gen=gen))
            if all(r.ok for r in replies):
                return gen
            self._ballot = max(self._ballot,
                               max(max(r.rgen, r.vgen) for r in replies) // 1000)
        raise FDBError("coordinators_changed", "write ballot contention")


async def elect_leader(process: SimProcess, coordinators: list[str],
                       priority: int, lease_seconds: float = 4.0,
                       poll_interval: float = 1.0):
    """Candidacy loop: returns when this process is nominated by a majority
    (tryBecomeLeaderInternal). Caller must keep calling maintain_leadership()
    (re-candidacy) to hold the lease."""
    net = process.net
    quorum = len(coordinators) // 2 + 1
    while True:
        votes = 0
        for addr in coordinators:
            try:
                r = await net.request(
                    process, Endpoint(addr, CoordToken.CANDIDACY),
                    CandidacyRequest(address=process.address, priority=priority,
                                     lease_seconds=lease_seconds))
                if r.leader == process.address:
                    votes += 1
            except FDBError:
                pass
        if votes >= quorum:
            return
        await net.loop.delay(poll_interval)


async def get_leader(process: SimProcess, coordinators: list[str]) -> str | None:
    """Client side (MonitorLeader): majority opinion on the current leader."""
    net = process.net
    counts: dict[str, int] = {}
    for addr in coordinators:
        try:
            r = await net.request(process, Endpoint(addr, CoordToken.GET_LEADER),
                                  None)
            if r.leader:
                counts[r.leader] = counts.get(r.leader, 0) + 1
        except FDBError:
            continue
    quorum = len(coordinators) // 2 + 1
    for leader, n in counts.items():
        if n >= quorum:
            return leader
    return None
