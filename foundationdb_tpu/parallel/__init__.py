"""Multi-device parallelism: mesh construction and the sharded conflict engine.

The reference scales conflict detection by partitioning the keyspace across
resolver processes (SURVEY.md §2.0; fdbserver/MasterProxyServer.actor.cpp:283-306
fan-out, masterserver.actor.cpp:955 resolutionBalancing). Here the same strategy
is a mesh axis: the conflict-set step function is sharded by key range over
devices, each device checks/merges only ranges clipped to its shard, and the
per-transaction verdicts combine with a min-collective — exactly the proxy's
"min over resolvers touched" rule (MasterProxyServer.actor.cpp:492-504).
"""

from foundationdb_tpu.parallel.sharded_conflict import (
    ShardedDeviceConflictSet, make_resolver_mesh, shard_cut_keys,
    sharded_conflict_step)

__all__ = [
    "ShardedDeviceConflictSet",
    "make_resolver_mesh",
    "shard_cut_keys",
    "sharded_conflict_step",
]
