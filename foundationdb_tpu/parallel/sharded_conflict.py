"""Key-partitioned conflict engine over a device mesh (SPMD via shard_map).

TPU-native analogue of the reference's multi-resolver scale-out (SURVEY.md
§2.0): the proxy splits every transaction's conflict ranges across resolvers
by a key-range map (MasterProxyServer.actor.cpp:283-306) and a transaction
commits only if every touched resolver said Committed — the proxy takes the
min over resolver verdicts (:492-504). Here each mesh device IS one resolver
shard:

- The versioned step-function state lives sharded along a `resolvers` mesh
  axis; shard d owns keys in [cut_d, cut_{d+1}) (static equal cuts of the
  uint32 first-limb space — the dynamic resolutionBalancing analogue rebalances
  cuts between epochs, not inside the jitted step).
- Each device clips the (replicated) batch's ranges to its shard. Clipping to
  an empty range makes the range inert in every phase of conflict_step
  (history check, intra-batch, merge all skip empty ranges), which reproduces
  "this resolver was not touched" without dynamic shapes.
- Per-txn statuses combine with lax.pmin over the axis: status numbering
  (Conflict=0 < TooOld=1 < Committed=2, ConflictSet.h:36-40) makes min exactly
  the proxy's combine rule.

Intra-batch semantics match the reference's per-resolver behavior: each
resolver applies "earlier transactions win" to the ranges it owns and merges
the writes of transactions *it* judged committed — a transaction aborted only
on another shard still leaves its writes in this shard's history. That can
only create false conflicts (safe), never false commits, and is identical to
the reference (Resolver.actor.cpp resolveBatch never learns other resolvers'
verdicts).

All collectives ride the mesh axis (ICI on a real slice); the host feeds one
replicated batch per step — no per-shard host round-trips.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

shard_map = jax.shard_map

from foundationdb_tpu.utils import keys as keylib
from foundationdb_tpu.ops.batch import TOO_OLD, TxnConflictInfo
from foundationdb_tpu.ops.conflict import (
    ConflictShapes, L, NEG, _REBASE_THRESHOLD, _key_lt, conflict_step,
    init_state, rebase_state)
from foundationdb_tpu.utils.knobs import KNOBS

RESOLVER_AXIS = "resolvers"


def make_resolver_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the resolver key-partition axis."""
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(np.asarray(devices), (RESOLVER_AXIS,))


def shard_cut_bytes(n_shards: int) -> list[bytes]:
    """Byte-space begin boundaries of the n equal key partitions
    (cuts[0] == b""); usable directly in host range maps."""
    return [b""] + [((d * (1 << 32)) // n_shards).to_bytes(4, "big")
                    for d in range(1, n_shards)]


def shard_cut_keys(n_shards: int) -> np.ndarray:
    """(n_shards+1, L) limb vectors: shard d owns [cuts[d], cuts[d+1]).

    Rows 0..n-1 are the exact encodings of shard_cut_bytes (so device-side
    limb comparisons agree with host byte-order comparisons for every key);
    the final sentinel is MAX (all-ones), after every real key.
    """
    from foundationdb_tpu.utils import keys as keylib

    cuts = np.zeros((n_shards + 1, L), dtype=np.uint32)
    for d, kb in enumerate(shard_cut_bytes(n_shards)):
        cuts[d] = keylib.encode_key(kb)
    cuts[n_shards, :] = 0xFFFFFFFF
    return cuts


def _clip_ranges(b, e, lo, hi):
    """Intersect half-open ranges [b, e) (L, N) with shard range [lo, hi) (L,).

    Empty results (b' >= e') are exactly the ranges this shard does not own;
    conflict_step ignores empty ranges in every phase.
    """
    lo_b = jnp.broadcast_to(lo[:, None], b.shape)
    hi_b = jnp.broadcast_to(hi[:, None], e.shape)
    b2 = jnp.where(_key_lt(b, lo[:, None])[None, :], lo_b, b)
    e2 = jnp.where(_key_lt(hi[:, None], e)[None, :], hi_b, e)
    return b2, e2


def sharded_conflict_step(mesh: Mesh, shapes: ConflictShapes,  # noqa: C901
                          max_write_life: int):
    """Build the jitted SPMD step: (stacked_state, batch) -> (state', statuses, info).

    stacked_state: state pytree with a leading n_shards axis, sharded over the
    mesh; batch: replicated (same encoding as conflict_step's batch).
    """
    if shapes.key_bytes != keylib.KEY_BYTES:
        raise ValueError(
            f"sharded engine only supports the default key width "
            f"({keylib.KEY_BYTES}B); got key_bytes={shapes.key_bytes}. "
            "Thread shapes.limbs through shard_cut_keys/_clip_ranges to "
            "narrow it.")
    n = mesh.devices.size
    cuts = jnp.asarray(shard_cut_keys(n))  # (n+1, L) — baked constant

    def local_step(state, batch):
        d = lax.axis_index(RESOLVER_AXIS)
        lo = cuts[d].astype(jnp.uint32)
        hi = cuts[d + 1].astype(jnp.uint32)
        state = jax.tree.map(lambda x: x[0], state)  # drop leading shard dim
        batch = dict(batch)
        batch["rb"], batch["re"] = _clip_ranges(batch["rb"], batch["re"], lo, hi)
        batch["wb"], batch["we"] = _clip_ranges(batch["wb"], batch["we"], lo, hi)
        new_state, statuses, info = conflict_step(
            state, batch, shapes=shapes, max_write_life=max_write_life)
        # proxy combine: min over shards (MasterProxyServer.actor.cpp:492-504)
        statuses = lax.pmin(statuses, RESOLVER_AXIS)
        info = {
            "overflow": lax.pmax(info["overflow"], RESOLVER_AXIS),
            "boundaries": lax.pmax(info["boundaries"], RESOLVER_AXIS),
            # mask padding slots (forced COMMITTED inside conflict_step)
            "committed": jnp.sum((statuses == 2) & batch["txn_valid"]),
        }
        return jax.tree.map(lambda x: x[None], new_state), statuses, info

    state_specs = {
        "bkeys": P(RESOLVER_AXIS), "bval": P(RESOLVER_AXIS),
        "nb": P(RESOLVER_AXIS), "oldest": P(RESOLVER_AXIS),
        "table": P(RESOLVER_AXIS), "poisoned": P(RESOLVER_AXIS),
    }
    batch_specs = {
        "rb": P(), "re": P(), "rtxn": P(), "wb": P(), "we": P(), "wtxn": P(),
        "snapshot": P(), "txn_valid": P(), "commit_version": P(),
        "advance_floor": P(),
    }
    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(state_specs, batch_specs),
        out_specs=(state_specs, P(), {"overflow": P(), "boundaries": P(),
                                      "committed": P()}),
        # conflict_step's fori_loop carries start from unvarying constants and
        # become shard-varying inside the loop; the static VMA check can't
        # type that, so it is disabled (collectives used are only pmin/pmax).
        check_vma=False,
    )
    return jax.jit(sharded)


def init_sharded_state(shapes: ConflictShapes, n_shards: int, oldest: int = 0):
    """Stacked per-shard initial states, leading axis = shard."""
    one = init_state(shapes, oldest=oldest)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n_shards,) + x.shape), one)


class ShardedDeviceConflictSet:
    """Multi-device ConflictSet: same host interface as DeviceConflictSet,
    state sharded by key range over a mesh (one logical resolver spanning
    devices — the reference's N-resolver topology collapsed into one SPMD
    program; Resolver.actor.cpp ordering/recovery semantics live in the host
    Resolver role unchanged).
    """

    def __init__(self, mesh: Mesh | None = None, capacity: int | None = None,
                 txns: int | None = None, reads_per_txn: int | None = None,
                 writes_per_txn: int | None = None, oldest_version: int = 0):
        from foundationdb_tpu.ops.conflict import BatchEncoder, _resolve_shapes

        self.mesh = mesh or make_resolver_mesh()
        self.n_shards = self.mesh.devices.size
        self.shapes = _resolve_shapes(capacity, txns, reads_per_txn, writes_per_txn)
        self.encoder = BatchEncoder(self.shapes, base_version=oldest_version)
        self.oldest_version = oldest_version
        self._state = init_sharded_state(self.shapes, self.n_shards, oldest=0)
        self._step = sharded_conflict_step(
            self.mesh, self.shapes, KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS)

    @property
    def base_version(self) -> int:
        return self.encoder.base_version

    def _maybe_rebase(self, commit_version: int):
        while commit_version - self.encoder.base_version > _REBASE_THRESHOLD:
            delta = min(commit_version - self.encoder.base_version - (1 << 24),
                        1 << 30)
            self._state = jax.vmap(lambda s: rebase_state(s, delta))(self._state)
            self.encoder.base_version += delta

    def detect(self, txns: list[TxnConflictInfo], commit_version: int) -> list[int]:
        return self.detect_async(txns, commit_version).result()

    def detect_async(self, txns: list[TxnConflictInfo], commit_version: int):
        from foundationdb_tpu.ops.conflict import detect_async_impl

        return detect_async_impl(self, txns, commit_version)

    def clear(self, oldest_version: int = 0):
        self.encoder.base_version = oldest_version
        self.oldest_version = oldest_version
        self._state = init_sharded_state(self.shapes, self.n_shards, oldest=0)
