"""Key-partitioned conflict engine over a device mesh (SPMD via shard_map).

TPU-native analogue of the reference's multi-resolver scale-out (SURVEY.md
§2.0): the proxy splits every transaction's conflict ranges across resolvers
by a key-range map (MasterProxyServer.actor.cpp:283-306) and a transaction
commits only if every touched resolver said Committed — the proxy takes the
min over resolver verdicts (:492-504). Here each mesh device IS one resolver
shard:

- The versioned step-function state lives sharded along a `resolvers` mesh
  axis; shard d owns keys in [cut_d, cut_{d+1}) (static equal cuts of the
  uint32 first-limb space — the dynamic resolutionBalancing analogue rebalances
  cuts between epochs, not inside the jitted step).
- Each device clips the (replicated) batch's ranges to its shard. Clipping to
  an empty range makes the range inert in every phase of conflict_step
  (history check, intra-batch, merge all skip empty ranges), which reproduces
  "this resolver was not touched" without dynamic shapes.
- Per-txn statuses combine with lax.pmin over the axis: status numbering
  (Conflict=0 < TooOld=1 < Committed=2, ConflictSet.h:36-40) makes min exactly
  the proxy's combine rule.

Intra-batch semantics match the reference's per-resolver behavior: each
resolver applies "earlier transactions win" to the ranges it owns and merges
the writes of transactions *it* judged committed — a transaction aborted only
on another shard still leaves its writes in this shard's history. That can
only create false conflicts (safe), never false commits, and is identical to
the reference (Resolver.actor.cpp resolveBatch never learns other resolvers'
verdicts).

All collectives ride the mesh axis (ICI on a real slice); the host feeds one
replicated batch per step — no per-shard host round-trips.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level with check_vma
    shard_map = jax.shard_map
    _SHARD_MAP_CHECK_KW = "check_vma"
except AttributeError:  # jax 0.4.x: experimental module, kwarg is check_rep
    from jax.experimental.shard_map import shard_map
    _SHARD_MAP_CHECK_KW = "check_rep"

from foundationdb_tpu.utils import keys as keylib
from foundationdb_tpu.ops.batch import TOO_OLD, TxnConflictInfo
from foundationdb_tpu.ops.conflict import (
    ConflictShapes, L, NEG, _REBASE_THRESHOLD, _key_lt, conflict_step,
    init_state, rebase_state)
from foundationdb_tpu.utils.knobs import KNOBS

RESOLVER_AXIS = "resolvers"


def make_resolver_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the resolver key-partition axis."""
    if devices is None:
        devices = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(np.asarray(devices), (RESOLVER_AXIS,))


def shard_cut_bytes(n_shards: int) -> list[bytes]:
    """Byte-space begin boundaries of the n equal key partitions
    (cuts[0] == b""); usable directly in host range maps."""
    return [b""] + [((d * (1 << 32)) // n_shards).to_bytes(4, "big")
                    for d in range(1, n_shards)]


def shard_cut_bytes_range(n_shards: int, begin: bytes = b"",
                          end: bytes | None = None) -> list[bytes]:
    """Equal cuts of the resolver's OWNED range [begin, end) — the inner
    mesh split under an outer ResolverMap partition. cuts[0] stays b"":
    shard 0 also absorbs the sub-`begin` space an outer-partitioned
    resolver is never offered, so clipping stays total without a per-range
    ownership check. `end=None` means "to the end of keyspace". Falls back
    to whole-space cuts when the range is too narrow to cut n ways at
    4-byte granularity (degenerate, but still correct: extra shards just
    sit idle on keyspace the resolver never sees)."""
    lo = int.from_bytes(begin[:4].ljust(4, b"\x00"), "big")
    hi = (1 << 32) if end is None else int.from_bytes(
        end[:4].ljust(4, b"\x00"), "big")
    if hi - lo < n_shards:
        return shard_cut_bytes(n_shards)
    return [b""] + [(lo + (d * (hi - lo)) // n_shards).to_bytes(4, "big")
                    for d in range(1, n_shards)]


def shard_cut_keys(n_shards: int) -> np.ndarray:
    """(n_shards+1, L) limb vectors: shard d owns [cuts[d], cuts[d+1]).

    Rows 0..n-1 are the exact encodings of shard_cut_bytes (so device-side
    limb comparisons agree with host byte-order comparisons for every key);
    the final sentinel is MAX (all-ones), after every real key.
    """
    from foundationdb_tpu.utils import keys as keylib

    cuts = np.zeros((n_shards + 1, L), dtype=np.uint32)
    for d, kb in enumerate(shard_cut_bytes(n_shards)):
        cuts[d] = keylib.encode_key(kb)
    cuts[n_shards, :] = 0xFFFFFFFF
    return cuts


def _clip_ranges(b, e, lo, hi):
    """Intersect half-open ranges [b, e) (L, N) with shard range [lo, hi) (L,).

    Empty results (b' >= e') are exactly the ranges this shard does not own;
    conflict_step ignores empty ranges in every phase.
    """
    lo_b = jnp.broadcast_to(lo[:, None], b.shape)
    hi_b = jnp.broadcast_to(hi[:, None], e.shape)
    b2 = jnp.where(_key_lt(b, lo[:, None])[None, :], lo_b, b)
    e2 = jnp.where(_key_lt(hi[:, None], e)[None, :], hi_b, e)
    return b2, e2


@functools.lru_cache(maxsize=1)
def _compiled_vmapped_rebase():
    """Per-shard rebase, compiled once per process with the stacked state
    donated (delta is a traced scalar). The previous inline
    `jax.vmap(...)(core)` built a fresh traced callable on every rebase —
    a full re-trace per call, on top of keeping the dead pre-rebase state
    alive (devlint DEV002/DEV006)."""
    from foundationdb_tpu.ops.conflict import _donate_state_argnums
    return jax.jit(jax.vmap(rebase_state, in_axes=(0, None)),
                   donate_argnums=_donate_state_argnums())


@functools.lru_cache(maxsize=1)
def _compiled_table_builder():
    """Vmapped _build_table, compiled once per process. rebalance_cuts
    previously did `jax.jit(jax.vmap(_build_table))(...)` inline — a
    re-trace AND re-compile on every partition move (devlint DEV002)."""
    from foundationdb_tpu.ops.conflict import _build_table
    return jax.jit(jax.vmap(_build_table))


_STEP_CACHE: dict = {}


def sharded_conflict_step(mesh: Mesh, shapes: ConflictShapes,  # noqa: C901
                          max_write_life: int, intra_mode: str = "scan",
                          intra_rounds: int = 0):
    key = (tuple(mesh.devices.flat), shapes, max_write_life, intra_mode,
           intra_rounds)
    cached = _STEP_CACHE.get(key)
    if cached is not None:
        return cached
    fn = _build_sharded_step(mesh, shapes, max_write_life, intra_mode,
                             intra_rounds)
    _STEP_CACHE[key] = fn
    return fn


def _build_sharded_step(mesh: Mesh, shapes: ConflictShapes,  # noqa: C901
                        max_write_life: int, intra_mode: str = "scan",
                        intra_rounds: int = 0):
    """Build the jitted SPMD step: (stacked_state, batch) -> (state', statuses, info).

    stacked_state: state pytree with a leading n_shards axis, sharded over the
    mesh; batch: replicated (same encoding as conflict_step's batch). The
    shard's owned key range [lo, hi) is PART OF THE STATE (not baked into the
    program), so resolutionBalancing can re-cut the partition between batches
    without recompiling.
    """
    if shapes.key_bytes != keylib.KEY_BYTES:
        raise ValueError(
            f"sharded engine only supports the default key width "
            f"({keylib.KEY_BYTES}B); got key_bytes={shapes.key_bytes}. "
            "Thread shapes.limbs through shard_cut_keys/_clip_ranges to "
            "narrow it.")

    def local_step(state, batch):
        state = jax.tree.map(lambda x: x[0], state)  # drop leading shard dim
        lo = state.pop("lo")
        hi = state.pop("hi")
        batch = dict(batch)
        batch["rb"], batch["re"] = _clip_ranges(batch["rb"], batch["re"], lo, hi)
        batch["wb"], batch["we"] = _clip_ranges(batch["wb"], batch["we"], lo, hi)
        new_state, statuses, info = conflict_step(
            state, batch, shapes=shapes, max_write_life=max_write_life,
            intra_mode=intra_mode, intra_rounds=intra_rounds)
        new_state["lo"] = lo
        new_state["hi"] = hi
        # proxy combine: min over shards (MasterProxyServer.actor.cpp:492-504)
        statuses = lax.pmin(statuses, RESOLVER_AXIS)
        info = {
            "overflow": lax.pmax(info["overflow"], RESOLVER_AXIS),
            "boundaries": lax.pmax(info["boundaries"], RESOLVER_AXIS),
            # mask padding slots (forced COMMITTED inside conflict_step)
            "committed": jnp.sum((statuses == 2) & batch["txn_valid"]),
            # the sharded engine always runs full sandwich rounds (see
            # ShardedDeviceConflictSet: the host fallback can't reproduce
            # per-shard intra semantics), so this stays True; combined
            # defensively anyway
            "converged": lax.pmin(
                info["converged"].astype(jnp.int32), RESOLVER_AXIS) > 0,
            # eligible on every shard — only consulted by the (never-taken)
            # fallback path
            "eligible": lax.pmin(
                info["eligible"].astype(jnp.int32), RESOLVER_AXIS) > 0,
        }
        return jax.tree.map(lambda x: x[None], new_state), statuses, info

    state_specs = {
        "bkeys": P(RESOLVER_AXIS), "bval": P(RESOLVER_AXIS),
        "nb": P(RESOLVER_AXIS), "oldest": P(RESOLVER_AXIS),
        "table": P(RESOLVER_AXIS), "poisoned": P(RESOLVER_AXIS),
        "lo": P(RESOLVER_AXIS), "hi": P(RESOLVER_AXIS),
    }
    batch_specs = {
        "rb": P(), "re": P(), "rtxn": P(), "wb": P(), "we": P(), "wtxn": P(),
        "snapshot": P(), "txn_valid": P(), "commit_version": P(),
        "advance_floor": P(),
    }
    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(state_specs, batch_specs),
        out_specs=(state_specs, P(), {"overflow": P(), "boundaries": P(),
                                      "committed": P(), "converged": P(),
                                      "eligible": P()}),
        # conflict_step's bounded-scan carries start from unvarying constants
        # and become shard-varying inside the loop; the static replication /
        # VMA check can't type that, so it is disabled (collectives are only
        # pmin/pmax).
        **{_SHARD_MAP_CHECK_KW: False},
    )
    from foundationdb_tpu.ops.conflict import _donate_state_argnums
    return jax.jit(sharded, donate_argnums=_donate_state_argnums())


def init_sharded_state(shapes: ConflictShapes, n_shards: int, oldest: int = 0,
                       cut_bytes: list[bytes] | None = None,
                       mesh: Mesh | None = None):
    """Stacked per-shard initial states, leading axis = shard. Each shard
    carries its owned range [lo, hi) as state (dynamic cuts).

    Pass `mesh` to place the state with the step's sharding up front:
    default-placed leaves make jit specialize the first step call on the
    unsharded layout and RE-specialize on its own mesh-sharded output — a
    second full XLA compile that would otherwise land on the first SERVED
    batch (warmup only pays for one)."""
    one = init_state(shapes, oldest=oldest)
    st = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n_shards,) + x.shape), one)
    cuts = np.zeros((n_shards + 1, L), dtype=np.uint32)
    for d, kb in enumerate(cut_bytes or shard_cut_bytes(n_shards)):
        cuts[d] = keylib.encode_key(kb)
    cuts[n_shards, :] = 0xFFFFFFFF
    st["lo"] = jnp.asarray(cuts[:n_shards])
    st["hi"] = jnp.asarray(cuts[1:])
    if mesh is not None:
        from jax.sharding import NamedSharding

        from foundationdb_tpu.utils import jaxenv
        st = jaxenv.device_put(st, NamedSharding(mesh, P(RESOLVER_AXIS)))
    return st


class ShardedDeviceConflictSet:
    """Multi-device ConflictSet: same host interface as DeviceConflictSet,
    state sharded by key range over a mesh (one logical resolver spanning
    devices — the reference's N-resolver topology collapsed into one SPMD
    program; Resolver.actor.cpp ordering/recovery semantics live in the host
    Resolver role unchanged).
    """

    def __init__(self, mesh: Mesh | None = None, capacity: int | None = None,
                 txns: int | None = None, reads_per_txn: int | None = None,
                 writes_per_txn: int | None = None, oldest_version: int = 0,
                 cut_bytes: list[bytes] | None = None):
        from foundationdb_tpu.ops.conflict import BatchEncoder, _resolve_shapes
        from foundationdb_tpu.utils.jaxenv import ensure_platform_honored
        ensure_platform_honored()
        self.mesh = mesh or make_resolver_mesh()
        self.n_shards = self.mesh.devices.size
        self.shapes = _resolve_shapes(capacity, txns, reads_per_txn, writes_per_txn)
        self.encoder = BatchEncoder(self.shapes, base_version=oldest_version)
        self.oldest_version = oldest_version
        self.cut_bytes = list(cut_bytes or shard_cut_bytes(self.n_shards))
        assert self.cut_bytes[0] == b"" and len(self.cut_bytes) == self.n_shards
        self._state = init_sharded_state(self.shapes, self.n_shards, oldest=0,
                                         cut_bytes=self.cut_bytes,
                                         mesh=self.mesh)
        # full sandwich rounds (T//2+1): the host-exact fallback resolves
        # intra conflicts with SINGLE-resolver semantics, which per-shard
        # "earlier txns win" + pmin does not reduce to, so the sharded
        # engine must always converge on device. The early-out cond makes
        # the unused rounds ~free once the bounds pinch.
        intra_rounds = (self.shapes.txns // 2 + 1
                        if str(KNOBS.CONFLICT_INTRA_MODE) == "scan" else 0)
        self._step = sharded_conflict_step(
            self.mesh, self.shapes, KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS,
            str(KNOBS.CONFLICT_INTRA_MODE), intra_rounds)
        # resolutionBalancing inputs (masterserver.actor.cpp:955-1012 via
        # Resolver iops sampling :146-151): per-shard range counts + a
        # bounded reservoir of range-begin prefixes
        self._load_counts = np.zeros(self.n_shards, dtype=np.int64)
        self._samples: list[int] = []  # first-4-byte ints of range begins
        self._batches_since_check = 0
        # cuts scheduled by rebalance_from_conflicts, applied by the next
        # detect_async (the dispatch thread owns all state restructures)
        self._pending_cuts: list[bytes] | None = None
        self._sample_rng = np.random.RandomState(0)
        self.rebalances = 0

    @property
    def base_version(self) -> int:
        return self.encoder.base_version

    def _maybe_rebase(self, commit_version: int):
        while commit_version - self.encoder.base_version > _REBASE_THRESHOLD:
            delta = min(commit_version - self.encoder.base_version - (1 << 24),
                        1 << 30)
            lo, hi = self._state["lo"], self._state["hi"]
            core = {k: v for k, v in self._state.items()
                    if k not in ("lo", "hi")}
            core = _compiled_vmapped_rebase()(core, np.int32(delta))
            core["lo"], core["hi"] = lo, hi
            self._state = core
            self.encoder.base_version += delta

    def plan_chunk(self, nr: int, nw: int):
        """Mesh program is fixed (sharding specs bake the shapes): no
        bucketed padding here, unlike the single-device engine."""
        return self.shapes, self._step

    def warmup(self):
        self.detect([], self.encoder.base_version + 1)

    def detect(self, txns: list[TxnConflictInfo], commit_version: int) -> list[int]:
        return self.detect_async(txns, commit_version).result()

    def detect_async(self, txns: list[TxnConflictInfo], commit_version: int):
        from foundationdb_tpu.ops.conflict import detect_async_impl

        if self._pending_cuts is not None:
            cuts, self._pending_cuts = self._pending_cuts, None
            if cuts != self.cut_bytes:
                self.rebalance_cuts(cuts, commit_version)
        self._record_load(txns)
        self._batches_since_check += 1
        if self._batches_since_check >= KNOBS.RESOLUTION_BALANCE_CHECK_BATCHES:
            self._batches_since_check = 0
            self.maybe_rebalance(commit_version)
        return detect_async_impl(self, txns, commit_version)

    def clear(self, oldest_version: int = 0):
        self.encoder.base_version = oldest_version
        self.oldest_version = oldest_version
        self._state = init_sharded_state(self.shapes, self.n_shards, oldest=0,
                                         cut_bytes=self.cut_bytes,
                                         mesh=self.mesh)
        # stale load/samples must not drive a rebalance of the fresh state
        self._load_counts[:] = 0
        self._samples.clear()
        self._batches_since_check = 0
        self._pending_cuts = None

    # -- resolutionBalancing --

    def _record_load(self, txns):
        """One vectorized pass per batch (this rides the resolver hot path:
        per-range Python would cost as much as the device step itself)."""
        begins = [b for t in txns for b, _e in t.read_ranges]
        wbegins = [b for t in txns for b, _e in t.write_ranges]
        if not begins and not wbegins:
            return
        prefixes = np.array(
            [int.from_bytes(b[:4].ljust(4, b"\x00"), "big")
             for b in begins + wbegins], dtype=np.uint64)
        cut_pref = np.array(
            [int.from_bytes(cb[:4].ljust(4, b"\x00"), "big")
             for cb in self.cut_bytes], dtype=np.uint64)
        shard_idx = np.searchsorted(cut_pref, prefixes, side="right") - 1
        np.add.at(self._load_counts, shard_idx, 1)
        wpref = prefixes[len(begins):]
        cap = 8192
        room = cap - len(self._samples)
        if room > 0:
            self._samples.extend(wpref[:room].tolist())
            wpref = wpref[room:]
        if len(wpref):
            js = self._sample_rng.randint(0, cap, size=len(wpref))
            for j, v in zip(js.tolist(), wpref.tolist()):
                self._samples[j] = v

    def maybe_rebalance(self, at_version: int) -> bool:
        """Re-cut the key partition when per-shard load skews (the between-
        batches analogue of masterserver resolutionBalancing: sampled load ->
        new cuts -> state restructure). Returns True if a rebalance ran."""
        total = int(self._load_counts.sum())
        if (total < KNOBS.RESOLUTION_BALANCE_MIN_SAMPLES
                or len(self._samples) < self.n_shards * 4):
            return False
        mean = total / self.n_shards
        if self._load_counts.max() <= KNOBS.RESOLUTION_BALANCE_SKEW * mean:
            return False
        qs = np.quantile(np.asarray(self._samples, dtype=np.float64),
                         [d / self.n_shards for d in range(1, self.n_shards)])
        new_cuts = [b""]
        for q in qs:
            cb = int(min(max(q, 0), (1 << 32) - 1)).to_bytes(4, "big")
            if cb <= new_cuts[-1]:
                return False  # degenerate sample (mass on one prefix): keep cuts
            new_cuts.append(cb)
        self.rebalance_cuts(new_cuts, at_version)
        return True

    def rebalance_from_conflicts(self, ranges) -> bool:
        """Conflict-mass-driven recut, the cross-epoch resolutionBalancing
        analogue: `ranges` is [(begin, end, rate)] from the resolver role's
        HotRangeSketch — per-range exponentially-decayed CONFLICT mass.
        Where maybe_rebalance recuts on raw read/write traffic, this path
        recuts on where aborts actually land, so a conflict-hot shard sheds
        keyspace even when range counts look balanced.

        Pure host numpy: it only PLANS and schedules the cuts (safe to call
        from the resolver's event loop — no device sync, devlint DEV001);
        detect_async applies the restructure at the next batch boundary on
        the dispatch path, so cuts never move under an in-flight batch.
        Same safety story as the load path: rebalance_cuts's conservative
        fill can only create false conflicts. Returns True iff a recut was
        scheduled."""
        if not ranges:
            return False
        prefs = np.array(
            [int.from_bytes(b[:4].ljust(4, b"\x00"), "big")
             for b, _e, _r in ranges], dtype=np.float64)
        mass = np.array([r for _b, _e, r in ranges], dtype=np.float64)
        total = float(mass.sum())
        if total <= 0.0:
            return False
        cut_pref = np.array(
            [int.from_bytes(cb[:4].ljust(4, b"\x00"), "big")
             for cb in self.cut_bytes], dtype=np.float64)
        shard_idx = np.searchsorted(cut_pref, prefs, side="right") - 1
        per_shard = np.zeros(self.n_shards, dtype=np.float64)
        np.add.at(per_shard, shard_idx, mass)
        skew = KNOBS.RESOLUTION_BALANCE_SKEW * (total / self.n_shards)
        if per_shard.max() <= skew:
            return False
        # weighted-quantile cuts: sort hot ranges by key prefix, cut where
        # cumulative conflict mass crosses each d/n target
        order = np.argsort(prefs, kind="stable")
        cum = np.cumsum(mass[order])
        targets = [total * d / self.n_shards
                   for d in range(1, self.n_shards)]
        idxs = np.searchsorted(cum, targets, side="left")
        sorted_prefs = prefs[order]
        new_cuts = [b""]
        for i in idxs:
            i = min(int(i), len(order) - 1)
            cb = int(sorted_prefs[i]).to_bytes(4, "big")
            while cb <= new_cuts[-1]:
                # target landed on/behind the previous cut (mass front-
                # loaded on few ranges): advance to the next distinct hot
                # prefix so a dominant range still gets isolated. Running
                # out means the mass sits on ONE prefix — a DD shard-split
                # problem, not a resolver cut problem; keep the cuts.
                i += 1
                if i >= len(order):
                    return False
                cb = int(sorted_prefs[i]).to_bytes(4, "big")
            new_cuts.append(cb)
        if new_cuts == self.cut_bytes:
            return False
        self._pending_cuts = new_cuts
        return True

    def rebalance_cuts(self, new_cut_bytes: list[bytes], at_version: int):
        """Move the partition to `new_cut_bytes`. Conflict state is SOFT
        (clearConflictSet semantics, SkipList.cpp:957): a shard's newly
        acquired subranges are filled at `at_version` — conservative-only
        (stale reads there conflict; never a false commit) — while retained
        subranges keep exact history. No cross-shard state movement, no
        recompilation (cuts are state, not program constants)."""
        from jax.sharding import NamedSharding

        from foundationdb_tpu.utils import jaxenv

        assert len(new_cut_bytes) == self.n_shards and new_cut_bytes[0] == b""
        K = self.shapes.capacity
        st = jaxenv.device_get(self._state)
        vfill = np.int32(self.encoder._clamp_off(at_version))

        cuts = np.zeros((self.n_shards + 1, L), dtype=np.uint32)
        for d, kb in enumerate(new_cut_bytes):
            cuts[d] = keylib.encode_key(kb)
        cuts[self.n_shards, :] = 0xFFFFFFFF

        old_lo, old_hi = st["lo"], st["hi"]  # (n, L)
        nb = st["nb"]
        new_bkeys = np.full_like(st["bkeys"], 0xFFFFFFFF)
        new_bval = np.full_like(st["bval"], int(NEG))
        new_nb = np.zeros_like(nb)

        def np_lt1(a, b):  # lexicographic a < b over (L,) uint32
            for i in range(L):
                if a[i] != b[i]:
                    return a[i] < b[i]
            return False

        def np_cmp_vec(keys, q):  # (L, N) keys vs (L,) q -> (lt, eq) masks
            lt = np.zeros(keys.shape[1], bool)
            eq = np.ones(keys.shape[1], bool)
            for i in range(L):
                lt |= eq & (keys[i] < q[i])
                eq &= keys[i] == q[i]
            return lt, eq

        for d in range(self.n_shards):
            lo, hi = cuts[d], cuts[d + 1]
            a = old_lo[d] if np_lt1(lo, old_lo[d]) else lo  # retained begin
            b = old_hi[d] if np_lt1(hi, old_hi[d]) else hi  # retained end
            keys_d = st["bkeys"][d]  # (L, K)
            vals_d = st["bval"][d]
            live = np.arange(K) < int(nb[d])
            out_k: list[np.ndarray] = []  # (L, ni) pieces
            out_v: list[np.ndarray] = []
            if np_lt1(a, b):  # retained interval non-empty
                if np_lt1(lo, a):  # acquired prefix [lo, a)
                    out_k.append(lo[:, None])
                    out_v.append(np.asarray([vfill], np.int32))
                # value in effect at `a` = last live boundary <= a
                lt_a, eq_a = np_cmp_vec(keys_d, a)
                le_a = live & (lt_a | eq_a)
                n_le = int(le_a.sum())
                at_a = int(vals_d[n_le - 1]) if n_le else int(NEG)
                out_k.append(a[:, None])
                out_v.append(np.asarray([at_a], np.int32))
                lt_b, _ = np_cmp_vec(keys_d, b)
                interior = live & ~(lt_a | eq_a) & lt_b
                out_k.append(keys_d[:, interior])
                out_v.append(vals_d[interior])
                if np_lt1(b, hi):  # acquired suffix [b, hi)
                    out_k.append(b[:, None])
                    out_v.append(np.asarray([vfill], np.int32))
            else:
                # nothing retained: whole new range conservative
                out_k.append(lo[:, None])
                out_v.append(np.asarray([vfill], np.int32))
            kcat = np.concatenate(out_k, axis=1)
            vcat = np.concatenate(out_v)
            if kcat.shape[1] > K:
                # cannot represent: collapse to fully conservative (safe)
                kcat = lo[:, None]
                vcat = np.asarray([vfill], np.int32)
            n = kcat.shape[1]
            new_bkeys[d, :, :n] = kcat
            new_bval[d, :n] = vcat
            new_nb[d] = n

        sharding = NamedSharding(self.mesh, P(RESOLVER_AXIS))
        bval_dev = jaxenv.device_put(new_bval, sharding)
        self._state = {
            "bkeys": jaxenv.device_put(new_bkeys, sharding),
            "bval": bval_dev,
            "nb": jaxenv.device_put(new_nb, sharding),
            "oldest": self._state["oldest"],
            "table": _compiled_table_builder()(bval_dev),
            "poisoned": self._state["poisoned"],
            "lo": jaxenv.device_put(cuts[: self.n_shards], sharding),
            "hi": jaxenv.device_put(cuts[1:], sharding),
        }
        self.cut_bytes = list(new_cut_bytes)
        self._load_counts[:] = 0
        self._samples.clear()
        self.rebalances += 1
