"""File backup agent + restore.

Reference: fdbclient/FileBackupAgent.actor.cpp — a backup is (a) range
snapshot files, each chunk read transactionally at SOME version during the
backup window, plus (b) the mutation log: proxies tee every committed
mutation in a backed-up range into \\xff/blog/<version><seq>
(MasterProxyServer.actor.cpp:664-776); the agent drains that range into log
files and clears what it consumed. Restore (fdbserver/Restore.actor.cpp)
loads the chunks, then applies log mutations with version > the chunk's
version for that range — yielding exactly the database state at the
backup's end version.

Backup metadata lives in the system keyspace (all flowing through the
metadata pipeline, so every proxy's tee switches on/off at a fenced
version):
  \\xff/backup/state         active | stopped
  \\xff/backup/beginVersion  decimal
  \\xff/backup/endVersion    decimal (written by stop)
  \\xff/backupRanges/<begin> -> <end>  (ranges the proxies tee)
"""

from __future__ import annotations

from bisect import bisect_right

from foundationdb_tpu.backup.taskbucket import TaskBucket
from foundationdb_tpu.utils import wire
from foundationdb_tpu.utils.errors import FDBError

BLOG_PREFIX = b"\xff/blog/"
BLOG_END = b"\xff/blog0"
STATE_KEY = b"\xff/backup/state"
BEGIN_KEY = b"\xff/backup/beginVersion"
END_KEY = b"\xff/backup/endVersion"
RANGES_PREFIX = b"\xff/backupRanges/"
RANGES_END = b"\xff/backupRanges0"


def backup_keys():
    return dict(blog=BLOG_PREFIX, state=STATE_KEY, begin=BEGIN_KEY,
                end=END_KEY, ranges=RANGES_PREFIX)


def blog_key(version: int, seq: int) -> bytes:
    return BLOG_PREFIX + version.to_bytes(8, "big") + seq.to_bytes(4, "big")


def parse_blog_key(key: bytes) -> tuple[int, int]:
    raw = key[len(BLOG_PREFIX):]
    return int.from_bytes(raw[:8], "big"), int.from_bytes(raw[8:12], "big")


class BackupAgent:
    """Drives one backup: start (ranges + snapshot tasks), agent loop
    (snapshot chunks via the TaskBucket; several agents may run), log
    tailer, stop."""

    def __init__(self, db, container, chunks: int = 8):
        self.db = db
        self.loop = db.loop
        self.container = container
        self.chunks = chunks
        self.tasks = TaskBucket(db)

    async def start(self, begin: bytes = b"", end: bytes = b"\xff"):
        """Activate the proxies' tee and enqueue snapshot-chunk tasks (one
        metadata txn: the tee and the task list appear atomically)."""
        from foundationdb_tpu.utils.keys import partition_boundaries
        bounds = [b for b in partition_boundaries(self.chunks)
                  if begin <= b < end] + [begin]
        bounds = sorted(set(bounds))

        async def body(tr):
            st = await tr.get(STATE_KEY)
            if st == b"active":
                raise FDBError("operation_failed", "backup already active")
            tr.set(STATE_KEY, b"active")
            tr.set(RANGES_PREFIX + begin, end)
            tr.clear_range(BLOG_PREFIX, BLOG_END)  # stale log of a prior run
            for i, lo in enumerate(bounds):
                hi = bounds[i + 1] if i + 1 < len(bounds) else end
                await self.tasks.add(
                    {"type": "snapshot_range", "begin": lo, "end": hi}, tr=tr)
        await self.db.transact(body, max_retries=200)

        async def note_begin(tr):
            # beginVersion = a version known to precede every tee'd commit's
            # consumption; the start txn's own commit version is the fence
            v = await tr.get_read_version()
            tr.set(BEGIN_KEY, b"%d" % v)
        await self.db.transact(note_begin, max_retries=200)

    async def run_agent(self):
        """Execute snapshot tasks until the bucket drains (crash-safe:
        unfinished tasks' leases expire and another agent re-runs them)."""
        while True:
            popped = await self.tasks.pop()
            if popped is None:
                if await self.tasks.is_empty():
                    return
                await self.loop.delay(1.0)
                continue
            key, task = popped
            assert task["type"] == "snapshot_range"
            rows = []
            version = None

            async def read_chunk(tr):
                nonlocal rows, version
                rows = await tr.get_range(task["begin"], task["end"])
                version = await tr.get_read_version()
            await self.db.transact(read_chunk, max_retries=200)
            self.container.write_file(
                "kvrange-%s" % task["begin"].hex(),
                {"begin": task["begin"], "end": task["end"],
                 "version": version, "rows": rows})
            await self.tasks.finish(key)

    async def drain_log(self, limit: int = 500) -> int:
        """Move a batch of \\xff/blog/ rows into a log file and clear them
        (the reference's eraseLogData after upload). Returns rows moved."""
        rows = []

        async def body(tr):
            nonlocal rows
            rows = await tr.get_range(BLOG_PREFIX, BLOG_END, limit=limit)
            if rows:
                tr.clear_range(BLOG_PREFIX, rows[-1][0] + b"\x00")
        await self.db.transact(body, max_retries=200)
        if rows:
            entries = [(parse_blog_key(k), v) for k, v in rows]
            # file name = the drained version range: unique across agents
            # (a stop() racing a tailer must not overwrite its files) and
            # lexicographically version-ordered
            first = entries[0][0]
            last = entries[-1][0]
            self.container.write_file(
                "log-%016x.%08x-%016x.%08x" % (first[0], first[1],
                                               last[0], last[1]),
                [((v, s), payload) for (v, s), payload in entries])
        return len(rows)

    async def run_log_tailer(self, poll: float = 1.0):
        """Continuously drain the mutation log while the backup is active."""
        while True:
            moved = await self.drain_log()
            if moved == 0:
                async def st(tr):
                    return await tr.get(STATE_KEY)
                state = await self.db.transact(st, max_retries=200)
                if state != b"active":
                    return
                await self.loop.delay(poll)

    async def stop(self) -> int:
        """Finish the backup: fence the end version, drain the remaining
        log, deactivate the tee. Returns the restorable end version."""
        # a throwaway committed write fences the end version: every earlier
        # committed mutation has version <= end_version
        fence_tr = [None]

        async def fence(tr):
            fence_tr[0] = tr
            tr.set(b"\xff/backup/fence", b"x")
        await self.db.transact(fence, max_retries=500)
        end_version = fence_tr[0].committed_version
        # every committed mutation <= end_version is either in the container
        # already or still in \xff/blog: drain until empty
        while await self.drain_log() > 0:
            pass

        async def deactivate(tr):
            tr.set(STATE_KEY, b"stopped")
            tr.set(END_KEY, b"%d" % end_version)
            tr.clear_range(RANGES_PREFIX, RANGES_END)
        await self.db.transact(deactivate, max_retries=200)
        # mutations committed between end_version and the deactivation fence
        # still tee'd into \xff/blog; they are beyond end_version and simply
        # ignored by restore — clear them
        while await self.drain_log() > 0:
            pass
        self.container.write_file("meta", {"end_version": end_version})
        return end_version


class RestoreAgent:
    """Apply a container into a cluster: chunks first, then log mutations
    above each chunk's version floor, up to the target version.

    Works against a LIVE cluster (Restore.actor.cpp's restore-into-running-
    database): every backed-up range is cleared before its chunk lands, so
    existing data under the restored ranges is replaced transactionally
    range by range; data outside them is untouched. `target_version` makes
    it point-in-time: any version in [max chunk version, end_version] —
    below the chunk floor there is no consistent base to roll forward from
    (fdbclient/FileBackupAgent.actor.cpp:941 restorable-version rules)."""

    def __init__(self, db, container):
        self.db = db
        self.container = container

    async def restore(self, target_version: int | None = None) -> int:
        from foundationdb_tpu.utils.types import Mutation, MutationType
        meta = self.container.read_file("meta")
        end_version = meta["end_version"]
        chunk_versions = [self.container.read_file(n)["version"]
                          for n in self.container.list_files("kvrange-")]
        min_restorable = max(chunk_versions) if chunk_versions else 0
        if target_version is None:
            target_version = end_version
        if not min_restorable <= target_version <= end_version:
            raise FDBError(
                "restore_invalid_version",
                f"target {target_version} outside restorable window "
                f"[{min_restorable}, {end_version}]")
        end_version = target_version
        floors: list[tuple[bytes, int]] = []  # (chunk begin, version)
        chunk_ends: dict[bytes, bytes] = {}
        for name in self.container.list_files("kvrange-"):
            chunk = self.container.read_file(name)
            floors.append((chunk["begin"], chunk["version"]))
            chunk_ends[chunk["begin"]] = chunk["end"]
            rows = chunk["rows"]
            for i in range(0, max(len(rows), 1), 100):
                part = rows[i:i + 100]

                async def w(tr, part=part, chunk=chunk, first=(i == 0)):
                    if first:
                        tr.clear_range(chunk["begin"], chunk["end"])
                    for k, v in part:
                        tr.set(k, v)
                await self.db.transact(w, max_retries=200)
        floors.sort()
        fkeys = [b for b, _v in floors]

        def floor_of(key: bytes) -> int:
            i = bisect_right(fkeys, key) - 1
            if i < 0:
                return 1 << 62  # outside every chunk: not backed up
            b = fkeys[i]
            if key >= chunk_ends[b]:
                return 1 << 62
            return floors[i][1]

        def clear_pieces(version: int, lo: bytes, hi: bytes):
            """Split a clear at chunk boundaries; keep pieces whose floor is
            below the mutation's version (replaying an OLDER clear over a
            NEWER chunk would delete restored rows)."""
            cuts = sorted({lo, hi} | {b for b in fkeys if lo < b < hi}
                          | {e for e in chunk_ends.values() if lo < e < hi})
            out = []
            for a, b in zip(cuts, cuts[1:]):
                if version > floor_of(a):
                    out.append((a, b))
            return out

        applied = 0
        entries = []
        for name in self.container.list_files("log-"):
            entries.extend(self.container.read_file(name))
        entries.sort(key=lambda e: e[0])  # (version, seq) order
        for (version, _seq), payload in entries:
            if version > end_version:
                continue
            muts = wire.loads(payload)
            todo = []
            for m in muts:
                if m.type == MutationType.CLEAR_RANGE:
                    todo.extend(
                        Mutation(MutationType.CLEAR_RANGE, a, b)
                        for a, b in clear_pieces(version, m.param1, m.param2))
                elif version > floor_of(m.param1):
                    todo.append(m)
            if not todo:
                continue

            async def w(tr, todo=todo):
                for m in todo:
                    if m.type == MutationType.CLEAR_RANGE:
                        tr.clear_range(m.param1, m.param2)
                    elif m.type == MutationType.SET_VALUE:
                        tr.set(m.param1, m.param2)
                    else:
                        # atomic ops replay as atomic ops: applied over the
                        # restored base in version order they compose to the
                        # same final value
                        tr.atomic_op(m.type, m.param1, m.param2)
            await self.db.transact(w, max_retries=200)
            applied += len(todo)
        return applied
