"""Backup/restore subsystem.

Reference: fdbclient/FileBackupAgent.actor.cpp (continuous backup: range
snapshots + mutation-log tail into a container), fdbclient/TaskBucket.actor.cpp
(the fault-tolerant task queue stored in the database that drives it),
fdbserver/Restore.actor.cpp, and the proxy's mutation-log tee
(MasterProxyServer.actor.cpp:664-776 writing into \\xff/blog/).
"""

from foundationdb_tpu.backup.agent import (
    BackupAgent, RestoreAgent, backup_keys)
from foundationdb_tpu.backup.container import BackupContainer
from foundationdb_tpu.backup.taskbucket import TaskBucket

__all__ = ["BackupAgent", "RestoreAgent", "BackupContainer", "TaskBucket",
           "backup_keys"]
