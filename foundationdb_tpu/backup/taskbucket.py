"""TaskBucket: a fault-tolerant task queue stored in the database itself.

Reference: fdbclient/TaskBucket.actor.cpp — tasks are KV rows; agents pop
one transactionally by writing a lease; a crashed agent's lease expires and
another agent re-pops the task; finishing clears the row. The conflict
check makes concurrent pops of the same task impossible.
"""

from __future__ import annotations

from foundationdb_tpu.utils import wire
from foundationdb_tpu.utils.errors import FDBError

PREFIX = b"\xff/taskBucket/"
END = b"\xff/taskBucket0"


class TaskBucket:
    def __init__(self, db, lease_seconds: float = 10.0):
        self.db = db
        self.loop = db.loop
        self.lease_seconds = lease_seconds
        self._seq = 0

    async def add(self, task: dict, tr=None):
        """Append a task (optionally inside a caller's transaction)."""
        self._seq += 1
        key = PREFIX + b"%016x-%08x" % (
            int(self.loop.now() * 1e6), self._seq)
        payload = wire.dumps({"task": task, "lease": -1.0})
        if tr is not None:
            tr.set(key, payload)
            return key

        async def w(t):
            t.set(key, payload)
        await self.db.transact(w, max_retries=100)
        return key

    async def pop(self):
        """Transactionally claim one available task (no task -> None).
        Availability = lease expired; claiming writes a fresh lease. Two
        agents racing on the same row conflict, so exactly one wins."""
        async def body(tr):
            now = self.loop.now()
            # page past live-leased rows: an expired task beyond the first
            # page must still be reclaimable (liveness), so keep scanning to
            # the end of the range, 20 rows at a time
            begin = PREFIX
            while True:
                rows = await tr.get_range(begin, END, limit=20)
                for k, v in rows:
                    obj = wire.loads(v)
                    if obj["lease"] < now:
                        tr.set(k, wire.dumps({
                            "task": obj["task"],
                            "lease": now + self.lease_seconds}))
                        return k, obj["task"]
                if len(rows) < 20:
                    return None
                begin = rows[-1][0] + b"\x00"
        return await self.db.transact(body, max_retries=100)

    async def extend(self, key: bytes):
        async def body(tr):
            v = await tr.get(key)
            if v is None:
                raise FDBError("operation_failed", "task finished under us")
            obj = wire.loads(v)
            tr.set(key, wire.dumps({
                "task": obj["task"],
                "lease": self.loop.now() + self.lease_seconds}))
        await self.db.transact(body, max_retries=100)

    async def finish(self, key: bytes):
        async def body(tr):
            tr.clear_range(key, key + b"\x00")
        await self.db.transact(body, max_retries=100)

    async def is_empty(self) -> bool:
        async def body(tr):
            rows = await tr.get_range(PREFIX, END, limit=1)
            return not rows
        return await self.db.transact(body, max_retries=100)
