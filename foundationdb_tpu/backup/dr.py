"""DR agent: continuous replication into a SECOND LIVE cluster + switchover.

Reference: fdbclient/DatabaseBackupAgent.actor.cpp — `dr_agent` keeps a
destination database a live, consistent copy of the source: an initial
snapshot copy, then a continuous tail of the source's mutation log applied
to the destination in version order (CopyLogRangeTaskFunc /
ApplyMutationsData), with an applied-version watermark stored IN the
destination so crashed/duplicated applications are idempotent. Switchover
(atomicSwitchover) fences the source, drains the remaining log, and flips
the primary marker — afterwards the destination is byte-identical through
the fence version.

Design differences from the reference, on purpose:
  - The initial snapshot reads the whole keyspace at ONE pinned read version
    (chunked reads with set_read_version) instead of a streamed multi-version
    snapshot + per-range log floors: exact, and the right trade at sim
    scale. Mutations are then applied strictly above that version.
  - There is no database-level lock primitive; switchover() requires the
    caller to have quiesced source writers (the test does), then fences with
    a marker commit exactly like BackupAgent.stop().

The mutation feed is the proxies' \\xff/blog tee (backup/agent.py keys):
rows are only CLEARED from the source after the destination transaction
recording them (and the watermark) committed — crash between the two just
re-applies idempotently.
"""

from __future__ import annotations

from foundationdb_tpu.backup.agent import (
    BEGIN_KEY, BLOG_END, BLOG_PREFIX, RANGES_END, RANGES_PREFIX, STATE_KEY,
    parse_blog_key)
from foundationdb_tpu.utils import wire
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.types import ATOMIC_OPS, Mutation, MutationType

DR_APPLIED = b"\xff/dr/applied"  # in the DESTINATION: versions <= are applied
DR_PRIMARY = b"\xff/dr/primary"  # which side serves writes after switchover


def apply_mutation(tr, m: Mutation):
    """Replay one post-substitution mutation (versionstamps were resolved by
    the source proxy before the tee, proxy.py _substitute)."""
    if m.type == MutationType.SET_VALUE:
        tr.set(m.param1, m.param2)
    elif m.type == MutationType.CLEAR_RANGE:
        tr.clear_range(m.param1, m.param2)
    elif m.type in ATOMIC_OPS:
        tr.atomic_op(m.type, m.param1, m.param2)
    else:
        raise FDBError("invalid_mutation_type", str(m.type))


class DRAgent:
    def __init__(self, src_db, dst_db, chunk_rows: int = 400):
        self.src = src_db
        self.dst = dst_db
        self.loop = src_db.loop
        self.chunk_rows = chunk_rows

    async def start(self):
        """Activate the source's mutation-log tee (the same proxy tee file
        backups use) and stamp the destination as a replica."""
        async def body(tr):
            st = await tr.get(STATE_KEY)
            if st == b"active":
                raise FDBError("operation_failed", "backup/DR already active")
            tr.set(STATE_KEY, b"active")
            tr.set(RANGES_PREFIX + b"", b"\xff")
            tr.clear_range(BLOG_PREFIX, BLOG_END)
        await self.src.transact(body, max_retries=200)

        async def note_begin(tr):
            v = await tr.get_read_version()
            tr.set(BEGIN_KEY, b"%d" % v)
        await self.src.transact(note_begin, max_retries=200)

        async def mark(tr):
            tr.set(DR_PRIMARY, b"remote")
        await self.dst.transact(mark, max_retries=200)

    async def initial_snapshot(self) -> int:
        """Copy the whole keyspace at one pinned version; set the
        destination watermark so the log tail starts exactly above it."""
        v0 = [None]

        async def pin(tr):
            v0[0] = await tr.get_read_version()
        await self.src.transact(pin, max_retries=200)

        cursor = b""
        while True:
            rows = []

            async def read(tr):
                nonlocal rows
                tr.set_read_version(v0[0])
                rows = await tr.get_range(cursor, b"\xff",
                                          limit=self.chunk_rows)
            await self.src.transact(read, max_retries=200)

            async def write(tr, rows=list(rows), first=(cursor == b"")):
                if first:
                    tr.clear_range(b"", b"\xff")
                for k, v in rows:
                    tr.set(k, v)
            await self.dst.transact(write, max_retries=200)
            if len(rows) < self.chunk_rows:
                break
            cursor = rows[-1][0] + b"\x00"

        async def mark(tr):
            tr.set(DR_APPLIED, b"%d" % v0[0])
        await self.dst.transact(mark, max_retries=200)
        return v0[0]

    async def drain_once(self, limit: int = 200) -> int:
        """Apply one batch of tee'd mutations to the destination, then clear
        them from the source. Returns source rows consumed."""
        rows = []

        async def read(tr):
            nonlocal rows
            rows = await tr.get_range(BLOG_PREFIX, BLOG_END, limit=limit)
        await self.src.transact(read, max_retries=200)
        if not rows:
            return 0
        if len(rows) == limit:
            # the limit may have cut MID-version (a version's rows are
            # written atomically by its commit, but a bounded read can see a
            # prefix): only complete versions may be applied, or the
            # watermark would hide the version's tail forever
            from foundationdb_tpu.backup.agent import blog_key
            last_v, _ = parse_blog_key(rows[-1][0])
            trimmed = [r for r in rows if parse_blog_key(r[0])[0] != last_v]
            if trimmed:
                rows = trimmed
            else:
                async def read_full(tr):
                    nonlocal rows
                    rows = await tr.get_range(blog_key(last_v, 0),
                                              blog_key(last_v + 1, 0))
                await self.src.transact(read_full, max_retries=200)
        # group by version: one destination transaction per source commit
        # version keeps apply atomic per version and bounds txn size by the
        # source's own commit batch limit
        groups: dict[int, list] = {}
        for k, payload in rows:
            version, _seq = parse_blog_key(k)
            groups.setdefault(version, []).extend(wire.loads(payload))
        for version in sorted(groups):
            async def apply(tr, version=version, muts=groups[version]):
                applied = int(await tr.get(DR_APPLIED) or b"0")
                if version <= applied:
                    return  # duplicated application (crash replay): skip
                for m in muts:
                    apply_mutation(tr, m)
                tr.set(DR_APPLIED, b"%d" % version)
            await self.dst.transact(apply, max_retries=500)

        async def clear(tr):
            tr.clear_range(BLOG_PREFIX, rows[-1][0] + b"\x00")
        await self.src.transact(clear, max_retries=200)
        return len(rows)

    async def run(self, poll: float = 0.5):
        """Continuous tail: drain until the DR is deactivated AND the log is
        empty (every tee'd mutation reached the destination).

        dr_agent is a daemon: a dead storage server or a recovery on either
        cluster surfaces as a transient FDBError mid-drain, and the agent
        must ride it out and resume — drain application is idempotent
        (watermark in the destination), so re-running a failed drain is
        always safe."""
        while True:
            try:
                moved = await self.drain_once()
                if moved == 0:
                    async def st(tr):
                        return await tr.get(STATE_KEY)
                    state = await self.src.transact(st, max_retries=200)
                    if state != b"active":
                        return
                    await self.loop.delay(poll)
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
                await self.loop.delay(poll)

    async def applied_version(self) -> int:
        async def rd(tr):
            return int(await tr.get(DR_APPLIED) or b"0")
        return await self.dst.transact(rd, max_retries=200)

    async def switchover(self) -> int:
        """atomicSwitchover: fence the (quiesced) source, drain the rest of
        the log into the destination, deactivate the tee and flip the
        primary markers. Returns the fence version — the destination is
        identical to the source through it."""
        fence_tr = [None]

        async def fence(tr):
            fence_tr[0] = tr
            tr.set(b"\xff/backup/fence", b"x")
        await self.src.transact(fence, max_retries=500)
        end_version = fence_tr[0].committed_version
        while await self.drain_once() > 0:
            pass

        async def deactivate(tr):
            tr.set(STATE_KEY, b"stopped")
            tr.clear_range(RANGES_PREFIX, RANGES_END)
            tr.set(DR_PRIMARY, b"remote")
        await self.src.transact(deactivate, max_retries=200)
        # late tee rows between the fence and deactivation: beyond the fence
        # version but still valid source commits — apply them too so the
        # destination converges to the final source state
        while await self.drain_once() > 0:
            pass

        async def promote(tr):
            tr.set(DR_PRIMARY, b"primary")
        await self.dst.transact(promote, max_retries=200)
        return end_version
