"""Backup containers: where snapshot/log files land.

Reference: fdbclient/BackupContainer.actor.cpp — file/blob-store abstraction
with kvrange and log files. Here: a directory container (real files, the
deployment path) and an in-memory container (deterministic sim tests).
"""

from __future__ import annotations

import os

from foundationdb_tpu.utils import wire


class BackupContainer:
    """In-memory container (sim tests): name -> bytes."""

    def __init__(self):
        self._files: dict[str, bytes] = {}

    def write_file(self, name: str, obj) -> None:
        self._files[name] = wire.dumps(obj)

    def read_file(self, name: str):
        return wire.loads(self._files[name])

    def list_files(self, prefix: str = "") -> list[str]:
        return sorted(n for n in self._files if n.startswith(prefix))


class DirBackupContainer(BackupContainer):
    """Directory-backed container (wire-encoded files on disk)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def write_file(self, name: str, obj) -> None:
        tmp = os.path.join(self.path, name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(wire.dumps(obj))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, name))

    def read_file(self, name: str):
        with open(os.path.join(self.path, name), "rb") as f:
            return wire.loads(f.read())

    def list_files(self, prefix: str = "") -> list[str]:
        return sorted(n for n in os.listdir(self.path)
                      if n.startswith(prefix) and not n.endswith(".tmp"))
