"""Backup containers: where snapshot/log files land.

Reference: fdbclient/BackupContainer.actor.cpp — file/blob-store abstraction
with kvrange and log files. Here: a directory container (real files, the
deployment path) and an in-memory container (deterministic sim tests).
"""

from __future__ import annotations

import os

from foundationdb_tpu.utils import wire


class BackupContainer:
    """In-memory container (sim tests): name -> bytes."""

    def __init__(self):
        self._files: dict[str, bytes] = {}

    def write_file(self, name: str, obj) -> None:
        self._files[name] = wire.dumps(obj)

    def read_file(self, name: str):
        return wire.loads(self._files[name])

    def list_files(self, prefix: str = "") -> list[str]:
        return sorted(n for n in self._files if n.startswith(prefix))


class BlobStoreBackupContainer(BackupContainer):
    """Object-store container over HTTP (fdbrpc/BlobStore.actor.cpp): files
    are objects under <bucket>/<name>, written with a CRC-32C integrity
    header that reads verify, with bounded retries around every request."""

    #: retry pacing (the reference blob store's bounded exponential backoff,
    #: BlobStore.actor.cpp knobs REQUEST_TRIES/BACKOFF): first retry after
    #: BACKOFF_BASE seconds, doubling up to BACKOFF_MAX.
    BACKOFF_BASE = 0.05
    BACKOFF_MAX = 1.0

    def __init__(self, url: str, bucket: str = "backup", retries: int = 3,
                 sleep=None):
        from foundationdb_tpu.net.http import HTTPConnection, HTTPError, _crc32c
        import time
        assert url.startswith("blobstore://"), url
        hostport = url[len("blobstore://"):].rstrip("/")
        host, _, port = hostport.partition(":")
        self._conn = HTTPConnection(host, int(port))
        self._bucket = bucket
        self._retries = retries
        self._HTTPError = HTTPError
        self._crc = _crc32c
        self._sleep = sleep if sleep is not None else time.sleep

    def _request(self, method, path, headers=None, body=b""):
        last = None
        for attempt in range(self._retries):
            if attempt:
                # back off before every retry: hammering a briefly
                # unavailable store back-to-back (and compounding with
                # HTTPConnection's own reconnect attempt) turns transient
                # blips into instant failures
                self._sleep(min(self.BACKOFF_MAX,
                                self.BACKOFF_BASE * (2 ** (attempt - 1))))
            try:
                return self._conn.request(method, path, headers, body)
            except (OSError, self._HTTPError) as e:
                last = e
        raise self._HTTPError(f"blobstore request failed: {last}")

    def write_file(self, name: str, obj) -> None:
        from urllib.parse import quote
        data = wire.dumps(obj)
        status, _h, _b = self._request(
            "PUT", f"/{self._bucket}/{quote(name)}",
            {"x-crc32c": str(self._crc(data))}, data)
        if status != 200:
            raise self._HTTPError(f"PUT {name}: HTTP {status}")

    def read_file(self, name: str):
        from urllib.parse import quote
        status, headers, body = self._request(
            "GET", f"/{self._bucket}/{quote(name)}")
        if status == 404:
            raise KeyError(name)
        if status != 200:
            raise self._HTTPError(f"GET {name}: HTTP {status}")
        want = headers.get("x-crc32c")
        if want is not None and int(want) != self._crc(body):
            raise self._HTTPError(f"GET {name}: checksum mismatch")
        return wire.loads(body)

    def list_files(self, prefix: str = "") -> list[str]:
        from urllib.parse import quote
        status, _h, body = self._request(
            "GET", f"/{self._bucket}?prefix={quote(prefix)}")
        if status != 200:
            raise self._HTTPError(f"LIST: HTTP {status}")
        return [n for n in body.decode().split("\n") if n]


class DirBackupContainer(BackupContainer):
    """Directory-backed container (wire-encoded files on disk)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def write_file(self, name: str, obj) -> None:
        tmp = os.path.join(self.path, name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(wire.dumps(obj))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, name))

    def read_file(self, name: str):
        with open(os.path.join(self.path, name), "rb") as f:
            return wire.loads(f.read())

    def list_files(self, prefix: str = "") -> list[str]:
        return sorted(n for n in os.listdir(self.path)
                      if n.startswith(prefix) and not n.endswith(".tmp"))
