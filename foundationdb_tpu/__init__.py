"""foundationdb_tpu — a TPU-native distributed transactional key-value framework.

A brand-new framework with the capabilities of FoundationDB (reference:
dongguaWDY/foundationdb v6.1.0), designed TPU-first:

- Ordered-keyspace, strict-serializable ACID transactions with optimistic MVCC
  (reference: fdbclient/NativeAPI.actor.cpp, fdbserver/Resolver.actor.cpp).
- The resolver's conflict detection is a batched interval-overlap engine that
  checks whole commit batches in one XLA launch against an HBM-resident
  version-history step function (replaces fdbserver/SkipList.cpp).
- An unbundled commit pipeline: proxies -> resolvers -> replicated logs ->
  storage servers (reference: fdbserver/MasterProxyServer.actor.cpp).
- A fully deterministic single-process cluster simulator with fault injection
  (reference: fdbrpc/sim2.actor.cpp).
- Multi-resolver key-space sharding expressed as a jax.sharding.Mesh axis with
  XLA collectives instead of RPC fan-out.

Subpackages (imported lazily — importing foundationdb_tpu does not pull in jax):

- foundationdb_tpu.utils     keys, errors, knobs, deterministic RNG, tracing
- foundationdb_tpu.core      futures/promises, deterministic event loop, simulator
- foundationdb_tpu.ops       device kernels (conflict engine) + CPU oracles
- foundationdb_tpu.parallel  mesh/sharding: multi-resolver shard_map pipeline
- foundationdb_tpu.server    roles: proxy, resolver, master, tlog, storage
- foundationdb_tpu.client    Transaction/Database API with read-your-writes
- foundationdb_tpu.models    flagship pipeline step used by bench/graft entry
"""

__version__ = "0.1.0"

# Protocol version, in the spirit of flow/serialize.h currentProtocolVersion.
PROTOCOL_VERSION = 0x0FDB00B0_71500001
