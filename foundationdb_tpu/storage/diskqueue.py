"""DiskQueue: durable append-only queue over two alternating checksummed files.

Reference: fdbserver/DiskQueue.actor.cpp + IDiskQueue.h:49-51 — the TLog's and
memory engine's WAL. Pages carry checksums; recovery scans forward and stops
at the first torn/corrupt page, so a crash can only lose a suffix. Space is
reclaimed by popping: when every entry in the older file has been popped, that
file is truncated and becomes the new tail — two files alternate forever.

Entries get monotonically increasing sequence numbers. The owner maps its own
notion of position (e.g. TLog versions) to sequences.

File interface required: append(bytes), sync(), read_all() -> bytes,
truncate(), truncate_to(size) — satisfied by core.sim.SimFile (which loses
unsynced appends on a simulated kill) and storage.localfile.LocalFile (real
fsync'd files; truncate_to = ftruncate).
"""

from __future__ import annotations

import struct
import zlib

_MAGIC = 0xFDB0D1C3
# magic, seq, pop_seq (queue's pop floor when written), payload_len, crc;
# the crc covers seq/pop_seq/len AND the payload (whole-page integrity, like
# the reference's page checksums — a flipped header field must not be trusted)
_HEADER = struct.Struct("<IQQII")
_CRCBODY = struct.Struct("<QQI")


def _page_crc(seq: int, pop_seq: int, payload: bytes) -> int:
    return zlib.crc32(payload, zlib.crc32(
        _CRCBODY.pack(seq, pop_seq, len(payload)))) & 0xFFFFFFFF


def _parse_entries(raw: bytes):
    """Yield (seq, pop_seq, payload, end_offset) until the first torn page."""
    off = 0
    n = len(raw)
    while off + _HEADER.size <= n:
        magic, seq, pop_seq, plen, crc = _HEADER.unpack_from(raw, off)
        if magic != _MAGIC or off + _HEADER.size + plen > n:
            return
        payload = raw[off + _HEADER.size: off + _HEADER.size + plen]
        if _page_crc(seq, pop_seq, payload) != crc:
            return
        off += _HEADER.size + plen
        yield seq, pop_seq, payload, off


class DiskQueue:
    def __init__(self, file0, file1):
        self.files = [file0, file1]
        self.active = 0  # writes go here; 1-active is the front being popped
        self.next_seq = 0
        self.pop_seq = 0  # entries with seq < pop_seq are discarded
        # live (unpopped, committed-or-pending) entries per file: [ (seq, payload) ]
        self._entries: list[list[tuple[int, bytes]]] = [[], []]
        self._unsynced = False

    # -- write path --

    def push(self, payload: bytes) -> int:
        seq = self.next_seq
        self.next_seq += 1
        crc = _page_crc(seq, self.pop_seq, payload)
        page = _HEADER.pack(_MAGIC, seq, self.pop_seq, len(payload), crc) + payload
        self.files[self.active].append(page)
        self._entries[self.active].append((seq, payload))
        self._unsynced = True
        return seq

    def commit(self):
        """Make all pushed entries durable (group commit: one sync)."""
        if self._unsynced:
            self.files[self.active].sync()
            self._unsynced = False

    # -- reclaim --

    def pop(self, upto_seq: int):
        """Discard entries with seq < upto_seq; truncate+swap when the front
        file is fully popped (DiskQueue.actor.cpp two-file alternation)."""
        self.pop_seq = max(self.pop_seq, upto_seq)
        front = 1 - self.active
        self._entries[front] = [e for e in self._entries[front]
                                if e[0] >= self.pop_seq]
        self._entries[self.active] = [e for e in self._entries[self.active]
                                      if e[0] >= self.pop_seq]
        if not self._entries[front]:
            self.files[front].truncate()
            # swap: future writes fill the emptied file, old active drains
            self.active = front

    # -- recovery --

    def recover(self) -> list[tuple[int, bytes]]:
        """Rebuild state from the two files after a restart.

        Returns surviving entries in sequence order. A torn tail in the file
        holding the newest entries truncates the queue there (suffix loss
        only, matching AsyncFileNonDurable crash semantics).
        """
        per_file = [list(_parse_entries(f.read_all())) for f in self.files]
        # the file whose entries start later is the active (newer) one
        def first_seq(entries):
            return entries[0][0] if entries else -1

        if first_seq(per_file[0]) >= first_seq(per_file[1]):
            newer, older = 0, 1
        else:
            newer, older = 1, 0
        entries = per_file[older] + per_file[newer]
        # pop floor self-described by the pages: popped entries are dead even
        # if still physically present in a not-yet-truncated file
        floor = max((p for _s, p, _d, _o in entries), default=0)
        # enforce contiguity from the floor: stop at the first gap (a lost
        # middle page means everything after it is unusable)
        out: list[tuple[int, bytes]] = []
        live: set[int] = set()
        for seq, _pop, payload, _off in entries:
            if seq < floor:
                continue
            if out and seq != out[-1][0] + 1:
                break
            out.append((seq, payload))
            live.add(seq)
        # Truncate each file's DEAD TAIL (pages past the last survivor):
        # reused sequence numbers appended after them would otherwise alias
        # stale dead pages on the next recovery. Per-file page runs are
        # seq-contiguous (files are wiped at swap), so survivors are always a
        # prefix-after-floor and dead pages past them are a physical tail.
        # Removing only dead bytes keeps recovery crash-idempotent on real
        # files (no window where committed data exists only in memory).
        for f_idx in (older, newer):
            keep_to = 0
            for seq, _pop, _d, end_off in per_file[f_idx]:
                if seq in live or seq < floor:
                    keep_to = end_off
                else:
                    break
            parsed_to = per_file[f_idx][-1][3] if per_file[f_idx] else 0
            if keep_to < parsed_to or keep_to < len(self.files[f_idx].read_all()):
                self.files[f_idx].truncate_to(keep_to)
            self._entries[f_idx] = [(s, d) for s, _p, d, _o in per_file[f_idx]
                                    if s in live]
        self.active = newer
        self.next_seq = out[-1][0] + 1 if out else 0
        self.pop_seq = floor
        self._unsynced = False
        return out

    # -- introspection (tests) --

    @property
    def live_entries(self) -> list[tuple[int, bytes]]:
        both = self._entries[1 - self.active] + self._entries[self.active]
        return sorted(both)
