"""Pluggable durable KV engines behind one interface.

Reference: fdbserver/IKeyValueStore.h:38-87 (interface + openKVStore dispatch
on KeyValueStoreType, fdbclient/FDBTypes.h:472). Engines here:

- MemoryKeyValueStore — the reference's `memory` engine
  (KeyValueStoreMemory.actor.cpp): all data in RAM, durability via a DiskQueue
  WAL of operations with periodic full snapshots; recovery replays
  snapshot + ops. Deterministic under the simulator (WAL on SimFiles).
- SSDKeyValueStore — the reference's `ssd` engine
  (KeyValueStoreSQLite.actor.cpp, a vendored SQLite B-tree). Here: the
  platform SQLite via the stdlib binding over a real file — a host B-tree for
  real deployments; not used inside the deterministic simulator.
- RedwoodKeyValueStore (storage/redwood.py) — the reference's
  `ssd-redwood-v1` direction (VersionedBTree.actor.cpp): WAL + memtable +
  immutable prefix-compressed sorted runs with leveled background
  compaction, for datasets the memory engine can't hold resident. Runs on
  SimFiles under the simulator (kill-injected durability faults apply) and
  on real files over the net transport.

Engines are synchronous at this layer; roles call commit() at their own
group-commit points (the event loop is cooperative, so a sync commit is a
deterministic scheduling point, so simulation determinism is preserved).
"""

from __future__ import annotations

from typing import Iterable, Protocol

from foundationdb_tpu.storage.diskqueue import DiskQueue
from foundationdb_tpu.utils import wire
from foundationdb_tpu.utils.errors import FDBError

# WAL op tags
_OP_SET = 0
_OP_CLEAR = 1
_OP_META = 2  # durable metadata (e.g. storage server's durable version)
_OP_SNAPSHOT = 3  # full-state snapshot chunk


class IKeyValueStore(Protocol):
    def set(self, key: bytes, value: bytes) -> None: ...
    def clear_range(self, begin: bytes, end: bytes) -> None: ...
    def set_metadata(self, key: str, value: bytes) -> None: ...
    def get_metadata(self, key: str) -> bytes | None: ...
    def get(self, key: bytes) -> bytes | None: ...
    def get_range(self, begin: bytes, end: bytes, limit: int = -1,
                  reverse: bool = False) -> list[tuple[bytes, bytes]]: ...
    def commit(self) -> None: ...
    def recover(self) -> None: ...


class MemoryKeyValueStore:
    """Hashmap + sorted index in RAM; DiskQueue WAL + snapshot for durability.

    Commit atomicity: mutations accumulate in a pending list and one commit()
    writes them as a SINGLE checksummed WAL entry — recovery sees a commit
    batch entirely or not at all. This matters for correctness of the storage
    server's updateStorage: its durable-version metadata must land atomically
    with the mutations it covers, or non-idempotent atomic ops would be
    re-applied after a crash (the reference gets the same property from its
    storage engines' transactional commits, IKeyValueStore.h commit()).
    """

    SNAPSHOT_OPS = 10_000  # ops between snapshots (KNOB-ish; small for sim)

    def __init__(self, file0, file1):
        from foundationdb_tpu.utils.indexedset import make_indexed_set
        self.queue = DiskQueue(file0, file1)
        self._data: dict[bytes, bytes] = {}
        # size-augmented ordered index (flow/IndexedSet.h): O(log n)
        # inserts and O(log n) byte sums over ranges (shard metrics)
        self._index = make_indexed_set()
        self._meta: dict[str, bytes] = {}
        self._pending: list[tuple] = []
        self._ops_since_snapshot = 0

    # -- mutation --

    def set(self, key: bytes, value: bytes) -> None:
        self._apply_set(key, value)
        self._pending.append((_OP_SET, key, value))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        self._apply_clear(begin, end)
        self._pending.append((_OP_CLEAR, begin, end))

    def set_metadata(self, key: str, value: bytes) -> None:
        self._meta[key] = value
        self._pending.append((_OP_META, key, value))

    def get_metadata(self, key: str) -> bytes | None:
        return self._meta.get(key)

    def _apply_set(self, key: bytes, value: bytes):
        self._index.insert(key, len(key) + len(value))
        self._data[key] = value

    def _apply_clear(self, begin: bytes, end: bytes):
        for k in self._index.range_keys(begin, end):
            del self._data[k]
            self._index.discard(k)

    # -- reads (always from RAM, like the reference memory engine) --

    def get(self, key: bytes) -> bytes | None:
        return self._data.get(key)

    def get_range(self, begin: bytes, end: bytes, limit: int = -1,
                  reverse: bool = False) -> list[tuple[bytes, bytes]]:
        if limit == 0:
            return []  # limit semantics: 0 rows; unlimited is limit < 0
        keys = self._index.range_keys(begin, end, max(limit, 0), reverse)
        return [(k, self._data[k]) for k in keys]

    def bytes_range(self, begin: bytes, end: bytes) -> tuple[int, int]:
        """(row count, key+value bytes) over [begin, end) in O(log n) —
        the augmented-sum read shard metrics are built on (the reference's
        byteSample serves the same query, storageserver byteSampleApplySet;
        here the index sum is exact rather than sampled)."""
        return self._index.sum_range(begin, end)

    def split_key(self, begin: bytes, end: bytes) -> bytes | None:
        """Median-by-count split candidate in O(log n)."""
        n, _b = self._index.sum_range(begin, end)
        if n < 4:
            return None
        k = self._index.nth(self._index.rank(begin) + n // 2)
        return None if k == begin else k

    # -- durability --

    def commit(self) -> None:
        if self._pending:
            self.queue.push(wire.dumps(self._pending))
            self._ops_since_snapshot += len(self._pending)
            self._pending = []
        if self._ops_since_snapshot >= self.SNAPSHOT_OPS:
            self._write_snapshot()
        self.queue.commit()

    def _write_snapshot(self):
        """Full-state snapshot entry, then pop everything before it — the
        memory engine's log compaction (KeyValueStoreMemory semantics)."""
        snap = wire.dumps(
            [(_OP_SNAPSHOT, list(self._data.items()), dict(self._meta))])
        seq = self.queue.push(snap)
        self.queue.commit()
        self.queue.pop(seq)
        self._ops_since_snapshot = 0

    def recover(self) -> None:
        from foundationdb_tpu.utils.indexedset import make_indexed_set
        self._data.clear()
        self._index = make_indexed_set()
        self._meta.clear()
        self._pending = []
        for _seq, payload in self.queue.recover():
            try:
                ops = wire.loads(payload)
            except wire.WireError as e:
                # DiskQueue checksums passed but the body is not ours: not a
                # torn tail, an incompatible/corrupt store (file_corrupt in
                # the reference's IKeyValueStore recovery)
                raise FDBError("file_corrupt", f"WAL entry undecodable: {e}")
            for op in ops:
                if op[0] == _OP_SNAPSHOT:
                    self._data = dict(op[1])
                    self._meta = dict(op[2])
                elif op[0] == _OP_SET:
                    self._data[op[1]] = op[2]
                elif op[0] == _OP_CLEAR:
                    for k in [k for k in self._data if op[1] <= k < op[2]]:
                        del self._data[k]
                elif op[0] == _OP_META:
                    self._meta[op[1]] = op[2]
        for k, v in self._data.items():
            self._index.insert(k, len(k) + len(v))
        self._ops_since_snapshot = 0


class SSDKeyValueStore:
    """Host B-tree engine over the platform SQLite (real deployments).

    The reference's ssd engine is a vendored SQLite B-tree driven through
    IKeyValueStore (KeyValueStoreSQLite.actor.cpp); binding the platform
    library gives the same storage shape without vendoring 150k LoC.
    """

    def __init__(self, path: str):
        import sqlite3

        # check_same_thread=False: the storage server commits off the actor
        # loop through run_blocking, which under the real event loop runs in
        # a worker thread; SQLite itself is serialized-mode thread-safe
        self.db = sqlite3.connect(path, isolation_level=None,
                                  check_same_thread=False)
        self.db.execute("PRAGMA journal_mode=WAL")
        self.db.execute("PRAGMA synchronous=FULL")
        self.db.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB) WITHOUT ROWID")
        self.db.execute(
            "CREATE TABLE IF NOT EXISTS meta (k TEXT PRIMARY KEY, v BLOB)")
        self.db.execute("BEGIN")

    def set(self, key: bytes, value: bytes) -> None:
        self.db.execute("INSERT OR REPLACE INTO kv VALUES (?, ?)", (key, value))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        self.db.execute("DELETE FROM kv WHERE k >= ? AND k < ?", (begin, end))

    def set_metadata(self, key: str, value: bytes) -> None:
        self.db.execute("INSERT OR REPLACE INTO meta VALUES (?, ?)", (key, value))

    def get_metadata(self, key: str) -> bytes | None:
        row = self.db.execute("SELECT v FROM meta WHERE k = ?", (key,)).fetchone()
        return row[0] if row else None

    def get(self, key: bytes) -> bytes | None:
        row = self.db.execute("SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return row[0] if row else None

    def get_range(self, begin: bytes, end: bytes, limit: int = -1,
                  reverse: bool = False) -> list[tuple[bytes, bytes]]:
        order = "DESC" if reverse else "ASC"
        q = f"SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k {order}"
        if limit >= 0:
            q += f" LIMIT {int(limit)}"
        return [(bytes(k), bytes(v))
                for k, v in self.db.execute(q, (begin, end)).fetchall()]

    def commit(self) -> None:
        self.db.execute("COMMIT")
        self.db.execute("BEGIN")

    def recover(self) -> None:
        pass  # SQLite recovers via its own WAL on connect


# the KeyValueStoreType universe (FDBTypes.h:472) — "ssd-2" is an alias the
# reference keeps for its second sqlite format; redwood is the log-structured
# engine in storage/redwood.py
VALID_STORAGE_ENGINES = ("memory", "ssd", "ssd-2", "redwood")


def validate_storage_engine(name: str) -> None:
    """Fail FAST on a bad STORAGE_ENGINE — at worker boot, not on the first
    storage recruitment minutes later (and never by silently falling back
    to some other engine)."""
    if name not in VALID_STORAGE_ENGINES:
        raise FDBError(
            "invalid_option",
            f"unknown STORAGE_ENGINE {name!r}: valid engines are "
            + ", ".join(VALID_STORAGE_ENGINES))


def open_kv_store(store_type: str, **kwargs) -> IKeyValueStore:
    """openKVStore dispatch (IKeyValueStore.h:66, KeyValueStoreType)."""
    if store_type == "memory":
        return MemoryKeyValueStore(kwargs["file0"], kwargs["file1"])
    if store_type in ("ssd", "ssd-2"):
        return SSDKeyValueStore(kwargs["path"])
    if store_type == "redwood":
        from foundationdb_tpu.storage.redwood import RedwoodKeyValueStore
        return RedwoodKeyValueStore(kwargs["file0"], kwargs["file1"],
                                    kwargs["open_file"],
                                    kwargs.get("existing_files"))
    validate_storage_engine(store_type)  # raises with the valid list
    raise FDBError("invalid_option", f"unknown storage engine {store_type}")
