"""Redwood: a log-structured versioned storage engine for real datasets.

Reference: fdbserver/VersionedBTree.actor.cpp (the `ssd-redwood-v1` engine) —
FDB's answer to multi-GB datasets the memory engine can't hold resident and
the sqlite shim serves too slowly. The shape reproduced here is Redwood's
write path rather than its B-tree page tree: an append-only WAL (the same
DiskQueue framing + CRC-32C the memory engine and TLog use) feeds an
in-memory memtable that flushes to immutable, prefix-compressed sorted
blocks with a block index, organized into levels and merged by background
compaction. Reads consult newest-to-oldest sources with range-tombstone
shadowing; recovery loads the surviving runs and replays the WAL tail.

On-disk layout — two regions, both CRC-32C checked:

  WAL          two alternating DiskQueue files (framing from diskqueue.py);
               one entry per commit() batch, ops tagged like the memory
               engine's WAL (_OP_SET / _OP_CLEAR / _OP_META).
  run files    one immutable file per flushed/compacted run, written once
               and synced. RedwoodRunHeader, then source run ids, a block
               index (last key + offset/length per block), an aux region
               (range tombstones + the metadata dict, wire-encoded), then
               the prefix-compressed blocks. Block and run header structs
               are pinned as PROTO005-style C-schema comments in
               native/fdb_native.c; the C and Python block codecs are
               bit-identical (tests/test_redwood.py parity fuzz).

Crash safety is ordering, not atomicity:

  flush     freeze memtable -> build run image (pure) -> append+sync the
            run file -> pop the WAL up to the freeze point. A crash between
            sync and pop replays WAL ops already in the run — idempotent
            (sets/clears/meta; atomics are resolved upstream by the storage
            server before they reach the engine).
  compact   build merged run -> append+sync -> truncate the source files.
            A crash in between leaves both; recovery drops any run listed
            as a source of a surviving valid run (and truncates it, healing
            the half-finished compaction).
  torn run  a partially-durable run file fails its body CRC and is ignored;
            its data is still covered by the WAL or by its source runs.

Maintenance is split so the storage server can drive it from its actor loop
without blocking (devlint DEV001 discipline, the resolver's
drain-off-the-loop idiom): `plan_maintenance()` freezes inputs on-loop and
returns a plan whose `.build()` is pure CPU+read-only-file work safe for
`loop.run_blocking`; `apply_maintenance(plan, image)` installs the result
on-loop. Decisions depend only on byte/run counts, so the same mutation
stream produces the same flush/compaction sequence — sim-deterministic.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Callable

from foundationdb_tpu.storage.diskqueue import DiskQueue
from foundationdb_tpu.utils import wire
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS

# WAL op tags — shared with the memory engine (storage/kvstore.py) so the
# two WALs stay mutually readable by eye and by tests
_OP_SET = 0
_OP_CLEAR = 1
_OP_META = 2

# ---------------------------------------------------------------------------
# block codec — bit-parity with native/fdb_native.c redwood_encode_block /
# redwood_decode_block (PROTO005 C-schema comments pin the structs there)
# ---------------------------------------------------------------------------

BLOCK_MAGIC = 0x5EDB10C5
RUN_MAGIC = 0x5EDB4513
RUN_FORMAT_VERSION = 2  # v2: per-run bloom section between aux and blocks
BLOOM_MAGIC = 0x5EDBB1F1

# RedwoodBlockHeader { magic: u32, n_entries: u32, payload_bytes: u32, crc: u32 }
_BLOCK_HEADER = struct.Struct("<IIII")
# RedwoodBlockEntry { shared: u16, suffix_len: u16, value_len: u32 }
_BLOCK_ENTRY = struct.Struct("<HHI")
# RedwoodRunHeader { magic: u32, format_version: u32, run_id: u64,
#                    meta_seq: u64, level: u32, n_blocks: u32, n_sources: u32,
#                    index_bytes: u32, aux_bytes: u32, bloom_bytes: u32,
#                    body_crc: u32 }
_RUN_HEADER = struct.Struct("<IIQQIIIIIII")
# RedwoodRunIndexEntry { offset: u32, length: u32, last_key_len: u16 }
_RUN_INDEX = struct.Struct("<IIH")
# RedwoodBloomHeader { magic: u32, n_hashes: u32, n_bits: u64, n_keys: u64 }
_BLOOM_HEADER = struct.Struct("<IIQQ")

# field lists the C-schema parity test (tests/test_redwood.py) cross-checks
# against the comments in fdb_native.c — this side is the binding authority
BLOCK_HEADER_FIELDS = ["magic", "n_entries", "payload_bytes", "crc"]
BLOCK_ENTRY_FIELDS = ["shared", "suffix_len", "value_len"]
RUN_HEADER_FIELDS = ["magic", "format_version", "run_id", "meta_seq",
                     "level", "n_blocks", "n_sources", "index_bytes",
                     "aux_bytes", "bloom_bytes", "body_crc"]
RUN_INDEX_FIELDS = ["offset", "length", "last_key_len"]
BLOOM_HEADER_FIELDS = ["magic", "n_hashes", "n_bits", "n_keys"]

_CRC32C_TABLE: list[int] | None = None


def crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli). The fallback computes the SAME polynomial as
    the native module: a store written by a native-enabled host must verify
    on a pure-Python host and vice versa (net/http.py makes the identical
    argument for its trailer checksums)."""
    from foundationdb_tpu import native
    if native.available():
        return native.mod.crc32c(data)
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    t = _CRC32C_TABLE
    c = 0xFFFFFFFF
    for b in data:
        c = t[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def _shared_prefix_len(a: bytes, b: bytes) -> int:
    n = min(len(a), len(b), 0xFFFF)
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


def py_encode_block(items: list[tuple[bytes, bytes]]) -> bytes:
    """Pure-Python block encoder; MUST stay byte-identical to the C
    redwood_encode_block (the parity fuzz in tests/test_redwood.py is the
    gate). Keys must be pre-sorted; prefix compression is against the
    previous key in the block."""
    parts = []
    prev = b""
    for k, v in items:
        if len(k) > 0xFFFF:
            raise FDBError("invalid_option", "redwood key exceeds 64KiB")
        shared = _shared_prefix_len(prev, k)
        suffix = k[shared:]
        parts.append(_BLOCK_ENTRY.pack(shared, len(suffix), len(v)))
        parts.append(suffix)
        parts.append(v)
        prev = k
    payload = b"".join(parts)
    return _BLOCK_HEADER.pack(BLOCK_MAGIC, len(items), len(payload),
                              crc32c(payload)) + payload


def py_decode_block(data: bytes) -> list[tuple[bytes, bytes]]:
    if len(data) < _BLOCK_HEADER.size:
        raise FDBError("file_corrupt", "redwood block shorter than header")
    magic, n, plen, crc = _BLOCK_HEADER.unpack_from(data, 0)
    payload = data[_BLOCK_HEADER.size:]
    if magic != BLOCK_MAGIC or len(payload) != plen:
        raise FDBError("file_corrupt", "redwood block header mismatch")
    if crc32c(payload) != crc:
        raise FDBError("file_corrupt", "redwood block checksum mismatch")
    out: list[tuple[bytes, bytes]] = []
    prev = b""
    off = 0
    for _ in range(n):
        shared, slen, vlen = _BLOCK_ENTRY.unpack_from(payload, off)
        off += _BLOCK_ENTRY.size
        key = prev[:shared] + payload[off:off + slen]
        off += slen
        out.append((key, payload[off:off + vlen]))
        off += vlen
        prev = key
    if off != plen:
        raise FDBError("file_corrupt", "redwood block trailing bytes")
    return out


def encode_block(items: list[tuple[bytes, bytes]]) -> bytes:
    from foundationdb_tpu import native
    if native.available() and hasattr(native.mod, "redwood_encode_block"):
        return native.mod.redwood_encode_block(items)
    return py_encode_block(items)


def decode_block(data: bytes) -> list[tuple[bytes, bytes]]:
    from foundationdb_tpu import native
    if native.available() and hasattr(native.mod, "redwood_decode_block"):
        return native.mod.redwood_decode_block(data)
    return py_decode_block(data)


# ---------------------------------------------------------------------------
# per-run bloom filters — bit-parity with native/fdb_native.c
# redwood_bloom_build / redwood_bloom_query
# ---------------------------------------------------------------------------

# Double hashing over CRC-32C: bit_i = (h1 + i*h2) % n_bits with
# h1 = crc32c(key) and h2 = crc32c(key + salt). The C side streams the salt
# byte into h1's CRC state, which equals hashing the concatenation.
_BLOOM_SALT = b"\xb1"


def _bloom_hashes(key: bytes) -> tuple[int, int]:
    return crc32c(key), crc32c(key + _BLOOM_SALT)


def py_bloom_build(keys: list[bytes], bits_per_key: int,
                   n_hashes: int) -> bytes:
    """Pure-Python bloom builder; MUST stay byte-identical to the C
    redwood_bloom_build (tests/test_redwood_native.py parity fuzz is the
    gate). An empty key list still yields a 64-bit all-zero filter so every
    query answers False — a bloom can shadow nothing it doesn't hold."""
    if bits_per_key < 1 or not 1 <= n_hashes <= 64:
        raise ValueError("bad bloom parameters")
    n_bits = max(64, len(keys) * bits_per_key)
    bits = bytearray((n_bits + 7) // 8)
    for k in keys:
        h1, h2 = _bloom_hashes(k)
        for i in range(n_hashes):
            bit = (h1 + i * h2) % n_bits
            bits[bit >> 3] |= 1 << (bit & 7)
    return _BLOOM_HEADER.pack(BLOOM_MAGIC, n_hashes, n_bits,
                              len(keys)) + bytes(bits)


def py_bloom_query(section: bytes, key: bytes) -> bool:
    if len(section) < _BLOOM_HEADER.size:
        raise ValueError("corrupt redwood bloom section")
    magic, n_hashes, n_bits, _n_keys = _BLOOM_HEADER.unpack_from(section, 0)
    if (magic != BLOOM_MAGIC or n_bits == 0 or not 1 <= n_hashes <= 64
            or len(section) - _BLOOM_HEADER.size != (n_bits + 7) // 8):
        raise ValueError("corrupt redwood bloom section")
    bits = memoryview(section)[_BLOOM_HEADER.size:]
    h1, h2 = _bloom_hashes(key)
    for i in range(n_hashes):
        bit = (h1 + i * h2) % n_bits
        if not (bits[bit >> 3] >> (bit & 7)) & 1:
            return False
    return True


def bloom_build(keys: list[bytes], bits_per_key: int, n_hashes: int) -> bytes:
    from foundationdb_tpu import native
    if native.available() and hasattr(native.mod, "redwood_bloom_build"):
        return native.mod.redwood_bloom_build(keys, bits_per_key, n_hashes)
    return py_bloom_build(keys, bits_per_key, n_hashes)


def bloom_query(section: bytes, key: bytes) -> bool:
    from foundationdb_tpu import native
    if native.available() and hasattr(native.mod, "redwood_bloom_query"):
        return native.mod.redwood_bloom_query(section, key)
    return py_bloom_query(section, key)


# ---------------------------------------------------------------------------
# run container (Python-assembled; blocks inside come from the codec above)
# ---------------------------------------------------------------------------

def build_run_image(entries: list[tuple[bytes, bytes]],
                    clears: list[tuple[bytes, bytes]],
                    meta: dict[str, bytes],
                    run_id: int, meta_seq: int, level: int,
                    sources: tuple[int, ...], block_bytes: int,
                    bloom_bits_per_key: int | None = None,
                    bloom_hashes: int | None = None) -> bytes:
    """Assemble one immutable run file image (pure — safe off-loop).
    Bloom parameters default to the REDWOOD_BLOOM_* knobs; bits_per_key 0
    writes no bloom section at all (bloom_bytes == 0)."""
    blocks: list[bytes] = []
    index_parts: list[bytes] = []
    cur: list[tuple[bytes, bytes]] = []
    cur_bytes = 0
    off = 0

    def close_block():
        nonlocal off, cur, cur_bytes
        blk = encode_block(cur)
        last_key = cur[-1][0]
        index_parts.append(_RUN_INDEX.pack(off, len(blk), len(last_key)))
        index_parts.append(last_key)
        blocks.append(blk)
        off += len(blk)
        cur = []
        cur_bytes = 0

    for k, v in entries:
        cur.append((k, v))
        cur_bytes += len(k) + len(v) + _BLOCK_ENTRY.size
        if cur_bytes >= block_bytes:
            close_block()
    if cur:
        close_block()
    # deterministic aux bytes: meta sorted by key, clears in accumulation
    # order (itself deterministic under the sim's scheduling)
    aux = wire.dumps((list(clears),
                      sorted(meta.items())))
    src = struct.pack(f"<{len(sources)}Q", *sources) if sources else b""
    index = b"".join(index_parts)
    bpk = (KNOBS.REDWOOD_BLOOM_BITS_PER_KEY if bloom_bits_per_key is None
           else bloom_bits_per_key)
    nh = KNOBS.REDWOOD_BLOOM_HASHES if bloom_hashes is None else bloom_hashes
    bloom = bloom_build([k for k, _ in entries], bpk, nh) if bpk > 0 else b""
    body = src + index + aux + bloom + b"".join(blocks)
    header = _RUN_HEADER.pack(RUN_MAGIC, RUN_FORMAT_VERSION, run_id, meta_seq,
                              level, len(blocks), len(sources), len(index),
                              len(aux), len(bloom), crc32c(body))
    return header + body


@dataclass
class _Run:
    """One immutable on-disk run: header fields + decoded index, with block
    payloads fetched lazily through the store's block cache."""

    run_id: int
    meta_seq: int
    level: int
    sources: tuple[int, ...]
    index: list[tuple[int, int, bytes]]  # (offset, length, last_key)
    clears: list[tuple[bytes, bytes]]
    meta: dict[str, bytes]
    blocks_off: int  # absolute file offset of the blocks region
    file: object
    name: str
    raw: bytes | None = None  # full image kept only when file lacks pread
    bloom: bytes = b""        # bloom section (b"" when the run has none)
    native: object | None = None  # C RedwoodRun handle (None = Python path)

    def read_block_bytes(self, i: int) -> bytes:
        off, length, _lk = self.index[i]
        if self.raw is not None:
            return self.raw[self.blocks_off + off:
                            self.blocks_off + off + length]
        return self.file.read_range(self.blocks_off + off, length)

    def first_block_for(self, key: bytes) -> int:
        """Index of the first block whose last_key >= key (== len(index)
        when every block ends before key)."""
        lo, hi = 0, len(self.index)
        while lo < hi:
            mid = (lo + hi) // 2
            if self.index[mid][2] < key:
                lo = mid + 1
            else:
                hi = mid
        return lo


def _native_run_handle(raw: bytes, clears: list[tuple[bytes, bytes]]):
    """C RedwoodRun handle for a validated image, or None (knob off, native
    unavailable, or the C open rejects it — degrade to the Python path, but
    never drop a run parse_run already accepted)."""
    if not KNOBS.REDWOOD_NATIVE_READS:
        return None
    from foundationdb_tpu import native
    if not (native.available() and hasattr(native.mod, "redwood_run_open")):
        return None
    try:
        return native.mod.redwood_run_open(
            bytes(raw), clears, KNOBS.REDWOOD_BLOCK_CACHE_BLOCKS)
    except (ValueError, TypeError, MemoryError):
        return None


def parse_run(raw: bytes, file, name: str) -> _Run | None:
    """Validate + decode a run file; None for anything torn or foreign
    (a crashed apply leaves a partial file — recovery must shrug it off).
    `file=None` marks a short-lived reader (compaction input): no native
    handle is opened for those."""
    try:
        if len(raw) < _RUN_HEADER.size:
            return None
        (magic, ver, run_id, meta_seq, level, n_blocks, n_sources,
         index_bytes, aux_bytes, bloom_bytes,
         body_crc) = _RUN_HEADER.unpack_from(raw, 0)
        if magic != RUN_MAGIC or ver != RUN_FORMAT_VERSION:
            return None
        body = raw[_RUN_HEADER.size:]
        if crc32c(body) != body_crc:
            return None
        off = 0
        sources = (struct.unpack_from(f"<{n_sources}Q", body, off)
                   if n_sources else ())
        off += 8 * n_sources
        index: list[tuple[int, int, bytes]] = []
        index_end = off + index_bytes
        while off < index_end:
            boff, blen, klen = _RUN_INDEX.unpack_from(body, off)
            off += _RUN_INDEX.size
            index.append((boff, blen, bytes(body[off:off + klen])))
            off += klen
        if len(index) != n_blocks or off != index_end:
            return None
        aux = wire.loads(bytes(body[off:off + aux_bytes]))
        clears = [(b, e) for b, e in aux[0]]
        meta = {k: v for k, v in aux[1]}
        bloom = bytes(body[off + aux_bytes:off + aux_bytes + bloom_bytes])
        if len(bloom) != bloom_bytes:
            return None
        blocks_off = _RUN_HEADER.size + off + aux_bytes + bloom_bytes
        keep_raw = raw if not hasattr(file, "read_range") else None
        native_handle = (_native_run_handle(raw, clears)
                         if file is not None else None)
        return _Run(run_id=run_id, meta_seq=meta_seq, level=level,
                    sources=tuple(sources), index=index, clears=clears,
                    meta=meta, blocks_off=blocks_off, file=file, name=name,
                    raw=keep_raw, bloom=bloom, native=native_handle)
    except (struct.error, wire.WireError, ValueError, TypeError):
        return None


# ---------------------------------------------------------------------------
# maintenance plans
# ---------------------------------------------------------------------------

@dataclass
class MaintenancePlan:
    """One unit of background work. `build` is pure (CPU + reads of
    immutable files) so the storage server can run it through
    loop.run_blocking; `apply_maintenance` installs the result on-loop."""

    kind: str                      # "flush" | "compact"
    run_id: int
    level: int                     # level the new run lands at
    build: Callable[[], bytes] = field(repr=False, default=None)
    wal_upto: int = 0              # flush: WAL pop point after install
    source_ids: tuple[int, ...] = ()  # compact: runs consumed
    drop_tombstones: bool = False  # compact: output is the oldest data


@dataclass
class _Frozen:
    """Immutable memtable awaiting flush (reads still see it)."""

    entries: dict[bytes, bytes]
    index: object
    clears: list[tuple[bytes, bytes]]
    meta: dict[str, bytes]
    wal_upto: int


def _covered(key: bytes, clears: list[tuple[bytes, bytes]]) -> bool:
    return any(b <= key < e for b, e in clears)


class RedwoodKeyValueStore:
    """IKeyValueStore over WAL + memtable + leveled immutable runs.

    Files come through two callables so the engine is transport-agnostic:
    the sim hands it SimFiles (kill-injected torn tails), the real transport
    _LocalFiles (fsync + pread). `open_file(name)` creates-or-opens a run
    file; `existing_files()` lists run-file names found on disk at recovery.
    Run files are named "rw.<run_id>" under whatever prefix the caller's
    open_file applies.
    """

    def __init__(self, file0, file1, open_file: Callable[[str], object],
                 existing_files: Callable[[], list[str]] | None = None):
        from foundationdb_tpu.utils.indexedset import make_indexed_set
        self.queue = DiskQueue(file0, file1)
        self._open_file = open_file
        self._existing_files = existing_files or (lambda: [])
        self._make_index = make_indexed_set
        self._mem: dict[bytes, bytes] = {}
        self._mem_index = make_indexed_set()
        self._mem_clears: list[tuple[bytes, bytes]] = []
        self._mem_bytes = 0
        self._imm: _Frozen | None = None
        self._meta: dict[str, bytes] = {}
        self._pending: list[tuple] = []
        self._levels: dict[int, list[_Run]] = {}  # newest-first per level
        self._next_run_id = 1
        self._wal_bytes = 0  # pushed since the last flush (meta churn bound)
        self._plan_active = False
        self._block_cache: dict[tuple[int, int], list] = {}
        # read-path observability; native per-handle counters are merged in
        # by read_stats() and folded here when a handle is retired
        self._read_stats: dict[str, int] = {
            "block_cache_hits": 0, "block_cache_misses": 0,
            "bloom_negatives": 0, "blocks_decoded": 0,
            "native_gets": 0, "fallback_gets": 0, "batch_gets": 0,
        }

    # -- mutation (same surface + WAL batching as the memory engine) --

    def set(self, key: bytes, value: bytes) -> None:
        self._apply_set(key, value)
        self._pending.append((_OP_SET, key, value))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        self._apply_clear(begin, end)
        self._pending.append((_OP_CLEAR, begin, end))

    def set_metadata(self, key: str, value: bytes) -> None:
        self._meta[key] = value
        self._pending.append((_OP_META, key, value))

    def get_metadata(self, key: str) -> bytes | None:
        return self._meta.get(key)

    def _apply_set(self, key: bytes, value: bytes):
        old = self._mem.get(key)
        if old is not None:
            self._mem_bytes -= len(key) + len(old)
        self._mem_index.insert(key, len(key) + len(value))
        self._mem[key] = value
        self._mem_bytes += len(key) + len(value)

    def _apply_clear(self, begin: bytes, end: bytes):
        # eager delete inside the memtable, plus a range tombstone that
        # shadows the frozen memtable and every older run
        for k in self._mem_index.range_keys(begin, end):
            self._mem_bytes -= len(k) + len(self._mem[k])
            del self._mem[k]
            self._mem_index.discard(k)
        self._mem_clears.append((begin, end))
        self._mem_bytes += len(begin) + len(end)

    # -- reads: newest source wins; tombstones shadow older sources --

    def _runs_newest_first(self):
        for level in sorted(self._levels):
            for run in self._levels[level]:
                yield run

    def _mem_lookup(self, key: bytes) -> tuple[bool, bytes | None]:
        """Resolve against the memtable + frozen memtable only:
        (resolved, value). Unresolved keys fall through to the runs."""
        if key in self._mem:
            return True, self._mem[key]
        if _covered(key, self._mem_clears):
            return True, None
        imm = self._imm
        if imm is not None:
            if key in imm.entries:
                return True, imm.entries[key]
            if _covered(key, imm.clears):
                return True, None
        return False, None

    def get(self, key: bytes) -> bytes | None:
        resolved, val = self._mem_lookup(key)
        if resolved:
            return val
        for run in self._runs_newest_first():
            found, val, shadowed = self._run_lookup(run, key)
            if found:
                return val
            if shadowed:
                return None
        return None

    def _block(self, run: _Run, i: int) -> list[tuple[bytes, bytes]]:
        ck = (run.run_id, i)
        blk = self._block_cache.get(ck)
        if blk is None:
            self._read_stats["block_cache_misses"] += 1
            self._read_stats["blocks_decoded"] += 1
            blk = decode_block(run.read_block_bytes(i))
            cap = KNOBS.REDWOOD_BLOCK_CACHE_BLOCKS
            if len(self._block_cache) >= cap:
                # drop the oldest insertion (dict preserves order) — a cheap
                # FIFO approximation of LRU, deterministic under sim
                self._block_cache.pop(next(iter(self._block_cache)))
            self._block_cache[ck] = blk
        else:
            self._read_stats["block_cache_hits"] += 1
        return blk

    def _run_lookup(self, run: _Run,
                    key: bytes) -> tuple[bool, bytes | None, bool]:
        """(found, value, shadowed-by-this-run's-clears): one run consulted
        through the native handle when it has one, else the Python path.
        Decision parity between the two is fuzz-gated
        (tests/test_redwood_native.py)."""
        h = run.native
        if h is not None:
            self._read_stats["native_gets"] += 1
            status, val = h.get(key)
            return status == 1, val, status == 2
        self._read_stats["fallback_gets"] += 1
        found, val = self._run_get(run, key)
        if found:
            return True, val, False
        return False, None, _covered(key, run.clears)

    def _run_get(self, run: _Run, key: bytes) -> tuple[bool, bytes | None]:
        """Pure-Python in-run point lookup (the native fallback path)."""
        if run.bloom and not bloom_query(run.bloom, key):
            self._read_stats["bloom_negatives"] += 1
            return False, None
        i = run.first_block_for(key)
        if i >= len(run.index):
            return False, None
        blk = self._block(run, i)
        lo, hi = 0, len(blk)
        while lo < hi:
            mid = (lo + hi) // 2
            if blk[mid][0] < key:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(blk) and blk[lo][0] == key:
            return True, blk[lo][1]
        return False, None

    # -- batched reads (native fast path) --

    def _native_handles(self) -> list | None:
        """Newest-first C run handles, or None unless EVERY run has one —
        a mixed cascade would evaluate shadowing out of order."""
        if not KNOBS.REDWOOD_NATIVE_READS:
            return None
        hs = []
        for run in self._runs_newest_first():
            if run.native is None:
                return None
            hs.append(run.native)
        return hs

    def get_batch(self, keys: list[bytes]) -> list[bytes | None]:
        """Point-read a batch: memtable/imm resolved in Python, then ONE
        C call cascades every remaining key through all run handles.
        Falls back to per-key get() when any run lacks a handle."""
        hs = self._native_handles()
        if hs is None:
            return [self.get(k) for k in keys]
        self._read_stats["batch_gets"] += 1
        out: list[bytes | None] = [None] * len(keys)
        pending_idx: list[int] = []
        pending_keys: list[bytes] = []
        for i, k in enumerate(keys):
            resolved, val = self._mem_lookup(k)
            if resolved:
                out[i] = val
            else:
                pending_idx.append(i)
                pending_keys.append(k)
        if pending_keys and hs:
            from foundationdb_tpu import native
            vals = native.mod.redwood_runs_get_batch(hs, pending_keys)
            for i, v in zip(pending_idx, vals):
                out[i] = v
            self._read_stats["native_gets"] += len(pending_keys)
        return out

    def get_batch_encoded(self, reads: list[tuple[bytes, int]], oldest: int,
                          tid: int) -> bytes | None:
        """Complete GetValuesReply wire frame for (key, version) pairs,
        serialized in one C call — values copied straight out of the mapped
        run images, never materialized as Python objects. Returns None when
        the native fast path is unavailable (caller encodes in Python)."""
        hs = self._native_handles()
        if hs is None:
            return None
        from foundationdb_tpu import native
        if not hasattr(native.mod, "redwood_runs_get_many_encode"):
            return None
        # memtable/imm resolution stays in Python; False = "cascade the
        # runs in C" (too-old reads are decided by version in C first)
        prefilled: list = []
        for k, _v in reads:
            resolved, val = self._mem_lookup(k)
            prefilled.append(val if resolved else False)
        self._read_stats["batch_gets"] += 1
        self._read_stats["native_gets"] += len(reads)
        return native.mod.redwood_runs_get_many_encode(
            hs, reads, oldest, tid, prefilled)

    def read_stats(self) -> dict[str, int]:
        """Cumulative read-path counters: store-level tallies merged with
        every live native handle's per-handle counters (retired handles are
        folded into the store tallies at close)."""
        out = dict(self._read_stats)
        for run in self._runs_newest_first():
            if run.native is not None:
                s = run.native.stats()
                out["block_cache_hits"] += s["block_cache_hits"]
                out["block_cache_misses"] += s["block_cache_misses"]
                out["bloom_negatives"] += s["bloom_negatives"]
                out["blocks_decoded"] += s["blocks_decoded"]
        return out

    def _retire_run(self, run: _Run) -> None:
        """Fold a native handle's counters into the store tallies and
        release its image before the run is dropped."""
        h = run.native
        if h is None:
            return
        s = h.stats()
        self._read_stats["block_cache_hits"] += s["block_cache_hits"]
        self._read_stats["block_cache_misses"] += s["block_cache_misses"]
        self._read_stats["bloom_negatives"] += s["bloom_negatives"]
        self._read_stats["blocks_decoded"] += s["blocks_decoded"]
        h.close()
        run.native = None

    def _run_range(self, run: _Run, begin: bytes, end: bytes):
        i = run.first_block_for(begin)
        while i < len(run.index):
            for k, v in self._block(run, i):
                if k < begin:
                    continue
                if k >= end:
                    return
                yield k, v
            i += 1

    def get_range(self, begin: bytes, end: bytes, limit: int = -1,
                  reverse: bool = False) -> list[tuple[bytes, bytes]]:
        if limit == 0:
            return []  # limit semantics: 0 rows; unlimited is limit < 0
        result: dict[bytes, bytes] = {}
        dead: set[bytes] = set()
        shadow: list[tuple[bytes, bytes]] = []

        def fold(pairs, clears):
            for k, v in pairs:
                if k in result or k in dead:
                    continue
                if _covered(k, shadow):
                    dead.add(k)
                    continue
                result[k] = v
            shadow.extend(clears)

        fold(((k, self._mem[k])
              for k in self._mem_index.range_keys(begin, end)),
             self._mem_clears)
        imm = self._imm
        if imm is not None:
            fold(((k, imm.entries[k])
                  for k in imm.index.range_keys(begin, end)), imm.clears)
        for run in self._runs_newest_first():
            fold(self._run_range(run, begin, end), run.clears)
        items = sorted(result.items(), reverse=reverse)
        if limit > 0:
            items = items[:limit]
        return items

    # -- durability --

    def commit(self) -> None:
        if self._pending:
            payload = wire.dumps(self._pending)
            self.queue.push(payload)
            self._wal_bytes += len(payload)
            self._pending = []
        self.queue.commit()

    # -- maintenance: plan on-loop, build off-loop, apply on-loop --

    def maintenance_due(self) -> bool:
        if self._plan_active:
            return False
        budget = KNOBS.REDWOOD_MEMTABLE_BYTES
        if self._imm is not None:
            return True
        if self._mem_bytes >= budget:
            return True
        # metadata-only churn (durable-version bumps) never fills the
        # memtable but grows the WAL forever; flush to reclaim it
        if self._wal_bytes >= 8 * budget and self.queue.live_entries:
            return True
        fan_in = KNOBS.REDWOOD_COMPACTION_FAN_IN
        return any(len(runs) >= fan_in for runs in self._levels.values())

    def plan_maintenance(self) -> MaintenancePlan | None:
        """Freeze inputs and return the next unit of work (None when
        nothing is due). One plan may be outstanding at a time."""
        if self._plan_active or not self.maintenance_due():
            return None
        if self._imm is None and (
                self._mem_bytes >= KNOBS.REDWOOD_MEMTABLE_BYTES
                or self._wal_bytes >= 8 * KNOBS.REDWOOD_MEMTABLE_BYTES):
            self._freeze()
        if self._imm is not None:
            return self._plan_flush()
        fan_in = KNOBS.REDWOOD_COMPACTION_FAN_IN
        for level in sorted(self._levels):
            if len(self._levels[level]) >= fan_in:
                return self._plan_compact(level)
        return None

    def _freeze(self):
        self._imm = _Frozen(entries=self._mem, index=self._mem_index,
                            clears=self._mem_clears, meta=dict(self._meta),
                            wal_upto=self.queue.next_seq)
        self._mem = {}
        self._mem_index = self._make_index()
        self._mem_clears = []
        self._mem_bytes = 0
        self._wal_bytes = 0

    def _plan_flush(self) -> MaintenancePlan:
        imm = self._imm
        run_id = self._next_run_id
        self._next_run_id += 1
        self._plan_active = True
        entries = sorted(imm.entries.items())
        block_bytes = KNOBS.REDWOOD_BLOCK_BYTES

        def build(entries=entries, clears=list(imm.clears),
                  meta=imm.meta, run_id=run_id, block_bytes=block_bytes):
            return build_run_image(entries, clears, meta, run_id=run_id,
                                   meta_seq=run_id, level=0, sources=(),
                                   block_bytes=block_bytes)

        return MaintenancePlan(kind="flush", run_id=run_id, level=0,
                               build=build, wal_upto=imm.wal_upto)

    def _plan_compact(self, level: int) -> MaintenancePlan:
        runs = list(self._levels[level])  # newest-first
        run_id = self._next_run_id
        self._next_run_id += 1
        self._plan_active = True
        # tombstones can be dropped only when nothing older remains below
        drop = not any(self._levels.get(deeper)
                       for deeper in self._levels if deeper > level)
        readers = [(r.meta_seq, r.clears, r.meta,
                    lambda r=r: r.raw if r.raw is not None else
                    r.file.read_all())
                   for r in runs]
        block_bytes = KNOBS.REDWOOD_BLOCK_BYTES
        source_ids = tuple(r.run_id for r in runs)

        def build(readers=readers, run_id=run_id, level=level, drop=drop,
                  source_ids=source_ids, block_bytes=block_bytes):
            merged: dict[bytes, bytes] = {}
            decided: set[bytes] = set()
            shadow: list[tuple[bytes, bytes]] = []
            all_clears: list[tuple[bytes, bytes]] = []
            for _ms, clears, _meta, read in readers:  # newest -> oldest
                run = parse_run(read(), file=None, name="")
                if run is None:
                    raise FDBError("file_corrupt",
                                   "redwood compaction source unreadable")
                for i in range(len(run.index)):
                    for k, v in decode_block(run.read_block_bytes(i)):
                        if k in decided:
                            continue
                        decided.add(k)
                        if _covered(k, shadow):
                            continue
                        merged[k] = v
                shadow.extend(clears)
                all_clears.extend(clears)
            meta_seq = max(ms for ms, _c, _m, _r in readers)
            meta = max(readers, key=lambda t: t[0])[2]
            out_clears = [] if drop else all_clears
            return build_run_image(sorted(merged.items()), out_clears, meta,
                                   run_id=run_id, meta_seq=meta_seq,
                                   level=level + 1, sources=source_ids,
                                   block_bytes=block_bytes)

        return MaintenancePlan(kind="compact", run_id=run_id,
                               level=level + 1, build=build,
                               source_ids=source_ids, drop_tombstones=drop)

    def apply_maintenance(self, plan: MaintenancePlan, image: bytes) -> None:
        """Install a built run: append+sync the file, THEN reclaim (WAL pop
        / source truncation) — the ordering the crash-safety argument in the
        module docstring depends on."""
        name = f"rw.{plan.run_id}"
        f = self._open_file(name)
        f.truncate()  # a crashed earlier attempt may have left a partial
        f.append(image)
        f.sync()
        run = parse_run(f.read_all() if not hasattr(f, "read_range")
                        else image, f, name)
        if run is None:  # pragma: no cover — image was built by us
            self._plan_active = False
            raise FDBError("io_error", "freshly written redwood run invalid")
        if hasattr(f, "read_range"):
            run.raw = None
        self._levels.setdefault(run.level, []).insert(0, run)
        if plan.kind == "flush":
            self._imm = None
            self.queue.pop(plan.wal_upto)
        else:
            drop = set(plan.source_ids)
            for level in list(self._levels):
                kept = [r for r in self._levels[level]
                        if r.run_id not in drop or r is run]
                for r in self._levels[level]:
                    if r.run_id in drop and r is not run:
                        self._retire_run(r)
                        r.file.truncate()
                self._levels[level] = kept
                if not kept:
                    del self._levels[level]
            for ck in [ck for ck in self._block_cache if ck[0] in drop]:
                del self._block_cache[ck]
        self._plan_active = False

    def maintain(self) -> int:
        """Synchronously drain all due maintenance (tests, benches, and
        engines used outside an actor loop). Returns plans applied."""
        n = 0
        while True:
            plan = self.plan_maintenance()
            if plan is None:
                return n
            self.apply_maintenance(plan, plan.build())
            n += 1

    # -- recovery --

    def recover(self) -> None:
        self._mem = {}
        self._mem_index = self._make_index()
        self._mem_clears = []
        self._mem_bytes = 0
        self._imm = None
        self._meta = {}
        self._pending = []
        self._levels = {}
        self._wal_bytes = 0
        self._plan_active = False
        self._block_cache = {}
        runs: list[_Run] = []
        for name in sorted(set(self._existing_files())):
            if not name.startswith("rw."):
                continue
            f = self._open_file(name)
            run = parse_run(f.read_all(), f, name)
            if run is not None:
                runs.append(run)
            else:
                f.truncate()  # torn/foreign: reclaim the space
        # a surviving compacted run supersedes its sources — a crash between
        # the merged run's sync and the source truncation leaves both, and
        # keeping both would double-count tombstone shadowing
        superseded = {s for r in runs for s in r.sources}
        for r in runs:
            if r.run_id in superseded:
                self._retire_run(r)
                r.file.truncate()
        runs = [r for r in runs if r.run_id not in superseded]
        for r in sorted(runs, key=lambda r: r.run_id, reverse=True):
            self._levels.setdefault(r.level, []).append(r)
        self._next_run_id = max((r.run_id for r in runs), default=0) + 1
        if runs:
            self._meta = dict(max(runs, key=lambda r: r.meta_seq).meta)
        for _seq, payload in self.queue.recover():
            try:
                ops = wire.loads(payload)
            except wire.WireError as e:
                raise FDBError("file_corrupt",
                               f"redwood WAL entry undecodable: {e}")
            for op in ops:
                if op[0] == _OP_SET:
                    self._apply_set(op[1], op[2])
                elif op[0] == _OP_CLEAR:
                    self._apply_clear(op[1], op[2])
                elif op[0] == _OP_META:
                    self._meta[op[1]] = op[2]
            self._wal_bytes += len(payload)

    # -- introspection (tests / benches) --

    def run_names(self) -> list[str]:
        return [r.name for r in self._runs_newest_first()]

    def level_shape(self) -> dict[int, int]:
        return {lv: len(rs) for lv, rs in sorted(self._levels.items())}
