"""Durable storage primitives: DiskQueue WAL and pluggable KV engines.

Reference layer: fdbserver/DiskQueue.actor.cpp (durable append-only queue of
two alternating checksummed files), fdbserver/IKeyValueStore.h (engine
interface), fdbserver/KeyValueStoreMemory.actor.cpp (hashmap + WAL/snapshot
memory engine), fdbserver/KeyValueStoreSQLite.actor.cpp (ssd B-tree engine).
"""

from foundationdb_tpu.storage.diskqueue import DiskQueue
from foundationdb_tpu.storage.kvstore import (
    IKeyValueStore, MemoryKeyValueStore, SSDKeyValueStore, open_kv_store)

__all__ = [
    "DiskQueue",
    "IKeyValueStore",
    "MemoryKeyValueStore",
    "SSDKeyValueStore",
    "open_kv_store",
]
