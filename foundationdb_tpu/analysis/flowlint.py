"""flowlint: AST-based actor-discipline & determinism analyzer.

The deterministic simulator (core/sim.py, after fdbrpc/sim2.actor.cpp) only
delivers its replay guarantee if no actor code smuggles in wall-clock time,
OS randomness, or settle-skipping control flow. This engine walks Python
sources, runs a registry of rules (rules.py, FLOW001..FLOW006) over each
module's AST, and diffs the findings against a checked-in baseline of
documented grandfathered violations — so every new violation fails tier-1
(tests/test_flowlint.py) the moment it is written.

Engine pieces:
  - Finding: one violation, with a line-number-independent identity key
    (rule, path, enclosing symbol, detail) so baselines survive edits.
  - ModuleContext: parsed module + parent links + qualname/suppression
    helpers shared by all rules.
  - Rule: base class; rules self-register via @register.
  - analyze_source / analyze_paths: run the registry over snippets or trees.
  - baseline load/apply/write: the allowlist workflow
    (`python -m foundationdb_tpu.analysis --update-baseline`).

Inline suppression: a line containing `# flowlint: ignore[FLOW00X]` (or
`ignore[all]`) is exempt — for the rare spot where the rule's static
approximation is provably wrong and a baseline entry would be noise.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Iterable, Iterator

PACKAGE_NAME = "foundationdb_tpu"

# Subpackages whose coroutines are sim-visible: they run under the
# deterministic loop and must draw time/randomness from it.
SIM_VISIBLE = ("core", "server", "net")


@dataclass(frozen=True)
class Finding:
    rule: str       # "FLOW001"
    path: str       # package-rooted posix path, e.g. foundationdb_tpu/server/resolver.py
    line: int
    symbol: str     # enclosing qualname ("Resolver._drain_group") or "<module>"
    detail: str     # stable token for baseline identity (offending name/attr)
    message: str

    @property
    def key(self) -> str:
        """Line-number-independent identity used for baseline matching."""
        return f"{self.rule}:{self.path}:{self.symbol}:{self.detail}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "detail": self.detail,
                "message": self.message}


class ModuleContext:
    """One parsed module plus the derived maps every rule needs."""

    def __init__(self, relpath: str, source: str, tree: ast.Module):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    # -- path classification --

    @property
    def subpackage(self) -> str:
        """First directory under the package root ("server", "core", ...)."""
        parts = self.relpath.split("/")
        if parts and parts[0] == PACKAGE_NAME:
            parts = parts[1:]
        return parts[0] if len(parts) > 1 else ""

    @property
    def sim_visible(self) -> bool:
        return self.subpackage in SIM_VISIBLE

    # -- tree helpers --

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        """Nearest enclosing def/async def, or None at module/class level."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def qualname(self, node: ast.AST) -> str:
        names = [anc.name for anc in self.ancestors(node)
                 if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef))]
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.insert(0, node.name)
        return ".".join(reversed(names)) or "<module>"

    def suppressed(self, line: int, rule: str) -> bool:
        if not 1 <= line <= len(self.lines):
            return False
        text = self.lines[line - 1]
        if "flowlint:" not in text:
            return False
        tag = text.split("flowlint:", 1)[1]
        return f"ignore[{rule}]" in tag or "ignore[all]" in tag

    # -- import resolution (aliases -> dotted module names) --

    @property
    def import_aliases(self) -> dict[str, str]:
        """Maps local name -> dotted origin: `import time` -> {"time":
        "time"}; `import jax.numpy as jnp` -> {"jnp": "jax.numpy"};
        `from time import sleep` -> {"sleep": "time.sleep"}."""
        cached = getattr(self, "_aliases", None)
        if cached is not None:
            return cached
        aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
                    if a.asname is None and "." in a.name:
                        # `import jax.numpy` binds "jax" but makes the
                        # submodule reachable as jax.numpy — record the root
                        aliases[a.name.split(".")[0]] = a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        self._aliases = aliases
        return aliases

    def resolve_dotted(self, node: ast.AST) -> str | None:
        """Dotted origin of a Name/Attribute chain, through import aliases:
        with `import time as t`, `t.sleep` resolves to "time.sleep"."""
        parts: list[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        origin = self.import_aliases.get(cur.id)
        if origin is None:
            return None
        parts.append(origin)
        return ".".join(reversed(parts))


class Rule:
    """One check. Subclasses set `code`/`summary` and implement check()."""

    code = "FLOW000"
    summary = ""

    def check(self, mod: ModuleContext) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, mod: ModuleContext, node: ast.AST, detail: str,
                message: str) -> Finding:
        return Finding(rule=self.code, path=mod.relpath,
                       line=getattr(node, "lineno", 0),
                       symbol=mod.qualname(node), detail=detail,
                       message=message)


_REGISTRY: list[type[Rule]] = []


def register(cls: type[Rule]) -> type[Rule]:
    _REGISTRY.append(cls)
    return cls


def active_rules() -> list[Rule]:
    # rules.py populates the registry on import
    from foundationdb_tpu.analysis import rules  # noqa: F401
    return [cls() for cls in sorted(_REGISTRY, key=lambda c: c.code)]


# ---------------------------------------------------------------- running

def analyze_source(source: str, relpath: str,
                   rules: list[Rule] | None = None) -> list[Finding]:
    """Run the registry over one module's source (tests feed snippets here;
    `relpath` decides path-scoped rules like FLOW001)."""
    tree = ast.parse(source)
    mod = ModuleContext(relpath, source, tree)
    out: list[Finding] = []
    for rule in (rules if rules is not None else active_rules()):
        for f in rule.check(mod):
            if not mod.suppressed(f.line, f.rule):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def canonical_relpath(abspath: str) -> str:
    """Package-rooted path for baseline stability: the same file keys
    identically no matter what directory the analyzer was launched from."""
    parts = os.path.abspath(abspath).replace(os.sep, "/").split("/")
    if PACKAGE_NAME in parts:
        return "/".join(parts[parts.index(PACKAGE_NAME):])
    return os.path.relpath(abspath).replace(os.sep, "/")


def iter_py_files(path: str) -> Iterator[str]:
    if os.path.isfile(path):
        yield path
        return
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs
                         if d not in ("__pycache__", ".git"))
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(root, name)


def analyze_paths(paths: list[str],
                  rules: list[Rule] | None = None) -> list[Finding]:
    rules = rules if rules is not None else active_rules()
    out: list[Finding] = []
    for path in paths:
        for file in iter_py_files(path):
            with open(file, encoding="utf-8") as f:
                source = f.read()
            try:
                out.extend(analyze_source(source, canonical_relpath(file),
                                          rules))
            except SyntaxError as e:
                out.append(Finding(
                    rule="FLOW000", path=canonical_relpath(file),
                    line=e.lineno or 0, symbol="<module>",
                    detail="syntax-error",
                    message=f"could not parse: {e.msg}"))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


# ---------------------------------------------------------------- baseline

@dataclass
class Baseline:
    """Allowlist of grandfathered findings. Every entry must carry a
    non-empty `reason` documenting why it is tolerated — update-baseline
    inserts a FIXME placeholder that the tier-1 test rejects."""

    path: str | None = None
    entries: list[dict] = field(default_factory=list)

    @property
    def keys(self) -> set[str]:
        return {_entry_key(e) for e in self.entries}


def _entry_key(entry: dict) -> str:
    return (f"{entry['rule']}:{entry['path']}:{entry['symbol']}:"
            f"{entry['detail']}")


def load_baseline(path: str | None) -> Baseline:
    if path is None or not os.path.exists(path):
        return Baseline(path=path)
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return Baseline(path=path, entries=list(data.get("entries", [])))


def apply_baseline(findings: list[Finding],
                   baseline: Baseline) -> tuple[list[Finding], list[dict]]:
    """-> (new findings not in the baseline, stale entries matching nothing)."""
    keys = baseline.keys
    new = [f for f in findings if f.key not in keys]
    live = {f.key for f in findings}
    stale = [e for e in baseline.entries if _entry_key(e) not in live]
    return new, stale


def write_baseline(path: str, findings: list[Finding],
                   old: Baseline) -> Baseline:
    """Regenerate the baseline from current findings, carrying forward the
    documented reasons of entries that still match."""
    reasons = {_entry_key(e): e.get("reason", "") for e in old.entries}
    entries, seen = [], set()
    for f in findings:
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append({
            "rule": f.rule, "path": f.path, "symbol": f.symbol,
            "detail": f.detail,
            "reason": reasons.get(f.key) or "FIXME: document why this is safe",
        })
    data = {"version": 1, "entries": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    return Baseline(path=path, entries=entries)


# ---------------------------------------------------------------- output

def format_text(findings: list[Finding]) -> str:
    return "\n".join(f"{f.path}:{f.line}: {f.rule} [{f.symbol}] {f.message}"
                     for f in findings)


def format_json(findings: list[Finding]) -> str:
    return json.dumps({"findings": [f.as_dict() for f in findings]},
                      indent=2, sort_keys=True)


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "flowlint_baseline.json")


def default_target() -> str:
    """The package directory itself (analyze everything)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
