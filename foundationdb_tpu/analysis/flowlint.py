"""flowlint: AST-based actor-discipline & determinism analyzer.

The deterministic simulator (core/sim.py, after fdbrpc/sim2.actor.cpp) only
delivers its replay guarantee if no actor code smuggles in wall-clock time,
OS randomness, or settle-skipping control flow. This engine walks Python
sources, runs a registry of rules over each module's AST — plus a
package-level pass for interprocedural rules — and diffs the findings
against a checked-in baseline of documented grandfathered violations, so
every new violation fails tier-1 the moment it is written.

Four rule families ride the engine:
  - flow (rules.py, FLOW001..FLOW006): actor discipline & determinism,
    enforced by tests/test_flowlint.py.
  - dev (devlint.py, DEV001..DEV008): JAX/device discipline on the hot
    path (readbacks, re-traces, transfer choke points), enforced by
    tests/test_devlint.py.
  - proto (protolint.py, PROTO001..PROTO008): protocol conformance on the
    RPC/wire layer (token routing, reply-on-all-paths, Python<->C schema
    parity), enforced by tests/test_protolint.py.
  - nat (natlint.py, NAT001..NAT007): native C-extension discipline over
    native/fdb_native.c itself (refcount balance on goto ladders, bounds
    checks, decoded-count validation), via the csource.py C front-end;
    enforced by tests/test_natlint.py alongside the ASan/UBSan fuzz
    harness (scripts/build_native.sh --sanitize).

Engine pieces:
  - Finding: one violation, with a line-number-independent identity key
    (rule, path, enclosing symbol, detail) so baselines survive edits.
  - ModuleContext: parsed module + parent links + qualname/suppression
    helpers shared by all rules.
  - PackageContext (callgraph.py): whole-target-set parse + call-site
    resolution, for rules whose evidence crosses module boundaries.
  - Rule: base class; rules self-register via @register and may implement
    check() (per module), check_package() (whole package), or both.
  - analyze_source / analyze_paths: run the registry over snippets or trees.
  - baseline load/apply/write: the allowlist workflow
    (`python -m foundationdb_tpu.analysis --update-baseline`), with a
    fuzzy second matching tier so renaming an enclosing function does not
    orphan its documented entries.

Inline suppression: a line containing `# flowlint: ignore[FLOW00X]` (or
`# devlint: ignore[DEV00X]`, `# protolint: ignore[PROTO00X]`,
`ignore[all]`, or a comma-separated code list) is exempt — for the rare spot where the rule's static approximation
is provably wrong and a baseline entry would be noise.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Iterator

PACKAGE_NAME = "foundationdb_tpu"

# Subpackages whose coroutines are sim-visible: they run under the
# deterministic loop and must draw time/randomness from it. testing/ hosts
# the simulated-cluster workloads — sim-visible code in every sense.
SIM_VISIBLE = ("core", "server", "net", "testing")

FAMILIES = ("flow", "dev", "proto", "nat")


def rule_family(code: str) -> str:
    """Family of a rule code: DEV* -> "dev", PROTO* -> "proto", NAT* ->
    "nat", everything else -> "flow"."""
    if code.startswith("DEV"):
        return "dev"
    if code.startswith("PROTO"):
        return "proto"
    if code.startswith("NAT"):
        return "nat"
    return "flow"


@dataclass(frozen=True)
class Finding:
    rule: str       # "FLOW001"
    path: str       # package-rooted posix path, e.g. foundationdb_tpu/server/resolver.py
    line: int
    symbol: str     # enclosing qualname ("Resolver._drain_group") or "<module>"
    detail: str     # stable token for baseline identity (offending name/attr)
    message: str

    @property
    def key(self) -> str:
        """Line-number-independent identity used for baseline matching."""
        return f"{self.rule}:{self.path}:{self.symbol}:{self.detail}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "detail": self.detail,
                "message": self.message}


class ModuleContext:
    """One parsed module plus the derived maps every rule needs."""

    def __init__(self, relpath: str, source: str, tree: ast.Module):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    # -- path classification --

    @property
    def subpackage(self) -> str:
        """First directory under the package root ("server", "core", ...)."""
        parts = self.relpath.split("/")
        if parts and parts[0] == PACKAGE_NAME:
            parts = parts[1:]
        return parts[0] if len(parts) > 1 else ""

    @property
    def sim_visible(self) -> bool:
        return self.subpackage in SIM_VISIBLE

    # -- tree helpers --

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def enclosing_function(self, node: ast.AST):
        """Nearest enclosing def/async def, or None at module/class level."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def qualname(self, node: ast.AST) -> str:
        names = [anc.name for anc in self.ancestors(node)
                 if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef))]
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.insert(0, node.name)
        return ".".join(reversed(names)) or "<module>"

    def suppressed(self, line: int, rule: str) -> bool:
        """`# flowlint: ignore[FLOW001]` / `# devlint: ignore[DEV007]` /
        `ignore[FLOW001,FLOW002]` / `ignore[all]`. Either tag word accepts
        either family's codes — the split exists for greppability, not
        scoping."""
        if not 1 <= line <= len(self.lines):
            return False
        text = self.lines[line - 1]
        for marker in ("flowlint:", "devlint:", "protolint:"):
            if marker not in text:
                continue
            tag = text.split(marker, 1)[1]
            m = re.search(r"ignore\[([^\]]*)\]", tag)
            if m is None:
                continue
            codes = {c.strip() for c in m.group(1).split(",")}
            if "all" in codes or rule in codes:
                return True
        return False

    # -- import resolution (aliases -> dotted module names) --

    @property
    def import_aliases(self) -> dict[str, str]:
        """Maps local name -> dotted origin: `import time` -> {"time":
        "time"}; `import jax.numpy as jnp` -> {"jnp": "jax.numpy"};
        `from time import sleep` -> {"sleep": "time.sleep"}."""
        cached = getattr(self, "_aliases", None)
        if cached is not None:
            return cached
        aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
                    if a.asname is None and "." in a.name:
                        # `import jax.numpy` binds "jax" but makes the
                        # submodule reachable as jax.numpy — record the root
                        aliases[a.name.split(".")[0]] = a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        self._aliases = aliases
        return aliases

    def resolve_dotted(self, node: ast.AST) -> str | None:
        """Dotted origin of a Name/Attribute chain, through import aliases:
        with `import time as t`, `t.sleep` resolves to "time.sleep"."""
        parts: list[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        origin = self.import_aliases.get(cur.id)
        if origin is None:
            return None
        parts.append(origin)
        return ".".join(reversed(parts))


class Rule:
    """One check. Subclasses set `code`/`summary` and implement check()
    (per-module) and/or check_package() (whole-target-set, for rules whose
    evidence crosses module boundaries)."""

    code = "FLOW000"
    summary = ""

    @property
    def family(self) -> str:
        return rule_family(self.code)

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        return ()

    def check_package(self, pkg) -> Iterable[Finding]:
        """pkg is a callgraph.PackageContext over every analyzed module."""
        return ()

    def finding(self, mod: ModuleContext, node: ast.AST, detail: str,
                message: str) -> Finding:
        return Finding(rule=self.code, path=mod.relpath,
                       line=getattr(node, "lineno", 0),
                       symbol=mod.qualname(node), detail=detail,
                       message=message)


_REGISTRY: list[type[Rule]] = []


def register(cls: type[Rule]) -> type[Rule]:
    _REGISTRY.append(cls)
    return cls


def active_rules(family: str = "all") -> list[Rule]:
    # importing the rule modules populates the registry
    from foundationdb_tpu.analysis import (  # noqa: F401
        devlint, natlint, protolint, rules)
    out = [cls() for cls in sorted(_REGISTRY, key=lambda c: c.code)]
    if family != "all":
        out = [r for r in out if r.family == family]
    return out


# ---------------------------------------------------------------- running

def _run_rules(mods: list[ModuleContext],
               rules: list[Rule]) -> list[Finding]:
    """Per-module checks + one package pass, suppression-filtered."""
    from foundationdb_tpu.analysis.callgraph import PackageContext
    pkg = PackageContext(mods)
    by_path = {m.relpath: m for m in mods}
    out: list[Finding] = []
    for rule in rules:
        found: list[Finding] = []
        for mod in mods:
            found.extend(rule.check(mod))
        found.extend(rule.check_package(pkg))
        for f in found:
            owner = by_path.get(f.path)
            if owner is None or not owner.suppressed(f.line, f.rule):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def analyze_source(source: str, relpath: str,
                   rules: list[Rule] | None = None) -> list[Finding]:
    """Run the registry over one module's source (tests feed snippets here;
    `relpath` decides path-scoped rules like FLOW001). Package rules see a
    one-module package."""
    tree = ast.parse(source)
    mod = ModuleContext(relpath, source, tree)
    return _run_rules([mod], rules if rules is not None else active_rules())


def canonical_relpath(abspath: str) -> str:
    """Package-rooted path for baseline stability: the same file keys
    identically no matter what directory the analyzer was launched from.
    Repo-level `scripts/` files anchor at the scripts dir the same way."""
    parts = os.path.abspath(abspath).replace(os.sep, "/").split("/")
    if PACKAGE_NAME in parts:
        return "/".join(parts[parts.index(PACKAGE_NAME):])
    if "scripts" in parts:
        return "/".join(parts[parts.index("scripts"):])
    return os.path.relpath(abspath).replace(os.sep, "/")


def iter_py_files(path: str) -> Iterator[str]:
    if os.path.isfile(path):
        yield path
        return
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs
                         if d not in ("__pycache__", ".git"))
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(root, name)


def analyze_paths(paths: list[str],
                  rules: list[Rule] | None = None) -> list[Finding]:
    """Parse every target file first, then run the registry over the whole
    set as ONE package — interprocedural rules see cross-module calls."""
    rules = rules if rules is not None else active_rules()
    mods: list[ModuleContext] = []
    out: list[Finding] = []
    seen: set[str] = set()
    for path in paths:
        for file in iter_py_files(path):
            relpath = canonical_relpath(file)
            if relpath in seen:
                continue
            seen.add(relpath)
            with open(file, encoding="utf-8") as f:
                source = f.read()
            try:
                mods.append(ModuleContext(relpath, source,
                                          ast.parse(source)))
            except SyntaxError as e:
                out.append(Finding(
                    rule="FLOW000", path=relpath,
                    line=e.lineno or 0, symbol="<module>",
                    detail="syntax-error",
                    message=f"could not parse: {e.msg}"))
    out.extend(_run_rules(mods, rules))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


# ---------------------------------------------------------------- baseline

@dataclass
class Baseline:
    """Allowlist of grandfathered findings. Every entry must carry a
    non-empty `reason` documenting why it is tolerated — update-baseline
    inserts a FIXME placeholder that the tier-1 test rejects."""

    path: str | None = None
    entries: list[dict] = field(default_factory=list)

    @property
    def keys(self) -> set[str]:
        return {_entry_key(e) for e in self.entries}


def _entry_key(entry: dict) -> str:
    return (f"{entry['rule']}:{entry['path']}:{entry['symbol']}:"
            f"{entry['detail']}")


def load_baseline(path: str | None) -> Baseline:
    if path is None or not os.path.exists(path):
        return Baseline(path=path)
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return Baseline(path=path, entries=list(data.get("entries", [])))


def _fuzzy_key(rule: str, path: str, detail: str) -> str:
    return f"{rule}:{path}:{detail}"


def apply_baseline(findings: list[Finding], baseline: Baseline,
                   families: set[str] | None = None,
                   ) -> tuple[list[Finding], list[dict]]:
    """-> (new findings not in the baseline, stale entries matching nothing).

    Matching is two-tier: exact identity key first, then (rule, path,
    detail) — so renaming the enclosing function (or moving the line) does
    not orphan a documented entry. The fuzzy tier is count-aware: two
    findings cannot both consume one entry.

    `families` restricts which baseline entries participate: a
    `--family flow` run must neither report the dev entries stale nor vice
    versa.
    """
    entries = [e for e in baseline.entries
               if families is None or rule_family(e["rule"]) in families]
    exact = {_entry_key(e) for e in entries}
    live = {f.key for f in findings}
    matched: set[int] = set()  # indexes of entries consumed (exact or fuzzy)
    for i, e in enumerate(entries):
        if _entry_key(e) in live:
            matched.add(i)
    # fuzzy tier: unmatched findings vs unmatched entries by (rule, path,
    # detail), greedy one-to-one
    fuzzy_pool: dict[str, list[int]] = {}
    for i, e in enumerate(entries):
        if i not in matched:
            fuzzy_pool.setdefault(
                _fuzzy_key(e["rule"], e["path"], e["detail"]), []).append(i)
    new: list[Finding] = []
    for f in findings:
        if f.key in exact:
            continue
        pool = fuzzy_pool.get(_fuzzy_key(f.rule, f.path, f.detail))
        if pool:
            matched.add(pool.pop(0))
            continue
        new.append(f)
    stale = [e for i, e in enumerate(entries) if i not in matched]
    return new, stale


def write_baseline(path: str, findings: list[Finding], old: Baseline,
                   families: set[str] | None = None) -> Baseline:
    """Regenerate the baseline from current findings, carrying forward the
    documented reasons of entries that still match (exactly, or fuzzily by
    (rule, path, detail) after a rename). Entries of families NOT in this
    run are preserved verbatim — a flow-only update cannot drop dev
    grandfathers."""
    reasons = {_entry_key(e): e.get("reason", "") for e in old.entries}
    fuzzy_reasons = {
        _fuzzy_key(e["rule"], e["path"], e["detail"]): e.get("reason", "")
        for e in old.entries}
    entries, seen = [], set()
    for e in old.entries:
        if families is not None and rule_family(e["rule"]) not in families:
            entries.append(dict(e))
            seen.add(_entry_key(e))
    for f in findings:
        if families is not None and rule_family(f.rule) not in families:
            continue
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append({
            "rule": f.rule, "path": f.path, "symbol": f.symbol,
            "detail": f.detail,
            "reason": reasons.get(f.key)
            or fuzzy_reasons.get(_fuzzy_key(f.rule, f.path, f.detail))
            or "FIXME: document why this is safe",
        })
    entries.sort(key=lambda e: (e["rule"], e["path"], e["symbol"],
                                e["detail"]))
    data = {"version": 1, "entries": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    return Baseline(path=path, entries=entries)


# ---------------------------------------------------------------- output

def format_text(findings: list[Finding]) -> str:
    return "\n".join(f"{f.path}:{f.line}: {f.rule} [{f.symbol}] {f.message}"
                     for f in findings)


def format_json(findings: list[Finding]) -> str:
    return json.dumps({"findings": [f.as_dict() for f in findings]},
                      indent=2, sort_keys=True)


def format_github(findings: list[Finding]) -> str:
    """GitHub Actions workflow annotations: one ::error line per finding,
    rendered inline on the PR diff by the runner."""
    out = []
    for f in findings:
        msg = f.message.replace("%", "%25").replace("\n", "%0A")
        out.append(f"::error file={f.path},line={f.line},"
                   f"title={f.rule} [{f.symbol}]::{msg}")
    return "\n".join(out)


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "flowlint_baseline.json")


def default_target() -> str:
    """The package directory itself (analyze everything)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_targets() -> list[str]:
    """Package dir + the repo-level scripts/ dir when it exists: profiling
    and A/B harness scripts drive the same device code paths the package
    rules protect."""
    pkg = default_target()
    scripts = os.path.join(os.path.dirname(pkg), "scripts")
    return [pkg] + ([scripts] if os.path.isdir(scripts) else [])
