"""csource: a lightweight C front-end for natlint (no compiler involved).

natlint (the fourth rule family, NAT001..NAT007) reads native/fdb_native.c —
hand-written CPython extension code whose whole failure class is structural:
a `goto err` ladder that releases one ref too few, a `memcpy` off the end of
a Py_buffer, a decoded count trusted before validation. Those properties
live in the *shape* of each function (which statement dominates which, what
a goto ladder releases on the way out), not in the token stream — so this
module builds just enough structure to ask shape questions:

  - tokenize(): comments / strings / chars / identifiers / numbers /
    punctuation, with line numbers; preprocessor lines become single 'pp'
    tokens so `#define` bodies can't unbalance the brace tracking.
  - parse_functions(): top-level function definitions with parsed parameter
    lists and a statement tree per body (if/for/while/do/switch/label/goto/
    return/blocks; everything else is a 'simple' statement of flat text).
  - CFunction: pre-order numbering + block paths for a textual dominance
    relation (A dominates B iff A's enclosing block chain is an ancestor of
    B's and A precedes B), goto-ladder resolution (the statements an error
    exit executes on its way to `return NULL`), and exit enumeration.

The model is deliberately approximate — it is a lint front-end, not a
compiler. The approximations are chosen one-sided where it matters: dominance
never claims an if-branch statement covers code after the join, and ladder
resolution follows fallthrough and chained gotos with a cycle guard. The
fixtures in tests/test_csource.py pin the round-trip on the real
fdb_native.c (every brace balanced, every function found) plus the ladder
shapes the NAT rules depend on.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# Object-like CPython macros that appear in statement position WITHOUT a
# trailing semicolon (they expand to `{`-fragments). Anything else that
# looks like a statement must end in ';' or '{'.
BARE_MACROS = ("Py_BEGIN_ALLOW_THREADS", "Py_END_ALLOW_THREADS",
               "Py_BLOCK_THREADS", "Py_UNBLOCK_THREADS")

_PUNCT2 = ("->", "<<", ">>", "<=", ">=", "==", "!=", "&&", "||", "+=",
           "-=", "*=", "/=", "%=", "&=", "|=", "^=", "++", "--")


@dataclass(frozen=True)
class Token:
    kind: str   # 'comment' | 'pp' | 'string' | 'char' | 'ident' | 'num' | 'punct'
    text: str
    line: int


def tokenize(source: str) -> list[Token]:
    """Full-fidelity token stream (comments and preprocessor lines kept as
    their own tokens so suppression scanning and brace tracking both work)."""
    out: list[Token] = []
    i, n, line = 0, len(source), 1
    while i < n:
        c = source[i]
        if c == "\n":
            line += 1
            i += 1
            continue
        if c in " \t\r\v\f":
            i += 1
            continue
        if c == "/" and i + 1 < n and source[i + 1] == "*":
            j = source.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append(Token("comment", source[i:j], line))
            line += source.count("\n", i, j)
            i = j
            continue
        if c == "/" and i + 1 < n and source[i + 1] == "/":
            j = source.find("\n", i)
            j = n if j < 0 else j
            out.append(Token("comment", source[i:j], line))
            i = j
            continue
        if c == "#" and _at_line_start(source, i):
            j = i
            while j < n:
                k = source.find("\n", j)
                if k < 0:
                    k = n
                if source[j:k].rstrip().endswith("\\"):
                    j = k + 1
                else:
                    break
            k = source.find("\n", j)
            k = n if k < 0 else k
            out.append(Token("pp", source[i:k], line))
            line += source.count("\n", i, k)
            i = k
            continue
        if c in "\"'":
            j = i + 1
            while j < n and source[j] != c:
                if source[j] == "\\":
                    j += 1
                j += 1
            j = min(j + 1, n)
            out.append(Token("string" if c == '"' else "char",
                             source[i:j], line))
            line += source.count("\n", i, j)
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (source[j].isalnum() or source[j] == "_"):
                j += 1
            out.append(Token("ident", source[i:j], line))
            i = j
            continue
        if c.isdigit():
            j = i
            while j < n and (source[j].isalnum() or source[j] in "._"
                             or (source[j] in "+-"
                                 and source[j - 1] in "eEpP")):
                j += 1
            out.append(Token("num", source[i:j], line))
            i = j
            continue
        two = source[i:i + 2]
        if two in _PUNCT2:
            out.append(Token("punct", two, line))
            i += 2
            continue
        out.append(Token("punct", c, line))
        i += 1
    return out


def _at_line_start(source: str, i: int) -> bool:
    j = i - 1
    while j >= 0 and source[j] in " \t":
        j -= 1
    return j < 0 or source[j] == "\n"


def code_tokens(tokens: list[Token]) -> list[Token]:
    """The parse stream: comments and preprocessor lines dropped."""
    return [t for t in tokens if t.kind not in ("comment", "pp")]


def suppressions(tokens: list[Token], marker: str = "natlint:"
                 ) -> dict[int, set[str]]:
    """Inline-suppression map from comment tokens: a comment containing
    `natlint: ignore[NAT004]` (comma lists and `all` accepted) suppresses
    on its own line AND the following line, matching the flowlint
    convention of tagging either the offending line or the line above."""
    import re
    out: dict[int, set[str]] = {}
    for t in tokens:
        if t.kind != "comment" or marker not in t.text:
            continue
        m = re.search(r"ignore\[([^\]]*)\]", t.text.split(marker, 1)[1])
        if m is None:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        last = t.line + t.text.count("\n")
        for ln in (t.line, last, last + 1):
            out.setdefault(ln, set()).update(codes)
    return out


# --------------------------------------------------------------- statements

@dataclass
class Stmt:
    """One statement. `text` is the flat token text: the full statement for
    simple/return/goto, the condition (or for-header) for if/for/while/do/
    switch. Numbering fields are filled by CFunction._number()."""

    kind: str            # simple|if|for|while|do|switch|case|label|goto|
    #                      return|break|continue|block
    line: int
    text: str = ""
    label: str = ""      # label/goto target
    body: list["Stmt"] = field(default_factory=list)
    orelse: list["Stmt"] = field(default_factory=list)
    order: int = -1
    block: tuple = ()
    parent: "Stmt | None" = None
    sibs: "list[Stmt] | None" = None  # the sibling list containing self
    idx: int = -1                     # index within sibs

    @property
    def is_loop(self) -> bool:
        return self.kind in ("for", "while", "do")


def _text(tokens: list[Token]) -> str:
    return " ".join(t.text for t in tokens)


class _Parser:
    def __init__(self, tokens: list[Token]):
        self.toks = tokens
        self.i = 0

    def peek(self, k: int = 0) -> Token | None:
        j = self.i + k
        return self.toks[j] if j < len(self.toks) else None

    def take(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def _balanced(self, opener: str, closer: str) -> list[Token]:
        """Consume from an `opener` token through its matching closer;
        returns the inner tokens."""
        assert self.take().text == opener
        depth, inner = 1, []
        while self.i < len(self.toks):
            t = self.take()
            if t.text == opener:
                depth += 1
            elif t.text == closer:
                depth -= 1
                if depth == 0:
                    return inner
            inner.append(t)
        return inner  # unterminated: best effort

    def parse_block(self) -> list[Stmt]:
        """Parse a `{ ... }` whose opening brace is the current token."""
        assert self.take().text == "{"
        out: list[Stmt] = []
        while self.i < len(self.toks):
            t = self.peek()
            if t is None or t.text == "}":
                if t is not None:
                    self.take()
                return out
            out.append(self.parse_stmt())
        return out

    def _body(self) -> list[Stmt]:
        """A statement body: braced block or single statement."""
        t = self.peek()
        if t is not None and t.text == "{":
            return self.parse_block()
        return [self.parse_stmt()]

    def parse_stmt(self) -> Stmt:  # noqa: C901 — a parser is a switch
        t = self.peek()
        line = t.line
        if t.text == "{":
            return Stmt("block", line, body=self.parse_block())
        if t.kind == "ident":
            kw = t.text
            if kw == "if":
                self.take()
                cond = _text(self._balanced("(", ")"))
                body = self._body()
                orelse: list[Stmt] = []
                nxt = self.peek()
                if nxt is not None and nxt.text == "else":
                    self.take()
                    orelse = self._body()
                return Stmt("if", line, text=cond, body=body, orelse=orelse)
            if kw in ("for", "while"):
                self.take()
                cond = _text(self._balanced("(", ")"))
                return Stmt(kw, line, text=cond, body=self._body())
            if kw == "do":
                self.take()
                body = self._body()
                cond = ""
                nxt = self.peek()
                if nxt is not None and nxt.text == "while":
                    self.take()
                    cond = _text(self._balanced("(", ")"))
                    if self.peek() is not None and self.peek().text == ";":
                        self.take()
                return Stmt("do", line, text=cond, body=body)
            if kw == "switch":
                self.take()
                cond = _text(self._balanced("(", ")"))
                return Stmt("switch", line, text=cond, body=self._body())
            if kw in ("case", "default"):
                taken = [self.take()]
                while self.i < len(self.toks) and self.peek().text != ":":
                    taken.append(self.take())
                if self.i < len(self.toks):
                    self.take()  # ':'
                return Stmt("case", line, text=_text(taken))
            if kw == "goto":
                self.take()
                label = self.take().text
                if self.peek() is not None and self.peek().text == ";":
                    self.take()
                return Stmt("goto", line, label=label,
                            text=f"goto {label}")
            if kw == "return":
                self.take()
                toks = self._until_semi()
                return Stmt("return", line, text=_text(toks))
            if kw in ("break", "continue"):
                self.take()
                if self.peek() is not None and self.peek().text == ";":
                    self.take()
                return Stmt(kw, line)
            if kw in BARE_MACROS:
                self.take()
                if self.peek() is not None and self.peek().text == ";":
                    self.take()
                return Stmt("simple", line, text=kw)
            nxt = self.peek(1)
            if nxt is not None and nxt.text == ":" and kw not in (
                    "default",) and (self.peek(2) is None
                                     or self.peek(2).text != ":"):
                # plain `label:` — ternaries never start a statement with
                # `ident :`, so this is unambiguous at statement position
                self.take()
                self.take()
                return Stmt("label", line, label=kw, text=f"{kw}:")
        toks = self._until_semi()
        return Stmt("simple", line, text=_text(toks))

    def _until_semi(self) -> list[Token]:
        """Consume one simple statement: through the next `;` at zero
        paren/brace depth (brace depth covers `int t[2] = {0, 1};`)."""
        out: list[Token] = []
        depth = 0
        while self.i < len(self.toks):
            t = self.peek()
            if depth == 0 and t.text == ";":
                self.take()
                return out
            if depth == 0 and t.text == "}":
                return out  # missing ';' before block close: don't eat it
            if t.text in "({[":
                depth += 1
            elif t.text in ")}]":
                depth -= 1
            out.append(self.take())
        return out


# --------------------------------------------------------------- functions

@dataclass
class CParam:
    type: str
    name: str


@dataclass
class CFunction:
    name: str
    line: int
    params: list[CParam]
    body: list[Stmt]
    static: bool = False
    return_type: str = ""

    def __post_init__(self):
        self.flat: list[Stmt] = []
        self.by_label: dict[str, Stmt] = {}
        self._number(self.body, (), None)

    def _number(self, stmts: list[Stmt], block: tuple, parent: Stmt | None):
        for idx, s in enumerate(stmts):
            s.order = len(self.flat)
            s.block = block
            s.parent = parent
            s.sibs = stmts
            s.idx = idx
            self.flat.append(s)
            if s.kind == "label":
                self.by_label[s.label] = s
            if s.body:
                self._number(s.body, block + (s.order,), s)
            if s.orelse:
                self._number(s.orelse, block + (-s.order - 1,), s)

    # -- shape queries ----------------------------------------------------

    def dominates(self, a: Stmt, b: Stmt) -> bool:
        """Textual dominance: a's enclosing block chain is an ancestor of
        (or equal to) b's, and a precedes b. Sound for the straight-line +
        structured-branch code this file contains; never lets an if-branch
        statement cover code after the join."""
        if a.order >= b.order:
            return False
        return a.block == b.block[:len(a.block)]

    def ancestors(self, s: Stmt):
        cur = s.parent
        while cur is not None:
            yield cur
            cur = cur.parent

    def ladder(self, label: str, _seen: frozenset = frozenset()
               ) -> list[Stmt]:
        """The statements executed after `goto label`: the label's following
        siblings (bodies flattened), falling through further labels and
        chasing chained gotos, up to and including the terminating return."""
        if label in _seen or label not in self.by_label:
            return []
        lab = self.by_label[label]
        out: list[Stmt] = []
        for s in lab.sibs[lab.idx + 1:]:
            out.extend(_flatten([s]))
            if s.kind == "return":
                return out
            if s.kind == "goto":
                return out + self.ladder(s.label, _seen | {label})
        return out

    def exits(self) -> list[tuple[Stmt, list[Stmt], Stmt | None]]:
        """Every (exit statement, path statements run on the way out,
        terminal return or None). Direct returns have an empty path; gotos
        carry their resolved ladder."""
        out = []
        for s in self.flat:
            if s.kind == "return":
                out.append((s, [], s))
            elif s.kind == "goto":
                path = self.ladder(s.label)
                term = next((p for p in reversed(path)
                             if p.kind == "return"), None)
                out.append((s, path, term))
        return out


def _flatten(stmts: list[Stmt]) -> list[Stmt]:
    out = []
    for s in stmts:
        out.append(s)
        out.extend(_flatten(s.body))
        out.extend(_flatten(s.orelse))
    return out


def _split_params(tokens: list[Token]) -> list[CParam]:
    if not tokens or (len(tokens) == 1 and tokens[0].text == "void"):
        return []
    groups: list[list[Token]] = [[]]
    depth = 0
    for t in tokens:
        if t.text in "([":
            depth += 1
        elif t.text in ")]":
            depth -= 1
        if t.text == "," and depth == 0:
            groups.append([])
        else:
            groups[-1].append(t)
    out = []
    for g in groups:
        idents = [t for t in g if t.kind == "ident"]
        if not idents:
            continue
        name = idents[-1].text
        type_toks = [t.text for t in g[:-1]] if g and g[-1].kind == "ident" \
            else [t.text for t in g if t is not idents[-1]]
        out.append(CParam(type=" ".join(type_toks), name=name))
    return out


def parse_functions(source: str) -> list[CFunction]:
    """Top-level function definitions. The match shape is
    `<type tokens> name ( params ) {` at zero brace depth — initializer
    braces and struct/typedef bodies are skipped wholesale."""
    toks = code_tokens(tokenize(source))
    out: list[CFunction] = []
    i, n = 0, len(toks)
    depth = 0
    while i < n:
        t = toks[i]
        if t.text == "{":
            depth += 1
            i += 1
            continue
        if t.text == "}":
            depth -= 1
            i += 1
            continue
        if depth == 0 and t.kind == "ident" and i + 1 < n \
                and toks[i + 1].text == "(" \
                and i > 0 and (toks[i - 1].kind == "ident"
                               or toks[i - 1].text == "*"):
            # find the matching ')' of the parameter list
            j, d = i + 1, 0
            while j < n:
                if toks[j].text == "(":
                    d += 1
                elif toks[j].text == ")":
                    d -= 1
                    if d == 0:
                        break
                j += 1
            if j + 1 < n and toks[j + 1].text == "{":
                # return type: the declaration tokens before the name
                k = i - 1
                while k >= 0 and (toks[k].kind == "ident"
                                  or toks[k].text == "*"):
                    k -= 1
                decl = [x.text for x in toks[k + 1:i]]
                params = _split_params(toks[i + 2:j])
                # body: parse the brace block starting at j+1
                p = _Parser(toks[j + 1:])
                body = p.parse_block()
                out.append(CFunction(
                    name=t.text, line=t.line, params=params, body=body,
                    static="static" in decl,
                    return_type=" ".join(x for x in decl
                                         if x != "static")))
                i = j + 1 + p.i
                continue
        i += 1
    return out
