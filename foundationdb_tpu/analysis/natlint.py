"""natlint rules NAT001..NAT007: native C-extension discipline.

flowlint guards actor code, devlint the device hot path, protolint the wire
contract — but native/fdb_native.c (the zero-Python data plane: CRC, block
codec, wire codec, conflict-range encoder, OMap/VStore skiplists) was only
covered indirectly, by parity fuzzes that notice divergence, not memory
errors. This family reads the C itself through the csource front-end and
checks each function's *shape*:

  NAT001  allocation results (malloc / PyMem_* / PyBytes_FromStringAndSize)
          used before any NULL test.
  NAT002  refcount balance on error paths: every `goto err*` ladder or
          early `return NULL`/-1 must release exactly the owned refs
          acquired so far (new-ref acquisitions tracked through loop
          conditions; stolen-ref stores, returns and alias stores end
          ownership; Py_XDECREF in the resolved goto ladder counts).
  NAT003  error returns of fallible CPython calls ignored — including the
          PyLong_As* family whose -1 is ambiguous without PyErr_Occurred().
  NAT004  raw buffer access with no dominating bounds check: memcpy /
          pointer arithmetic on Py_buffer-derived pointers outside a
          dominating `.len` comparison (the decode-side `goto corrupt`
          pattern is the compliant shape), and PySequence_Fast_GET_ITEM on
          objects never validated by PySequence_Fast / GET_SIZE.
  NAT005  wire-struct emits inconsistent with the PROTO005 schema comments:
          a hard-coded field-count varint that disagrees with the comment's
          field list, or an 'R' struct emit with no schema comment at all
          (shares protolint.parse_c_schemas — one C schema model).
  NAT006  GIL held across an unbounded pure-C bulk loop (a static helper
          looping over a caller-supplied byte length with zero CPython
          calls) from an entry point with no Py_BEGIN_ALLOW_THREADS window.
  NAT007  decoded counts trusted before validation: an integer read out of
          the input buffer (memcpy-into or varint) used as an allocation
          size with no dominating value check.

Like the static dominance model in csource, every approximation here is
chosen one-sided: borrowed-ref calls are not acquisitions, unresolvable
stores count as escapes, and a conditional release only cancels ownership
where it dominates. tests/test_natlint.py pins each rule on fixtures both
ways (violating and compliant), pins the pre-fix live-violation shapes this
family found in fdb_native.c, and mutation-proves NAT002 by deleting a
Py_DECREF from a real error ladder.

Inline suppression in C uses a comment `/* natlint: ignore[NAT00X] */` on
the flagged line or the line above (see csource.suppressions).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Iterable

from foundationdb_tpu.analysis import csource
from foundationdb_tpu.analysis.flowlint import Finding, Rule, register
from foundationdb_tpu.analysis.protolint import (
    C_RELPATH, _C_COMMENT_RE, _C_EMIT_RE, _C_SCHEMA_RE, parse_c_schemas)

# CPython constructors and other calls whose result is a NEW reference the
# caller owns. Borrowed-ref calls (PyDict_GetItem, *_GET_ITEM) are
# deliberately absent — listing one would fabricate leaks.
NEWREF_FNS = frozenset((
    "PyLong_FromLong", "PyLong_FromLongLong", "PyLong_FromUnsignedLong",
    "PyLong_FromUnsignedLongLong", "PyLong_FromSsize_t", "PyLong_FromSize_t",
    "PyFloat_FromDouble", "PyBool_FromLong", "PyBytes_FromStringAndSize",
    "PyBytes_FromString", "PyUnicode_FromString", "PyUnicode_DecodeUTF8",
    "PyUnicode_FromStringAndSize", "PyList_New", "PyTuple_New",
    "PyDict_New", "PySet_New", "PyTuple_Pack", "PySequence_Fast",
    "PyObject_GetIter", "PyIter_Next", "PyObject_GetAttrString",
    "PyObject_CallObject", "PyObject_CallOneArg", "PyObject_CallNoArgs",
    "PyObject_Call", "PyObject_CallFunctionObjArgs", "PyObject_Str",
    "Py_BuildValue", "Py_NewRef", "PyErr_NewException", "PyModule_Create",
    "PySequence_List", "PySequence_Tuple", "PyDict_Copy",
))

# calls that STEAL a reference to one of their arguments
STEALER_FNS = frozenset((
    "PyList_SET_ITEM", "PyTuple_SET_ITEM", "PyList_SetItem",
    "PyTuple_SetItem", "PyModule_AddObject",
))

# raw allocators whose NULL return must be tested (NAT001)
ALLOC_FNS = frozenset((
    "malloc", "calloc", "realloc", "PyMem_Malloc", "PyMem_Calloc",
    "PyMem_Realloc", "PyMem_RawMalloc", "PyMem_RawRealloc", "PyMem_New",
    "PyObject_Malloc", "PyBytes_FromStringAndSize",
))

# fallible CPython calls and how their error return is signalled (NAT003):
#   neg    -> returns a negative int on error; any dominating condition
#             mentioning the result (or calling inside a condition) counts
#   zero   -> returns 0/NULL-ish falsy on error; same acceptance
#   errocc -> -1 is a VALID value too: the check must involve
#             PyErr_Occurred() or an explicit -1 comparison
FALLIBLE_FNS = {
    "PyObject_IsTrue": "neg", "PyObject_Not": "neg",
    "PyObject_SetAttrString": "neg", "PyList_Append": "neg",
    "PyDict_SetItem": "neg", "PyDict_SetItemString": "neg",
    "PyObject_GetBuffer": "neg", "PyBytes_AsStringAndSize": "neg",
    "PyObject_SetItem": "neg", "PyList_Sort": "neg", "PyType_Ready": "neg",
    "PyModule_AddObject": "neg", "PyModule_AddIntConstant": "neg",
    "PyArg_ParseTuple": "zero", "PyArg_ParseTupleAndKeywords": "zero",
    "PyLong_AsLong": "errocc", "PyLong_AsLongLong": "errocc",
    "PyLong_AsUnsignedLongLong": "errocc", "PyLong_AsSsize_t": "errocc",
    "PyLong_AsSize_t": "errocc", "PyFloat_AsDouble": "errocc",
}

# allocation calls whose size argument a decoded count must not reach
# unvalidated (NAT007)
SIZE_SINK_FNS = ("PyList_New", "PyTuple_New", "PyBytes_FromStringAndSize",
                 "malloc", "calloc", "realloc", "PyMem_Malloc",
                 "PyMem_New", "PyMem_Realloc")

_COND_KINDS = ("if", "for", "while", "do", "switch")

# the size a pure-C bulk loop must be gated on before NAT006 considers the
# entry compliant without a window is a policy question for the fix, not
# the rule: the rule only demands SOME Py_BEGIN_ALLOW_THREADS in the caller
GIL_WINDOW = "Py_BEGIN_ALLOW_THREADS"

_CAST_CALL_RE = re.compile(
    r"^(?:\(\s*[\w\s\*]+?\s*\)\s*)*([A-Za-z_]\w*)\s*\(")


def _normalize(text: str) -> str:
    return text.replace(" ", "")


def _mentions_plain(text: str, var: str) -> bool:
    """var appears as a plain value: not `&var` (address-of for an out
    param) and not `x.var` / `x->var` (a member that shares the name)."""
    for m in re.finditer(rf"\b{re.escape(var)}\b", text):
        j = m.start() - 1
        while j >= 0 and text[j] == " ":
            j -= 1
        if j >= 0 and text[j] == "&" and text[j - 1:j] != "&":
            continue  # `&var` address-of; `&& var` is a plain mention
        if j >= 0 and (text[j] == "." or text[j - 1:j + 1] == "->"):
            continue
        return True
    return False


def _split_assign(text: str) -> tuple[str, str, str] | None:
    """(lhs_var, lhs_text, rhs) of a token-text assignment, or None. Token
    join guarantees a lone `=` appears as ` = ` while `==`/`+=`/... stay
    single tokens, so the match is unambiguous."""
    padded = f" {text} "
    idx = padded.find(" = ")
    if idx < 0:
        return None
    lhs = padded[:idx].strip()
    rhs = padded[idx + 3:].strip()
    m = re.search(r"([A-Za-z_]\w*)\s*$", lhs)
    if m is None:
        return None
    return m.group(1), lhs, rhs


def _leading_call(rhs: str) -> str | None:
    m = _CAST_CALL_RE.match(rhs)
    return m.group(1) if m else None


def _call_args(text: str, open_paren: int) -> list[str]:
    """Top-level comma-split arguments of the call whose '(' sits at
    `open_paren` in `text`."""
    depth, cur, out = 0, [], []
    for ch in text[open_paren:]:
        if ch in "([":
            depth += 1
            if depth == 1:
                continue
        elif ch in ")]":
            depth -= 1
            if depth == 0:
                break
        if depth == 1 and ch == ",":
            out.append("".join(cur))
            cur = []
            continue
        cur.append(ch)
    if "".join(cur).strip():
        out.append("".join(cur))
    return out


@dataclass
class _Acq:
    var: str
    stmt: csource.Stmt
    fn_name: str      # the acquiring call, for messages
    in_loop_cond: bool


class _FnModel:
    """Shared per-function facts the NAT rules query."""

    def __init__(self, fn: csource.CFunction):
        self.fn = fn
        self.texts: list[tuple[csource.Stmt, str, bool]] = []
        for s in fn.flat:
            if s.kind in ("simple", "return", "goto"):
                self.texts.append((s, s.text, False))
            elif s.kind in _COND_KINDS:
                self.texts.append((s, s.text, True))
        self.assigns: list[tuple[csource.Stmt, str, str, bool]] = []
        for s, text, is_cond in self.texts:
            if s.kind == "return":
                continue
            sp = _split_assign(text)
            if sp is not None:
                self.assigns.append((s, sp[0], sp[2], is_cond))
        self.acquisitions: list[_Acq] = []
        for s, var, rhs, is_cond in self.assigns:
            call = _leading_call(rhs)
            if call in NEWREF_FNS:
                self.acquisitions.append(_Acq(
                    var=var, stmt=s, fn_name=call,
                    in_loop_cond=is_cond and s.is_loop))
        for s, text, _ in self.texts:
            for m in re.finditer(r"Py_X?INCREF\s*\(\s*([A-Za-z_]\w*)\s*\)",
                                 text):
                self.acquisitions.append(_Acq(
                    var=m.group(1), stmt=s, fn_name="Py_INCREF",
                    in_loop_cond=False))

    # -- ownership events -------------------------------------------------

    def releases_in(self, stmt: csource.Stmt, var: str) -> bool:
        for text in (stmt.text,):
            for m in re.finditer(
                    r"Py_(?:XDECREF|DECREF|CLEAR)\s*\(\s*([A-Za-z_]\w*)"
                    r"\s*\)|Py_SETREF\s*\(\s*([A-Za-z_]\w*)\s*,", text):
                if var in m.groups():
                    return True
        return False

    def ends_ownership(self, stmt: csource.Stmt, var: str) -> bool:
        """Release, escape, or reassignment of `var` at this statement."""
        text = stmt.text
        if self.releases_in(stmt, var):
            return True
        if stmt.kind == "return" and _mentions_plain(text, var):
            return True
        if any(fn in text for fn in STEALER_FNS) \
                and _mentions_plain(text, var):
            return True
        sp = _split_assign(text) if stmt.kind == "simple" else None
        if sp is not None:
            lhs_var, _, rhs = sp
            if lhs_var == var:
                return True  # rebound: the old ref's story ends here
            if _mentions_plain(rhs, var):
                return True  # aliased into a structure the callee owns
        return False

    def null_guarded(self, exit_stmt: csource.Stmt, var: str) -> bool:
        """The exit sits in the failure branch of var's own NULL test —
        var is provably NULL there, nothing to release."""
        for anc in self.fn.ancestors(exit_stmt):
            if anc.kind != "if":
                continue
            depth = len(anc.block)
            if len(exit_stmt.block) <= depth \
                    or exit_stmt.block[depth] != anc.order:
                continue  # in the else branch (or unrelated)
            cond = anc.text
            if re.search(rf"!\s*{re.escape(var)}\b", cond) \
                    or re.search(rf"\b{re.escape(var)}\s*==\s*NULL", cond) \
                    or re.search(rf"NULL\s*==\s*{re.escape(var)}", cond) \
                    or ("!" in cond and " = " in f" {cond} "
                        and _mentions_plain(cond, var)):
                return True
        return False

    def dominating(self, target: csource.Stmt):
        for s in self.fn.flat:
            if self.fn.dominates(s, target):
                yield s

    def first_mention_after(self, stmt: csource.Stmt, var: str
                            ) -> csource.Stmt | None:
        for s in self.fn.flat[stmt.order + 1:]:
            if s.text and re.search(rf"\b{re.escape(var)}\b", s.text):
                return s
        return None


# ---------------------------------------------------------------------------
# per-function checks (NAT001/2/3/4/6/7) and the schema check (NAT005)
# ---------------------------------------------------------------------------

def _f(code: str, relpath: str, line: int, symbol: str, detail: str,
       message: str) -> Finding:
    return Finding(rule=code, path=relpath, line=line, symbol=symbol,
                   detail=detail, message=message)


def _check_alloc(model: _FnModel, relpath: str) -> Iterable[Finding]:
    fn = model.fn
    for s, var, rhs, is_cond in model.assigns:
        call = _leading_call(rhs)
        if call not in ALLOC_FNS or is_cond:
            continue
        use = model.first_mention_after(s, var)
        if use is None:
            continue  # result parked; a later pass may see the real use
        if use.kind in _COND_KINDS or use.kind == "return":
            continue  # tested (or propagated for the caller to test)
        if re.search(rf"\b{re.escape(var)}\s*\?", use.text):
            continue  # ternary NULL test: `x = var ? f(var) : NULL`
        yield _f("NAT001", relpath, use.line, fn.name,
                 f"unchecked-alloc:{var}",
                 f"{call}() result '{var}' (line {s.line}) is used before "
                 f"any NULL test — allocation failure dereferences NULL")
    # allocation calls whose result never lands in a variable at all
    for s, text, is_cond in model.texts:
        if is_cond or s.kind == "return":
            continue
        sp = _split_assign(text)
        for call in ALLOC_FNS:
            m = re.search(rf"\b{call}\s*\(", text)
            if m is None:
                continue
            if sp is not None and _leading_call(sp[2]) == call:
                continue  # the assigned case above
            yield _f("NAT001", relpath, s.line, fn.name,
                     f"discarded-alloc:{call}",
                     f"{call}() called with its result consumed inline — "
                     f"a NULL on allocation failure flows straight into "
                     f"the surrounding expression")


def _check_refcounts(model: _FnModel, relpath: str) -> Iterable[Finding]:
    fn = model.fn
    if fn.name.startswith("PyInit_"):
        return  # module init: PyModule_AddObject steal-on-success noise
    for exit_stmt, path, term in fn.exits():
        if term is None:
            continue
        ret = _normalize(term.text)
        if ret not in ("NULL", "-1"):
            continue
        for acq in model.acquisitions:
            v, s = acq.var, acq.stmt
            if acq.in_loop_cond:
                pfx = s.block + (s.order,)
                if exit_stmt.block[:len(pfx)] != pfx:
                    continue  # loop-cond ref is NULL once the loop exits
                if exit_stmt.order <= s.order:
                    continue
            elif not fn.dominates(s, exit_stmt):
                continue
            if exit_stmt is s:
                continue
            if any(r.order > s.order and model.ends_ownership(r, v)
                   for r in model.dominating(exit_stmt)):
                continue
            if any(model.releases_in(p, v) for p in path):
                continue
            if model.null_guarded(exit_stmt, v):
                continue
            where = f"goto {exit_stmt.label}" if exit_stmt.kind == "goto" \
                else f"return {term.text}"
            yield _f("NAT002", relpath, exit_stmt.line, fn.name,
                     f"leak:{v}@{exit_stmt.label or 'return'}",
                     f"error path `{where}` (line {exit_stmt.line}) exits "
                     f"without releasing '{v}', acquired from "
                     f"{acq.fn_name}() at line {s.line} — the ref leaks "
                     f"on every failure through this path")


def _check_fallible(model: _FnModel, relpath: str) -> Iterable[Finding]:
    fn = model.fn
    for s, text, is_cond in model.texts:
        if is_cond or s.kind == "return":
            continue  # tested in a condition / propagated to the caller
        if text.startswith("( void )"):
            continue
        sp = _split_assign(text)
        for call, mode in FALLIBLE_FNS.items():
            if re.search(rf"\b{call}\s*\(", text) is None:
                continue
            if sp is not None and _leading_call(sp[2]) == call:
                var = sp[0]
                use = model.first_mention_after(s, var)
                if use is not None and use.kind in _COND_KINDS + ("return",):
                    if mode != "errocc":
                        continue
                    cond = use.text
                    if "PyErr_Occurred" in cond \
                            or "-1" in _normalize(cond):
                        continue
                    yield _f("NAT003", relpath, use.line, fn.name,
                             f"ambiguous-errcheck:{call}:{var}",
                             f"'{var}' from {call}() is tested without "
                             f"PyErr_Occurred()/-1 — a legitimate -1 "
                             f"value and an error are indistinguishable")
                    continue
                where = use.line if use is not None else s.line
                yield _f("NAT003", relpath, where, fn.name,
                         f"unchecked-call:{call}:{var}",
                         f"'{var}' from fallible {call}() (line {s.line}) "
                         f"is used before any error test — a pending "
                         f"exception propagates into garbage data")
            else:
                yield _f("NAT003", relpath, s.line, fn.name,
                         f"ignored-call:{call}",
                         f"error return of {call}() is ignored — on "
                         f"failure an exception is left pending for some "
                         f"unrelated later call to trip over")


def _check_buffers(model: _FnModel, relpath: str) -> Iterable[Finding]:
    fn = model.fn
    # -- PySequence_Fast discipline --------------------------------------
    fastvars = {var for _, var, rhs, _ in model.assigns
                if _leading_call(rhs) == "PySequence_Fast"}
    # a PyObject* parameter was validated by the caller (static helpers
    # like enc_container_items receive an already-Fast sequence)
    param_objs = {p.name for p in fn.params if "PyObject" in p.type}
    sizevars: dict[str, set[str]] = {}
    for _, var, rhs, _ in model.assigns:
        m = re.search(r"PySequence_Fast_GET_SIZE\s*\(\s*([A-Za-z_]\w*)", rhs)
        if m is not None:
            sizevars.setdefault(m.group(1), set()).add(var)
    for s, text, _ in model.texts:
        for m in re.finditer(
                r"PySequence_Fast_(?:GET_ITEM|ITEMS)\s*\(\s*([A-Za-z_]\w*)",
                text):
            target = m.group(1)
            if target in param_objs:
                continue
            if target not in fastvars:
                yield _f("NAT004", relpath, s.line, fn.name,
                         f"unvalidated-fast:{target}",
                         f"PySequence_Fast_GET_ITEM on '{target}', which "
                         f"never went through PySequence_Fast() — a "
                         f"non-list/tuple argument reads wild memory")
                continue
            guarded = any(
                d.kind in _COND_KINDS and (
                    f"PySequence_Fast_GET_SIZE ( {target}" in d.text
                    or any(_mentions_plain(d.text, sv)
                           for sv in sizevars.get(target, ())))
                for d in model.dominating(s))
            if not guarded:
                yield _f("NAT004", relpath, s.line, fn.name,
                         f"unbounded-fast:{target}",
                         f"PySequence_Fast_GET_ITEM on '{target}' with no "
                         f"dominating PySequence_Fast_GET_SIZE bound — "
                         f"the index can run past the item array")
    # -- Py_buffer-derived raw pointers ----------------------------------
    bufvars = [m.group(1) for _, text, _ in model.texts
               for m in [re.search(r"\bPy_buffer\s+([A-Za-z_]\w*)", text)]
               if m is not None]
    if not bufvars:
        return
    aliases: set[str] = set()      # integer size aliases of any buffer
    derived: set[str] = set()      # pointers derived from any .buf
    for _, var, rhs, _ in model.assigns:
        if any(re.search(rf"\b{bv}\s*\.\s*len\b", rhs) for bv in bufvars):
            aliases.add(var)
        if any(re.search(rf"\b{bv}\s*\.\s*buf\b", rhs) for bv in bufvars):
            derived.add(var)
        elif "[" not in rhs and any(
                re.match(rf"^(?:\(\s*[\w\s\*]+?\s*\)\s*)*{dv}\b", rhs)
                for dv in list(derived)):
            derived.add(var)
    for s, text, is_cond in model.texts:
        used = [dv for dv in derived
                if (re.search(rf"\bmemcpy\s*\(", text)
                    and _mentions_plain(text, dv))
                or re.search(rf"\b{dv}\s*\[", text)]
        if not used or is_cond:
            continue
        for dv in used:
            guard = any(
                d.kind in _COND_KINDS and (
                    any(re.search(rf"\b{bv}\s*\.\s*len\b", d.text)
                        for bv in bufvars)
                    or any(_mentions_plain(d.text, a) for a in aliases)
                    or (_mentions_plain(d.text, dv)
                        and re.search(r"[<>]", d.text)))
                for d in model.dominating(s))
            if not guard:
                yield _f("NAT004", relpath, s.line, fn.name,
                         f"unguarded-buffer:{dv}",
                         f"raw access through '{dv}' (derived from a "
                         f"Py_buffer) with no dominating bounds check "
                         f"against the buffer length — a short input "
                         f"reads past the mapped region")


def _check_gil(models: list[_FnModel], relpath: str) -> Iterable[Finding]:
    bulk: set[str] = set()
    for model in models:
        fn = model.fn
        if not fn.static:
            continue
        ptr = any("*" in p.type and ("char" in p.type or "uint8_t" in p.type)
                  for p in fn.params)
        sizes = [p.name for p in fn.params
                 if "*" not in p.type
                 and re.search(r"\b(size_t|Py_ssize_t)\b", p.type)]
        if not ptr or not sizes:
            continue
        body = " ".join(s.text for s in fn.flat)
        if re.search(r"\bPy\w+", body):
            continue
        if any(s.is_loop and any(_mentions_plain(s.text, sz)
                                 for sz in sizes)
               for s in fn.flat):
            bulk.add(fn.name)
    for model in models:
        fn = model.fn
        if not any("PyObject" in p.type for p in fn.params):
            continue
        has_window = any(GIL_WINDOW in s.text for s in fn.flat)
        if has_window:
            continue
        for s, text, _ in model.texts:
            for helper in bulk:
                if re.search(rf"\b{helper}\s*\(", text):
                    yield _f("NAT006", relpath, s.line, fn.name,
                             f"gil:{helper}",
                             f"{helper}() loops over a caller-supplied "
                             f"byte length with the GIL held and no "
                             f"Py_BEGIN_ALLOW_THREADS window in "
                             f"{fn.name}() — a large input stalls every "
                             f"other thread for the whole pass")


def _check_decoded_counts(model: _FnModel, relpath: str
                          ) -> Iterable[Finding]:
    fn = model.fn
    decoded: dict[str, csource.Stmt] = {}
    for s, text, _ in model.texts:
        m = re.search(r"\bmemcpy\s*\(\s*&\s*([A-Za-z_]\w*)\s*,", text)
        if m is not None:
            decoded.setdefault(m.group(1), s)
        for cm in re.finditer(r"\b\w*varint\w*\s*\(", text):
            args = _call_args(text, cm.end() - 1)
            # out-params beyond the first argument are decode targets
            # (rb_varint(&r, &n)); the write side (wb_varint(&w, v))
            # passes plain values there and captures nothing
            for arg in args[1:]:
                am = re.match(r"^\s*&\s*([A-Za-z_]\w*)\s*$", arg)
                if am is not None:
                    decoded.setdefault(am.group(1), s)
    if not decoded:
        return
    for s, text, is_cond in model.texts:
        if is_cond:
            continue
        for var, src in decoded.items():
            if not any(re.search(rf"\b{sink}\s*\([^;]*\b{var}\b", text)
                       for sink in SIZE_SINK_FNS):
                continue
            if not fn.dominates(src, s):
                continue
            validated = any(
                d.kind in ("if", "while") and d is not src
                and _mentions_plain(d.text, var)
                for d in model.dominating(s))
            if not validated:
                yield _f("NAT007", relpath, s.line, fn.name,
                         f"decoded:{var}",
                         f"'{var}' is decoded from the input buffer "
                         f"(line {src.line}) and used as an allocation "
                         f"size with no dominating validation — a "
                         f"corrupt count allocates unbounded memory "
                         f"before any CRC/length check can reject it")


def _check_schemas(source: str, relpath: str,
                   fns: list[csource.CFunction]) -> Iterable[Finding]:
    def symbol_at(line: int) -> str:
        for fn in fns:
            last = max((s.line for s in fn.flat), default=fn.line)
            if fn.line <= line <= last:
                return fn.name
        return "<file>"

    for schema in parse_c_schemas(source):
        if schema.emit_count is not None \
                and schema.emit_count != len(schema.fields):
            yield _f("NAT005", relpath, schema.line, symbol_at(schema.line),
                     f"schema-count:{schema.name}",
                     f"schema comment for {schema.name} lists "
                     f"{len(schema.fields)} field(s) but the struct emit "
                     f"that follows hard-codes {schema.emit_count} — the "
                     f"comment and the wire bytes have drifted apart")
    claimed: set[int] = set()
    for cm in _C_COMMENT_RE.finditer(source):
        for sm in _C_SCHEMA_RE.finditer(cm.group(0)):
            if sm is None:
                continue
            em = _C_EMIT_RE.search(source, cm.end(), cm.end() + 2500)
            if em is not None:
                claimed.add(em.start())
    for em in _C_EMIT_RE.finditer(source):
        if em.start() in claimed:
            continue
        line = source.count("\n", 0, em.start()) + 1
        yield _f("NAT005", relpath, line, symbol_at(line),
                 "undocumented-emit",
                 f"'R' struct emit with field count {em.group(1)} has no "
                 f"schema comment in the preceding window — PROTO005 "
                 f"cannot cross-check it against the Python dataclass")


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def analyze_c_source(source: str, relpath: str = C_RELPATH
                     ) -> list[Finding]:
    """Run every NAT rule over one C translation unit. Tests feed fixture
    snippets and mutated copies of the real file here; the registered rules
    below feed the real file."""
    fns = csource.parse_functions(source)
    models = [_FnModel(fn) for fn in fns]
    findings: list[Finding] = []
    for model in models:
        findings.extend(_check_alloc(model, relpath))
        findings.extend(_check_refcounts(model, relpath))
        findings.extend(_check_fallible(model, relpath))
        findings.extend(_check_buffers(model, relpath))
        findings.extend(_check_decoded_counts(model, relpath))
    findings.extend(_check_gil(models, relpath))
    findings.extend(_check_schemas(source, relpath, fns))
    supp = csource.suppressions(csource.tokenize(source))
    findings = [f for f in findings
                if not _suppressed(supp, f.line, f.rule)]
    findings.sort(key=lambda f: (f.line, f.rule, f.detail))
    return findings


def _suppressed(supp: dict[int, set[str]], line: int, rule: str) -> bool:
    codes = supp.get(line, ())
    return "all" in codes or rule in codes


def c_source_path() -> str | None:
    """The real extension source, located from the installed package (same
    resolution as protolint's PROTO005)."""
    from foundationdb_tpu.analysis import flowlint
    path = os.path.join(flowlint.default_target(), "native", "fdb_native.c")
    return path if os.path.exists(path) else None


def _package_findings(pkg) -> list[Finding]:
    """One shared analysis per run, cached on the PackageContext like
    devlint's fixpoint; each registered rule filters its own code."""
    cached = pkg.caches.get("natlint")
    if cached is not None:
        return cached
    findings: list[Finding] = []
    # only analyze the real file when the run actually targets the package
    # (snippet runs in other families' tests must not see C findings)
    if "foundationdb_tpu/native/__init__.py" in pkg.by_relpath:
        path = c_source_path()
        if path is not None:
            with open(path, encoding="utf-8") as f:
                findings = analyze_c_source(f.read())
    pkg.caches["natlint"] = findings
    return findings


class _NatRule(Rule):
    def check_package(self, pkg) -> Iterable[Finding]:
        return [f for f in _package_findings(pkg) if f.rule == self.code]


@register
class UncheckedAllocation(_NatRule):
    code = "NAT001"
    summary = ("allocation results (malloc/PyMem_*/PyBytes_FromStringAndSize"
               ") must be NULL-tested before first use")


@register
class ErrorPathRefBalance(_NatRule):
    code = "NAT002"
    summary = ("every goto-ladder / early-return error path must release "
               "exactly the owned references acquired so far")


@register
class UncheckedFallibleCall(_NatRule):
    code = "NAT003"
    summary = ("fallible CPython calls must have their error return tested "
               "(PyLong_As* additionally via PyErr_Occurred/-1)")


@register
class UnboundedBufferAccess(_NatRule):
    code = "NAT004"
    summary = ("raw memcpy/pointer access on Py_buffer-derived pointers and "
               "PySequence_Fast items needs a dominating bounds check")


@register
class WireStructEmitParity(_NatRule):
    code = "NAT005"
    summary = ("wire-struct emits must match their PROTO005 schema comments "
               "(field count) and every 'R' emit must carry one")


@register
class GilAcrossBulkLoop(_NatRule):
    code = "NAT006"
    summary = ("pure-C bulk loops over caller-supplied lengths need a "
               "Py_BEGIN_ALLOW_THREADS window in their Python entry point")


@register
class TrustedDecodedCount(_NatRule):
    code = "NAT007"
    summary = ("counts decoded from input buffers must be validated before "
               "sizing an allocation")
