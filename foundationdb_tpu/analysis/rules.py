"""flowlint rules FLOW001..FLOW007: the actor-discipline contract.

Each rule encodes one bug class the deterministic simulator cannot tolerate
(docs/flowlint.md has the narrative; ADVICE round 5 found FLOW002/FLOW003
instances by hand before this existed). Rules are static approximations:
they may over-flag (baseline or `# flowlint: ignore[...]` the provable
false positives) but are designed never to miss the exemplar patterns —
tests/test_flowlint.py pins both directions per rule.
"""

from __future__ import annotations

import ast
from typing import Iterable

from foundationdb_tpu.analysis.flowlint import (
    Finding, ModuleContext, Rule, register)

# -------------------------------------------------------------- FLOW001

# Dotted origins that read wall-clock time or OS entropy. Sim-visible
# coroutines must use loop.now()/loop.delay() and DeterministicRandom
# instead — one stray call makes a (seed, spec) replay diverge.
_NONDET_EXACT = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.sleep",
    "os.urandom", "uuid.uuid4", "uuid.uuid1",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
}
_NONDET_PREFIXES = ("random.", "secrets.")


@register
class NondeterminismInSimCode(Rule):
    code = "FLOW001"
    summary = ("wall clock / OS randomness in a sim-visible coroutine "
               "(core/, server/, net/) — use the sim clock or "
               "DeterministicRandom")

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        if not mod.sim_visible:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            origin = mod.resolve_dotted(node.func)
            if origin is None:
                continue
            if origin not in _NONDET_EXACT and \
                    not origin.startswith(_NONDET_PREFIXES):
                continue
            if not any(isinstance(a, ast.AsyncFunctionDef)
                       for a in mod.ancestors(node)):
                continue  # only coroutines are sim-scheduled
            yield self.finding(
                mod, node, origin,
                f"nondeterministic call {origin}() inside a sim-visible "
                f"coroutine; use the event-loop clock / DeterministicRandom")


# -------------------------------------------------------------- FLOW002

_SETTLE_ATTRS = {"set", "send", "trigger"}


@register
class UnprotectedGateSettle(Rule):
    code = "FLOW002"
    summary = ("gate settle (Promise.send / NotifiedVersion.set / "
               "AsyncTrigger.trigger) reachable after an await but not "
               "protected by try/finally — cancellation wedges waiters")

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        for fn in ast.walk(mod.tree):
            if isinstance(fn, ast.AsyncFunctionDef):
                yield from self._check_coroutine(mod, fn)

    def _check_coroutine(self, mod: ModuleContext,
                         fn: ast.AsyncFunctionDef) -> Iterable[Finding]:
        awaits = [n for n in ast.walk(fn) if isinstance(n, ast.Await)
                  and mod.enclosing_function(n) is fn]
        if not awaits:
            return

        def pos(n):
            return (n.lineno, n.col_offset)

        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SETTLE_ATTRS
                    and len(node.args) <= 1 and not node.keywords
                    and self._self_rooted(node.func.value)):
                # Only instance-state gates (self.version, self._drained_seq,
                # self._wake): a reply Promise arrives as a parameter and the
                # transport breaks owed replies when the process dies, so a
                # skipped reply.send() cannot wedge anyone.
                continue
            if mod.enclosing_function(node) is not fn or any(
                    isinstance(a, ast.Lambda) for a in mod.ancestors(node)):
                continue  # inside a nested callback: runs at its own time
            prior = [a for a in awaits if pos(a) < pos(node)]
            if not prior:
                continue  # cancellation lands at awaits; none precede it
            if self._protected(mod, node, prior):
                continue
            target = ast.unparse(node.func)
            yield self.finding(
                mod, node, target,
                f"{target}() runs after an await but outside any "
                f"try/finally covering that await — a cancellation at the "
                f"await skips the settle and wedges every waiter")

    @staticmethod
    def _self_rooted(node: ast.AST) -> bool:
        cur = node
        while isinstance(cur, ast.Attribute):
            cur = cur.value
        return isinstance(cur, ast.Name) and cur.id == "self"

    @staticmethod
    def _protected(mod: ModuleContext, settle: ast.Call,
                   prior_awaits: list[ast.Await]) -> bool:
        """True iff the settle sits in the finalbody of a Try that encloses
        every await that can execute before it (so no cancellation point
        can skip the finally)."""
        for anc in mod.ancestors(settle):
            if not isinstance(anc, ast.Try) or not anc.finalbody:
                continue
            in_final = any(settle is d or settle in ast.walk(d)
                           for d in anc.finalbody)
            if not in_final:
                continue
            covered = set(ast.walk(anc))
            if all(a in covered for a in prior_awaits):
                return True
        return False


# -------------------------------------------------------------- FLOW003

_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
             "update", "add", "discard", "popleft", "appendleft"}
_LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition"}
_XTHREAD_MARKERS = {"threading.Event", "threading.Condition"}


@register
class UnlockedSharedMutation(Rule):
    code = "FLOW003"
    summary = ("instance attribute mutated across threads without "
               "consistently holding the class's threading.Lock")

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        if not any(o == "threading" or o.startswith("threading.")
                   for o in mod.import_aliases.values()):
            return  # module does not advertise thread-safety
        for cls in ast.walk(mod.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(mod, cls)

    def _check_class(self, mod: ModuleContext,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        lock_attrs: set[str] = set()
        has_xthread_marker = False
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                origin = mod.resolve_dotted(node.value.func)
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        if origin in _LOCK_CTORS:
                            lock_attrs.add(t.attr)
                        if origin in _XTHREAD_MARKERS:
                            has_xthread_marker = True

        # (attr) -> {"locked": [...nodes], "unlocked": [...nodes]},
        # plus the set of methods each attr is mutated from
        sites: dict[str, dict[str, list]] = {}
        methods: dict[str, set[str]] = {}
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if meth.name == "__init__":
                continue  # construction happens-before publication
            for attr, node in self._mutations(meth):
                if attr in lock_attrs:
                    continue
                held = self._under_lock(mod, node, lock_attrs)
                d = sites.setdefault(attr, {"locked": [], "unlocked": []})
                d["locked" if held else "unlocked"].append(node)
                methods.setdefault(attr, set()).add(meth.name)

        for attr, d in sorted(sites.items()):
            if lock_attrs:
                if d["locked"] and d["unlocked"]:
                    for node in d["unlocked"]:
                        yield self.finding(
                            mod, node, attr,
                            f"self.{attr} is mutated both under and outside "
                            f"the class lock; this unlocked site races the "
                            f"locked ones")
            elif has_xthread_marker and len(methods.get(attr, ())) >= 2:
                for node in d["unlocked"]:
                    yield self.finding(
                        mod, node, attr,
                        f"self.{attr} is mutated from multiple methods of a "
                        f"cross-thread class (threading.Event present) with "
                        f"no lock at all")

    @staticmethod
    def _mutations(meth: ast.AST):
        """(attr, node) for every `self.X = ...` / `self.X op= ...` /
        `self.X.append(...)`-style mutation inside `meth`."""
        for node in ast.walk(meth):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    yield t.attr, node
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS \
                    and isinstance(node.func.value, ast.Attribute) \
                    and isinstance(node.func.value.value, ast.Name) \
                    and node.func.value.value.id == "self":
                yield node.func.value.attr, node

    @staticmethod
    def _under_lock(mod: ModuleContext, node: ast.AST,
                    lock_attrs: set[str]) -> bool:
        for anc in mod.ancestors(node):
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Attribute) \
                            and isinstance(ctx.value, ast.Name) \
                            and ctx.value.id == "self" \
                            and ctx.attr in lock_attrs:
                        return True
        return False


# -------------------------------------------------------------- FLOW004

@register
class SwallowedCancellation(Rule):
    code = "FLOW004"
    summary = ("bare except / except BaseException without re-raise inside "
               "an actor — swallows operation_cancelled, so kills cannot "
               "reap the actor")

    _BROAD = {"BaseException", "CancelledError"}

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for t in ast.walk(fn):
                if isinstance(t, ast.Try) and mod.enclosing_function(t) is fn:
                    yield from self._check_try(mod, t)

    def _check_try(self, mod: ModuleContext, t: ast.Try) -> Iterable[Finding]:
        earlier_reraises = False
        for h in t.handlers:
            names = self._handler_names(h)
            has_raise = any(isinstance(n, ast.Raise) for n in ast.walk(h))
            if h.type is None:
                yield self.finding(
                    mod, h, "bare-except",
                    "bare `except:` in an actor catches cancellation; name "
                    "the errors, or re-raise operation_cancelled")
            elif names & self._BROAD and not has_raise \
                    and not earlier_reraises:
                caught = " | ".join(sorted(names & self._BROAD))
                yield self.finding(
                    mod, h, caught,
                    f"`except {caught}` without re-raise swallows "
                    f"cancellation — kills can no longer reap this actor")
            earlier_reraises = earlier_reraises or has_raise

    @staticmethod
    def _handler_names(h: ast.ExceptHandler) -> set[str]:
        nodes = []
        if isinstance(h.type, ast.Tuple):
            nodes = h.type.elts
        elif h.type is not None:
            nodes = [h.type]
        names = set()
        for n in nodes:
            if isinstance(n, ast.Name):
                names.add(n.id)
            elif isinstance(n, ast.Attribute):
                names.add(n.attr)
        return names


# -------------------------------------------------------------- FLOW005

_GATE_FUTURES = {"when_at_least", "on_trigger", "on_change"}


@register
class DroppedCoroutineOrFuture(Rule):
    code = "FLOW005"
    summary = ("coroutine called but never awaited / gate future dropped "
               "on the floor")

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        # Only module-level async defs and class-level async methods: a
        # nested `async def run()` is function-local (always handed straight
        # to spawn/submit) and its common name would collide with unrelated
        # sync methods across the module.
        top_async: set[str] = set()
        method_async: set[str] = set()
        for parent in ast.walk(mod.tree):
            if isinstance(parent, ast.Module):
                top_async |= {n.name for n in parent.body
                              if isinstance(n, ast.AsyncFunctionDef)}
            elif isinstance(parent, ast.ClassDef):
                method_async |= {n.name for n in parent.body
                                 if isinstance(n, ast.AsyncFunctionDef)}
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            name = None
            if isinstance(call.func, ast.Name):
                if call.func.id in top_async:
                    name = call.func.id
            elif isinstance(call.func, ast.Attribute):
                # attribute matches only on self.<async method>: matching
                # arbitrary receivers by name alone would flag every
                # `tr.set(...)` whenever some class has an async set()
                if isinstance(call.func.value, ast.Name) \
                        and call.func.value.id == "self" \
                        and call.func.attr in (method_async | top_async):
                    name = call.func.attr
            if name is not None:
                yield self.finding(
                    mod, call, name,
                    f"{name}() is an async def but the coroutine is "
                    f"discarded — await it or hand it to spawn()")
            elif isinstance(call.func, ast.Attribute) \
                    and call.func.attr in _GATE_FUTURES:
                yield self.finding(
                    mod, call, call.func.attr,
                    f"{call.func.attr}() returns a Future that is dropped "
                    f"on the floor — await it or register a callback")


# -------------------------------------------------------------- FLOW006

_DEVICE_TOUCHING_JAX = {
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.device_put", "jax.default_backend",
    "jax.block_until_ready",
}
_DEVICE_ROOT_PREFIXES = ("jax.numpy.", "jax.lax.")


@register
class DeviceEvalAtImport(Rule):
    code = "FLOW006"
    summary = ("jnp/jax evaluation at module import time — initializes the "
               "device backend for every importer (and hangs if the "
               "accelerator runtime is wedged)")

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if mod.enclosing_function(node) is not None:
                continue  # runs at call time, not import time
            origin = mod.resolve_dotted(node.func)
            if origin is None:
                continue
            if origin in _DEVICE_TOUCHING_JAX \
                    or origin.startswith(_DEVICE_ROOT_PREFIXES):
                yield self.finding(
                    mod, node, origin,
                    f"{origin}() evaluated at import time initializes the "
                    f"device backend for every importer; build it lazily "
                    f"inside a function (see ops/conflict.py NEG)")


# -------------------------------------------------------------- FLOW007

def _trace_event_root(call: ast.Call) -> ast.Call | None:
    """Innermost Call of a fluent chain when it constructs a TraceEvent
    (`TraceEvent(...).detail(...).error(...)`); None otherwise."""
    node = call
    while isinstance(node.func, ast.Attribute) \
            and isinstance(node.func.value, ast.Call):
        node = node.func.value
    if isinstance(node.func, ast.Name) and node.func.id == "TraceEvent":
        return node
    return None


@register
class UnloggedTraceEvent(Rule):
    code = "FLOW007"
    summary = ("TraceEvent built but never .log()'d — the event silently "
               "vanishes (the reference logs from the destructor; ours "
               "only on an explicit .log())")

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            # case 1: a fluent chain as a bare expression statement whose
            # outermost call is not .log()
            if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                call = node.value
                if _trace_event_root(call) is None:
                    continue
                last = call.func.attr \
                    if isinstance(call.func, ast.Attribute) else None
                if last != "log":
                    yield self.finding(
                        mod, call, "TraceEvent",
                        "TraceEvent chain discarded without .log() — "
                        "nothing is emitted")
            # case 2: bound to a name that is never .log()'d in scope
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                tgt = node.targets[0]
                if _trace_event_root(node.value) is None:
                    continue
                outer = node.value.func
                if isinstance(outer, ast.Attribute) and outer.attr == "log":
                    continue  # `x = TraceEvent(...).log()` already emitted
                scope = mod.enclosing_function(node) or mod.tree
                logged = escaped = False
                for use in ast.walk(scope):
                    if not (isinstance(use, ast.Name) and use.id == tgt.id
                            and isinstance(use.ctx, ast.Load)):
                        continue
                    parent = mod.parents.get(use)
                    if isinstance(parent, ast.Attribute):
                        if parent.attr == "log":
                            logged = True
                        continue  # .detail()/.error() keep the chain alive
                    escaped = True  # returned / passed along: out of scope
                if not logged and not escaped:
                    yield self.finding(
                        mod, node.value, tgt.id,
                        f"TraceEvent bound to {tgt.id!r} but never "
                        f".log()'d in this scope — nothing is emitted")
