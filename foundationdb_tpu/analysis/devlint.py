"""devlint rules DEV001..DEV008: JAX/device discipline for the hot path.

The conflict kernel's throughput story (docs/performance.md) died a dozen
small deaths before this existed: a re-traced jit in the rebalance path, an
eager un-donated state rebase, raw device transfers scattered outside the
jaxenv choke points. Each rule encodes one of those bug classes; like the
flow family they are static approximations tuned to never miss the
exemplar shape (tests/test_devlint.py pins both directions per rule).

DEV001 and DEV006 are interprocedural: they consume the PackageContext
call graph (callgraph.py) and per-function summaries, so a coroutine that
calls a blocking helper defined two modules away is flagged at the call
site. Resolution is conservative — an attribute call on an arbitrary
receiver only counts when EVERY same-named method in the package shares
the property, and unresolvable calls are assumed fine — so the family
under-approximates rather than spray false positives.
"""

from __future__ import annotations

import ast
from typing import Iterable

from foundationdb_tpu.analysis.callgraph import FunctionInfo, PackageContext
from foundationdb_tpu.analysis.flowlint import (
    Finding, ModuleContext, Rule, register)

# device→host synchronization points (DEV001)
_ALWAYS_BLOCKING = {"jax.block_until_ready", "jax.device_get"}
# host materializers: blocking only when fed a device-tainted value
_HOST_MATERIALIZERS = {"numpy.asarray", "numpy.array"}
# tracing wrappers whose per-call construction costs a re-trace (DEV002)
_TRACE_CTORS = {"jax.jit", "jax.vmap", "jax.pmap"}
# jnp constructors whose size argument bakes into the compiled program (DEV005)
_JNP_SIZED_CTORS = {
    "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.full", "jax.numpy.empty",
    "jax.numpy.arange", "jax.numpy.eye", "jax.numpy.linspace",
    "jax.numpy.tri", "jax.numpy.broadcast_to",
}
# raw transfer entry points (DEV007); jaxenv.py is the sanctioned home
_TRANSFER_FNS = {
    "jax.device_put", "jax.device_get", "jax.device_put_sharded",
    "jax.device_put_replicated",
}
_SANCTIONED_TRANSFER_MODULE = "foundationdb_tpu/utils/jaxenv.py"
# np.random.* entry points that do NOT share the module-global PRNG (DEV008)
_NP_RANDOM_OK = {
    "numpy.random.RandomState", "numpy.random.default_rng",
    "numpy.random.Generator", "numpy.random.SeedSequence",
    "numpy.random.PCG64", "numpy.random.Philox", "numpy.random.MT19937",
}
# jax.random.* that produce/derive keys rather than consuming one (DEV008)
_JAX_RANDOM_NONCONSUMING = {"split", "PRNGKey", "key", "fold_in",
                            "wrap_key_data", "key_data", "clone"}


def _origin(mod: ModuleContext, node: ast.AST) -> str | None:
    return mod.resolve_dotted(node)


def _owned(mod: ModuleContext, fn: ast.AST):
    """Nodes whose nearest enclosing def is `fn` (lambda bodies included,
    nested defs excluded)."""
    for node in ast.walk(fn):
        if mod.enclosing_function(node) is fn:
            yield node


def _module_level(mod: ModuleContext):
    for node in ast.walk(mod.tree):
        if mod.enclosing_function(node) is None:
            yield node


def _jax_rooted(mod: ModuleContext, expr: ast.AST) -> bool:
    """Expression contains a call/attribute chain resolving into jax.*."""
    for node in ast.walk(expr):
        if isinstance(node, (ast.Attribute, ast.Name)):
            origin = _origin(mod, node)
            if origin and (origin == "jax" or origin.startswith("jax.")):
                return True
    return False


def _sanctioned_offload(mod: ModuleContext, node: ast.AST) -> bool:
    """Inside an argument handed to `*.run_blocking(...)` — the loop's
    worker-thread offload, where blocking on the device is the point."""
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.Call) \
                and isinstance(anc.func, ast.Attribute) \
                and anc.func.attr == "run_blocking" \
                and not any(node is n for n in ast.walk(anc.func)):
            return True
    return False


def _self_attr(node: ast.AST) -> str | None:
    """"X" for `self.X`, else None."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _same_target(a: ast.AST, b: ast.AST) -> bool:
    if isinstance(a, ast.Name) and isinstance(b, ast.Name):
        return a.id == b.id
    sa, sb = _self_attr(a), _self_attr(b)
    return sa is not None and sa == sb


# ---------------------------------------------------------------------------
# shared package analysis (computed once, cached on the PackageContext)
# ---------------------------------------------------------------------------

class _DevAnalysis:
    """Call-graph summaries every DEV rule shares: device taint, the
    blocks-on-host fixpoint, jit targets and trace reachability."""

    def __init__(self, pkg: PackageContext):
        self.pkg = pkg
        self._taint: dict[str, set[str]] = {}
        self._compute_blocking()
        self._compute_jit_targets()

    # ---------------------------------------------------------- device taint

    def tainted_names(self, fn: FunctionInfo) -> set[str]:
        """Local names assigned from jnp/jax-rooted expressions (two
        propagation passes: tainted = device value until proven host)."""
        cached = self._taint.get(fn.fqname)
        if cached is not None:
            return cached
        tainted: set[str] = set()
        assigns = [n for n in _owned(fn.mod, fn.node)
                   if isinstance(n, ast.Assign) and len(n.targets) == 1
                   and isinstance(n.targets[0], ast.Name)]
        for _ in range(2):
            for n in assigns:
                name = n.targets[0].id
                if name in tainted:
                    continue
                if _jax_rooted(fn.mod, n.value) or any(
                        isinstance(x, ast.Name) and x.id in tainted
                        for x in ast.walk(n.value)):
                    tainted.add(name)
        self._taint[fn.fqname] = tainted
        return tainted

    def _is_tainted_expr(self, fn: FunctionInfo, expr: ast.AST) -> bool:
        if _jax_rooted(fn.mod, expr):
            return True
        tainted = self.tainted_names(fn)
        return any(isinstance(x, ast.Name) and x.id in tainted
                   for x in ast.walk(expr))

    # ------------------------------------------------- blocks-on-host summary

    def _direct_blocks(self, fn: FunctionInfo) -> list[tuple[ast.AST, str]]:
        out: list[tuple[ast.AST, str]] = []
        mod = fn.mod
        for node in _owned(mod, fn.node):
            if not isinstance(node, ast.Call):
                continue
            if _sanctioned_offload(mod, node):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) \
                    and func.attr == "block_until_ready":
                out.append((node, "block_until_ready"))
                continue
            if isinstance(func, ast.Attribute) and func.attr == "item" \
                    and node.args == [] \
                    and self._is_tainted_expr(fn, func.value):
                out.append((node, ".item"))
                continue
            origin = _origin(mod, func)
            if origin in _ALWAYS_BLOCKING:
                out.append((node, origin))
            elif origin in _HOST_MATERIALIZERS and node.args \
                    and self._is_tainted_expr(fn, node.args[0]):
                out.append((node, origin))
            elif isinstance(func, ast.Name) and func.id in ("float", "int") \
                    and len(node.args) == 1 \
                    and self._is_tainted_expr(fn, node.args[0]):
                out.append((node, func.id))
        return out

    def _compute_blocking(self) -> None:
        """Fixpoint: a function blocks on host if it contains a blocking
        primitive, or if every candidate of one of its (non-offloaded)
        calls blocks. Call sites that introduced blocking are recorded for
        DEV001's at-the-call-site reporting."""
        for fn in self.pkg.iter_functions():
            direct = self._direct_blocks(fn)
            fn.summary["direct_blocks"] = direct
            fn.summary["blocks"] = bool(direct)
            fn.summary["blocking_calls"] = []
            fn.summary["calls"] = [
                n for n in _owned(fn.mod, fn.node)
                if isinstance(n, ast.Call)
                and not _sanctioned_offload(fn.mod, n)]
        changed = True
        while changed:
            changed = False
            for fn in self.pkg.iter_functions():
                if fn.summary["blocks"] and not fn.summary["calls"]:
                    continue
                for call in fn.summary["calls"]:
                    cands = self.pkg.resolve_call(fn.mod, call)
                    cands = [c for c in cands if c.fqname != fn.fqname]
                    if not cands or not all(c.summary["blocks"]
                                            for c in cands):
                        continue
                    rec = (call, cands[0].qualname)
                    if rec not in fn.summary["blocking_calls"]:
                        fn.summary["blocking_calls"].append(rec)
                    if not fn.summary["blocks"]:
                        fn.summary["blocks"] = True
                        changed = True

    # --------------------------------------------- jit targets & reachability

    def _partial_of_jit(self, mod: ModuleContext,
                        call: ast.Call) -> ast.Call | None:
        """The inner functools.partial(f, ...) of jax.jit(partial(f, ...))."""
        if call.args and isinstance(call.args[0], ast.Call) \
                and _origin(mod, call.args[0].func) == "functools.partial":
            return call.args[0]
        return None

    def _static_argnum_names(self, fnnode, call: ast.Call) -> set[str]:
        names: set[str] = set()
        params = [a.arg for a in fnnode.args.posonlyargs + fnnode.args.args]
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                vals = (kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value])
                for v in vals:
                    if isinstance(v, ast.Constant) and isinstance(v.value, int) \
                            and v.value < len(params):
                        names.add(params[v.value])
            elif kw.arg == "static_argnames":
                for x in ast.walk(kw.value):
                    if isinstance(x, ast.Constant) and isinstance(x.value, str):
                        names.add(x.value)
        return names

    def _target_entry(self, info: FunctionInfo,
                      static_extra: set[str]) -> None:
        """Mark `info` as a direct trace target; traced params = positional
        params minus static ones. Keyword-only params count as static: in
        this codebase they are partial-bound or defaulted config (shapes,
        intra_mode, ...), never runtime arrays."""
        args = info.node.args
        positional = [a.arg for a in args.posonlyargs + args.args]
        static = set(static_extra) | {a.arg for a in args.kwonlyargs}
        traced = {p for p in positional if p not in static and p != "self"}
        prev = self.jit_targets.get(info.fqname)
        if prev is not None:
            traced &= prev  # multiple jit sites: traced where ALL agree
        self.jit_targets[info.fqname] = traced

    def _jit_arg_candidates(self, mod, name: str) -> list[FunctionInfo]:
        """Functions a Name handed to jax.jit/shard_map may denote: normal
        resolution first, then a unique same-module NESTED def (factories
        like _build_sharded_step jit a closure-local step function)."""
        cands = self.pkg.resolve_call(
            mod, ast.Call(func=ast.Name(id=name), args=[], keywords=[]))
        if cands:
            return cands
        nested = [f for f in self.pkg.functions.values()
                  if f.relpath == mod.relpath and f.name == name]
        return nested if len(nested) == 1 else []

    def _compute_jit_targets(self) -> None:
        self.jit_targets: dict[str, set[str]] = {}
        for mod in self.pkg.modules:
            # decorated defs
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info = self.pkg.functions.get(
                        f"{mod.relpath}::{mod.qualname(node)}")
                    if info is None:
                        continue
                    for dec in node.decorator_list:
                        static: set[str] = set()
                        target = None
                        if _origin(mod, dec) == "jax.jit":
                            target = info
                        elif isinstance(dec, ast.Call):
                            o = _origin(mod, dec.func)
                            if o == "jax.jit":
                                target = info
                                static = self._static_argnum_names(node, dec)
                            elif o == "functools.partial" and dec.args \
                                    and _origin(mod, dec.args[0]) == "jax.jit":
                                target = info
                                static = self._static_argnum_names(node, dec)
                        if target is not None:
                            self._target_entry(target, static)
                # functions passed to jax.jit(...) / shard_map(...)
                if not isinstance(node, ast.Call):
                    continue
                origin = _origin(mod, node.func)
                is_shard_map = (isinstance(node.func, ast.Name)
                                and node.func.id == "shard_map") \
                    or (origin or "").endswith(".shard_map")
                if origin != "jax.jit" and not is_shard_map:
                    continue
                if not node.args:
                    continue
                fn_arg = node.args[0]
                static = set()
                partial = self._partial_of_jit(mod, node)
                if partial is not None:
                    static = {kw.arg for kw in partial.keywords
                              if kw.arg is not None}
                    fn_arg = partial.args[0] if partial.args else None
                if isinstance(fn_arg, ast.Name):
                    for info in self._jit_arg_candidates(mod, fn_arg.id):
                        static |= self._static_argnum_names(info.node, node)
                        self._target_entry(info, static)

        # trace reachability: BFS from direct targets through resolvable
        # calls (a helper called from inside a jitted function runs traced,
        # so its shapes are static by construction)
        self.trace_reachable: set[str] = set(self.jit_targets)
        frontier = [self.pkg.functions[fq] for fq in self.jit_targets
                    if fq in self.pkg.functions]
        while frontier:
            fn = frontier.pop()
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                for cand in self.pkg.resolve_call(fn.mod, node):
                    if cand.fqname not in self.trace_reachable:
                        self.trace_reachable.add(cand.fqname)
                        frontier.append(cand)


def _analysis(pkg: PackageContext) -> _DevAnalysis:
    a = pkg.caches.get("devlint")
    if a is None:
        a = _DevAnalysis(pkg)
        pkg.caches["devlint"] = a
    return a


# -------------------------------------------------------------- DEV001

@register
class ImplicitReadbackInActor(Rule):
    code = "DEV001"
    summary = ("device→host readback (block_until_ready / device_get / "
               "np.asarray / float() / .item() on device values) inside a "
               "sim-visible coroutine — blocks the event loop; offload via "
               "loop.run_blocking. Interprocedural: a helper that blocks is "
               "flagged at the coroutine's call site.")

    def check_package(self, pkg: PackageContext) -> Iterable[Finding]:
        ana = _analysis(pkg)
        for fn in pkg.iter_functions():
            if not fn.is_async or not fn.mod.sim_visible:
                continue
            for node, detail in fn.summary.get("direct_blocks", ()):
                yield self.finding(
                    fn.mod, node, detail,
                    f"{detail} synchronizes device→host on the event-loop "
                    f"thread inside coroutine {fn.qualname}; move it into "
                    f"loop.run_blocking(...)")
            for call, callee in fn.summary.get("blocking_calls", ()):
                yield self.finding(
                    fn.mod, call, callee,
                    f"{callee}() blocks on a device→host sync (possibly "
                    f"transitively) and is called from coroutine "
                    f"{fn.qualname} on the event-loop thread; wrap the call "
                    f"in loop.run_blocking(...)")


# -------------------------------------------------------------- DEV002

@register
class JitConstructedPerCall(Rule):
    code = "DEV002"
    summary = ("jax.jit/vmap/pmap constructed per call (immediately invoked "
               "or built inside a loop) — re-traces and re-compiles every "
               "invocation; hoist to a cached factory")

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            origin = _origin(mod, node.func)
            if origin not in _TRACE_CTORS:
                continue
            parent = mod.parents.get(node)
            if isinstance(parent, ast.Call) and parent.func is node:
                yield self.finding(
                    mod, parent, origin,
                    f"{origin}(...)(...) builds a fresh traced callable and "
                    f"invokes it once — every call re-traces (and for jit, "
                    f"re-compiles); bind it once in a cached factory")
                continue
            for anc in mod.ancestors(node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break
                if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                    yield self.finding(
                        mod, node, origin,
                        f"{origin}(...) constructed inside a loop — one "
                        f"re-trace per iteration; hoist the wrapper out of "
                        f"the loop")
                    break


# -------------------------------------------------------------- DEV003

@register
class TracedValueBranch(Rule):
    code = "DEV003"
    summary = ("Python if/while on a traced parameter inside a jit target — "
               "ConcretizationTypeError at trace time (or a silently baked-"
               "in constant); use lax.cond/jnp.where")

    def check_package(self, pkg: PackageContext) -> Iterable[Finding]:
        ana = _analysis(pkg)
        for fqname, traced in ana.jit_targets.items():
            fn = pkg.functions.get(fqname)
            if fn is None or not traced:
                continue
            for node in _owned(fn.mod, fn.node):
                if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                    hits = sorted({x.id for x in ast.walk(node.test)
                                   if isinstance(x, ast.Name)
                                   and x.id in traced})
                    if hits:
                        yield self.finding(
                            fn.mod, node, hits[0],
                            f"Python branch on traced parameter "
                            f"'{hits[0]}' inside jit target {fn.qualname}; "
                            f"use lax.cond / jnp.where (static config "
                            f"belongs in keyword-only/static args)")


# -------------------------------------------------------------- DEV004

@register
class BadStaticArgnums(Rule):
    code = "DEV004"
    summary = ("static_argnums that are not integer constants, or a static "
               "position fed an array/unhashable value at a call site — "
               "TypeError (unhashable) or a retrace per distinct value")

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        static_positions: dict[str, tuple[int, ...]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            origin = _origin(mod, node.func)
            is_jit = origin == "jax.jit" or (
                origin == "functools.partial" and node.args
                and _origin(mod, node.args[0]) == "jax.jit")
            if not is_jit:
                continue
            positions: list[int] = []
            for kw in node.keywords:
                if kw.arg != "static_argnums":
                    continue
                vals = (kw.value.elts
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value])
                for v in vals:
                    if isinstance(v, ast.Constant) \
                            and isinstance(v.value, int):
                        positions.append(v.value)
                    else:
                        yield self.finding(
                            mod, kw.value, "static_argnums",
                            "static_argnums must be integer constants — a "
                            "computed/array value makes the cache key "
                            "unhashable or unstable")
            if not positions:
                continue
            # g = jax.jit(f, static_argnums=(k,)) — remember g's positions
            parent = mod.parents.get(node)
            tgt = node
            if isinstance(parent, ast.Call):  # functools.partial wrapper
                tgt = parent
                parent = mod.parents.get(parent)
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                    and isinstance(parent.targets[0], ast.Name) \
                    and parent.value is tgt:
                static_positions[parent.targets[0].id] = tuple(positions)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            positions = static_positions.get(node.func.id)
            if not positions:
                continue
            for k in positions:
                if k >= len(node.args):
                    continue
                arg = node.args[k]
                if isinstance(arg, (ast.List, ast.Dict, ast.Set,
                                    ast.ListComp, ast.DictComp, ast.SetComp)) \
                        or _jax_rooted(mod, arg):
                    yield self.finding(
                        mod, node, node.func.id,
                        f"static position {k} of {node.func.id}() receives "
                        f"an array/unhashable value — static args are "
                        f"hashed into the compile-cache key; pass arrays "
                        f"as traced operands")


# -------------------------------------------------------------- DEV005

@register
class ShapeDependentConstructor(Rule):
    code = "DEV005"
    summary = ("jnp constructor sized by len()/.shape-derived host "
               "arithmetic outside any traced context — a new compiled "
               "program per batch size; pad to bucketed shapes")

    def check_package(self, pkg: PackageContext) -> Iterable[Finding]:
        ana = _analysis(pkg)
        for fn in pkg.iter_functions():
            if fn.fqname in ana.trace_reachable:
                continue  # shapes are static under trace by construction
            shape_locals = self._shape_derived_locals(fn)
            for node in _owned(fn.mod, fn.node):
                if not isinstance(node, ast.Call):
                    continue
                origin = _origin(fn.mod, node.func)
                if origin not in _JNP_SIZED_CTORS:
                    continue
                exprs = list(node.args) + [kw.value for kw in node.keywords]
                for e in exprs:
                    if self._shape_dependent(e, shape_locals):
                        yield self.finding(
                            fn.mod, node, origin,
                            f"{origin}() sized by data-dependent host "
                            f"arithmetic in {fn.qualname} — every distinct "
                            f"size compiles a fresh program; pad to the "
                            f"bucketed shapes (BatchEncoder.bucket_shapes)")
                        break

    @staticmethod
    def _shape_derived_locals(fn: FunctionInfo) -> set[str]:
        out: set[str] = set()
        for node in _owned(fn.mod, fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and ShapeDependentConstructor._shape_dependent(
                        node.value, out):
                out.add(node.targets[0].id)
        return out

    @staticmethod
    def _shape_dependent(expr: ast.AST, shape_locals: set[str]) -> bool:
        for x in ast.walk(expr):
            if isinstance(x, ast.Attribute) and x.attr == "shape":
                return True
            if isinstance(x, ast.Call) and isinstance(x.func, ast.Name) \
                    and x.func.id == "len":
                return True
            if isinstance(x, ast.Name) and x.id in shape_locals:
                return True
        return False


# -------------------------------------------------------------- DEV006

@register
class MissingDonation(Rule):
    code = "DEV006"
    summary = ("state-overwrite call `x = f(x, ...)` through a jit with no "
               "donate_argnums (or an eager un-jitted device function) — "
               "the dead input buffer doubles HBM traffic/footprint")

    def check_package(self, pkg: PackageContext) -> Iterable[Finding]:
        for mod in pkg.modules:
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.value, ast.Call)):
                    continue
                target, call = node.targets[0], node.value
                if not call.args or not _same_target(target, call.args[0]):
                    continue
                yield from self._check_site(pkg, mod, node, call)

    def _check_site(self, pkg, mod, node, call) -> Iterable[Finding]:
        func = call.func
        jit_vars = self._jit_vars(mod)
        if isinstance(func, ast.Name):
            donated = jit_vars.get(func.id)
            if donated is False:
                yield self.finding(
                    mod, node, func.id,
                    f"{func.id}() is a jit with no donate_argnums but its "
                    f"first operand is overwritten by the result — donate "
                    f"it (see _donate_state_argnums) to halve state "
                    f"traffic")
                return
            if donated is None:
                for cand in pkg.resolve_call(mod, call):
                    fac = self._factory_donation(cand)
                    if fac is False:
                        yield self.finding(
                            mod, node, func.id,
                            f"{func.id}() returns a jit with no "
                            f"donate_argnums; its first operand is "
                            f"overwritten by the result — add "
                            f"donate_argnums to the factory's jit")
                    elif fac is None and self._touches_device(cand):
                        yield self.finding(
                            mod, node, func.id,
                            f"{func.id}() runs device ops eagerly (op-by-op "
                            f"dispatch, no donation) and its result "
                            f"overwrites its first operand — wrap it in a "
                            f"cached jit with donate_argnums")
        elif isinstance(func, ast.Call) and isinstance(func.func, ast.Name):
            # factory invocation: _compiled_rebase()(state, delta)
            for cand in pkg.resolve_call(
                    mod, ast.Call(func=func.func, args=[], keywords=[])):
                if self._factory_donation(cand) is False:
                    yield self.finding(
                        mod, node, func.func.id,
                        f"{func.func.id}() returns a jit with no "
                        f"donate_argnums; its first operand is overwritten "
                        f"by the result — add donate_argnums to the "
                        f"factory's jit")

    @staticmethod
    def _jit_vars(mod: ModuleContext) -> dict[str, bool]:
        """name -> has donate_argnums, for `g = jax.jit(...)` assignments.
        Cached on the ModuleContext (never keyed by relpath: tests reuse
        one snippet path across many distinct parses)."""
        got = getattr(mod, "_dev_jit_vars", None)
        if got is not None:
            return got
        out: dict[str, bool] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and _origin(mod, node.value.func) == "jax.jit":
                out[node.targets[0].id] = any(
                    kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in node.value.keywords)
        mod._dev_jit_vars = out
        return out

    @staticmethod
    def _factory_donation(fn: FunctionInfo) -> bool | None:
        """True/False when `fn` returns a jax.jit(...) with/without
        donation; None when it is not a jit factory."""
        for node in _owned(fn.mod, fn.node):
            if isinstance(node, ast.Return) and node.value is not None \
                    and isinstance(node.value, ast.Call) \
                    and _origin(fn.mod, node.value.func) == "jax.jit":
                return any(kw.arg in ("donate_argnums", "donate_argnames")
                           for kw in node.value.keywords)
        return None

    @staticmethod
    def _touches_device(fn: FunctionInfo) -> bool:
        for node in _owned(fn.mod, fn.node):
            if isinstance(node, ast.Call):
                origin = _origin(fn.mod, node.func)
                if origin and origin.startswith(("jax.numpy.", "jax.lax.")):
                    return True
        return False


# -------------------------------------------------------------- DEV007

@register
class RawDeviceTransfer(Rule):
    code = "DEV007"
    summary = ("jax.device_put/device_get outside the utils/jaxenv.py choke "
               "points — bypasses platform honoring and bounded discovery "
               "(can hang on a wedged runtime); use jaxenv.device_put/"
               "device_get")

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        if mod.relpath == _SANCTIONED_TRANSFER_MODULE:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            origin = _origin(mod, node.func)
            if origin in _TRANSFER_FNS:
                yield self.finding(
                    mod, node, origin,
                    f"raw {origin}() outside utils/jaxenv.py — transfers "
                    f"must go through the jaxenv choke points so "
                    f"JAX_PLATFORMS stays honored and discovery stays "
                    f"bounded")


# -------------------------------------------------------------- DEV008

@register
class PRNGDiscipline(Rule):
    code = "DEV008"
    summary = ("module-global numpy PRNG use, or a jax.random key consumed "
               "more than once without split — cross-instance coupling / "
               "identical draws")

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            origin = _origin(mod, node.func)
            if origin and origin.startswith("numpy.random.") \
                    and origin not in _NP_RANDOM_OK:
                yield self.finding(
                    mod, node, origin,
                    f"{origin}() mutates/draws from numpy's module-global "
                    f"PRNG — seed a local RandomState/default_rng instead "
                    f"(global state couples every engine instance and "
                    f"breaks seed replay)")
        for fn in ast.walk(mod.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_key_reuse(mod, fn)

    def _check_key_reuse(self, mod: ModuleContext,
                         fn: ast.AST) -> Iterable[Finding]:
        rotated: set[str] = set()
        uses: dict[str, list[ast.Call]] = {}
        for node in _owned(mod, fn):
            if isinstance(node, ast.Assign):
                if any(isinstance(x, ast.Call)
                       and (_origin(mod, x.func) or "").endswith(
                           "random.split")
                       for x in ast.walk(node.value)):
                    for t in node.targets:
                        for x in ast.walk(t):
                            if isinstance(x, ast.Name):
                                rotated.add(x.id)
            if not isinstance(node, ast.Call):
                continue
            origin = _origin(mod, node.func)
            if not origin or not origin.startswith("jax.random."):
                continue
            if origin.rsplit(".", 1)[1] in _JAX_RANDOM_NONCONSUMING:
                continue
            if node.args and isinstance(node.args[0], ast.Name):
                uses.setdefault(node.args[0].id, []).append(node)
        for name, calls in sorted(uses.items()):
            if name in rotated or len(calls) < 2:
                continue
            for call in calls[1:]:
                yield self.finding(
                    mod, call, f"key:{name}",
                    f"jax.random key '{name}' is consumed by more than one "
                    f"draw without jax.random.split — identical randomness "
                    f"on every reuse; split the key per draw")
