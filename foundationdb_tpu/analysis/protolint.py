"""protolint rules PROTO001..PROTO008: protocol conformance for the RPC layer.

The wire contract lives in three hand-synchronized places: the token table
and request/reply dataclasses (server/interfaces.py), the frame router
(net/transport.py), and the C struct emitters (native/fdb_native.c). flowlint
covers actor discipline and devlint covers device discipline; nothing checked
the protocol itself — a token sent with no registered handler, a handler that
drops its reply promise on one control-flow path (the client then waits out
the full RPC timeout: the resolver-wedge class PR 1 fixed by hand), or a C
emitter whose hard-coded field count silently drifts from the Python
dataclass.

The family shares one package-level analysis (_ProtoAnalysis, cached on the
PackageContext like devlint's): the token census (declarations, register
sites, Endpoint send sites), the dataclass/field index, the statically parsed
wire registry, and an interprocedural reply-settlement interpreter.

Reply settlement (PROTO002) is an abstract interpretation over each
reply-holding function: statements either settle the promise (send/
send_error), hand it off (passed to a resolvable callee — which is then
analyzed itself, so the chain handler -> spawn -> delegate -> helper is
covered), escape it (stored in a container/attribute or passed to an
unresolvable call — conservatively assumed fine), or exit (return/raise).
`await` is the may-raise primitive: in a spawned coroutine an exception or
cancellation landing on an await while the reply is unsettled is NOT
answered by the transport (only sync-handler raises are), so the caller
wedges until RPC timeout. Approximations are one-sided where possible:
unresolvable calls and escapes assume fine (under-approximate), and only
awaits/raises count as may-raise points.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Iterable

from foundationdb_tpu.analysis.callgraph import FunctionInfo, PackageContext
from foundationdb_tpu.analysis.flowlint import (
    Finding, ModuleContext, Rule, register)

_SETTLE_ATTRS = ("send", "send_error")
# builtins that probe a value without retaining it: passing the reply here is
# neither a settle nor an escape
_NOEFFECT_BUILTINS = {"getattr", "hasattr", "isinstance", "len", "bool",
                      "id", "repr", "str", "type", "print"}
# annotation names that encode without a registry entry (utils/wire.py tags)
_WIRE_OK_NAMES = {
    "int", "float", "bool", "str", "bytes", "bytearray", "memoryview",
    "list", "tuple", "dict", "set", "frozenset", "object", "None",
    "Any", "Optional", "Union", "List", "Dict", "Tuple", "Set", "ClassVar",
}

C_RELPATH = "foundationdb_tpu/native/fdb_native.c"


# ---------------------------------------------------------------------------
# C schema parsing (PROTO005) — module-level so tests can feed mutated copies
# ---------------------------------------------------------------------------

@dataclass
class CSchema:
    """One `ClassName { f1: ..., f2 }` schema comment in the C source, plus
    the hard-coded field-count varint of the next 'R' struct emit."""

    name: str
    fields: list[str]
    line: int
    emit_count: int | None  # None when no emitter follows the comment


_C_COMMENT_RE = re.compile(r"/\*.*?\*/", re.S)
_C_SCHEMA_RE = re.compile(r"(\w+)\s*\{([^{}]+)\}")
# the struct emit shape: 'R' tag, type-id varint, then the field-count varint
# as an integer literal (the drift the rule exists to catch)
_C_EMIT_RE = re.compile(
    r"wb_byte\(\s*&\w+\s*,\s*'R'\s*\)[^;]*?"
    r"wb_varint\(\s*&\w+\s*,\s*\w+\s*\)[^;]*?"
    r"wb_varint\(\s*&\w+\s*,\s*(\d+)\s*\)")


def _split_c_fields(body: str) -> list[str] | None:
    """Field names from a schema comment body, splitting on top-level commas
    only (types like `[(0, value|None) | (1, errname)]` contain commas)."""
    parts: list[str] = []
    depth = 0
    cur: list[str] = []
    for ch in body:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
            continue
        cur.append(ch)
    if "".join(cur).strip():
        parts.append("".join(cur))
    names = []
    for p in parts:
        name = p.split(":", 1)[0].strip()
        if not name.isidentifier():
            return None
        names.append(name)
    return names or None


def parse_c_schemas(source: str) -> list[CSchema]:
    """Every `Name { fields }` schema inside a C comment, with the field
    count of the first struct emit that follows it. Callers filter by
    registered class name — prose braces won't survive that."""
    out: list[CSchema] = []
    for cm in _C_COMMENT_RE.finditer(source):
        text = cm.group(0)
        for sm in _C_SCHEMA_RE.finditer(text):
            fields = _split_c_fields(sm.group(2))
            if fields is None:
                continue
            line = source[:cm.start() + sm.start()].count("\n") + 1
            em = _C_EMIT_RE.search(source, cm.end(), cm.end() + 2500)
            out.append(CSchema(name=sm.group(1), fields=fields, line=line,
                               emit_count=int(em.group(1)) if em else None))
    return out


def c_parity_problems(schemas: list[CSchema],
                      py_fields: dict[str, list[str]],
                      registered: set[str]) -> list[tuple[CSchema, str]]:
    """Cross-check C schemas against the Python dataclass field lists.
    Returns (schema, message) per divergence; tests feed mutated copies of
    either side to prove the gate trips."""
    problems: list[tuple[CSchema, str]] = []
    seen: set[tuple] = set()
    for s in schemas:
        if s.name not in registered:
            continue  # brace-y prose, not a schema
        pf = py_fields.get(s.name)
        if pf is None:
            key = (s.name, "missing")
            if key not in seen:
                seen.add(key)
                problems.append((s, f"C emitter schema for {s.name} has no "
                                    f"matching Python dataclass"))
            continue
        if s.fields != pf:
            key = (s.name, tuple(s.fields))
            if key not in seen:
                seen.add(key)
                problems.append((s, f"C emitter schema for {s.name} lists "
                                    f"fields {s.fields} but the Python "
                                    f"dataclass declares {pf} — the native "
                                    f"fast path would emit frames the Python "
                                    f"decoder mis-fills"))
        if s.emit_count is not None and s.emit_count != len(pf):
            key = (s.name, "count", s.emit_count)
            if key not in seen:
                seen.add(key)
                problems.append((s, f"C emitter for {s.name} hard-codes a "
                                    f"field count of {s.emit_count} but the "
                                    f"Python dataclass has {len(pf)} "
                                    f"field(s) — decode fills the tail from "
                                    f"defaults or truncates"))
    return problems


# ---------------------------------------------------------------------------
# shared package analysis
# ---------------------------------------------------------------------------

@dataclass
class _TokenDecl:
    value: int
    node: ast.AST
    mod: ModuleContext


@dataclass
class _RegSite:
    cls_key: tuple[str, str]  # (relpath, TokenClassName)
    attr: str
    handler: FunctionInfo | None
    node: ast.AST
    mod: ModuleContext


@dataclass
class _SendSite:
    cls_key: tuple[str, str]
    attr: str
    node: ast.AST
    mod: ModuleContext
    kind: str | None = None  # "request" | "one_way" | None (bare Endpoint)
    payload_cls: str | None = None


@dataclass
class _DC:
    name: str
    fields: list[str]
    node: ast.ClassDef
    mod: ModuleContext


class _Out:
    """Abstract-interpretation outcome of a statement list: the possible
    settled-states at fall-through, at returns, and at may-raise points,
    plus the concrete exit nodes observed with an unsettled state."""

    __slots__ = ("fall", "returns", "raises", "bad")

    def __init__(self, fall: Iterable[bool] = ()):
        self.fall: set[bool] = set(fall)
        self.returns: set[bool] = set()
        self.raises: set[bool] = set()
        self.bad: list[tuple[str, ast.AST]] = []  # ("return"|"raise", node)


class _ProtoAnalysis:
    """The census + interpreter every PROTO rule shares."""

    def __init__(self, pkg: PackageContext):
        self.pkg = pkg
        # (relpath, ClassName) -> {ATTR: _TokenDecl}
        self.token_classes: dict[tuple[str, str], dict[str, _TokenDecl]] = {}
        self._token_dotted: dict[str, tuple[str, str]] = {}
        self.registers: list[_RegSite] = []
        self.sends: list[_SendSite] = []
        # token refs that are neither a register arg nor an Endpoint arg:
        # a token passed through a variable (`self._pick_proxy(Token.X)`,
        # `_quorum_call(CoordToken.Y, ...)`) reaches a send site the static
        # Endpoint scan can't see — count it reachable
        self.indirect_refs: set[tuple[tuple[str, str], str]] = set()
        self.dataclasses: dict[str, list[_DC]] = {}
        # wire registry, statically parsed from any module defining
        # _register_all: id -> [names], plus the flat registered-name set
        self.registry_present = False
        self.registry_ids: dict[int, list[tuple[str, ast.AST,
                                                ModuleContext]]] = {}
        self.registered_names: set[str] = set()
        self._outcomes_memo: dict[tuple[str, str], _Out] = {}
        self._collect_tokens()
        self._collect_dataclasses()
        self._collect_registry()
        self._collect_sites()

    # ------------------------------------------------------------- censuses

    def _collect_tokens(self) -> None:
        from foundationdb_tpu.analysis.callgraph import _dotted_module_name
        for mod in self.pkg.modules:
            for node in mod.tree.body:
                if not (isinstance(node, ast.ClassDef)
                        and node.name.endswith("Token")):
                    continue
                decls: dict[str, _TokenDecl] = {}
                for stmt in node.body:
                    if isinstance(stmt, ast.Assign) \
                            and len(stmt.targets) == 1 \
                            and isinstance(stmt.targets[0], ast.Name) \
                            and isinstance(stmt.value, ast.Constant) \
                            and isinstance(stmt.value.value, int):
                        decls[stmt.targets[0].id] = _TokenDecl(
                            stmt.value.value, stmt, mod)
                if not decls:
                    continue
                key = (mod.relpath, node.name)
                self.token_classes[key] = decls
                dn = _dotted_module_name(mod.relpath)
                if dn is not None:
                    self._token_dotted[f"{dn}.{node.name}"] = key

    def resolve_token_ref(self, mod: ModuleContext,
                          expr: ast.AST) -> tuple[tuple[str, str], str] | None:
        """(token class key, ATTR) for `Token.X` / `CoordToken.Y`, through
        import aliases; None when the class isn't in the analyzed set."""
        if not (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)):
            return None
        local = (mod.relpath, expr.value.id)
        if local in self.token_classes:
            return local, expr.attr
        dotted = mod.resolve_dotted(expr.value)
        key = self._token_dotted.get(dotted) if dotted else None
        if key is not None:
            return key, expr.attr
        return None

    def _collect_dataclasses(self) -> None:
        for mod in self.pkg.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                if not any(self._is_dataclass_dec(d)
                           for d in node.decorator_list):
                    continue
                fields = [s.target.id for s in node.body
                          if isinstance(s, ast.AnnAssign)
                          and isinstance(s.target, ast.Name)]
                self.dataclasses.setdefault(node.name, []).append(
                    _DC(node.name, fields, node, mod))

    @staticmethod
    def _is_dataclass_dec(dec: ast.AST) -> bool:
        if isinstance(dec, ast.Call):
            dec = dec.func
        return (isinstance(dec, ast.Name) and dec.id == "dataclass") or \
            (isinstance(dec, ast.Attribute) and dec.attr == "dataclass")

    def dataclass_fields(self, name: str) -> list[str] | None:
        """Field list for `name`, preferring the interfaces module when a
        class name is (unusually) defined twice."""
        entries = self.dataclasses.get(name)
        if not entries:
            return None
        for e in entries:
            if e.mod.relpath.endswith("server/interfaces.py"):
                return e.fields
        return entries[0].fields

    def _collect_registry(self) -> None:
        for mod in self.pkg.modules:
            fn = next((n for n in mod.tree.body
                       if isinstance(n, ast.FunctionDef)
                       and n.name == "_register_all"), None)
            if fn is None:
                continue
            self.registry_present = True
            for n in ast.walk(fn):
                if isinstance(n, ast.Tuple) and len(n.elts) == 2 \
                        and isinstance(n.elts[0], ast.Constant) \
                        and isinstance(n.elts[0].value, int) \
                        and isinstance(n.elts[1], (ast.Name, ast.Attribute)):
                    cls = n.elts[1]
                    name = cls.attr if isinstance(cls, ast.Attribute) \
                        else cls.id
                    self.registry_ids.setdefault(
                        n.elts[0].value, []).append((name, n, mod))
                    self.registered_names.add(name)

    def _collect_sites(self) -> None:
        consumed: set[int] = set()
        for mod in self.pkg.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if isinstance(func, ast.Attribute) \
                        and func.attr == "register" and len(node.args) >= 2:
                    tok = self.resolve_token_ref(mod, node.args[0])
                    if tok is not None:
                        consumed.add(id(node.args[0]))
                        self.registers.append(_RegSite(
                            tok[0], tok[1],
                            self._resolve_handler(mod, node, node.args[1]),
                            node, mod))
                    continue
                if self._is_endpoint_ctor(mod, func) and len(node.args) >= 2:
                    tok = self.resolve_token_ref(mod, node.args[1])
                    if tok is None:
                        continue
                    consumed.add(id(node.args[1]))
                    site = _SendSite(tok[0], tok[1], node, mod)
                    parent = mod.parents.get(node)
                    if isinstance(parent, ast.Call) \
                            and isinstance(parent.func, ast.Attribute) \
                            and parent.func.attr in ("request", "one_way") \
                            and len(parent.args) >= 3:
                        site.kind = parent.func.attr
                        site.payload_cls = self._payload_class(
                            mod, node, parent.args[2])
                    self.sends.append(site)
        for mod in self.pkg.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Attribute) \
                        and id(node) not in consumed:
                    tok = self.resolve_token_ref(mod, node)
                    if tok is not None:
                        self.indirect_refs.add(tok)

    @staticmethod
    def _is_endpoint_ctor(mod: ModuleContext, func: ast.AST) -> bool:
        if isinstance(func, ast.Name) and func.id == "Endpoint":
            return True
        dotted = mod.resolve_dotted(func)
        return bool(dotted) and dotted.endswith(".Endpoint")

    def _resolve_handler(self, mod: ModuleContext, call: ast.Call,
                         expr: ast.AST) -> FunctionInfo | None:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            for anc in mod.ancestors(call):
                if isinstance(anc, ast.ClassDef):
                    return self.pkg.classes.get(
                        (mod.relpath, anc.name), {}).get(expr.attr)
        if isinstance(expr, ast.Name):
            cands = self.pkg.resolve_call(
                mod, ast.Call(func=ast.Name(id=expr.id), args=[],
                              keywords=[]))
            if len(cands) == 1:
                return cands[0]
        return None

    def _payload_class(self, mod: ModuleContext, anchor: ast.AST,
                       expr: ast.AST) -> str | None:
        """Dataclass name a send payload resolves to: a direct constructor,
        or a local `name = Cls(...)` in the enclosing function."""
        if isinstance(expr, ast.Call):
            return self._class_name_of(expr.func)
        if isinstance(expr, ast.Name):
            fn = mod.enclosing_function(anchor)
            if fn is not None:
                for n in ast.walk(fn):
                    if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                            and isinstance(n.targets[0], ast.Name) \
                            and n.targets[0].id == expr.id \
                            and isinstance(n.value, ast.Call):
                        return self._class_name_of(n.value.func)
        return None

    def _class_name_of(self, func: ast.AST) -> str | None:
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
        return name if name in self.dataclasses else None

    # -------------------------------------------- reply-settlement machinery

    def handler_params(self, fn: FunctionInfo) -> list[str]:
        a = fn.node.args
        params = [x.arg for x in a.posonlyargs + a.args]
        if fn.class_name is not None and params \
                and params[0] in ("self", "cls"):
            params = params[1:]
        return params

    def reply_param(self, fn: FunctionInfo) -> str | None:
        params = self.handler_params(fn)
        return params[1] if len(params) >= 2 else None

    def _passing_calls(self, param: str, node: ast.AST) -> list[ast.Call]:
        out = []
        for c in ast.walk(node):
            if isinstance(c, ast.Call):
                exprs = list(c.args) + [k.value for k in c.keywords]
                if any(isinstance(x, ast.Name) and x.id == param
                       for e in exprs for x in ast.walk(e)):
                    out.append(c)
        return out

    @staticmethod
    def _innermost(calls: list[ast.Call]) -> list[ast.Call]:
        """Calls whose arg subtree does not contain another passing call —
        spawn(self._commit(req, reply)) credits _commit, not spawn."""
        out = []
        for c in calls:
            arg_nodes = {id(x) for e in (list(c.args)
                                         + [k.value for k in c.keywords])
                         for x in ast.walk(e)}
            if not any(o is not c and id(o) in arg_nodes for o in calls):
                out.append(c)
        return out

    def _map_param(self, cand: FunctionInfo, call: ast.Call,
                   param: str) -> str | None:
        params = self.handler_params(cand)
        for i, argx in enumerate(call.args):
            if isinstance(argx, ast.Name) and argx.id == param:
                return params[i] if i < len(params) else None
        kwonly = [x.arg for x in cand.node.args.kwonlyargs]
        for kw in call.keywords:
            if isinstance(kw.value, ast.Name) and kw.value.id == param \
                    and kw.arg:
                return kw.arg if (kw.arg in params or kw.arg in kwonly) \
                    else None
        return None

    def reply_closure(self, root: FunctionInfo,
                      param: str) -> list[tuple[FunctionInfo, str]]:
        """(function, reply-param-name) pairs reachable from `root` by
        passing the reply through resolvable calls."""
        seen = {(root.fqname, param)}
        order = [(root, param)]
        i = 0
        while i < len(order):
            fn, p = order[i]
            i += 1
            for c in self._passing_calls(p, fn.node):
                for cand in self.pkg.resolve_call_strict(fn.mod, c):
                    mp = self._map_param(cand, c, p)
                    if mp is not None and (cand.fqname, mp) not in seen:
                        seen.add((cand.fqname, mp))
                        order.append((cand, mp))
        return order

    @staticmethod
    def _has_await(node: ast.AST) -> bool:
        return any(isinstance(n, ast.Await) for n in ast.walk(node))

    def _effect(self, fn: FunctionInfo, param: str, node: ast.AST) -> bool:
        """True when executing `node` guarantees the reply is settled or
        handed off/escaped: a direct send/send_error, a pass to a resolvable
        in-package callee (analyzed separately via the closure), or an
        escape (stored, or passed to an unresolvable call)."""
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id == param \
                    and n.func.attr in _SETTLE_ATTRS:
                return True
        passing = self._passing_calls(param, node)
        for c in self._innermost(passing):
            if isinstance(c.func, ast.Name) \
                    and c.func.id in _NOEFFECT_BUILTINS:
                continue
            # any other receiving call counts: a strict-resolvable callee is
            # analyzed itself via reply_closure, an unresolvable one is an
            # escape (assume fine) — either way this frame is off the hook
            return True
        # bare occurrence outside any call argument (x = reply, return reply,
        # tuple literals in assignments): escaped
        covered = {id(x) for c in passing
                   for e in (list(c.args) + [k.value for k in c.keywords])
                   for x in ast.walk(e)}
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id == param \
                    and id(n) not in covered:
                par = fn.mod.parents.get(n)
                if isinstance(par, ast.Attribute):
                    continue  # reply.X probe, not an escape
                return True
        return False

    def _apply(self, fn: FunctionInfo, param: str, node: ast.AST,
               cur: set[bool], out: _Out) -> set[bool]:
        """One simple statement / expression. A raise landing on an await is
        recorded against the PRE-state — unless the awaited expression
        itself consumes the reply (`await self._helper(reply)`): then the
        callee's frame owns the raise path and is analyzed separately."""
        awaits = [n for n in ast.walk(node) if isinstance(n, ast.Await)]
        if awaits:
            consumed = any(self._effect(fn, param, aw.value)
                           for aw in awaits)
            pre = {True} if (consumed and cur) else cur
            out.raises |= pre
            if False in pre:
                out.bad.append(("raise", node))
        if cur and self._effect(fn, param, node):
            return {True}
        return cur

    def _exec(self, fn: FunctionInfo, param: str, stmts: list[ast.stmt],
              in_states: set[bool]) -> _Out:
        out = _Out()
        cur = set(in_states)
        for stmt in stmts:
            if not cur:
                break
            if isinstance(stmt, ast.Return):
                cur = self._apply(fn, param, stmt, cur, out)
                out.returns |= cur
                if False in cur:
                    out.bad.append(("return", stmt))
                cur = set()
            elif isinstance(stmt, ast.Raise):
                out.raises |= cur
                if False in cur:
                    out.bad.append(("raise", stmt))
                cur = set()
            elif isinstance(stmt, ast.If):
                cur = self._apply(fn, param, stmt.test, cur, out)
                o1 = self._exec(fn, param, stmt.body, cur)
                self._merge(out, o1)
                nxt = set(o1.fall)
                if stmt.orelse:
                    o2 = self._exec(fn, param, stmt.orelse, cur)
                    self._merge(out, o2)
                    nxt |= o2.fall
                else:
                    nxt |= cur
                cur = nxt
            elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
                head = stmt.test if isinstance(stmt, ast.While) else stmt.iter
                cur = self._apply(fn, param, head, cur, out)
                if isinstance(stmt, ast.AsyncFor):
                    out.raises |= cur
                    if False in cur:
                        out.bad.append(("raise", stmt))
                states = set(cur)
                for _ in range(2):
                    o = self._exec(fn, param, stmt.body, states)
                    self._merge(out, o)
                    states = states | o.fall
                if isinstance(stmt, ast.While) \
                        and isinstance(stmt.test, ast.Constant) \
                        and stmt.test.value is True \
                        and not any(isinstance(n, ast.Break)
                                    for n in ast.walk(stmt)):
                    cur = set()  # while True with no break: no fall-through
                else:
                    cur = states
            elif isinstance(stmt, ast.Try) or \
                    stmt.__class__.__name__ == "TryStar":
                cur = self._exec_try(fn, param, stmt, cur, out)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    cur = self._apply(fn, param, item.context_expr, cur, out)
                if isinstance(stmt, ast.AsyncWith):
                    out.raises |= cur
                    if False in cur:
                        out.bad.append(("raise", stmt))
                o = self._exec(fn, param, stmt.body, cur)
                self._merge(out, o)
                cur = o.fall
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue  # nested defs: no effect on this frame's reply
            else:
                cur = self._apply(fn, param, stmt, cur, out)
        out.fall = cur
        return out

    @staticmethod
    def _merge(out: _Out, child: _Out) -> None:
        out.returns |= child.returns
        out.raises |= child.raises
        out.bad.extend(child.bad)

    def _exec_try(self, fn: FunctionInfo, param: str, stmt,
                  cur: set[bool], out: _Out) -> set[bool]:
        o_body = self._exec(fn, param, stmt.body, cur)
        loc = _Out()
        loc.returns |= o_body.returns
        after = set(o_body.fall)
        body_bad = list(o_body.bad)
        if stmt.orelse:
            oe = self._exec(fn, param, stmt.orelse, o_body.fall)
            loc.returns |= oe.returns
            loc.raises |= oe.raises
            loc.bad.extend(oe.bad)
            after = set(oe.fall)
        if stmt.handlers:
            # approximation: every may-raise in the body is caught here (the
            # framework's awaits raise FDBError, and broad handlers dominate
            # this codebase); the handler bodies are analyzed from the
            # settled-states the body could raise in
            loc.bad.extend((k, n) for k, n in body_bad if k != "raise")
            if o_body.raises:
                for h in stmt.handlers:
                    oh = self._exec(fn, param, h.body, set(o_body.raises))
                    loc.returns |= oh.returns
                    loc.raises |= oh.raises
                    loc.bad.extend(oh.bad)
                    after |= oh.fall
        else:
            loc.raises |= o_body.raises
            loc.bad.extend(body_bad)
        if stmt.finalbody:
            probe = self._exec(fn, param, stmt.finalbody, {False})
            if probe.fall == {True}:
                # finally settles unconditionally: every exit through it is
                # settled, so local unsettled exits are rescued
                after = {True} if after else after
                loc.returns = {True} if loc.returns else loc.returns
                loc.raises = {True} if loc.raises else loc.raises
                loc.bad = []
            else:
                o_fin = self._exec(fn, param, stmt.finalbody, after)
                self._merge(loc, o_fin)
                after = o_fin.fall
        self._merge(out, loc)
        return after

    def outcomes(self, fn: FunctionInfo, param: str) -> _Out:
        key = (fn.fqname, param)
        got = self._outcomes_memo.get(key)
        if got is None:
            got = self._exec(fn, param, fn.node.body, {False})
            self._outcomes_memo[key] = got
        return got

    # ----------------------------------------------------- derived indexes

    def registered_tokens(self) -> set[tuple[tuple[str, str], str]]:
        return {(r.cls_key, r.attr) for r in self.registers}

    def sent_tokens(self) -> set[tuple[tuple[str, str], str]]:
        return {(s.cls_key, s.attr) for s in self.sends}

    def handlers_of(self, cls_key: tuple[str, str],
                    attr: str) -> list[FunctionInfo]:
        out, seen = [], set()
        for r in self.registers:
            if (r.cls_key, r.attr) == (cls_key, attr) \
                    and r.handler is not None \
                    and r.handler.fqname not in seen:
                seen.add(r.handler.fqname)
                out.append(r.handler)
        return out


def _analysis(pkg: PackageContext) -> _ProtoAnalysis:
    a = pkg.caches.get("protolint")
    if a is None:
        a = _ProtoAnalysis(pkg)
        pkg.caches["protolint"] = a
    return a


# -------------------------------------------------------------- PROTO001

@register
class TokenRouting(Rule):
    code = "PROTO001"
    summary = ("token <-> handler coverage: duplicate token ints (frames "
               "route to the wrong handler silently), tokens sent but never "
               "register()ed (callers get broken_promise), registered but "
               "unreachable from any send site, or declared dead")

    def check_package(self, pkg: PackageContext) -> Iterable[Finding]:
        ana = _analysis(pkg)
        by_value: dict[int, list[tuple[tuple[str, str], str]]] = {}
        for key, decls in sorted(ana.token_classes.items()):
            for attr, d in sorted(decls.items()):
                by_value.setdefault(d.value, []).append((key, attr))
        for value, owners in sorted(by_value.items()):
            if len(owners) > 1:
                names = ", ".join(f"{k[1]}.{a}" for k, a in owners)
                for key, attr in owners[1:]:
                    d = ana.token_classes[key][attr]
                    yield self.finding(
                        d.mod, d.node, f"{key[1]}.{attr}",
                        f"token value {value} is bound to {names} — token "
                        f"ints share one routing namespace per process; a "
                        f"duplicate silently routes frames to whichever "
                        f"handler registered last")
        registered = ana.registered_tokens()
        sent = ana.sent_tokens()
        for s in ana.sends:
            if (s.cls_key, s.attr) not in registered:
                yield self.finding(
                    s.mod, s.node, f"{s.cls_key[1]}.{s.attr}",
                    f"{s.cls_key[1]}.{s.attr} is sent to but no role "
                    f"register()s it — every request gets broken_promise")
        reachable = sent | ana.indirect_refs
        reported: set[tuple] = set()
        for r in ana.registers:
            tok = (r.cls_key, r.attr)
            if tok not in reachable and tok not in reported:
                reported.add(tok)
                yield self.finding(
                    r.mod, r.node, f"{r.cls_key[1]}.{r.attr}",
                    f"{r.cls_key[1]}.{r.attr} is registered but unreachable "
                    f"from any Endpoint send site — dead handler")
        for key, decls in sorted(ana.token_classes.items()):
            for attr, d in sorted(decls.items()):
                tok = (key, attr)
                if tok not in registered and tok not in reachable:
                    yield self.finding(
                        d.mod, d.node, f"{key[1]}.{attr}",
                        f"{key[1]}.{attr} is declared but neither "
                        f"registered nor sent — dead protocol surface")


# -------------------------------------------------------------- PROTO002

@register
class ReplyOnAllPaths(Rule):
    code = "PROTO002"
    summary = ("a handler (or the coroutine it spawns) can exit with its "
               "reply promise unsettled — early return, or an await that "
               "raises/cancels outside a settling try — wedging the caller "
               "until the full RPC timeout. Interprocedural through every "
               "call the reply is passed to.")

    def check_package(self, pkg: PackageContext) -> Iterable[Finding]:
        ana = _analysis(pkg)
        emitted: set[tuple] = set()
        roots: list[tuple[FunctionInfo, str]] = []
        seen_roots: set[str] = set()
        for r in ana.registers:
            if r.handler is None or r.handler.fqname in seen_roots:
                continue
            seen_roots.add(r.handler.fqname)
            param = ana.reply_param(r.handler)
            if param is not None:
                roots.append((r.handler, param))
        for root, root_param in roots:
            for fn, param in ana.reply_closure(root, root_param):
                out = ana.outcomes(fn, param)
                if False in out.fall:
                    key = (fn.fqname, "fall")
                    if key not in emitted:
                        emitted.add(key)
                        yield self.finding(
                            fn.mod, fn.node, "fall-unsettled",
                            f"{fn.qualname} can fall off the end without "
                            f"settling the reply promise — the caller waits "
                            f"out the full RPC timeout")
                for kind, node in out.bad:
                    if kind == "raise" and not fn.is_async:
                        continue  # sync-handler raises are answered by the
                        # transport (unknown_error); spawned-coroutine
                        # raises are not
                    key = (fn.fqname, kind, id(node))
                    if key not in emitted:
                        emitted.add(key)
                        if kind == "return":
                            msg = (f"{fn.qualname} returns with the reply "
                                   f"promise possibly unsettled — the "
                                   f"caller waits out the full RPC timeout")
                        else:
                            msg = (f"an await in {fn.qualname} can raise or "
                                   f"be cancelled while the reply is "
                                   f"unsettled; errors in a spawned "
                                   f"coroutine are not answered by the "
                                   f"transport — settle (or send_error) in "
                                   f"an enclosing try")
                        yield self.finding(fn.mod, node,
                                           f"{kind}-unsettled", msg)


# -------------------------------------------------------------- PROTO003

@register
class RequestReplyPairing(Rule):
    code = "PROTO003"
    summary = ("request/reply type pairing: one token sent with different "
               "request dataclasses, a handler annotated for a different "
               "request type than its senders construct, or one token's "
               "handlers constructing different reply dataclasses")

    def check_package(self, pkg: PackageContext) -> Iterable[Finding]:
        ana = _analysis(pkg)
        by_tok: dict[tuple, list[_SendSite]] = {}
        for s in ana.sends:
            if s.payload_cls is not None:
                by_tok.setdefault((s.cls_key, s.attr), []).append(s)
        for tok, sites in sorted(by_tok.items()):
            classes = sorted({s.payload_cls for s in sites})
            label = f"{tok[0][1]}.{tok[1]}"
            if len(classes) > 1:
                yield self.finding(
                    sites[0].mod, sites[0].node, label,
                    f"{label} is sent with inconsistent request types: "
                    f"{', '.join(classes)} — one token must resolve to one "
                    f"request dataclass")
                continue
            req_cls = classes[0]
            for h in ana.handlers_of(*tok):
                ann = self._req_annotation(ana, h)
                if ann is not None and ann != req_cls:
                    yield self.finding(
                        h.mod, h.node, label,
                        f"handler {h.qualname} annotates its request as "
                        f"{ann} but senders of {label} construct {req_cls}")
        for tok in sorted(set(by_tok) | {(r.cls_key, r.attr)
                                         for r in ana.registers}):
            replies: set[str] = set()
            anchor: FunctionInfo | None = None
            for h in ana.handlers_of(*tok):
                param = ana.reply_param(h)
                if param is None:
                    continue
                anchor = anchor or h
                for fn, p in ana.reply_closure(h, param):
                    replies |= self._reply_ctors(ana, fn, p)
            if len(replies) > 1 and anchor is not None:
                label = f"{tok[0][1]}.{tok[1]}"
                yield self.finding(
                    anchor.mod, anchor.node, label,
                    f"handlers of {label} construct inconsistent reply "
                    f"types: {', '.join(sorted(replies))}")

    @staticmethod
    def _req_annotation(ana: _ProtoAnalysis,
                        fn: FunctionInfo) -> str | None:
        a = fn.node.args
        args = a.posonlyargs + a.args
        if fn.class_name is not None and args \
                and args[0].arg in ("self", "cls"):
            args = args[1:]
        if not args:
            return None
        ann = args[0].annotation
        name = None
        if isinstance(ann, ast.Name):
            name = ann.id
        elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.split(".")[-1]
        elif isinstance(ann, ast.Attribute):
            name = ann.attr
        return name if name in ana.dataclasses else None

    @staticmethod
    def _reply_ctors(ana: _ProtoAnalysis, fn: FunctionInfo,
                     param: str) -> set[str]:
        out: set[str] = set()
        for n in ast.walk(fn.node):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id == param and n.func.attr == "send" \
                    and n.args and isinstance(n.args[0], ast.Call):
                name = ana._class_name_of(n.args[0].func)
                if name is not None:
                    out.add(name)
        return out


# -------------------------------------------------------------- PROTO004

@register
class SerializerConformance(Rule):
    code = "PROTO004"
    summary = ("wire-serializer conformance: a dataclass crossing "
               "NetTransport with no registry entry (WireError at the first "
               "real-transport send — invisible under the sim, which "
               "delivers by reference), a duplicate wire id, or a "
               "registered dataclass whose field type is an unregistered "
               "dataclass")

    def check_package(self, pkg: PackageContext) -> Iterable[Finding]:
        ana = _analysis(pkg)
        if not ana.registry_present:
            return
        for tid, entries in sorted(ana.registry_ids.items()):
            if len(entries) > 1:
                names = ", ".join(e[0] for e in entries)
                name, node, mod = entries[1]
                yield self.finding(
                    mod, node, f"id:{tid}",
                    f"wire type id {tid} is pinned to more than one class "
                    f"({names}) — ids are wire format and must be unique")
        for s in ana.sends:
            if s.payload_cls is not None \
                    and s.payload_cls not in ana.registered_names:
                yield self.finding(
                    s.mod, s.node, s.payload_cls,
                    f"{s.payload_cls} crosses the transport at this send "
                    f"site but has no wire-registry entry — the first "
                    f"real-network send raises WireError (the sim delivers "
                    f"by reference and never catches this)")
        for name in sorted(ana.registered_names):
            for dc in ana.dataclasses.get(name, ()):
                yield from self._check_fields(ana, dc)

    def _check_fields(self, ana: _ProtoAnalysis,
                      dc: _DC) -> Iterable[Finding]:
        for stmt in dc.node.body:
            if not (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                continue
            ann = stmt.annotation
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                try:
                    ann = ast.parse(ann.value, mode="eval").body
                except SyntaxError:
                    continue
            for n in ast.walk(ann):
                if isinstance(n, ast.Name) \
                        and n.id not in _WIRE_OK_NAMES \
                        and n.id in ana.dataclasses \
                        and n.id not in ana.registered_names:
                    yield self.finding(
                        dc.mod, stmt, f"{dc.name}.{stmt.target.id}",
                        f"registered dataclass {dc.name} field "
                        f"'{stmt.target.id}' is typed {n.id}, a dataclass "
                        f"with no wire-registry entry — encoding raises "
                        f"WireError on the first populated instance")


# -------------------------------------------------------------- PROTO005

@register
class CSchemaParity(Rule):
    code = "PROTO005"
    summary = ("Python<->C schema parity: the struct schemas and hard-coded "
               "field counts in native/fdb_native.c's wire-frame emitters "
               "must match the Python dataclass field lists — a field added "
               "on one side silently mis-fills decoded replies")

    def check_package(self, pkg: PackageContext) -> Iterable[Finding]:
        ana = _analysis(pkg)
        if not ana.registry_present:
            return  # snippet run: no wire registry, nothing to cross-check
        c_path = self._c_source_path(pkg)
        if c_path is None:
            return
        with open(c_path, encoding="utf-8") as f:
            source = f.read()
        py_fields = {name: ana.dataclass_fields(name)
                     for name in ana.registered_names}
        py_fields = {k: v for k, v in py_fields.items() if v is not None}
        for schema, message in c_parity_problems(
                parse_c_schemas(source), py_fields, ana.registered_names):
            yield Finding(rule=self.code, path=C_RELPATH, line=schema.line,
                          symbol=schema.name,
                          detail=f"{schema.name}:schema", message=message)

    @staticmethod
    def _c_source_path(pkg: PackageContext) -> str | None:
        """The C source next to the analyzed package, found from the wire
        module's location on disk (works no matter the analysis cwd)."""
        from foundationdb_tpu.analysis import flowlint
        path = os.path.join(flowlint.default_target(),
                            "native", "fdb_native.c")
        return path if os.path.exists(path) else None


# -------------------------------------------------------------- PROTO006

@register
class TimeoutDiscipline(Rule):
    code = "PROTO006"
    summary = ("request(..., timeout=None) not wrapped in loop.timeout(...) "
               "— an unbounded remote wait survives peer death only via "
               "broken_promise; anything else wedges the caller forever")

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "request"):
                continue
            if not any(kw.arg == "timeout"
                       and isinstance(kw.value, ast.Constant)
                       and kw.value.value is None
                       for kw in node.keywords):
                continue
            wrapped = any(isinstance(anc, ast.Call)
                          and isinstance(anc.func, ast.Attribute)
                          and anc.func.attr == "timeout"
                          for anc in mod.ancestors(node))
            if not wrapped:
                yield self.finding(
                    mod, node, "timeout=None",
                    "request(..., timeout=None) with no enclosing "
                    "loop.timeout(...): the wait is unbounded — bound the "
                    "delivery or document why the wait may be infinite")


# -------------------------------------------------------------- PROTO007

@register
class RetransmitDedup(Rule):
    code = "PROTO007"
    summary = ("retransmit-dedup discipline: a request type carrying "
               "request_num must also carry the epoch fence, and its "
               "handlers must actually read request_num (a retried request "
               "that is not deduped double-allocates/double-applies)")

    def check_package(self, pkg: PackageContext) -> Iterable[Finding]:
        ana = _analysis(pkg)
        for name, entries in sorted(ana.dataclasses.items()):
            for dc in entries:
                if "request_num" in dc.fields and "epoch" not in dc.fields:
                    yield self.finding(
                        dc.mod, dc.node, name,
                        f"{name} carries request_num (a retried request) "
                        f"but no epoch fence — a retransmit answered by a "
                        f"deposed generation's handler dedup cache crosses "
                        f"recovery boundaries")
        by_tok: dict[tuple, str] = {}
        for s in ana.sends:
            if s.payload_cls is not None:
                fields = ana.dataclass_fields(s.payload_cls) or []
                if "request_num" in fields:
                    by_tok[(s.cls_key, s.attr)] = s.payload_cls
        for tok, cls in sorted(by_tok.items()):
            for h in ana.handlers_of(*tok):
                param = ana.reply_param(h)
                closure = (ana.reply_closure(h, param)
                           if param is not None else [(h, "")])
                if not any(self._reads_request_num(fn)
                           for fn, _p in closure):
                    yield self.finding(
                        h.mod, h.node, f"{cls}->{h.name}",
                        f"handler {h.qualname} receives {cls} (which "
                        f"carries request_num) but never reads it — "
                        f"retransmitted requests are re-executed instead "
                        f"of answered from the dedup cache")

    @staticmethod
    def _reads_request_num(fn: FunctionInfo) -> bool:
        return any(isinstance(n, ast.Attribute) and n.attr == "request_num"
                   for n in ast.walk(fn.node))


# -------------------------------------------------------------- PROTO008

@register
class ReplyErrorHandling(Rule):
    code = "PROTO008"
    summary = ("an awaited request inside a long-running (while) loop with "
               "no try between the await and the loop — one reply-error "
               "frame (kind=2: dead peer, deposed role, handler raise) "
               "kills the actor permanently instead of one iteration")

    def check(self, mod: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Await):
                continue
            if not any(isinstance(c, ast.Call)
                       and isinstance(c.func, ast.Attribute)
                       and c.func.attr == "request"
                       for c in ast.walk(node.value)):
                continue
            guarded = False
            loop = None
            for anc in mod.ancestors(node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break
                if isinstance(anc, ast.Try) and anc.handlers:
                    # a try anywhere in the function counts — outside the
                    # loop it converts "actor dies" into a handled exit
                    guarded = True
                if isinstance(anc, ast.While) and loop is None:
                    loop = anc
            if loop is not None and not guarded:
                yield self.finding(
                    mod, node, "unguarded-await",
                    "awaited request inside a long-running loop with no "
                    "try/except between the await and the loop — a single "
                    "reply-error (dead peer, deposed role) permanently "
                    "kills this actor; catch FDBError per iteration")
