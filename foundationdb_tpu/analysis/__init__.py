"""flowlint: the repo's own static analyzer for actor discipline.

See docs/flowlint.md. Public surface:

    from foundationdb_tpu.analysis import flowlint
    findings = flowlint.analyze_paths(["foundationdb_tpu/"])

or the CLI: `python -m foundationdb_tpu.analysis --format=json`.
"""

from foundationdb_tpu.analysis.flowlint import (  # noqa: F401
    Finding, analyze_paths, analyze_source, apply_baseline, load_baseline,
    write_baseline)
