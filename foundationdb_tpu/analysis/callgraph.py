"""Package-level call graph for interprocedural lint rules.

flowlint's original engine was strictly per-module: every rule saw one
ModuleContext at a time, so a coroutine that called a blocking helper
defined two modules away was invisible. PackageContext parses the whole
target set once, indexes every function/method, and resolves call sites
through import aliases — enough for per-function summaries (devlint's
blocks-on-host propagation, jit-target reachability) to cross module
boundaries.

Resolution is deliberately conservative:

  - `f(...)` resolves to the module-level `f` in the same module, to the
    function a `from pkg.mod import f` alias names, or to `Cls.__init__`
    when `f` is a class defined/imported in the module.
  - `self.m(...)` resolves to method `m` of the enclosing class when it
    defines one.
  - `obj.m(...)` on an arbitrary receiver resolves to EVERY method named
    `m` across the package ("duck candidates"). Callers that need
    soundness-against-false-positives must require that *all* candidates
    share the property they propagate (see devlint's blocking fixpoint).

Unresolved calls return no candidates; rules treat that as "assume fine"
— the engine under-approximates rather than spray false positives.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from foundationdb_tpu.analysis.flowlint import ModuleContext, PACKAGE_NAME


@dataclass
class FunctionInfo:
    """One def/async def anywhere in the package, plus room for the
    per-function summaries interprocedural rules compute over it."""

    fqname: str                 # "<relpath>::<qualname>"
    relpath: str
    qualname: str
    node: ast.AST               # FunctionDef | AsyncFunctionDef
    mod: ModuleContext
    is_async: bool
    class_name: str | None      # enclosing class for methods, else None
    summary: dict = field(default_factory=dict)  # rule-family scratch space

    @property
    def name(self) -> str:
        return self.node.name


def _dotted_module_name(relpath: str) -> str | None:
    """foundationdb_tpu/ops/conflict.py -> foundationdb_tpu.ops.conflict;
    paths outside the package (scripts/...) have no importable name."""
    if not relpath.startswith(PACKAGE_NAME + "/"):
        return None
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class PackageContext:
    """All ModuleContexts of one analysis run + cross-module indexes."""

    def __init__(self, modules: list[ModuleContext]):
        self.modules = list(modules)
        self.by_relpath: dict[str, ModuleContext] = {
            m.relpath: m for m in self.modules}
        self.by_dotted: dict[str, ModuleContext] = {}
        for m in self.modules:
            dn = _dotted_module_name(m.relpath)
            if dn is not None:
                self.by_dotted[dn] = m

        # (relpath, name) -> FunctionInfo for module-level functions
        self.top_level: dict[tuple[str, str], FunctionInfo] = {}
        # (relpath, ClassName) -> {method name -> FunctionInfo}
        self.classes: dict[tuple[str, str], dict[str, FunctionInfo]] = {}
        # method name -> [FunctionInfo ...] across every class (duck index)
        self.methods_by_name: dict[str, list[FunctionInfo]] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self._index()
        # rule families stash shared computed state here (e.g. devlint's
        # blocking fixpoint) so eight rules don't redo one analysis
        self.caches: dict[str, object] = {}

    # ---------------------------------------------------------------- build

    def _index(self) -> None:
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                qual = mod.qualname(node)
                info = FunctionInfo(
                    fqname=f"{mod.relpath}::{qual}",
                    relpath=mod.relpath, qualname=qual, node=node, mod=mod,
                    is_async=isinstance(node, ast.AsyncFunctionDef),
                    class_name=self._enclosing_class_name(mod, node))
                self.functions[info.fqname] = info
                parent = mod.parents.get(node)
                if isinstance(parent, ast.Module):
                    self.top_level[(mod.relpath, node.name)] = info
                elif isinstance(parent, ast.ClassDef):
                    cls = self.classes.setdefault(
                        (mod.relpath, parent.name), {})
                    cls[node.name] = info
                    if not node.name.startswith("__"):
                        self.methods_by_name.setdefault(
                            node.name, []).append(info)

    @staticmethod
    def _enclosing_class_name(mod: ModuleContext,
                              node: ast.AST) -> str | None:
        parent = mod.parents.get(node)
        return parent.name if isinstance(parent, ast.ClassDef) else None

    # ------------------------------------------------------------- resolve

    def _lookup_in_module(self, mod: ModuleContext,
                          name: str) -> list[FunctionInfo]:
        info = self.top_level.get((mod.relpath, name))
        if info is not None:
            return [info]
        cls = self.classes.get((mod.relpath, name))
        if cls is not None:  # ClassName(...) -> __init__ when defined
            init = cls.get("__init__")
            return [init] if init is not None else []
        return []

    def _resolve_alias(self, mod: ModuleContext,
                       name: str) -> list[FunctionInfo]:
        """`from pkg.mod import f [as g]` / `import pkg.mod as m; m.f`."""
        origin = mod.import_aliases.get(name)
        if not origin or "." not in origin:
            return []
        modname, attr = origin.rsplit(".", 1)
        target = self.by_dotted.get(modname)
        if target is None:
            return []
        return self._lookup_in_module(target, attr)

    def resolve_call(self, mod: ModuleContext,
                     call: ast.Call) -> list[FunctionInfo]:
        """Candidate callees of one call site; [] when unresolvable."""
        func = call.func
        if isinstance(func, ast.Name):
            local = self._lookup_in_module(mod, func.id)
            if local:
                return local
            return self._resolve_alias(mod, func.id)
        if isinstance(func, ast.Attribute):
            # self.m(...) -> the enclosing class's own method
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                for anc in mod.ancestors(call):
                    if isinstance(anc, ast.ClassDef):
                        info = self.classes.get(
                            (mod.relpath, anc.name), {}).get(func.attr)
                        if info is not None:
                            return [info]
                        break
            # m.f(...) through a module alias (import pkg.mod as m)
            dotted = mod.resolve_dotted(func)
            if dotted and "." in dotted:
                modname, attr = dotted.rsplit(".", 1)
                target = self.by_dotted.get(modname)
                if target is not None:
                    return self._lookup_in_module(target, attr)
            # arbitrary receiver: every method of that name in the package
            return list(self.methods_by_name.get(func.attr, []))
        return []

    def resolve_call_strict(self, mod: ModuleContext,
                            call: ast.Call) -> list[FunctionInfo]:
        """resolve_call without the duck-candidate fallback: only
        same-module names, `self.m` on a method the enclosing class itself
        defines, and module-alias dotted calls resolve; an arbitrary
        receiver resolves to nothing. For rules that HAND OFF tracked state
        to the callee (protolint's reply closure): duck candidates would
        claim `queue.append(reply)` hands the reply to SimFile.append."""
        func = call.func
        if isinstance(func, ast.Name):
            local = self._lookup_in_module(mod, func.id)
            return local or self._resolve_alias(mod, func.id)
        if isinstance(func, ast.Attribute):
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                for anc in mod.ancestors(call):
                    if isinstance(anc, ast.ClassDef):
                        info = self.classes.get(
                            (mod.relpath, anc.name), {}).get(func.attr)
                        return [info] if info is not None else []
            dotted = mod.resolve_dotted(func)
            if dotted and "." in dotted:
                modname, attr = dotted.rsplit(".", 1)
                target = self.by_dotted.get(modname)
                if target is not None:
                    return self._lookup_in_module(target, attr)
        return []

    # -------------------------------------------------------------- helpers

    def function_of(self, mod: ModuleContext,
                    node: ast.AST) -> FunctionInfo | None:
        """FunctionInfo owning `node` (nearest enclosing def/async def)."""
        fn = mod.enclosing_function(node)
        if fn is None:
            return None
        return self.functions.get(f"{mod.relpath}::{mod.qualname(fn)}")

    def iter_functions(self):
        return iter(self.functions.values())
