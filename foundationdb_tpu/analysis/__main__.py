"""CLI: `python -m foundationdb_tpu.analysis [paths...]`.

Exit codes: 0 = clean (every finding baselined), 1 = new violations,
2 = usage error. `--update-baseline` regenerates the allowlist, carrying
forward documented reasons and stamping FIXME on new entries so an
undocumented grandfather can never slip through tier-1.
"""

from __future__ import annotations

import argparse
import sys

from foundationdb_tpu.analysis import flowlint


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.analysis",
        description="flowlint: actor-discipline & determinism analyzer")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to analyze "
                             "(default: the foundationdb_tpu package)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", default=flowlint.default_baseline_path(),
                        help="baseline allowlist path (default: the "
                             "checked-in flowlint_baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, baselined or not")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    rules = flowlint.active_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.code}  {r.summary}")
        return 0

    paths = args.paths or [flowlint.default_target()]
    findings = flowlint.analyze_paths(paths, rules)

    if args.update_baseline:
        flowlint.write_baseline(args.baseline, findings,
                                flowlint.load_baseline(args.baseline))
        print(f"baseline written: {args.baseline} "
              f"({len(findings)} finding(s))", file=sys.stderr)
        return 0

    if args.no_baseline:
        new, stale = findings, []
    else:
        baseline = flowlint.load_baseline(args.baseline)
        new, stale = flowlint.apply_baseline(findings, baseline)

    out = (flowlint.format_json(new) if args.format == "json"
           else flowlint.format_text(new))
    if out:
        print(out)
    for entry in stale:
        print(f"warning: stale baseline entry "
              f"{flowlint._entry_key(entry)} matches nothing "
              f"(run --update-baseline)", file=sys.stderr)
    if new:
        print(f"flowlint: {len(new)} new violation(s) "
              f"({len(findings) - len(new)} baselined)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
