"""CLI: `python -m foundationdb_tpu.analysis [paths...]`.

Exit codes: 0 = clean (every finding baselined), 1 = new violations or
baseline drift under --check, 2 = usage error. `--update-baseline`
regenerates the allowlist, carrying forward documented reasons and
stamping FIXME on new entries so an undocumented grandfather can never
slip through tier-1. `--update-baseline --check` performs a dry run: it
compares the would-be baseline against the committed one and fails on any
difference (the drift gate scripts/lint.sh runs in CI).
"""

from __future__ import annotations

import argparse
import json
import sys

from foundationdb_tpu.analysis import flowlint


def _family_set(family: str) -> set[str] | None:
    return None if family == "all" else {family}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.analysis",
        description="flowlint/devlint/protolint/natlint: actor-discipline, "
                    "determinism, device-discipline, protocol-conformance "
                    "and native-C analyzer")
    parser.add_argument("paths", nargs="*",
                        help="files/directories to analyze (default: the "
                             "foundationdb_tpu package + repo scripts/)")
    parser.add_argument("--family",
                        choices=flowlint.FAMILIES + ("all",),
                        default="all",
                        help="rule family to run (default: all)")
    parser.add_argument("--format", choices=("text", "json", "github"),
                        default="text")
    parser.add_argument("--baseline", default=flowlint.default_baseline_path(),
                        help="baseline allowlist path (default: the "
                             "checked-in flowlint_baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, baselined or not")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings")
    parser.add_argument("--check", action="store_true",
                        help="with --update-baseline: don't write; exit 1 "
                             "if the regenerated baseline would differ "
                             "from the committed one (drift detection)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    rules = flowlint.active_rules(args.family)
    families = _family_set(args.family)
    if args.list_rules:
        for r in rules:
            print(f"{r.code}  {r.summary}")
        return 0
    if args.check and not args.update_baseline:
        parser.error("--check requires --update-baseline")

    paths = args.paths or flowlint.default_targets()
    findings = flowlint.analyze_paths(paths, rules)

    if args.update_baseline:
        old = flowlint.load_baseline(args.baseline)
        if args.check:
            import os
            import tempfile
            fd, tmp = tempfile.mkstemp(suffix=".json")
            os.close(fd)
            try:
                flowlint.write_baseline(tmp, findings, old,
                                        families=families)
                with open(tmp, encoding="utf-8") as f:
                    regenerated = json.load(f)
            finally:
                os.unlink(tmp)
            committed = {"version": 1,
                         "entries": sorted(
                             old.entries,
                             key=lambda e: (e["rule"], e["path"],
                                            e["symbol"], e["detail"]))}
            if regenerated != committed:
                print("baseline drift: the committed baseline no longer "
                      "matches current findings (run --update-baseline "
                      "and document any new entries)", file=sys.stderr)
                return 1
            print("baseline up to date", file=sys.stderr)
            return 0
        flowlint.write_baseline(args.baseline, findings, old,
                                families=families)
        print(f"baseline written: {args.baseline} "
              f"({len(findings)} finding(s))", file=sys.stderr)
        return 0

    if args.no_baseline:
        new, stale = findings, []
    else:
        baseline = flowlint.load_baseline(args.baseline)
        new, stale = flowlint.apply_baseline(findings, baseline,
                                             families=families)

    formatter = {"json": flowlint.format_json,
                 "github": flowlint.format_github,
                 "text": flowlint.format_text}[args.format]
    out = formatter(new)
    if out:
        print(out)
    for entry in stale:
        print(f"warning: stale baseline entry "
              f"{flowlint._entry_key(entry)} matches nothing "
              f"(run --update-baseline)", file=sys.stderr)
    if new:
        print(f"flowlint: {len(new)} new violation(s) "
              f"({len(findings) - len(new)} baselined)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
