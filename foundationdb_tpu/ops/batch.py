"""Shared batch types for the conflict engine.

Reference interface: fdbserver/ConflictSet.h:27-44 — ConflictBatch collects
transactions (read snapshot + read/write conflict ranges), detectConflicts
returns a per-transaction result in {TransactionConflict, TransactionTooOld,
TransactionCommitted} (:36-40). We keep the reference's result numbering so
logs/tests line up.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# ConflictSet.h:36-40 TransactionConflictStatus
CONFLICT = 0
TOO_OLD = 1
COMMITTED = 2

STATUS_NAMES = {CONFLICT: "Conflict", TOO_OLD: "TooOld", COMMITTED: "Committed"}


@dataclass
class TxnConflictInfo:
    """One transaction's conflict information (CommitTransaction.h:89-101).

    Ranges are half-open [begin, end) byte-string pairs.
    """

    read_snapshot: int
    read_ranges: list[tuple[bytes, bytes]] = field(default_factory=list)
    write_ranges: list[tuple[bytes, bytes]] = field(default_factory=list)


# Conflict-engine config validation — the validate_storage_engine analogue
# (storage/kvstore.py:246). Lives here rather than server/resolver.py on
# purpose: resolver.py imports the device stack, and every worker (storage-
# only processes included) must be able to fail fast at boot without paying
# a jax import.
VALID_CONFLICT_BACKENDS = ("oracle", "device", "sharded")


def validate_conflict_config(backend=None, num_shards=None):
    """Fail at worker boot on a misconfigured resolver, not on the first
    commit batch minutes later. Arguments default to the live knobs; the
    device-count check against CONFLICT_NUM_SHARDS happens later, at engine
    construction, where discovery is already bounded."""
    from foundationdb_tpu.utils.errors import FDBError
    from foundationdb_tpu.utils.knobs import KNOBS

    if backend is None:
        backend = KNOBS.CONFLICT_BACKEND
    if backend not in VALID_CONFLICT_BACKENDS:
        raise FDBError(
            "invalid_option",
            f"unknown CONFLICT_BACKEND {backend!r}: valid backends are "
            + ", ".join(VALID_CONFLICT_BACKENDS))
    if num_shards is None:
        num_shards = KNOBS.CONFLICT_NUM_SHARDS
    if isinstance(num_shards, bool) or not isinstance(num_shards, int) \
            or num_shards < 0:
        raise FDBError(
            "invalid_option",
            f"CONFLICT_NUM_SHARDS must be a non-negative integer "
            f"(0 = span every attached device); got {num_shards!r}")
