"""Shared batch types for the conflict engine.

Reference interface: fdbserver/ConflictSet.h:27-44 — ConflictBatch collects
transactions (read snapshot + read/write conflict ranges), detectConflicts
returns a per-transaction result in {TransactionConflict, TransactionTooOld,
TransactionCommitted} (:36-40). We keep the reference's result numbering so
logs/tests line up.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# ConflictSet.h:36-40 TransactionConflictStatus
CONFLICT = 0
TOO_OLD = 1
COMMITTED = 2

STATUS_NAMES = {CONFLICT: "Conflict", TOO_OLD: "TooOld", COMMITTED: "Committed"}


@dataclass
class TxnConflictInfo:
    """One transaction's conflict information (CommitTransaction.h:89-101).

    Ranges are half-open [begin, end) byte-string pairs.
    """

    read_snapshot: int
    read_ranges: list[tuple[bytes, bytes]] = field(default_factory=list)
    write_ranges: list[tuple[bytes, bytes]] = field(default_factory=list)
