"""CPU oracle for conflict detection — the obviously-correct reference.

Plays the role the reference's naive structures play for its optimized engine:
SkipList.cpp keeps a `MiniConflictSet2` (:1010-1026) and a naive interval map
oracle so the fast path can be cross-checked for *identical abort decisions*
(miniConflictSetTest :1394). Our device kernel is validated against this class
the same way.

Semantics implemented (from SkipList.cpp / Resolver.actor.cpp):

- State is the max-commit-version step function over the keyspace: for any key
  k, maxver(k) = max version of any committed write range covering k within
  the MVCC window. (The skiplist's nodes+versions encode exactly this.)
- A batch at commit version V:
  1. too-old: a txn with read ranges whose read_snapshot < oldestVersion gets
     TransactionTooOld (SkipList.cpp:985 — note: only if it HAS read ranges;
     blind writes never expire).
  2. history check: txn conflicts iff any read range [b,e) has
     max(maxver over [b,e)) > read_snapshot (checkReadConflictRanges :1210).
  3. intra-batch, in batch order: a not-yet-conflicting txn conflicts if a
     read range overlaps a write range of an *earlier non-conflicting* txn in
     this batch; surviving txns then publish their writes
     (checkIntraBatchConflicts :1133 — earlier txns win; aborted txns'
     writes are invisible).
  4. surviving txns' write ranges are merged into the step function at V
     (combine/mergeWriteConflictRanges :1260-1337).
  5. window GC: oldestVersion advances to V - MAX_WRITE_TRANSACTION_LIFE;
     values below the floor are clamped to it and equal-value segments
     coalesce (removeBefore :665, done wholesale here).
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right, insort

from foundationdb_tpu.ops.batch import COMMITTED, CONFLICT, TOO_OLD, TxnConflictInfo
from foundationdb_tpu.utils.knobs import KNOBS

_FLOOR = -(1 << 62)


class OracleConflictSet:
    """Naive step-function interval map over byte-string keys."""

    def __init__(self, oldest_version: int = 0):
        # keys[i] begins segment i; segment i spans [keys[i], keys[i+1]) and
        # the last segment extends to +infinity. keys[0] is always b"".
        self.keys: list[bytes] = [b""]
        self.vals: list[int] = [_FLOOR]
        self.oldest_version = oldest_version
        self._gc_countdown = 64  # batches between coalescing sweeps

    # -- step function primitives --
    def _seg_of(self, key: bytes) -> int:
        return bisect_right(self.keys, key) - 1

    def range_max(self, begin: bytes, end: bytes) -> int:
        if end <= begin:
            return _FLOOR
        i0 = self._seg_of(begin)
        i1 = bisect_left(self.keys, end)
        return max(self.vals[i0:i1])

    def _ensure_boundary(self, key: bytes):
        i = self._seg_of(key)
        if self.keys[i] != key:
            self.keys.insert(i + 1, key)
            self.vals.insert(i + 1, self.vals[i])

    def add_range(self, begin: bytes, end: bytes, version: int):
        if end <= begin:
            return
        # inlined double _ensure_boundary reusing the bisect positions:
        # this is the resolver's per-write-range hot loop (one call per
        # written key per committed transaction)
        keys, vals = self.keys, self.vals
        i0 = bisect_right(keys, begin) - 1
        if keys[i0] != begin:
            i0 += 1
            keys.insert(i0, begin)
            vals.insert(i0, vals[i0 - 1])
        i1 = bisect_left(keys, end, i0)
        if i1 == len(keys) or keys[i1] != end:
            keys.insert(i1, end)
            vals.insert(i1, vals[i1 - 1])
        for i in range(i0, i1):
            if vals[i] < version:
                vals[i] = version

    def remove_before(self, version: int, force: bool = False):
        """Advance the window floor; clamp + coalesce (removeBefore :665).

        The floor ALWAYS advances (it drives TooOld decisions). The
        clamp-and-coalesce sweep is O(segments) and decision-neutral — a
        stored value below the floor can never exceed an allowed snapshot —
        so it runs only periodically (or when forced), the same
        amortization the reference gets from incremental removeBefore."""
        if version <= self.oldest_version:
            return
        self.oldest_version = version
        self._gc_countdown -= 1
        if not force and self._gc_countdown > 0 and len(self.keys) < 65536:
            return
        self._gc_countdown = 64
        nk, nv = [], []
        for k, v in zip(self.keys, self.vals):
            # Clamping values below the floor up to the floor is decision-
            # equivalent: queries always have read_snapshot >= oldest_version
            # (older snapshots were rejected as TooOld), so `v > snapshot` is
            # unchanged for every allowed query.
            v = max(v, version)
            if nv and nv[-1] == v:
                continue  # coalesce equal-value neighbors
            nk.append(k)
            nv.append(v)
        self.keys, self.vals = nk, nv
        self.keys[0] = b""

    # -- batch interface (ConflictBatch) --
    def detect(self, txns: list[TxnConflictInfo], commit_version: int) -> list[int]:
        statuses = [COMMITTED] * len(txns)
        oldest = self.oldest_version

        # 1+2: too-old and history conflicts
        for t, txn in enumerate(txns):
            if txn.read_ranges and txn.read_snapshot < oldest:
                statuses[t] = TOO_OLD
                continue
            for b, e in txn.read_ranges:
                if self.range_max(b, e) > txn.read_snapshot:
                    statuses[t] = CONFLICT
                    break

        # 3: intra-batch, earlier txns win, aborted writers invisible
        published = _RangeSet()
        for t, txn in enumerate(txns):
            if statuses[t] != COMMITTED:
                continue
            if any(published.overlaps(b, e) for b, e in txn.read_ranges):
                statuses[t] = CONFLICT
                continue
            for b, e in txn.write_ranges:
                published.add(b, e)

        # 4: merge surviving writes at commit_version
        for t, txn in enumerate(txns):
            if statuses[t] == COMMITTED:
                for b, e in txn.write_ranges:
                    self.add_range(b, e, commit_version)

        # 5: advance the MVCC window
        self.remove_before(commit_version - KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS)
        return statuses


class _RangeSet:
    """Set of half-open ranges with overlap query (intra-batch write set).
    Kept as sorted disjoint intervals: add/overlaps are O(log n) instead of
    the naive O(n) scan (which made big batches quadratic)."""

    def __init__(self):
        self._begins: list[bytes] = []
        self._ends: list[bytes] = []

    def add(self, begin: bytes, end: bytes):
        if end <= begin:
            return
        bs, es = self._begins, self._ends
        lo = bisect_right(bs, begin)
        if lo > 0 and es[lo - 1] >= begin:
            lo -= 1  # previous interval touches/overlaps
        hi = lo
        n = len(bs)
        while hi < n and bs[hi] <= end:
            hi += 1
        if lo == hi:
            bs.insert(lo, begin)
            es.insert(lo, end)
        else:
            nb = min(begin, bs[lo])
            ne = max(end, es[hi - 1])
            bs[lo:hi] = [nb]
            es[lo:hi] = [ne]

    def overlaps(self, begin: bytes, end: bytes) -> bool:
        if end <= begin or not self._begins:
            return False
        i = bisect_right(self._begins, begin)
        if i > 0 and self._ends[i - 1] > begin:
            return True
        return i < len(self._begins) and self._begins[i] < end
