"""Device kernels and their CPU oracles.

The centerpiece is the conflict engine (SURVEY.md §3.2 north star): the
reference's SkipList-based ConflictSet (fdbserver/SkipList.cpp) re-designed as
a batched interval-overlap kernel over an HBM-resident version-history step
function, one XLA launch per commit batch.
"""

from foundationdb_tpu.ops.batch import (  # noqa: F401
    COMMITTED,
    CONFLICT,
    TOO_OLD,
    TxnConflictInfo,
)
