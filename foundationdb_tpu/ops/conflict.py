"""The TPU conflict engine: batched MVCC conflict detection in one XLA launch.

This replaces fdbserver/SkipList.cpp (the reference's hand-tuned CPU conflict
engine, SURVEY.md §3.2) with a TPU-first design. The reference hides memory
latency with 16 interleaved skiplist cursors (SkipList.cpp:526-552) and a
hierarchical bitmask (:1028-1130); we instead make the whole batch a dense
tensor program:

State = the *max-commit-version step function* over the keyspace, stored as
device-resident sorted boundary keys (fixed-width uint32 limbs) + per-segment
version offsets + a sparse-table (power-of-two window) max pyramid — the dense
analogue of the skiplist's per-level max-version annotations (:324-357).

detect = ONE jitted function built around ONE lax.sort of
[state boundaries | read begins | read ends | write begins | write ends]
(multi-limb binary searches lose to a single wide sort on TPU: each bisection
step is a latency-bound multi-limb gather, while the sort runs at bandwidth):
  1. too-old filter (SkipList.cpp:985 semantics)
  2. history check: each read endpoint's rank among state boundaries comes
     from the sort; O(1) sparse-table range-max over the segment versions,
     compare against each txn's read snapshot (replaces CheckMax :755-837)
  3. intra-batch: endpoint ranks from the same sort feed a dyadic
     sort/scan evaluator for "earlier txns win" semantics — each fixpoint
     sweep is O(n log n) prefix scans over per-level sorted write endpoints
     instead of the old dense (NW, NR) overlap matrix mat-vec, and the
     sweep count is statically bounded (a lax.scan with an early-out cond,
     never an unbounded while_loop); unconverged batches fall back to an
     exact host-side pass (replaces MiniConflictSet :1028-1130; see
     docs/conflict_kernel.md)
  4. merge of surviving writes into the step function: the sorted array IS
     the union; slots, coverage, and values are carved out with prefix scans
     and one compaction scatter (replaces mergeWriteConflictRanges :1260)
  5. window GC by clamp + coalesce (replaces removeBefore :665)

Versions on device are int32 *offsets* from a host-kept int64 base (the MVCC
window is only 5e6 versions wide — fdbserver/Knobs.cpp:30-34 — so offsets fit
comfortably; the host rebases long before overflow). This keeps the kernel in
TPU-native 32-bit arithmetic.

Keys are exact up to KEY_BYTES (24) bytes; longer keys collapse to their
prefix, which can only create false conflicts (safe), never false commits
(utils/keys.py).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from foundationdb_tpu.ops.batch import COMMITTED, CONFLICT, TOO_OLD, TxnConflictInfo
from foundationdb_tpu.utils import keys as keylib
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.stats import CounterCollection

# Process-wide device-kernel gauges (merged into RESOLVER_METRICS):
# dispatch count from detect_async_impl, readback-wait wall seconds from
# drain_and_collect (perf_counter — wall time by design: the wait happens
# off-loop, where sim virtual time does not advance).
kernel_metrics = CounterCollection("ConflictKernel")
_kernel_dispatches = kernel_metrics.counter("KernelDispatches")
_readback_waits = kernel_metrics.counter("ReadbackWaits")
_readback_wait_seconds = kernel_metrics.counter("ReadbackWaitSeconds")


def compile_cache_stats() -> dict:
    """Compile-cache hits/misses across the jitted entry points."""
    step, scan = _compiled_step.cache_info(), _compiled_scan.cache_info()
    return {"CompileCacheHits": step.hits + scan.hits,
            "CompileCacheMisses": step.misses + scan.misses}

L = keylib.NUM_LIMBS  # default key limbs (6 data + 1 length; see ConflictShapes.key_bytes)
_NEG_INT = -(1 << 30)
# "no version" sentinel, below any clamped offset. A plain host int on
# purpose: a module-level jnp scalar would initialize the device backend at
# IMPORT time, which every server role (and any tool importing the client
# stack) would pay — and hang on, if the accelerator runtime is wedged.
# jnp expressions promote it exactly like the former device constant.
NEG = _NEG_INT
_REBASE_THRESHOLD = 1 << 29


def _bulk_encode_at(keys: list[bytes], slots: list[int], out: np.ndarray, *,
                    round_up: bool):
    """Encode keys into out[:, slots[i]] (strided layout)."""
    if not keys:
        return
    nl = out.shape[0]
    tmp = np.empty((nl, len(keys)), dtype=np.uint32)
    _bulk_encode(keys, tmp, round_up=round_up)
    out[:, np.asarray(slots, dtype=np.int64)] = tmp[:, : len(keys)]


def _bulk_encode(keys: list[bytes], out: np.ndarray, *, round_up: bool):
    """Encode keys into out[:, :len(keys)] (SoA limbs), C path if built.
    The limb count (and so the key width) comes from `out`'s shape."""
    if not keys:
        return
    from foundationdb_tpu import native

    nl = out.shape[0]
    key_bytes = (nl - 1) * 4
    if native.available():
        tmp = np.empty((nl, len(keys)), dtype=np.uint32)
        native.mod.encode_keys_into(keys, tmp, round_up, key_bytes)
        out[:, : len(keys)] = tmp
    else:
        buf = np.zeros(nl, dtype=np.uint32)
        for i, k in enumerate(keys):
            keylib.encode_key(k, buf, round_up=round_up, key_bytes=key_bytes)
            out[:, i] = buf


# ---------------------------------------------------------------------------
# multi-limb key comparisons (vectorized lexicographic)
# ---------------------------------------------------------------------------

def _key_lt(a, b):
    """a < b lexicographically; a, b are (L, ...) uint32."""
    lt = jnp.zeros(a.shape[1:], dtype=bool)
    eq = jnp.ones(a.shape[1:], dtype=bool)
    for i in range(a.shape[0]):
        lt = lt | (eq & (a[i] < b[i]))
        eq = eq & (a[i] == b[i])
    return lt


def _key_eq(a, b):
    eq = jnp.ones(a.shape[1:], dtype=bool)
    for i in range(a.shape[0]):
        eq = eq & (a[i] == b[i])
    return eq


# ---------------------------------------------------------------------------
# sparse table (range-max in O(1) per query)
# ---------------------------------------------------------------------------

def _build_table(vals):
    """vals: (K,) int32 -> (LEVELS, K) power-of-two window maxima.

    table[l, i] = max(vals[i : i + 2**l]) (clipped at K). The dense analogue
    of the skiplist's level max-version pyramid (SkipList.cpp:324-357).
    """
    K = vals.shape[0]
    levels = max(1, int(np.ceil(np.log2(max(K, 2)))) + 1)
    rows = [vals]
    cur = vals
    for l in range(1, levels):
        shift = 1 << (l - 1)
        shifted = jnp.concatenate([cur[shift:], jnp.full(min(shift, K), NEG, cur.dtype)])[:K]
        cur = jnp.maximum(cur, shifted)
        rows.append(cur)
    return jnp.stack(rows)


def _range_max(table, i0, i1):
    """Max over vals[i0:i1) for vectors i0 < i1 (int32 arrays)."""
    w = jnp.maximum(i1 - i0, 1)
    lvl = 31 - lax.clz(w)  # floor(log2(w))
    left = table[lvl, i0]
    right = table[lvl, jnp.maximum(i1 - (1 << lvl).astype(jnp.int32), i0)]
    return jnp.maximum(left, right)


# ---------------------------------------------------------------------------
# the jitted step
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ConflictShapes:
    """Static shapes of one conflict batch (one XLA program per instance).

    `key_bytes` sets the exact-comparison width (keys longer than it collapse
    conservatively onto their prefix, utils/keys.py): compare cost on device
    scales linearly with the limb count, so clusters with bounded keys run a
    narrower engine — the reference's memcmp cost scales with key length the
    same way (SkipList.cpp getCharacter/compare)."""

    capacity: int  # K: boundary slots in the step function
    txns: int  # T
    reads: int  # NR: total read ranges per batch (flattened)
    writes: int  # NW: total write ranges per batch
    key_bytes: int = keylib.KEY_BYTES
    # strided=True fixes the range->txn map at TRACE time: read slot j
    # belongs to txn j // (reads//txns), write slot j to txn j // (writes//
    # txns); unused slots are padded with empty ranges. Every per-txn fold
    # (blocked reads -> txn, has_reads, commit -> writes) then compiles to a
    # reshape-reduce instead of a data-dependent scatter/gather — the
    # scatters cost ~0.5ms each on TPU and the intra-batch fixpoint pays one
    # PER EVALUATION. Requires every txn to fit the stride (the encoder
    # rejects oversized txns); the dynamic layout remains the default.
    strided: bool = False

    def __post_init__(self):
        if self.key_bytes % 4 or not 4 <= self.key_bytes <= 64:
            raise ValueError(
                f"key_bytes must be a multiple of 4 in [4, 64], got "
                f"{self.key_bytes} (the limb encoding is 4 bytes wide and "
                f"the native encoder caps at 64)")
        if self.strided and (self.reads % self.txns or self.writes % self.txns):
            raise ValueError("strided layout needs reads/writes divisible by txns")

    @property
    def limbs(self) -> int:
        return self.key_bytes // 4 + 1


def _carry_last_flagged(values, flags):
    """At each position: `values` at the most recent position with flags=True
    (inclusive), or the dtype value passed at unflagged position 0 if none yet.
    One associative scan (the 'last valid' monoid)."""
    def op(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av), af | bf
    out, _ = lax.associative_scan(op, (values, flags))
    return out


def _seg_cummax(vals, reset):
    """Inclusive running max of `vals` restarting wherever reset=True
    (segmented cummax; one associative scan — the monoid carries whether a
    segment boundary was crossed)."""
    def op(a, b):
        av, ar = a
        bv, br = b
        return jnp.where(br, bv, jnp.maximum(av, bv)), ar | br
    out, _ = lax.associative_scan(op, (vals, reset))
    return out


# ---------------------------------------------------------------------------
# intra-batch scan evaluator (dyadic decomposition over the txn index)
# ---------------------------------------------------------------------------

def _intra_scan_levels(T, wtxn_c, rtxn, rbr, rer, wbr, wer):
    """Sweep-invariant geometry for the scan intra-batch evaluator.

    One level per power-of-two block size 2^l (l < ceil(log2 T)). At level l
    writes sort by (wtxn >> l, wbr); a read of txn t queries block
    (t >> l) - 1, i.e. the aligned block of 2^l transactions immediately
    before t's block. The union of those query blocks over all levels is
    exactly [0, t) — the canonical dyadic prefix — so "some committed
    EARLIER txn's write overlaps this read" decomposes into per-level
    queries whose candidates are contiguous runs of the level-sorted order:

      case A (write begins strictly inside the read): a prefix-sum count
        between the two query positions;
      case B (write begins at-or-before the read's begin and covers it): a
        block-segmented running max of committed write ends, gathered at the
        first query position.

    Query positions ride the same per-level sort as two query elements per
    read (class keys order them against equal write begins so <= / < fall
    out of the element order), so the geometry costs one sort + one
    inverse-permutation scatter per level PER STEP and is reused by every
    fixpoint sweep. A read of txn 0 gets query block -1, which sorts before
    every write and self-masks; padding reads/writes are masked by the
    validity masks the caller folds into the committed-write vector.
    """
    NW = wbr.shape[0]
    NR = rbr.shape[0]
    M = NW + 2 * NR
    n_levels = max(1, int(T - 1).bit_length())
    arange_m = jnp.arange(M, dtype=jnp.int32)
    # class tiebreak at equal (block, rank): hi-query(-1) < write(0) <
    # lo-query(1) => lo counts wbr <= rbr, hi counts wbr < rer
    cls = jnp.concatenate([
        jnp.zeros(NW, jnp.int32), jnp.ones(NR, jnp.int32),
        jnp.full(NR, -1, jnp.int32)])
    key2 = jnp.concatenate([wbr, rbr, rer])
    qblk0 = rtxn  # block keys are recomputed per level from the txn index
    levels = []
    for l in range(n_levels):
        key1 = jnp.concatenate(
            [wtxn_c >> l, (qblk0 >> l) - 1, (qblk0 >> l) - 1])
        s1, _s2, _scl, si = lax.sort([key1, key2, cls, arange_m], num_keys=3)
        inv = jnp.zeros(M, jnp.int32).at[si].set(arange_m)
        is_w = si < NW
        src = jnp.minimum(si, NW - 1)
        werl = jnp.where(is_w, wer[src], -1)
        bnd = jnp.concatenate([jnp.ones(1, bool), s1[1:] != s1[:-1]])
        levels.append((src, is_w, werl, bnd,
                       inv[NW:NW + NR], inv[NW + NR:]))
    return levels


def _intra_scan_blocked(c_w, levels, rbr):
    """blocked_r[j] = some write with c_w=True belonging to an earlier txn
    overlaps read j. `c_w` is the (NW,) committed∧valid∧nonempty write mask;
    exactness matches the dense overlap-matrix formulation element for
    element (same ranks, same strict earlier-txn order)."""
    NR = rbr.shape[0]
    blocked = jnp.zeros(NR, bool)
    for src, is_w, werl, bnd, qlo, qhi in levels:
        cm = is_w & c_w[src]
        pref = jnp.cumsum(cm.astype(jnp.int32))  # queries contribute 0
        count_a = pref[qhi] - pref[qlo]
        segmax = _seg_cummax(jnp.where(cm, werl, -1), bnd)
        blocked = blocked | (count_a > 0) | (segmax[qlo] > rbr)
    return blocked


def _run_sandwich(f, g, rounds: int):
    """Statically-bounded lower/upper sandwich on the antitone map f.

    upper ⊇ truth ⊇ lower is invariant; each round tightens both by one
    dependency depth from each side, and rounds are skipped via lax.cond
    once the bounds pinch (so runtime tracks the batch's ACTUAL chain depth,
    like the old while_loop, but the trip count — hence the jaxpr — is
    bounded). rounds >= T//2 guarantees convergence for any batch; smaller
    bounds report converged=False and the host wrapper finishes those txns
    exactly (DetectHandle.result). Returns (lower, upper, converged)."""
    upper = g
    lower = f(upper)

    def round_fn(lu, _):
        def go(lu):
            lo, up = lu
            up2 = f(lo)
            return f(up2), up2
        lu2 = lax.cond(jnp.all(lu[0] == lu[1]), lambda x: x, go, lu)
        return lu2, None

    (lower, upper), _ = lax.scan(round_fn, (lower, upper), None,
                                 length=max(rounds, 0))
    return lower, upper, jnp.all(lower == upper)


def _auto_rounds(T: int) -> int:
    """Default sandwich bound: full-convergence for small batches (T//2+1
    rounds make any chain depth exact), capped at 32 for large ones — a
    depth-65 dependency chain inside one chunk is adversarial, and those
    batches still get exact statuses from the host fallback."""
    return min(T // 2 + 1, 32)


def conflict_step(state: dict, batch: dict, *, shapes: ConflictShapes,
                  max_write_life: int, ablate: str = "",
                  intra_mode: str = "scan", intra_rounds: int = 0):
    """Pure function: (state, batch) -> (state', statuses, info). Jit-able.

    intra_mode selects the intra-batch fixpoint evaluator: "scan" (default,
    per-level sorted prefix scans, statically bounded sweeps) or "legacy"
    (dense overlap matrix + unbounded while_loop — the pre-overhaul path,
    kept for A/B verification). intra_rounds bounds the scan evaluator's
    sandwich rounds (0 = auto, see _auto_rounds).

    state:
      bkeys (L,K) uint32 sorted; bval (K,) i32; nb () i32; oldest () i32;
      table (LEVELS,K) i32
    batch:
      txn_valid (T,) bool; snapshot (T,) i32 (version offsets)
      rb, re (L,NR) u32; rtxn (NR,) i32 (= T for padding);
      wb, we (L,NW) u32; wtxn (NW,) i32 (= T for padding)
      commit_version () i32 offset
      advance_floor () bool — advance the MVCC window after this chunk
      (False for all but the last chunk of a logical batch)

    Layout: ONE lax.sort of [state boundaries | rb | re | wb | we] per step
    feeds everything — history positions (instead of a 19-step multi-limb
    bisection whose per-step gathers dominated the profile), intra-batch
    endpoint ranks (instead of a second sort), and the merged union of state
    with committed write endpoints (instead of a second bisection plus a
    scatter-built union). On TPU a 330k-wide multi-operand sort costs ~2ms
    while each bisection costs ~6.4ms in gathers, so the sort is the cheapest
    way to position queries in the state.
    """
    T, NR, NW, K = shapes.txns, shapes.reads, shapes.writes, shapes.capacity
    L = shapes.limbs
    bkeys, bval, nb, oldest, table = (
        state["bkeys"], state["bval"], state["nb"], state["oldest"], state["table"])
    rb, re, rtxn = batch["rb"], batch["re"], batch["rtxn"]
    wb, we, wtxn = batch["wb"], batch["we"], batch["wtxn"]
    snapshot, txn_valid = batch["snapshot"], batch["txn_valid"]
    vnew = batch["commit_version"]

    if shapes.strided:
        # slot validity from the key itself: real keys never carry the
        # 0xFFFFFFFF length limb the padding uses, so empty-but-real ranges
        # (b == e) still count as "has reads" for the too-old rule
        rvalid = rb[L - 1] != jnp.uint32(0xFFFFFFFF)
        wvalid = wb[L - 1] != jnp.uint32(0xFFFFFFFF)
        has_reads = rvalid.reshape(T, NR // T).any(axis=1)
    else:
        rvalid = rtxn < T
        wvalid = wtxn < T
        has_reads = (jnp.zeros(T + 1, bool).at[rtxn].max(rvalid))[:T]

    # ---- 0. THE sort: [state | rb | re | wb | we] ----
    # Class tiebreak at equal keys: re(0) < state(1) < rb/wb/we(2).
    #  - rb after equal state keys  -> #state<=rb = upper bound (segment of rb)
    #  - re before equal state keys -> #state<re  = lower bound
    #  - wb/we after equal state keys -> duplicate endpoint lands in the SAME
    #    union slot as the state boundary it equals
    N_ALL = K + 2 * NR + 2 * NW
    allk = jnp.concatenate([bkeys, rb, re, wb, we], axis=1)  # (L, N_ALL)
    cls = jnp.concatenate([
        jnp.ones(K, jnp.int32),
        jnp.full(NR, 2, jnp.int32), jnp.zeros(NR, jnp.int32),
        jnp.full(2 * NW, 2, jnp.int32)])
    vpay = jnp.concatenate([bval, jnp.full(2 * NR + 2 * NW, NEG, jnp.int32)])
    sort_ops = [allk[i] for i in range(L)] + [
        cls, vpay, jnp.arange(N_ALL, dtype=jnp.int32)]
    sorted_ops = lax.sort(sort_ops, num_keys=L + 1)
    skeys = jnp.stack(sorted_ops[:L])       # (L, N_ALL) sorted
    scls = sorted_ops[L]
    sval = sorted_ops[L + 1]                # state values in sorted order
    sidx = sorted_ops[L + 2]                # original element index
    # inverse permutation: sorted position of each original element
    spos = jnp.zeros(N_ALL, jnp.int32).at[sidx].set(
        jnp.arange(N_ALL, dtype=jnp.int32))
    is_state = scls == 1
    cum_state = jnp.cumsum(is_state.astype(jnp.int32))  # inclusive

    # ---- 1. too-old (only txns with read ranges expire: SkipList.cpp:985) ----
    too_old = txn_valid & has_reads & (snapshot < oldest)

    # ---- 2. history check: range-max of step function vs snapshot ----
    if ablate in ("no_hist", "only_merge"):
        hist_conflict = jnp.zeros(T, bool)
    else:
        ub_rb = cum_state[spos[K:K + NR]]        # #state keys <= rb
        lb_re = cum_state[spos[K + NR:K + 2 * NR]]  # #state keys < re
        i0 = jnp.maximum(ub_rb - 1, 0)  # segment containing begin
        i1 = lb_re  # first boundary >= end
        nonempty = _key_lt(rb, re)
        maxver = _range_max(table, i0, jnp.maximum(i1, i0 + 1))
        rsnap = (jnp.repeat(snapshot, NR // T) if shapes.strided
                 else snapshot[jnp.minimum(rtxn, T - 1)])
        read_hits = rvalid & nonempty & (maxver > rsnap)
        if shapes.strided:
            hist_conflict = read_hits.reshape(T, NR // T).any(axis=1)
        else:
            hist_conflict = (jnp.zeros(T + 1, bool).at[rtxn].max(read_hits))[:T]

    g0 = txn_valid & ~too_old & ~hist_conflict
    if ablate in ("no_intra", "only_merge", "only_hist"):
        commit = g0
        statuses = jnp.where(
            commit, COMMITTED,
            jnp.where(too_old, TOO_OLD, CONFLICT)).astype(jnp.int32)
        statuses = jnp.where(txn_valid, statuses, COMMITTED)
        return _merge_phase(state, batch, statuses, commit, shapes,
                            max_write_life, ablate, sort_products=(
                                skeys, scls, sval, sidx, spos, cum_state),
                            eligible=g0)
    # ---- 3. intra-batch: endpoint ranks -> overlap queries -> fixpoint ----
    # Endpoint ranks come from the big sort: rank = number of distinct
    # batch-endpoint key groups at-or-before this element, which is
    # order-isomorphic to the keys over batch endpoints (state elements
    # interleave but contribute no rank). The default "scan" evaluator
    # answers each sweep's "does a committed earlier txn's write overlap
    # this read" with per-level prefix scans over sorted write endpoints
    # (geometry built once per step, _intra_scan_levels) — O(n log n) per
    # sweep with no n×n matrix materialized; the "legacy" evaluator is the
    # pre-overhaul dense (NW, NR) int8 matvec + unbounded while_loop.
    is_batch = ~is_state
    newgrp = jnp.concatenate(
        [jnp.ones(1, bool), ~_key_eq(skeys[:, 1:], skeys[:, :-1])])
    cum_b_excl = jnp.cumsum(is_batch.astype(jnp.int32)) - is_batch
    grp_start_b = lax.cummax(jnp.where(newgrp, cum_b_excl, -1))
    first_b = is_batch & (cum_b_excl == grp_start_b)
    rank_grp = jnp.cumsum(first_b.astype(jnp.int32)) - 1
    # carry each group's first-batch rank forward (monotone -> cummax)
    rank_carried = lax.cummax(jnp.where(first_b, rank_grp, -1))
    qranks = rank_carried[spos[K:]]          # ranks of [rb | re | wb | we]
    rbr, rer = qranks[:NR], qranks[NR:2 * NR]
    wbr, wer = qranks[2 * NR:2 * NR + NW], qranks[2 * NR + NW:]

    # empty/inverted ranges (end <= begin) participate in neither side;
    # strict wtxn < rtxn = "earlier txns win" (checkIntraBatchConflicts
    # SkipList.cpp:1139-1152 processes in batch order)
    g = g0
    wtxn_c = jnp.minimum(wtxn, T - 1)
    r_ok = rvalid & (rbr < rer)
    w_ok = wvalid & (wbr < wer)

    def fold_reads(blocked_r):
        if shapes.strided:
            return blocked_r.reshape(T, NR // T).any(axis=1)
        return (jnp.zeros(T + 1, bool).at[rtxn].max(blocked_r))[:T]

    if intra_mode == "legacy":
        if shapes.strided:
            order_ok = (
                (jnp.arange(NW, dtype=jnp.int32) // (NW // T))[:, None]
                < (jnp.arange(NR, dtype=jnp.int32) // (NR // T))[None, :])
        else:
            order_ok = wtxn[:, None] < rtxn[None, :]
        overlap = ((wbr[:, None] < rer[None, :])
                   & (rbr[None, :] < wer[:, None])
                   & w_ok[:, None] & r_ok[None, :]
                   & order_ok)  # (NW, NR)
        # int8 halves the fixpoint's HBM traffic vs bf16 (the matrix read
        # dominates each matvec); int8 x int8 -> int32 runs on the MXU
        ovf = overlap.astype(jnp.int8)

        def _f_commit(c):
            """f(c)[t] = g[t] and no committed-in-c earlier txn's write
            overlaps any of t's reads."""
            cm = jnp.repeat(c, NW // T) if shapes.strided else c[wtxn_c]
            cw = (cm & wvalid).astype(jnp.int8)
            blocked_r = lax.dot_general(
                cw[None, :], ovf, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)[0] > 0
            return g & ~fold_reads(blocked_r)

        upper = g
        lower = _f_commit(upper)

        def cond(lu):
            lower, upper = lu
            return jnp.any(lower != upper)

        def body(lu):
            lower, upper = lu
            upper2 = _f_commit(lower)
            lower2 = _f_commit(upper2)
            return lower2, upper2

        lower, upper = body((lower, upper))
        lower, upper = lax.while_loop(cond, body, (lower, upper))
        commit = lower
        merge_commit = commit
        converged = jnp.asarray(True)
    else:
        levels = _intra_scan_levels(T, wtxn_c, rtxn, rbr, rer, wbr, wer)

        def _f_commit(c):
            cw = ((jnp.repeat(c, NW // T) if shapes.strided
                   else c[wtxn_c]) & w_ok)
            blocked_r = _intra_scan_blocked(cw, levels, rbr) & r_ok
            return g & ~fold_reads(blocked_r)

        rounds = intra_rounds if intra_rounds > 0 else _auto_rounds(T)
        # statuses come from `lower` (⊆ truth: never a false commit) and the
        # merge uses `upper` (⊇ truth: never a missing write in history);
        # both are the truth itself whenever converged — always, for
        # rounds >= T//2+1
        commit, merge_commit, converged = _run_sandwich(_f_commit, g, rounds)

    statuses = jnp.where(
        commit, COMMITTED,
        jnp.where(too_old, TOO_OLD, CONFLICT)).astype(jnp.int32)
    statuses = jnp.where(txn_valid, statuses, COMMITTED)
    return _merge_phase(state, batch, statuses, commit, shapes,
                        max_write_life, ablate, sort_products=(
                            skeys, scls, sval, sidx, spos, cum_state),
                        merge_commit=merge_commit, converged=converged,
                        eligible=g)


def _merge_phase(state, batch, statuses, commit, shapes, max_write_life,
                 ablate="", sort_products=None, merge_commit=None,
                 converged=None, eligible=None):
    T, NR, NW, K = shapes.txns, shapes.reads, shapes.writes, shapes.capacity
    L = shapes.limbs
    bkeys, bval, nb, oldest = (
        state["bkeys"], state["bval"], state["nb"], state["oldest"])
    wb, we, wtxn = batch["wb"], batch["we"], batch["wtxn"]
    vnew = batch["commit_version"]
    wvalid = wtxn < T
    wtxn_c = jnp.minimum(wtxn, T - 1)
    if merge_commit is None:
        merge_commit = commit
    if converged is None:
        converged = jnp.asarray(True)
    if eligible is None:
        eligible = commit

    if ablate in ("no_merge", "only_hist"):
        new_oldest = jnp.maximum(
            oldest, jnp.where(batch["advance_floor"],
                              vnew - jnp.int32(max_write_life), oldest))
        new_state = dict(state, oldest=new_oldest.astype(jnp.int32))
        info = {"overflow": state["poisoned"], "boundaries": nb,
                "committed": jnp.sum(commit.astype(jnp.int32)),
                "converged": converged, "eligible": eligible}
        return new_state, statuses, info

    # ---- 4. merge surviving writes into the step function at vnew ----
    # The union of state boundaries and committed write endpoints is already
    # IN the big sorted array (sort_products); dead elements — read
    # endpoints, uncommitted/empty writes, dead state slots — are simply not
    # union slots, and the merged state is carved out with prefix scans + one
    # compaction scatter. This replaces the previous incremental design's
    # per-batch multi-limb bisection of candidates into the state (the single
    # most expensive gather loop) with sort products that history and
    # intra-batch checks already paid for (the device analogue of the
    # reference's finger-merge, mergeWriteConflictRanges SkipList.cpp:1260).
    skeys, scls, sval, sidx, spos, cum_state = sort_products
    N_ALL = K + 2 * NR + 2 * NW
    if shapes.strided:
        wvalid = wb[L - 1] != jnp.uint32(0xFFFFFFFF)
        commit_w = jnp.repeat(merge_commit, NW // T)
    else:
        commit_w = merge_commit[wtxn_c]
    # committed, non-empty writes only: an inverted range would inject a
    # reversed -1/+1 coverage delta and cancel other writes' coverage
    cw = wvalid & commit_w & _key_lt(wb, we)
    # coverage deltas at each write endpoint's sorted position: +1 at
    # committed begins, -1 at committed ends (positions are unique)
    delta_w = jnp.concatenate([cw.astype(jnp.int32), -(cw.astype(jnp.int32))])
    pos_w = spos[K + 2 * NR:]
    delta_sorted = jnp.zeros(N_ALL, jnp.int32).at[pos_w].set(delta_w)

    # union slot sources: live state boundaries + committed write endpoints
    is_state = scls == 1
    live_state = is_state & (sidx < nb)
    is_src = live_state | (delta_sorted != 0)
    # one representative (slot) per distinct key among sources; the class
    # tiebreak sorted state before equal write endpoints, so a duplicate
    # endpoint joins the state boundary's slot
    newgrp = jnp.concatenate(
        [jnp.ones(1, bool), ~_key_eq(skeys[:, 1:], skeys[:, :-1])])
    cum_src_excl = jnp.cumsum(is_src.astype(jnp.int32)) - is_src
    grp_start_src = lax.cummax(jnp.where(newgrp, cum_src_excl, -1))
    rep = is_src & (cum_src_excl == grp_start_src)

    # value of each slot under the CURRENT step function: the last live state
    # boundary's value at-or-before it, carried forward by scan (sorted-order
    # values rode the sort as a payload operand; an N_ALL-wide scan is
    # cheaper than the random bval gather it replaces)
    val_u = _carry_last_flagged(jnp.where(live_state, sval, NEG), live_state)

    # coverage at a slot = total delta through the END of its key group
    # (within a group the +1/-1 order is arbitrary; at the group end it has
    # settled). Backward-carry the group-end prefix sum to every member.
    csum_delta = jnp.cumsum(delta_sorted)
    grp_last = jnp.concatenate([newgrp[1:], jnp.ones(1, bool)])
    cover_cnt = jnp.flip(_carry_last_flagged(
        jnp.flip(jnp.where(grp_last, csum_delta, 0)), jnp.flip(grp_last)))
    cover = cover_cnt > 0
    newval = jnp.where(cover, jnp.maximum(val_u, vnew), val_u)

    # ---- 5. window GC: clamp to new floor + coalesce equal neighbors ----
    # advance_floor is False for all but the last chunk of a logical batch:
    # the too-old check and history clamping must use the PRE-batch floor for
    # every transaction of the batch (the reference advances oldestVersion
    # once per detectConflicts call, SkipList.cpp:1199-1206).
    floor = jnp.where(batch["advance_floor"],
                      vnew - jnp.int32(max_write_life), oldest)
    new_oldest = jnp.maximum(oldest, floor)
    newval = jnp.maximum(newval, new_oldest)

    # coalesce (removeBefore's segment-merge analogue): a slot is redundant
    # if its value equals its predecessor slot's post-clamp value
    cum_rep = jnp.cumsum(rep.astype(jnp.int32))
    rep_val_carried = _carry_last_flagged(jnp.where(rep, newval, NEG), rep)
    prev_rep_val = jnp.concatenate(
        [jnp.full(1, NEG, jnp.int32), rep_val_carried[:-1]])
    keep2 = rep & ((cum_rep == 1) | (newval != prev_rep_val))
    n2 = jnp.sum(keep2.astype(jnp.int32))
    # compact kept slots to the front: one int32 source scatter, then gather
    # keys/values from the sorted arrays (indices are monotone)
    cpos = jnp.cumsum(keep2.astype(jnp.int32)) - 1
    cpos = jnp.where(keep2, jnp.minimum(cpos, K - 1), K)
    csrc = jnp.full(K + 1, -1, jnp.int32).at[cpos].set(
        jnp.arange(N_ALL, dtype=jnp.int32))[:K]
    kept = csrc >= 0
    csrc_c = jnp.clip(csrc, 0, N_ALL - 1)
    out_keys = jnp.where(kept[None, :], skeys[:, csrc_c],
                         jnp.uint32(0xFFFFFFFF))
    out_vals = jnp.where(kept, newval[csrc_c], NEG)

    overflow = n2 > K

    # Overflow poisons the state (sticky): truncation would drop the
    # highest-key history segments and cause FALSE COMMITS for batches
    # already enqueued behind this one (detect_async pipelines without a
    # host sync). Instead the whole keyspace collapses to one segment at
    # vnew, so every later stale read conflicts — conservative-only — until
    # the owner sees info["overflow"] and reconstructs (clearConflictSet
    # semantics, SkipList.cpp:957). This batch's own statuses are computed
    # pre-merge and remain exact.
    poisoned = state["poisoned"] | overflow
    pois_keys = jnp.full((L, K), jnp.uint32(0xFFFFFFFF)).at[:, 0].set(
        jnp.zeros(L, dtype=jnp.uint32))  # encode(b"") == all-zero limbs
    pois_vals = jnp.full(K, NEG, jnp.int32).at[0].set(vnew)
    out_keys = jnp.where(poisoned, pois_keys, out_keys)
    out_vals = jnp.where(poisoned, pois_vals, out_vals)
    n2 = jnp.where(poisoned, 1, n2)
    new_table = state["table"] if ablate == "no_table" else _build_table(out_vals)

    new_state = {
        "bkeys": out_keys,
        "bval": out_vals,
        "nb": jnp.minimum(n2, K).astype(jnp.int32),
        "oldest": new_oldest.astype(jnp.int32),
        "table": new_table,
        "poisoned": poisoned,
    }
    info = {"overflow": poisoned, "boundaries": n2,
            "committed": jnp.sum(commit.astype(jnp.int32)),
            "converged": converged, "eligible": eligible}
    return new_state, statuses, info


def rebase_state(state: dict, delta: int):
    """Shift all version offsets down by delta (host rebases the int64 base)."""
    d = jnp.int32(delta)
    bval = jnp.maximum(state["bval"] - d, NEG)
    return {
        "bkeys": state["bkeys"],
        "bval": bval,
        "nb": state["nb"],
        "oldest": jnp.maximum(state["oldest"] - d, NEG),
        "table": _build_table(bval),
        "poisoned": state["poisoned"],
    }


def init_state(shapes: ConflictShapes, oldest: int = 0):
    K = shapes.capacity
    L = shapes.limbs
    maxk = np.full((L, K), 0xFFFFFFFF, dtype=np.uint32)
    maxk[:, 0] = 0  # segment 0: [b"" (all-zero limbs), next) -> NEG
    bval = np.full(K, int(NEG), dtype=np.int32)
    return {
        "bkeys": jnp.asarray(maxk),
        "bval": jnp.asarray(bval),
        "nb": jnp.int32(1),
        "oldest": jnp.int32(oldest),
        "table": _build_table(jnp.asarray(bval)),
        "poisoned": jnp.asarray(False),
    }


# ---------------------------------------------------------------------------
# host wrapper: the ConflictSet a Resolver instantiates
# ---------------------------------------------------------------------------

def _donate_state_argnums() -> tuple:
    """Donate the state operand (bkeys + table dominate HBM) on accelerator
    backends: the update is written in place of the old state instead of
    alongside it, halving the step's state traffic and footprint. CPU's
    runtime can't alias these buffers and would warn on every program, so
    donation is gated to real accelerators."""
    return (0,) if jax.default_backend() in ("tpu", "gpu") else ()


@functools.lru_cache(maxsize=32)
def _compiled_step(shapes: ConflictShapes, max_write_life: int,
                   intra_mode: str = "scan", intra_rounds: int = 0):
    """One compiled program per (shapes, window, intra config) — shared
    across instances."""
    return jax.jit(functools.partial(
        conflict_step, shapes=shapes, max_write_life=max_write_life,
        intra_mode=intra_mode, intra_rounds=intra_rounds),
        donate_argnums=_donate_state_argnums())


@functools.lru_cache(maxsize=1)
def _compiled_rebase():
    """Compiled rebase_state with the state operand donated: the rebase
    overwrites the engine's only reference to the old state, so eager
    op-by-op dispatch (jnp.maximum + _build_table per call, old buffers
    alive until the host reassignment lands) doubled state traffic for
    nothing. One program per process — delta is a traced scalar."""
    return jax.jit(rebase_state, donate_argnums=_donate_state_argnums())


def conflict_scan(state: dict, stacked: dict, *, shapes: ConflictShapes,
                  max_write_life: int, intra_mode: str = "scan",
                  intra_rounds: int = 0):
    """Run M conflict batches in ONE device dispatch via lax.scan.

    `stacked` has the same fields as a conflict_step batch with a leading
    batch axis (M, ...). Returns (final_state, statuses (M, T) int8,
    committed (M,) int32, overflow (M,) bool). Dispatch overhead (several ms
    per program launch through the runtime) amortizes over M batches — the
    device analogue of the proxy's pipelined commitBatch gating
    (MasterProxyServer.actor.cpp:364-366).
    """
    def stepfn(st, batch):
        st2, statuses, info = conflict_step(
            st, batch, shapes=shapes, max_write_life=max_write_life,
            intra_mode=intra_mode, intra_rounds=intra_rounds)
        return st2, (statuses.astype(jnp.int8), info["committed"],
                     info["overflow"])
    final, (stat, comm, ovf) = lax.scan(stepfn, state, stacked)
    return final, stat, comm, ovf


@functools.lru_cache(maxsize=32)
def _compiled_scan(shapes: ConflictShapes, max_write_life: int,
                   intra_mode: str = "scan", intra_rounds: int = 0):
    return jax.jit(functools.partial(
        conflict_scan, shapes=shapes, max_write_life=max_write_life,
        intra_mode=intra_mode, intra_rounds=intra_rounds),
        donate_argnums=_donate_state_argnums())


def _resolve_shapes(capacity=None, txns=None, reads_per_txn=None,
                    writes_per_txn=None, key_bytes=None,
                    strided=False) -> ConflictShapes:
    k = KNOBS
    t = txns or k.CONFLICT_BATCH_TXNS
    return ConflictShapes(
        capacity=capacity or k.CONFLICT_STATE_CAPACITY,
        txns=t,
        reads=t * (reads_per_txn or k.CONFLICT_BATCH_READS_PER_TXN),
        writes=t * (writes_per_txn or k.CONFLICT_BATCH_WRITES_PER_TXN),
        key_bytes=key_bytes or keylib.KEY_BYTES,
        strided=strided,
    )


class BatchEncoder:
    """Host-side batch encoding/chunking, shared by the single-device and
    mesh-sharded engines (and the driver entry points)."""

    def __init__(self, shapes: ConflictShapes, base_version: int = 0):
        self.shapes = shapes
        self.L = shapes.limbs
        self.base_version = base_version
        self._rings: dict = {}
        self._last_slot: dict | None = None
        if shapes.strided:
            self._strided_rtxn = jnp.asarray(
                np.arange(shapes.reads, dtype=np.int32)
                // (shapes.reads // shapes.txns))
            self._strided_wtxn = jnp.asarray(
                np.arange(shapes.writes, dtype=np.int32)
                // (shapes.writes // shapes.txns))

    def _clamp_off(self, version: int) -> int:
        off = version - self.base_version
        return int(max(min(off, (1 << 31) - 1), _NEG_INT))

    def _buffers(self, sh: ConflictShapes) -> dict:
        """Reusable encode buffers (a small ring per shape bucket): batch
        N+1 encodes into a slot whose previous dispatch is provably consumed
        (its readback marker is_ready), so the encode output lands straight
        in long-lived host buffers instead of fresh allocations every batch
        — the host side of the dispatch/readback double-buffering. Slots are
        created on demand up to CONFLICT_ENCODE_RING; if every slot is still
        in flight the encode falls back to a fresh allocation (never blocks,
        never aliases an in-flight transfer)."""
        T = sh.txns
        ring = self._rings.setdefault((sh.reads, sh.writes), [])
        slot = None
        for s in ring:
            m = s.get("marker")
            if m is None or not hasattr(m, "is_ready") or m.is_ready():
                slot = s
                break
        if slot is None and len(ring) < KNOBS.CONFLICT_ENCODE_RING:
            slot = {}
            ring.append(slot)
        if slot is None:
            slot = {}
        if "rb" not in slot:
            slot["rb"] = np.empty((self.L, sh.reads), np.uint32)
            slot["re"] = np.empty((self.L, sh.reads), np.uint32)
            slot["wb"] = np.empty((self.L, sh.writes), np.uint32)
            slot["we"] = np.empty((self.L, sh.writes), np.uint32)
            slot["snap"] = np.empty(T, np.int32)
            slot["valid"] = np.empty(T, bool)
            if not sh.strided:
                slot["rtxn"] = np.empty(sh.reads, np.int32)
                slot["wtxn"] = np.empty(sh.writes, np.int32)
        for f in ("rb", "re", "wb", "we"):
            slot[f].fill(0xFFFFFFFF)
        slot["snap"].fill(0)
        slot["valid"].fill(False)
        if not sh.strided:
            slot["rtxn"].fill(T)
            slot["wtxn"].fill(T)
        slot["marker"] = None
        self._last_slot = slot
        return slot

    def mark_in_flight(self, marker):
        """Attach the dispatch's readback array to the most recent encode's
        buffer slot: once it is_ready() the step has consumed its inputs and
        the slot becomes reusable."""
        if self._last_slot is not None:
            self._last_slot["marker"] = marker
            self._last_slot = None

    def bucket_shapes(self, nr: int, nw: int) -> ConflictShapes:
        """Smallest shape bucket covering a chunk with nr reads / nw writes.

        Serving batches are usually far smaller than the configured maximum
        (and often one-sided: write-only batches carry zero read ranges), so
        padding every dispatch to the full shape wastes transfer bytes and
        device sort rows. Two buckets per axis (full/16 and full) bound the
        compiled-program count at 4 — the TPU-serving bucketed-padding
        pattern; warmup() pre-compiles all of them."""
        import dataclasses
        sh = self.shapes

        def pick(n, full):
            small = max(full // 16, 8)
            return small if n <= small else full
        r, w = pick(nr, sh.reads), pick(nw, sh.writes)
        if (r, w) == (sh.reads, sh.writes):
            return sh
        return dataclasses.replace(sh, reads=r, writes=w)

    def encode_batch(self, txns: list[TxnConflictInfo], commit_version: int,
                     skip: list[bool] | None = None,
                     shapes: ConflictShapes | None = None):
        """Build one device batch. Key encoding is bulk (C extension when
        available — feeding the device is a host hot path, the analogue of
        the reference's C++ key juggling in SkipList.cpp addTransaction)."""
        sh = shapes or self.shapes
        T = sh.txns
        assert len(txns) <= T
        if not sh.strided:
            from foundationdb_tpu import native
            if native.available() and hasattr(native.mod,
                                              "encode_conflict_ranges"):
                return self._encode_batch_c(txns, commit_version, skip, sh)
        rkeys_b: list[bytes] = []
        rkeys_e: list[bytes] = []
        wkeys_b: list[bytes] = []
        wkeys_e: list[bytes] = []
        rt: list[int] = []
        wt: list[int] = []
        buf = self._buffers(sh)
        snap, valid = buf["snap"], buf["valid"]
        rpt, wpt = sh.reads // T, sh.writes // T
        for t, txn in enumerate(txns):
            if skip is not None and skip[t]:
                continue  # host already decided TOO_OLD; not in this batch
            valid[t] = True
            snap[t] = self._clamp_off(txn.read_snapshot)
            # oversized txns were rejected by split_for_capacity (the gate on
            # the detect path — raising there happens before any chunk of the
            # logical batch touches device state)
            for i, (b, e) in enumerate(txn.read_ranges):
                rkeys_b.append(b)
                rkeys_e.append(e)
                rt.append(t * rpt + i if sh.strided else t)
            for i, (b, e) in enumerate(txn.write_ranges):
                wkeys_b.append(b)
                wkeys_e.append(e)
                wt.append(t * wpt + i if sh.strided else t)

        rb, re, wb, we = buf["rb"], buf["re"], buf["wb"], buf["we"]
        # Leaves stay HOST numpy (long-lived ring buffers, see _buffers):
        # the jitted step's implicit argument transfer is asynchronous and
        # batched (sub-ms enqueue), while an explicit device_put per leaf
        # costs a synchronous handshake each — on a remote-attached device
        # that is milliseconds per leaf.
        if sh.strided:
            # ranges land at their txn's stride slots; rtxn/wtxn are implied
            # by position and ignored by the kernel (cached device constants)
            _bulk_encode_at(rkeys_b, rt, rb, round_up=False)
            _bulk_encode_at(rkeys_e, rt, re, round_up=True)
            _bulk_encode_at(wkeys_b, wt, wb, round_up=False)
            _bulk_encode_at(wkeys_e, wt, we, round_up=True)
            return {
                "rb": rb, "re": re,
                "rtxn": self._strided_rtxn,
                "wb": wb, "we": we,
                "wtxn": self._strided_wtxn,
                "snapshot": snap, "txn_valid": valid,
                "commit_version": np.int32(self._clamp_off(commit_version)),
                "advance_floor": np.bool_(True),
            }
        _bulk_encode(rkeys_b, rb, round_up=False)
        _bulk_encode(rkeys_e, re, round_up=True)
        _bulk_encode(wkeys_b, wb, round_up=False)
        _bulk_encode(wkeys_e, we, round_up=True)
        rtxn, wtxn = buf["rtxn"], buf["wtxn"]
        rtxn[: len(rt)] = rt
        wtxn[: len(wt)] = wt
        return {
            "rb": rb, "re": re, "rtxn": rtxn,
            "wb": wb, "we": we, "wtxn": wtxn,
            "snapshot": snap, "txn_valid": valid,
            "commit_version": np.int32(self._clamp_off(commit_version)),
            "advance_floor": np.bool_(True),
        }

    def _encode_batch_c(self, txns: list[TxnConflictInfo],
                        commit_version: int, skip: list[bool] | None,
                        sh: ConflictShapes):
        """Pooled-layout encode with the C flattener: one native pass writes
        keys (limb-encoded) + range→txn maps straight into the buffers,
        replacing the per-range Python loop (the host hot path when the
        device engine serves live commit batches)."""
        from foundationdb_tpu import native
        T = sh.txns
        buf = self._buffers(sh)
        rb, re, wb, we = buf["rb"], buf["re"], buf["wb"], buf["we"]
        rtxn, wtxn = buf["rtxn"], buf["wtxn"]
        snap, valid = buf["snap"], buf["valid"]
        native.mod.encode_conflict_ranges(
            txns, skip, rb, re, wb, we, rtxn, wtxn, (self.L - 1) * 4,
            snap, valid, self.base_version)
        return {
            "rb": rb, "re": re, "rtxn": rtxn,
            "wb": wb, "we": we, "wtxn": wtxn,
            "snapshot": snap, "txn_valid": valid,
            "commit_version": np.int32(self._clamp_off(commit_version)),
            "advance_floor": np.bool_(True),
        }

    def split_for_capacity(self, txns):
        sh = self.shapes
        if sh.strided:
            # capacity is per-txn (the stride); chunk by txn count only
            rpt, wpt = sh.reads // sh.txns, sh.writes // sh.txns
            for txn in txns:
                if (len(txn.read_ranges) > rpt
                        or len(txn.write_ranges) > wpt):
                    raise FDBError(
                        "transaction_too_large",
                        f"{len(txn.read_ranges)} reads / "
                        f"{len(txn.write_ranges)} writes exceed the strided "
                        f"layout ({rpt}/{wpt} per txn)")
            return [txns[i:i + sh.txns]
                    for i in range(0, max(len(txns), 1), sh.txns)]
        subs, cur, nr, nw = [], [], 0, 0
        for txn in txns:
            tr, tw = len(txn.read_ranges), len(txn.write_ranges)
            if tr > sh.reads or tw > sh.writes:
                raise FDBError("transaction_too_large",
                               f"{tr} reads / {tw} writes exceed batch shape")
            if cur and (nr + tr > sh.reads or nw + tw > sh.writes or len(cur) >= sh.txns):
                subs.append(cur)
                cur, nr, nw = [], 0, 0
            cur.append(txn)
            nr += tr
            nw += tw
        subs.append(cur)
        return subs


def detect_async_impl(engine, txns: list[TxnConflictInfo],
                      commit_version: int) -> "DetectHandle":
    """Enqueue a whole logical batch on device and return a handle; no
    host↔device synchronization happens until handle.result().

    Shared by DeviceConflictSet and ShardedDeviceConflictSet (`engine` needs:
    encoder, _step, _state, oldest_version, _maybe_rebase). This is the
    proxy's pipelining pattern (MasterProxyServer.actor.cpp:364-366,426-428):
    batch N+1's transfer/compute overlaps batch N's result readback.
    """
    engine._maybe_rebase(commit_version)
    enc = engine.encoder
    subs = enc.split_for_capacity(txns)
    # The too-old decision is taken here with exact int64 versions (device
    # offsets saturate across extreme rebases); flagged txns are excluded
    # from the device batch entirely.
    pre_batch_oldest = engine.oldest_version
    base = enc.base_version
    chunks = []
    for i, sub in enumerate(subs):
        # TOO_OLD when below the MVCC floor, AND when the snapshot's device
        # offset would saturate at the NEG sentinel (a >2^30-stale snapshot
        # after a rebase): a saturated snapshot compares equal to "no
        # version" and would silently MISS conflicts — rejecting it is the
        # conservative direction (the reference also throws too_old for
        # anything beyond its window, SkipList.cpp:985 semantics)
        host_too_old = [bool(t.read_ranges)
                        and (t.read_snapshot < pre_batch_oldest
                             or t.read_snapshot - base <= _NEG_INT)
                        for t in sub]
        nr = sum(len(t.read_ranges) for t, old in zip(sub, host_too_old)
                 if not old)
        nw = sum(len(t.write_ranges) for t, old in zip(sub, host_too_old)
                 if not old)
        shapes, step = engine.plan_chunk(nr, nw)
        batch = enc.encode_batch(sub, commit_version, skip=host_too_old,
                                 shapes=shapes)
        # the MVCC floor advances once per logical batch (last chunk), so
        # every chunk's too-old check uses the pre-batch floor
        batch["advance_floor"] = np.bool_(i == len(subs) - 1)
        _kernel_dispatches.increment()
        new_state, statuses, info = step(engine._state, batch)
        engine._state = new_state
        # statuses + intra-eligibility + overflow + convergence fused into
        # ONE fixed-shape device array (enqueue-only): every chunk is read
        # back as a single transfer
        combined = _combine_status(statuses, info["eligible"],
                                   info["overflow"], info["converged"])
        enc.mark_in_flight(combined)
        # double-buffering: the D2H copy starts NOW, overlapped with the
        # NEXT chunk's/batch's encode + dispatch, so a later drain (or
        # result()) finds the bytes already on the host instead of starting
        # the transfer under a sync. CONFLICT_READBACK_OVERLAP=False keeps
        # the fully synchronous pre-overlap shape as a measurable ablation
        # (decisions are identical either way — only timing shifts).
        if (KNOBS.CONFLICT_READBACK_OVERLAP
                and hasattr(combined, "copy_to_host_async")):
            combined.copy_to_host_async()
        chunks.append((sub, host_too_old, combined))
    # the kernel's floor advance is replicated host-side exactly
    # (floor = commit_version - window on the last chunk, monotonic max)
    engine.oldest_version = max(
        engine.oldest_version,
        commit_version - KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS)
    return DetectHandle(chunks)


class DeviceConflictSet:
    """Drop-in conflict set backed by the jitted device step.

    Mirrors the seam in fdbserver/ConflictSet.h:27-44: construct, feed batches
    of TxnConflictInfo, get {CONFLICT, TOO_OLD, COMMITTED} per transaction.
    Arbitrary batch sizes are handled by chunking to the static shape
    (chunk order preserves batch order, so intra-batch "earlier txns win"
    semantics are exact: later chunks see earlier chunks' merged writes).
    """

    def __init__(self, capacity: int | None = None, txns: int | None = None,
                 reads_per_txn: int | None = None, writes_per_txn: int | None = None,
                 oldest_version: int = 0, key_bytes: int | None = None,
                 strided: bool = False):
        from foundationdb_tpu.utils.jaxenv import ensure_platform_honored
        ensure_platform_honored()
        self.shapes = _resolve_shapes(capacity, txns, reads_per_txn,
                                      writes_per_txn, key_bytes, strided)
        self.encoder = BatchEncoder(self.shapes, base_version=oldest_version)
        self.oldest_version = oldest_version
        self._state = init_state(self.shapes, oldest=0)
        self._intra = (str(KNOBS.CONFLICT_INTRA_MODE),
                       int(KNOBS.CONFLICT_INTRA_ROUNDS))
        self._step = _compiled_step(self.shapes,
                                    KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS,
                                    *self._intra)

    @property
    def base_version(self) -> int:
        return self.encoder.base_version

    def _maybe_rebase(self, commit_version: int):
        # Shift in <= 2^30 steps so each delta fits int32; values saturate at
        # NEG, so repeated shifts are exact for any version gap.
        while commit_version - self.encoder.base_version > _REBASE_THRESHOLD:
            delta = min(commit_version - self.encoder.base_version - (1 << 24),
                        1 << 30)
            self._state = _compiled_rebase()(self._state, np.int32(delta))
            self.encoder.base_version += delta

    # -- ConflictBatch interface --
    def detect(self, txns: list[TxnConflictInfo], commit_version: int) -> list[int]:
        return self.detect_async(txns, commit_version).result()

    def detect_async(self, txns: list[TxnConflictInfo],
                     commit_version: int) -> "DetectHandle":
        return detect_async_impl(self, txns, commit_version)

    def plan_chunk(self, nr: int, nw: int):
        """(shapes, compiled step) for a chunk: bucketed padding keeps the
        transfer bytes and the device sort sized to the chunk, not to the
        configured maximum (see BatchEncoder.bucket_shapes)."""
        shapes = (self.encoder.bucket_shapes(nr, nw)
                  if not self.shapes.strided else self.shapes)
        return shapes, _compiled_step(
            shapes, KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS, *self._intra)

    def warmup(self):
        """Compile every serving bucket now (boot-time cost, served-path
        savings; the persistent compile cache makes it once per machine)."""
        sh = self.shapes
        if sh.strided:
            self.detect([], self.encoder.base_version + 1)
            return
        combos = {(r, w)
                  for r in (0, sh.reads) for w in (0, sh.writes)}
        for nr, nw in combos:
            shapes, step = self.plan_chunk(nr, nw)
            batch = self.encoder.encode_batch(
                [], self.encoder.base_version + 1, shapes=shapes)
            new_state, statuses, _info = step(self._state, batch)
            self._state = new_state
            statuses.block_until_ready()

    def clear(self, oldest_version: int = 0):
        """clearConflictSet (SkipList.cpp:957): state is soft/reconstructable."""
        self.encoder.base_version = oldest_version
        self.oldest_version = oldest_version
        self._state = init_state(self.shapes, oldest=0)


@functools.cache
def _combine_fn():
    # one program per process: statuses/eligible are always (shapes.txns,),
    # overflow/converged scalars — the fixed output layout
    # [statuses | eligible | overflow | converged] keeps the tunnel's
    # compile cache warm and makes every chunk readback a single transfer
    return jax.jit(lambda s, g, o, c: jnp.concatenate(
        [s.astype(jnp.int32), g.astype(jnp.int32),
         jnp.asarray(o, jnp.int32)[None], jnp.asarray(c, jnp.int32)[None]]))


def _combine_status(statuses, eligible, overflow, converged):
    return _combine_fn()(statuses, eligible, overflow, converged)


def drain_handles(handles: list["DetectHandle"]) -> None:
    """Materialize many DetectHandles with overlapped device→host copies.

    Each pending chunk's combined status array gets an ASYNC host copy
    enqueued first; the materializing np.asarray then finds the data already
    in flight, so N batches' readbacks cost ~one device round trip total
    instead of N (dominant on a remote-attached device). result() on each
    handle afterwards touches no device state. This is the serving-path
    analogue of conflict_scan's single-readback chaining: round-trip latency
    is paid once per DRAIN, so resolver throughput is set by dispatch rate,
    not round-trip time.
    """
    pend = [h for h in handles if h._result is None and h._chunks]
    arrs = [c[2] for h in pend for c in h._chunks]
    if KNOBS.CONFLICT_READBACK_OVERLAP:
        for a in arrs:
            if hasattr(a, "copy_to_host_async"):
                a.copy_to_host_async()
    for h in pend:
        h._chunks = [(sub, too_old, np.asarray(a))
                     for sub, too_old, a in h._chunks]


def drain_and_collect(
        handles: list["DetectHandle"], timing: dict | None = None,
) -> list[tuple[list[int] | None, "FDBError | None"]]:
    """drain_handles + result() for every handle, entirely off-loop.

    One (statuses, error) pair per handle, in order. This exists so a
    coroutine can offload the WHOLE materialization in a single
    loop.run_blocking(...) call: result() can fall back to the exact host
    intra-batch pass (_exact_intra_host) on an unconverged chunk, which is
    milliseconds of host compute the event-loop thread should never eat.
    Errors are returned, not raised — a capacity overflow on one handle
    must not strand the remaining handles' results.

    When `timing` is given, the device-sync ("drain_seconds") and host-
    materialization ("collect_seconds") halves are recorded separately so
    the caller can attribute them to distinct spans (the sharded path bills
    the verdict unpack as Resolver.ShardCombine)."""
    import time
    t0 = time.perf_counter()
    drain_handles(handles)
    t1 = time.perf_counter()
    out: list[tuple[list[int] | None, FDBError | None]] = []
    for h in handles:
        try:
            out.append((h.result(), None))
        except FDBError as e:
            out.append((None, e))
    t2 = time.perf_counter()
    if timing is not None:
        timing["drain_seconds"] = t1 - t0
        timing["collect_seconds"] = t2 - t1
    _readback_waits.increment()
    _readback_wait_seconds.increment(t2 - t0)
    return out


def _exact_intra_host(sub, host_too_old, eligible):
    """Exact sequential intra-batch resolution for an unconverged chunk.

    The device's sandwich bound ran out before the chunk's dependency chains
    pinched (possible only for chains deeper than 2*rounds). Its too-old and
    history decisions are exact regardless (`eligible` = survived both), so
    the remaining greedy "earlier txns win" pass runs here against the
    chunk's original byte ranges — the same loop as the oracle's step 3.
    The device merged the sandwich UPPER bound into its state (a superset of
    the writes committed here), which can only create false conflicts for
    later batches, never false commits."""
    from foundationdb_tpu.ops.conflict_oracle import _RangeSet
    statuses = []
    published = _RangeSet()
    for t, txn in enumerate(sub):
        if host_too_old[t]:
            statuses.append(TOO_OLD)
            continue
        if not eligible[t]:
            statuses.append(CONFLICT)
            continue
        if any(published.overlaps(b, e) for b, e in txn.read_ranges):
            statuses.append(CONFLICT)
            continue
        for b, e in txn.write_ranges:
            published.add(b, e)
        statuses.append(COMMITTED)
    return statuses


class DetectHandle:
    """Deferred result of detect_async: statuses fetched on first result().

    Each chunk is (sub_txns, host_too_old, combined) where combined is the
    device readback [statuses(T) | eligible(T) | overflow | converged]."""

    def __init__(self, chunks):
        self._chunks = chunks
        self._result: list[int] | None = None

    def result(self) -> list[int]:
        if self._result is None:
            out: list[int] = []
            for sub, host_too_old, combined in self._chunks:
                arr = np.asarray(combined)
                n = len(sub)
                tc = (len(arr) - 2) // 2
                if arr[2 * tc]:
                    # Overflow: the truncated state dropped the highest-key
                    # history segments and could cause false commits —
                    # fatal; the owner reconstructs (clearConflictSet
                    # semantics, SkipList.cpp:957: conflict state is soft).
                    raise FDBError(
                        "internal_error",
                        "conflict state capacity exceeded; raise CONFLICT_STATE_CAPACITY")
                if arr[2 * tc + 1]:
                    statuses = arr[:n]
                else:
                    statuses = _exact_intra_host(sub, host_too_old,
                                                 arr[tc:tc + n])
                out.extend(TOO_OLD if old else int(s)
                           for s, old in zip(statuses, host_too_old))
            self._result = out
            self._chunks = None
        return self._result
