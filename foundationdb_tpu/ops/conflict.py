"""The TPU conflict engine: batched MVCC conflict detection in one XLA launch.

This replaces fdbserver/SkipList.cpp (the reference's hand-tuned CPU conflict
engine, SURVEY.md §3.2) with a TPU-first design. The reference hides memory
latency with 16 interleaved skiplist cursors (SkipList.cpp:526-552) and a
hierarchical bitmask (:1028-1130); we instead make the whole batch a dense
tensor program:

State = the *max-commit-version step function* over the keyspace, stored as
device-resident sorted boundary keys (fixed-width uint32 limbs) + per-segment
version offsets + a sparse-table (power-of-two window) max pyramid — the dense
analogue of the skiplist's per-level max-version annotations (:324-357).

detect = ONE jitted function:
  1. too-old filter (SkipList.cpp:985 semantics)
  2. history check: vectorized binary search of every read range's endpoints
     over the boundary array + O(1) sparse-table range-max, compare against
     each txn's read snapshot (replaces CheckMax :755-837)
  3. intra-batch: endpoint ranking by one lax.sort, pairwise read/write
     overlap, txn-level dependency matrix, and an exact
     lower/upper-bound fixpoint for "earlier txns win" semantics (replaces
     MiniConflictSet :1028-1130; converges in <= chain-depth iterations,
     each a tiny boolean mat-vec)
  4. merge of surviving writes into the step function by sort/dedupe/coverage
     prefix-sums (replaces mergeWriteConflictRanges :1260-1318)
  5. window GC by clamp + coalesce (replaces removeBefore :665)

Versions on device are int32 *offsets* from a host-kept int64 base (the MVCC
window is only 5e6 versions wide — fdbserver/Knobs.cpp:30-34 — so offsets fit
comfortably; the host rebases long before overflow). This keeps the kernel in
TPU-native 32-bit arithmetic.

Keys are exact up to KEY_BYTES (24) bytes; longer keys collapse to their
prefix, which can only create false conflicts (safe), never false commits
(utils/keys.py).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from foundationdb_tpu.ops.batch import COMMITTED, CONFLICT, TOO_OLD, TxnConflictInfo
from foundationdb_tpu.utils import keys as keylib
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS

L = keylib.NUM_LIMBS  # default key limbs (6 data + 1 length; see ConflictShapes.key_bytes)
NEG = jnp.int32(-(1 << 30))  # "no version" sentinel, below any clamped offset
_REBASE_THRESHOLD = 1 << 29


def _bulk_encode(keys: list[bytes], out: np.ndarray, *, round_up: bool):
    """Encode keys into out[:, :len(keys)] (SoA limbs), C path if built.
    The limb count (and so the key width) comes from `out`'s shape."""
    if not keys:
        return
    from foundationdb_tpu import native

    nl = out.shape[0]
    key_bytes = (nl - 1) * 4
    if native.available():
        tmp = np.empty((nl, len(keys)), dtype=np.uint32)
        native.mod.encode_keys_into(keys, tmp, round_up, key_bytes)
        out[:, : len(keys)] = tmp
    else:
        buf = np.zeros(nl, dtype=np.uint32)
        for i, k in enumerate(keys):
            keylib.encode_key(k, buf, round_up=round_up, key_bytes=key_bytes)
            out[:, i] = buf


# ---------------------------------------------------------------------------
# multi-limb key comparisons (vectorized lexicographic)
# ---------------------------------------------------------------------------

def _key_lt(a, b):
    """a < b lexicographically; a, b are (L, ...) uint32."""
    lt = jnp.zeros(a.shape[1:], dtype=bool)
    eq = jnp.ones(a.shape[1:], dtype=bool)
    for i in range(a.shape[0]):
        lt = lt | (eq & (a[i] < b[i]))
        eq = eq & (a[i] == b[i])
    return lt


def _key_eq(a, b):
    eq = jnp.ones(a.shape[1:], dtype=bool)
    for i in range(a.shape[0]):
        eq = eq & (a[i] == b[i])
    return eq


def _searchsorted(bkeys, queries, side):
    """Vectorized binary search over sorted multi-limb keys.

    bkeys: (L, K) sorted ascending; queries: (L, Q).
    side='left'  -> first index i with bkeys[:,i] >= q (lower bound)
    side='right' -> first index i with bkeys[:,i] >  q (upper bound)
    side may also be a (Q,) bool array: True = 'right' for that query,
    letting several logical searches share one unrolled bisection.

    The bisection is UNROLLED (static step count): a lax loop here costs a
    device-visible sync per iteration, which profiling showed dominating the
    whole conflict step.
    """
    K = bkeys.shape[1]
    Q = queries.shape[1]
    lo = jnp.zeros(Q, dtype=jnp.int32)
    hi = jnp.full(Q, K, dtype=jnp.int32)
    steps = max(1, int(np.ceil(np.log2(max(K, 2)))) + 1)

    for _ in range(steps):
        mid = (lo + hi) // 2
        midkeys = bkeys[:, mid]  # (L, Q) gather
        if isinstance(side, str):
            if side == "left":
                go_right = _key_lt(midkeys, queries)
            else:
                go_right = ~_key_lt(queries, midkeys)  # midkeys <= q
        else:
            go_right = jnp.where(side, ~_key_lt(queries, midkeys),
                                 _key_lt(midkeys, queries))
        # once converged (lo == hi) the interval is empty: without this guard
        # a surplus unrolled step at lo == hi == K gathers the clamped last
        # key and can push lo to K+1 for queries above every stored key,
        # which the merge's slot arithmetic would consume unclamped
        go_right = go_right & (lo < hi)
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, mid)
    return lo


# ---------------------------------------------------------------------------
# sparse table (range-max in O(1) per query)
# ---------------------------------------------------------------------------

def _build_table(vals):
    """vals: (K,) int32 -> (LEVELS, K) power-of-two window maxima.

    table[l, i] = max(vals[i : i + 2**l]) (clipped at K). The dense analogue
    of the skiplist's level max-version pyramid (SkipList.cpp:324-357).
    """
    K = vals.shape[0]
    levels = max(1, int(np.ceil(np.log2(max(K, 2)))) + 1)
    rows = [vals]
    cur = vals
    for l in range(1, levels):
        shift = 1 << (l - 1)
        shifted = jnp.concatenate([cur[shift:], jnp.full(min(shift, K), NEG, cur.dtype)])[:K]
        cur = jnp.maximum(cur, shifted)
        rows.append(cur)
    return jnp.stack(rows)


def _range_max(table, i0, i1):
    """Max over vals[i0:i1) for vectors i0 < i1 (int32 arrays)."""
    w = jnp.maximum(i1 - i0, 1)
    lvl = 31 - lax.clz(w)  # floor(log2(w))
    left = table[lvl, i0]
    right = table[lvl, jnp.maximum(i1 - (1 << lvl).astype(jnp.int32), i0)]
    return jnp.maximum(left, right)


# ---------------------------------------------------------------------------
# the jitted step
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ConflictShapes:
    """Static shapes of one conflict batch (one XLA program per instance).

    `key_bytes` sets the exact-comparison width (keys longer than it collapse
    conservatively onto their prefix, utils/keys.py): compare cost on device
    scales linearly with the limb count, so clusters with bounded keys run a
    narrower engine — the reference's memcmp cost scales with key length the
    same way (SkipList.cpp getCharacter/compare)."""

    capacity: int  # K: boundary slots in the step function
    txns: int  # T
    reads: int  # NR: total read ranges per batch (flattened)
    writes: int  # NW: total write ranges per batch
    key_bytes: int = keylib.KEY_BYTES

    @property
    def limbs(self) -> int:
        return self.key_bytes // 4 + 1


def conflict_step(state: dict, batch: dict, *, shapes: ConflictShapes,
                  max_write_life: int, ablate: str = ""):
    """Pure function: (state, batch) -> (state', statuses, info). Jit-able.

    state:
      bkeys (L,K) uint32 sorted; bval (K,) i32; nb () i32; oldest () i32;
      table (LEVELS,K) i32
    batch:
      txn_valid (T,) bool; snapshot (T,) i32 (version offsets)
      rb, re (L,NR) u32; rtxn (NR,) i32 (= T for padding);
      wb, we (L,NW) u32; wtxn (NW,) i32 (= T for padding)
      commit_version () i32 offset
      advance_floor () bool — advance the MVCC window after this chunk
      (False for all but the last chunk of a logical batch)
    """
    T, NR, NW, K = shapes.txns, shapes.reads, shapes.writes, shapes.capacity
    L = shapes.limbs
    bkeys, bval, nb, oldest, table = (
        state["bkeys"], state["bval"], state["nb"], state["oldest"], state["table"])
    rb, re, rtxn = batch["rb"], batch["re"], batch["rtxn"]
    wb, we, wtxn = batch["wb"], batch["we"], batch["wtxn"]
    snapshot, txn_valid = batch["snapshot"], batch["txn_valid"]
    vnew = batch["commit_version"]

    rvalid = rtxn < T
    wvalid = wtxn < T
    has_reads = (jnp.zeros(T + 1, bool).at[rtxn].max(rvalid))[:T]

    # ---- 1. too-old (only txns with read ranges expire: SkipList.cpp:985) ----
    too_old = txn_valid & has_reads & (snapshot < oldest)

    # ---- 2. history check: range-max of step function vs snapshot ----
    if ablate in ("no_hist", "only_merge"):
        hist_conflict = jnp.zeros(T, bool)
    else:
        # one fused bisection: [rb -> upper bound, re -> lower bound]
        hist_q = jnp.concatenate([rb, re], axis=1)
        hist_side = jnp.concatenate([jnp.ones(NR, bool), jnp.zeros(NR, bool)])
        hist_idx = _searchsorted(bkeys, hist_q, hist_side)
        i0 = hist_idx[:NR] - 1  # segment containing begin
        i1 = hist_idx[NR:]  # first boundary >= end
        i0 = jnp.maximum(i0, 0)
        nonempty = _key_lt(rb, re)
        maxver = _range_max(table, i0, jnp.maximum(i1, i0 + 1))
        rsnap = snapshot[jnp.minimum(rtxn, T - 1)]
        read_hits = rvalid & nonempty & (maxver > rsnap)
        hist_conflict = (jnp.zeros(T + 1, bool).at[rtxn].max(read_hits))[:T]

    g0 = txn_valid & ~too_old & ~hist_conflict
    if ablate in ("no_intra", "only_merge", "only_hist"):
        commit = g0
        statuses = jnp.where(
            commit, COMMITTED,
            jnp.where(too_old, TOO_OLD, CONFLICT)).astype(jnp.int32)
        statuses = jnp.where(txn_valid, statuses, COMMITTED)
        return _merge_phase(state, batch, statuses, commit, shapes,
                            max_write_life, ablate)
    # ---- 3. intra-batch: endpoint ranks -> pairwise overlap -> fixpoint ----
    # The (T,T) dependency matrix of the first design required a 2D scatter
    # (~170ms/batch on TPU); instead the fixpoint operates directly on the
    # (NW, NR) range-overlap matrix via an MXU matvec: committed writes ->
    # blocked reads is one bf16 matmul with exact f32 accumulation (0/1
    # values), then a cheap 1D segment-max folds reads back to transactions.
    allk = jnp.concatenate([rb, re, wb, we], axis=1)  # (L, NA)
    NA = 2 * NR + 2 * NW
    ops = [allk[i] for i in range(L)] + [jnp.arange(NA, dtype=jnp.int32)]
    sorted_ops = lax.sort(ops, num_keys=L)
    perm = sorted_ops[L]
    skeys = jnp.stack(sorted_ops[:L])
    newgrp = jnp.concatenate(
        [jnp.ones(1, bool), ~_key_eq(skeys[:, 1:], skeys[:, :-1])])
    rank_sorted = jnp.cumsum(newgrp.astype(jnp.int32)) - 1
    ranks = jnp.zeros(NA, jnp.int32).at[perm].set(rank_sorted)
    rbr, rer = ranks[:NR], ranks[NR:2 * NR]
    wbr, wer = ranks[2 * NR:2 * NR + NW], ranks[2 * NR + NW:]

    # empty/inverted ranges (end <= begin) participate in neither side;
    # strict wtxn < rtxn = "earlier txns win" (checkIntraBatchConflicts
    # SkipList.cpp:1139-1152 processes in batch order)
    r_nonempty = rbr < rer
    w_nonempty = wbr < wer
    overlap = ((wbr[:, None] < rer[None, :]) & (rbr[None, :] < wer[:, None])
               & (wvalid & w_nonempty)[:, None] & (rvalid & r_nonempty)[None, :]
               & (wtxn[:, None] < rtxn[None, :]))  # (NW, NR)
    ovf = overlap.astype(jnp.bfloat16)
    g = txn_valid & ~too_old & ~hist_conflict
    wtxn_c = jnp.minimum(wtxn, T - 1)

    def _f_commit(c):
        """f(c)[t] = g[t] and no committed-in-c earlier txn's write overlaps
        any of t's reads."""
        cw = (c[wtxn_c] & wvalid).astype(jnp.bfloat16)
        blocked_r = lax.dot_general(
            cw[None, :], ovf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)[0] > 0
        blocked_t = (jnp.zeros(T + 1, bool).at[rtxn].max(blocked_r))[:T]
        return g & ~blocked_t

    upper = g
    lower = _f_commit(upper)

    def cond(lu):
        lower, upper = lu
        return jnp.any(lower != upper)

    def body(lu):
        lower, upper = lu
        upper2 = _f_commit(lower)
        lower2 = _f_commit(upper2)
        return lower2, upper2

    # typical dependency chains are shallow: unroll the first sandwich rounds
    # (each device-loop iteration costs a sync) and fall back to the loop only
    # for adversarially deep chains
    for _ in range(2):
        lower, upper = body((lower, upper))
    lower, upper = lax.while_loop(cond, body, (lower, upper))
    commit = lower

    statuses = jnp.where(
        commit, COMMITTED,
        jnp.where(too_old, TOO_OLD, CONFLICT)).astype(jnp.int32)
    statuses = jnp.where(txn_valid, statuses, COMMITTED)
    return _merge_phase(state, batch, statuses, commit, shapes,
                        max_write_life, ablate)


def _merge_phase(state, batch, statuses, commit, shapes, max_write_life,
                 ablate=""):
    T, NR, NW, K = shapes.txns, shapes.reads, shapes.writes, shapes.capacity
    L = shapes.limbs
    bkeys, bval, nb, oldest = (
        state["bkeys"], state["bval"], state["nb"], state["oldest"])
    wb, we, wtxn = batch["wb"], batch["we"], batch["wtxn"]
    vnew = batch["commit_version"]
    wvalid = wtxn < T
    wtxn_c = jnp.minimum(wtxn, T - 1)

    if ablate in ("no_merge", "only_hist"):
        new_oldest = jnp.maximum(
            oldest, jnp.where(batch["advance_floor"],
                              vnew - jnp.int32(max_write_life), oldest))
        new_state = dict(state, oldest=new_oldest.astype(jnp.int32))
        info = {"overflow": state["poisoned"], "boundaries": nb,
                "committed": jnp.sum(commit.astype(jnp.int32))}
        return new_state, statuses, info

    # ---- 4. merge surviving writes into the step function at vnew ----
    # Incremental: only the 2NW candidate endpoints are sorted (the state's K
    # boundaries are already sorted); the union is built by binary-searching
    # each side into the other and scattering to merged positions. This
    # replaces the original design's three full (K+2NW)-wide multi-limb sorts
    # per batch with one 2NW-wide sort — the device analogue of the
    # reference's finger-merge (mergeWriteConflictRanges SkipList.cpp:1260,
    # which also only walks the *new* ranges).
    # committed, non-empty writes only: an inverted range would inject a
    # reversed -1/+1 coverage delta and cancel other writes' coverage
    cw = wvalid & commit[wtxn_c] & _key_lt(wb, we)
    CU = 2 * NW
    maxk = jnp.full((L, 1), jnp.uint32(0xFFFFFFFF))
    cand = jnp.concatenate([wb, we], axis=1)  # (L, CU)
    cand_valid = jnp.concatenate([cw, cw])
    cand = jnp.where(cand_valid[None, :], cand, maxk)
    # delta for coverage counting: +1 at committed write begins, -1 at ends
    cand_delta = jnp.concatenate(
        [cw.astype(jnp.int32), -(cw.astype(jnp.int32))])

    # sort candidates (dead ones carry delta 0 and key maxk -> sort last)
    s = lax.sort([cand[i] for i in range(L)] + [cand_delta], num_keys=L)
    skeys = jnp.stack(s[:L])
    sdelta = s[L]
    live = sdelta != 0
    first = jnp.concatenate(
        [jnp.ones(1, bool), ~_key_eq(skeys[:, 1:], skeys[:, :-1])]) & live
    grp = jnp.cumsum(first.astype(jnp.int32)) - 1  # unique-key rank
    mc = jnp.sum(first.astype(jnp.int32))  # number of unique candidate keys
    # unique representatives packed to ranks [0, mc); others -> dump slot CU.
    # One int32 scatter + a gather instead of scattering the (L, .) limbs.
    pos_rep = jnp.where(first, grp, CU)
    rep_src = jnp.full(CU + 1, CU - 1, jnp.int32).at[pos_rep].set(
        jnp.arange(CU, dtype=jnp.int32))[:CU]
    ulive = jnp.arange(CU) < mc
    ukeys = jnp.where(ulive[None, :], skeys[:, rep_src],
                      jnp.uint32(0xFFFFFFFF))
    gdelta = jnp.zeros(CU + 1, jnp.int32).at[jnp.where(live, grp, CU)].add(
        jnp.where(live, sdelta, 0))[:CU]
    # ONE lower-bound bisection serves both merge searches: state keys are
    # unique, so upper_bound = lb + dup, and the value lookup
    # bval[max(ub-1, 0)] = bval[clip(lb - 1 + dup)] — this halves the
    # merge's bisection queries (the single most expensive gather loop).
    ia = _searchsorted(bkeys, ukeys, "left")  # first state key >= cand
    dup = _key_eq(bkeys[:, jnp.minimum(ia, K - 1)], ukeys) & (ia < nb)
    # value of each unique candidate key under the current step function
    uval = bval[jnp.clip(ia - 1 + dup.astype(jnp.int32), 0, K - 1)]

    # union-merge positions: state key i -> i + (#new-unique candidates < it);
    # candidate j -> (#state keys < it) + (#new-unique candidates before j).
    # A candidate equal to a state key maps to the SAME slot (no new slot).
    is_new = ulive & ~dup
    pre = jnp.cumsum(is_new.astype(jnp.int32)) - is_new.astype(jnp.int32)
    pre_total = jnp.sum(is_new.astype(jnp.int32))
    # new-unique candidates preceding each state key, WITHOUT a second binary
    # search (K queries over the candidates would gather (L,K) per bisection
    # step) and without a (K,)-wide gather: a new-unique candidate j is
    # strictly below exactly the state keys i >= ia[j] (new means not equal
    # to any state key), so a scatter-add at ia[j] followed by a prefix sum
    # gives each state key's slot shift.
    dmark = jnp.zeros(K + 1, jnp.int32).at[
        jnp.where(is_new, ia, K)].add(jnp.where(is_new, 1, 0))
    slotA = jnp.arange(K) + jnp.cumsum(dmark)[:K]
    slotB = ia + pre
    nu = nb + pre_total  # union size
    KU = K + CU  # + 1 dump slot

    # Build the union via ONE int32 source-index scatter + gathers: scattering
    # the (L, ...) key limbs directly costs L scatter rows, while gathers of
    # the same shape are cheap on TPU.
    liveA = jnp.arange(K) < nb
    posA = jnp.where(liveA, slotA, KU)
    posB = jnp.where(ulive, slotB, KU)
    src = jnp.full(KU + 1, -1, jnp.int32)
    src = src.at[posA].set(jnp.arange(K, dtype=jnp.int32))
    # B written second: a dup slot resolves to its candidate (same key; the
    # candidate carries the coverage delta and an identical value)
    src = src.at[posB].set(K + jnp.arange(CU, dtype=jnp.int32))
    is_b = src >= K
    src_c = jnp.clip(src, 0, K + CU - 1)
    # one fused value/delta lookup over a concatenated [state | candidate]
    # table instead of two separate per-source gathers + select
    vtab = jnp.concatenate([bval, uval])
    dtab = jnp.concatenate([jnp.zeros(K, jnp.int32), gdelta])
    val_u = jnp.where(src >= 0, vtab[src_c], NEG)
    delta_u = jnp.where(is_b, dtab[src_c], 0)

    # coverage: prefix-sum of deltas in key order; >0 => segment covered by a
    # committed write of this batch, so its version becomes vnew
    cover = jnp.cumsum(delta_u) > 0
    idxu = jnp.arange(KU + 1)
    live_u = idxu < nu
    newval = jnp.where(cover & live_u, jnp.maximum(val_u, vnew), val_u)

    # ---- 5. window GC: clamp to new floor + coalesce equal neighbors ----
    # advance_floor is False for all but the last chunk of a logical batch:
    # the too-old check and history clamping must use the PRE-batch floor for
    # every transaction of the batch (the reference advances oldestVersion
    # once per detectConflicts call, SkipList.cpp:1199-1206).
    floor = jnp.where(batch["advance_floor"],
                      vnew - jnp.int32(max_write_life), oldest)
    new_oldest = jnp.maximum(oldest, floor)
    newval = jnp.where(live_u, jnp.maximum(newval, new_oldest), NEG)

    # coalesce (removeBefore's segment-merge analogue): a slot is redundant
    # if its value equals its predecessor's post-clamp value
    prev_val = jnp.concatenate([jnp.full(1, NEG, jnp.int32), newval[:-1]])
    keep2 = live_u & ((idxu == 0) | (newval != prev_val))
    n2 = jnp.sum(keep2.astype(jnp.int32))
    # compact kept slots to the front: one int32 source scatter, then gather
    # keys directly from their ORIGINAL arrays (state / unique candidates)
    # through the composed index — the union's key array is never
    # materialized at all.
    cpos = jnp.cumsum(keep2.astype(jnp.int32)) - 1
    cpos = jnp.where(keep2, jnp.minimum(cpos, K - 1), K)
    csrc = jnp.full(K + 1, -1, jnp.int32).at[cpos].set(
        jnp.arange(KU + 1, dtype=jnp.int32))[:K]
    kept = csrc >= 0
    csrc_c = jnp.clip(csrc, 0, KU)
    fsrc = src[csrc_c]  # source id of each final slot (composed)
    f_is_a = kept & (fsrc >= 0) & (fsrc < K)
    f_is_b = kept & (fsrc >= K)
    out_keys = jnp.where(
        f_is_a[None, :], bkeys[:, jnp.clip(fsrc, 0, K - 1)],
        jnp.where(f_is_b[None, :], ukeys[:, jnp.clip(fsrc - K, 0, CU - 1)],
                  jnp.uint32(0xFFFFFFFF)))
    out_vals = jnp.where(kept, newval[csrc_c], NEG)

    overflow = n2 > K

    # Overflow poisons the state (sticky): truncation would drop the
    # highest-key history segments and cause FALSE COMMITS for batches
    # already enqueued behind this one (detect_async pipelines without a
    # host sync). Instead the whole keyspace collapses to one segment at
    # vnew, so every later stale read conflicts — conservative-only — until
    # the owner sees info["overflow"] and reconstructs (clearConflictSet
    # semantics, SkipList.cpp:957). This batch's own statuses are computed
    # pre-merge and remain exact.
    poisoned = state["poisoned"] | overflow
    pois_keys = jnp.broadcast_to(maxk, (L, K)).at[:, 0].set(
        jnp.zeros(L, dtype=jnp.uint32))  # encode(b"") == all-zero limbs
    pois_vals = jnp.full(K, NEG, jnp.int32).at[0].set(vnew)
    out_keys = jnp.where(poisoned, pois_keys, out_keys)
    out_vals = jnp.where(poisoned, pois_vals, out_vals)
    n2 = jnp.where(poisoned, 1, n2)
    new_table = state["table"] if ablate == "no_table" else _build_table(out_vals)

    new_state = {
        "bkeys": out_keys,
        "bval": out_vals,
        "nb": jnp.minimum(n2, K).astype(jnp.int32),
        "oldest": new_oldest.astype(jnp.int32),
        "table": new_table,
        "poisoned": poisoned,
    }
    info = {"overflow": poisoned, "boundaries": n2,
            "committed": jnp.sum(commit.astype(jnp.int32))}
    return new_state, statuses, info


def rebase_state(state: dict, delta: int):
    """Shift all version offsets down by delta (host rebases the int64 base)."""
    d = jnp.int32(delta)
    bval = jnp.maximum(state["bval"] - d, NEG)
    return {
        "bkeys": state["bkeys"],
        "bval": bval,
        "nb": state["nb"],
        "oldest": jnp.maximum(state["oldest"] - d, NEG),
        "table": _build_table(bval),
        "poisoned": state["poisoned"],
    }


def init_state(shapes: ConflictShapes, oldest: int = 0):
    K = shapes.capacity
    L = shapes.limbs
    maxk = np.full((L, K), 0xFFFFFFFF, dtype=np.uint32)
    maxk[:, 0] = 0  # segment 0: [b"" (all-zero limbs), next) -> NEG
    bval = np.full(K, int(NEG), dtype=np.int32)
    return {
        "bkeys": jnp.asarray(maxk),
        "bval": jnp.asarray(bval),
        "nb": jnp.int32(1),
        "oldest": jnp.int32(oldest),
        "table": _build_table(jnp.asarray(bval)),
        "poisoned": jnp.asarray(False),
    }


# ---------------------------------------------------------------------------
# host wrapper: the ConflictSet a Resolver instantiates
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _compiled_step(shapes: ConflictShapes, max_write_life: int):
    """One compiled program per (shapes, window) — shared across instances."""
    return jax.jit(functools.partial(
        conflict_step, shapes=shapes, max_write_life=max_write_life))


def conflict_scan(state: dict, stacked: dict, *, shapes: ConflictShapes,
                  max_write_life: int):
    """Run M conflict batches in ONE device dispatch via lax.scan.

    `stacked` has the same fields as a conflict_step batch with a leading
    batch axis (M, ...). Returns (final_state, statuses (M, T) int8,
    committed (M,) int32, overflow (M,) bool). Dispatch overhead (several ms
    per program launch through the runtime) amortizes over M batches — the
    device analogue of the proxy's pipelined commitBatch gating
    (MasterProxyServer.actor.cpp:364-366).
    """
    def stepfn(st, batch):
        st2, statuses, info = conflict_step(
            st, batch, shapes=shapes, max_write_life=max_write_life)
        return st2, (statuses.astype(jnp.int8), info["committed"],
                     info["overflow"])
    final, (stat, comm, ovf) = lax.scan(stepfn, state, stacked)
    return final, stat, comm, ovf


@functools.lru_cache(maxsize=32)
def _compiled_scan(shapes: ConflictShapes, max_write_life: int):
    return jax.jit(functools.partial(
        conflict_scan, shapes=shapes, max_write_life=max_write_life))


def _resolve_shapes(capacity=None, txns=None, reads_per_txn=None,
                    writes_per_txn=None, key_bytes=None) -> ConflictShapes:
    k = KNOBS
    t = txns or k.CONFLICT_BATCH_TXNS
    return ConflictShapes(
        capacity=capacity or k.CONFLICT_STATE_CAPACITY,
        txns=t,
        reads=t * (reads_per_txn or k.CONFLICT_BATCH_READS_PER_TXN),
        writes=t * (writes_per_txn or k.CONFLICT_BATCH_WRITES_PER_TXN),
        key_bytes=key_bytes or keylib.KEY_BYTES,
    )


class BatchEncoder:
    """Host-side batch encoding/chunking, shared by the single-device and
    mesh-sharded engines (and the driver entry points)."""

    def __init__(self, shapes: ConflictShapes, base_version: int = 0):
        self.shapes = shapes
        self.L = shapes.limbs
        self.base_version = base_version

    def _clamp_off(self, version: int) -> int:
        off = version - self.base_version
        return int(max(min(off, (1 << 31) - 1), int(NEG)))

    def encode_batch(self, txns: list[TxnConflictInfo], commit_version: int,
                     skip: list[bool] | None = None):
        """Build one device batch. Key encoding is bulk (C extension when
        available — feeding the device is a host hot path, the analogue of
        the reference's C++ key juggling in SkipList.cpp addTransaction)."""
        sh = self.shapes
        T = sh.txns
        assert len(txns) <= T
        rkeys_b: list[bytes] = []
        rkeys_e: list[bytes] = []
        wkeys_b: list[bytes] = []
        wkeys_e: list[bytes] = []
        rt: list[int] = []
        wt: list[int] = []
        snap = np.zeros(T, np.int32)
        valid = np.zeros(T, bool)
        for t, txn in enumerate(txns):
            if skip is not None and skip[t]:
                continue  # host already decided TOO_OLD; not in this batch
            valid[t] = True
            snap[t] = self._clamp_off(txn.read_snapshot)
            for b, e in txn.read_ranges:
                rkeys_b.append(b)
                rkeys_e.append(e)
                rt.append(t)
            for b, e in txn.write_ranges:
                wkeys_b.append(b)
                wkeys_e.append(e)
                wt.append(t)

        rb = np.full((self.L, sh.reads), 0xFFFFFFFF, np.uint32)
        re = np.full((self.L, sh.reads), 0xFFFFFFFF, np.uint32)
        wb = np.full((self.L, sh.writes), 0xFFFFFFFF, np.uint32)
        we = np.full((self.L, sh.writes), 0xFFFFFFFF, np.uint32)
        _bulk_encode(rkeys_b, rb, round_up=False)
        _bulk_encode(rkeys_e, re, round_up=True)
        _bulk_encode(wkeys_b, wb, round_up=False)
        _bulk_encode(wkeys_e, we, round_up=True)
        rtxn = np.full(sh.reads, T, np.int32)
        wtxn = np.full(sh.writes, T, np.int32)
        rtxn[: len(rt)] = rt
        wtxn[: len(wt)] = wt
        return {
            "rb": jnp.asarray(rb), "re": jnp.asarray(re), "rtxn": jnp.asarray(rtxn),
            "wb": jnp.asarray(wb), "we": jnp.asarray(we), "wtxn": jnp.asarray(wtxn),
            "snapshot": jnp.asarray(snap), "txn_valid": jnp.asarray(valid),
            "commit_version": jnp.int32(self._clamp_off(commit_version)),
            "advance_floor": jnp.asarray(True),
        }

    def split_for_capacity(self, txns):
        sh = self.shapes
        subs, cur, nr, nw = [], [], 0, 0
        for txn in txns:
            tr, tw = len(txn.read_ranges), len(txn.write_ranges)
            if tr > sh.reads or tw > sh.writes:
                raise FDBError("transaction_too_large",
                               f"{tr} reads / {tw} writes exceed batch shape")
            if cur and (nr + tr > sh.reads or nw + tw > sh.writes or len(cur) >= sh.txns):
                subs.append(cur)
                cur, nr, nw = [], 0, 0
            cur.append(txn)
            nr += tr
            nw += tw
        subs.append(cur)
        return subs


def detect_async_impl(engine, txns: list[TxnConflictInfo],
                      commit_version: int) -> "DetectHandle":
    """Enqueue a whole logical batch on device and return a handle; no
    host↔device synchronization happens until handle.result().

    Shared by DeviceConflictSet and ShardedDeviceConflictSet (`engine` needs:
    encoder, _step, _state, oldest_version, _maybe_rebase). This is the
    proxy's pipelining pattern (MasterProxyServer.actor.cpp:364-366,426-428):
    batch N+1's transfer/compute overlaps batch N's result readback.
    """
    engine._maybe_rebase(commit_version)
    enc = engine.encoder
    subs = enc.split_for_capacity(txns)
    # The too-old decision is taken here with exact int64 versions (device
    # offsets saturate across extreme rebases); flagged txns are excluded
    # from the device batch entirely.
    pre_batch_oldest = engine.oldest_version
    chunks = []
    for i, sub in enumerate(subs):
        host_too_old = [bool(t.read_ranges) and t.read_snapshot < pre_batch_oldest
                        for t in sub]
        batch = enc.encode_batch(sub, commit_version, skip=host_too_old)
        # the MVCC floor advances once per logical batch (last chunk), so
        # every chunk's too-old check uses the pre-batch floor
        batch["advance_floor"] = jnp.asarray(i == len(subs) - 1)
        new_state, statuses, info = engine._step(engine._state, batch)
        engine._state = new_state
        chunks.append((len(sub), host_too_old, statuses, info))
    # the kernel's floor advance is replicated host-side exactly
    # (floor = commit_version - window on the last chunk, monotonic max)
    engine.oldest_version = max(
        engine.oldest_version,
        commit_version - KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS)
    return DetectHandle(chunks)


class DeviceConflictSet:
    """Drop-in conflict set backed by the jitted device step.

    Mirrors the seam in fdbserver/ConflictSet.h:27-44: construct, feed batches
    of TxnConflictInfo, get {CONFLICT, TOO_OLD, COMMITTED} per transaction.
    Arbitrary batch sizes are handled by chunking to the static shape
    (chunk order preserves batch order, so intra-batch "earlier txns win"
    semantics are exact: later chunks see earlier chunks' merged writes).
    """

    def __init__(self, capacity: int | None = None, txns: int | None = None,
                 reads_per_txn: int | None = None, writes_per_txn: int | None = None,
                 oldest_version: int = 0, key_bytes: int | None = None):
        self.shapes = _resolve_shapes(capacity, txns, reads_per_txn,
                                      writes_per_txn, key_bytes)
        self.encoder = BatchEncoder(self.shapes, base_version=oldest_version)
        self.oldest_version = oldest_version
        self._state = init_state(self.shapes, oldest=0)
        self._step = _compiled_step(self.shapes,
                                    KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS)

    @property
    def base_version(self) -> int:
        return self.encoder.base_version

    def _maybe_rebase(self, commit_version: int):
        # Shift in <= 2^30 steps so each delta fits int32; values saturate at
        # NEG, so repeated shifts are exact for any version gap.
        while commit_version - self.encoder.base_version > _REBASE_THRESHOLD:
            delta = min(commit_version - self.encoder.base_version - (1 << 24),
                        1 << 30)
            self._state = rebase_state(self._state, delta)
            self.encoder.base_version += delta

    # -- ConflictBatch interface --
    def detect(self, txns: list[TxnConflictInfo], commit_version: int) -> list[int]:
        return self.detect_async(txns, commit_version).result()

    def detect_async(self, txns: list[TxnConflictInfo],
                     commit_version: int) -> "DetectHandle":
        return detect_async_impl(self, txns, commit_version)

    def clear(self, oldest_version: int = 0):
        """clearConflictSet (SkipList.cpp:957): state is soft/reconstructable."""
        self.encoder.base_version = oldest_version
        self.oldest_version = oldest_version
        self._state = init_state(self.shapes, oldest=0)


class DetectHandle:
    """Deferred result of detect_async: statuses fetched on first result()."""

    def __init__(self, chunks):
        self._chunks = chunks
        self._result: list[int] | None = None

    def result(self) -> list[int]:
        if self._result is None:
            out: list[int] = []
            for n, host_too_old, statuses, info in self._chunks:
                if bool(info["overflow"]):
                    # The truncated state dropped the highest-key history
                    # segments and could cause false commits — fatal; the
                    # owner reconstructs (clearConflictSet semantics,
                    # SkipList.cpp:957: conflict state is soft).
                    raise FDBError(
                        "internal_error",
                        "conflict state capacity exceeded; raise CONFLICT_STATE_CAPACITY")
                dev_statuses = np.asarray(statuses[:n])
                out.extend(TOO_OLD if old else int(s)
                           for s, old in zip(dev_statuses, host_too_old))
            self._result = out
            self._chunks = None
        return self._result
