"""Deterministic cluster simulator: processes, network, faults.

Reference: fdbrpc/sim2.actor.cpp — Sim2 swaps the global INetwork so the REAL
server code runs on simulated NICs/disks/clock in one OS process
(`sim2.actor.cpp:721`); connections have deterministic latency and can be
clogged (`:133-179`); processes/machines can be killed and rebooted
(`:1190-1213`, KillType ladder in simulator.h:41). RPC semantics come from
fdbrpc/FlowTransport.actor.cpp + fdbrpc/fdbrpc.h: a RequestStream is a
(address, token) endpoint, and a ReplyPromise inside a request is a
network-traversing promise — the callee replies through it, and a dead callee
surfaces as broken_promise to the caller (TOKEN_IGNORE path,
FlowTransport.actor.cpp:455-487).

Everything here is host-side control plane; device work (the conflict kernel)
is invoked by roles built on top.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from foundationdb_tpu.core.eventloop import ActorTask, EventLoop, TaskPriority
from foundationdb_tpu.core.future import Future, Promise
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom
from foundationdb_tpu.utils.trace import TraceEvent


class KillType:
    """simulator.h:41 KillType ladder (subset)."""

    RebootProcess = "RebootProcess"  # process restarts, durable state kept
    KillProcess = "KillProcess"  # process gone until explicitly rebooted
    RebootAndDelete = "RebootAndDelete"  # restarts with durable state wiped


@dataclass
class Endpoint:
    address: str
    token: int


class SimProcess:
    """One simulated server/client process (sim2's ProcessInfo analogue)."""

    def __init__(self, net: "SimNetwork", address: str, machine_id: str, dc_id: str):
        self.net = net
        self.address = address
        self.machine_id = machine_id
        self.dc_id = dc_id
        self.alive = True
        self.handlers: dict[int, Callable[[Any, Promise], None]] = {}
        self.actors: list[ActorTask] = []
        self.files: dict[str, "SimFile"] = {}
        self.boot_fn: Callable[["SimProcess"], None] | None = None
        self.reboots = 0

    # -- actor management: actors die with the process --
    def spawn(self, coro, name: str = "actor") -> ActorTask:
        task = self.net.loop.spawn(coro, name=f"{self.address}/{name}")
        self.actors.append(task)
        # completed actors drop out of the kill list (long-lived processes
        # spawn one actor per request; keeping them all would leak)
        task.add_system_callback(lambda _f: self.actors.remove(task)
                                 if task in self.actors else None)
        return task

    # -- endpoint registration (RequestStream server side) --
    def register(self, token: int, handler: Callable[[Any, Promise], None]):
        self.handlers[token] = handler

    def deregister(self, token: int):
        self.handlers.pop(token, None)


class SimFile:
    """Simulated durable file that loses unsynced writes on kill.

    Reference: fdbrpc/AsyncFileNonDurable.actor.h:134 — on a machine failure,
    writes that were not fsync'd are (deterministically-randomly) dropped,
    which is how the reference proves its recovery handles torn/lost writes.
    """

    def __init__(self, name: str, rng: DeterministicRandom):
        self.name = name
        self.rng = rng
        self.durable = b""
        self.pending: list[bytes] = []  # appended, not yet synced

    def append(self, data: bytes):
        self.pending.append(data)

    def sync(self):
        self.durable += b"".join(self.pending)
        self.pending.clear()

    def read_all(self) -> bytes:
        return self.durable + b"".join(self.pending)

    def truncate(self):
        """Discard all contents (durable and pending) — used by DiskQueue
        file alternation; the truncate itself is treated as durable."""
        self.durable = b""
        self.pending.clear()

    def truncate_to(self, size: int):
        """Durably truncate to `size` bytes (ftruncate semantics)."""
        self.durable = self.read_all()[:size]
        self.pending.clear()

    def on_kill(self):
        """Each unsynced append independently survives or is lost; a lost
        prefix truncates everything after it (append-only log semantics)."""
        kept = []
        for chunk in self.pending:
            if self.rng.coinflip(0.5):
                kept.append(chunk)
            else:
                break  # torn tail: later appends can't be durable either
        self.durable += b"".join(kept)
        self.pending.clear()


class SimNetwork:
    """Simulated transport + fault injection over one EventLoop."""

    def __init__(self, loop: EventLoop, rng: DeterministicRandom):
        self.loop = loop
        self.rng = rng
        self.processes: dict[str, SimProcess] = {}
        self._clogged_until: dict[tuple[str, str], float] = {}
        self._partitioned: set[tuple[str, str]] = set()
        # invariant oracles observe only under simulation, with state scoped
        # to THIS network so coexisting sims can't mix acked versions
        # (fdbrpc/sim_validation.cpp pattern)
        from foundationdb_tpu.core import sim_validation
        self.validation = sim_validation.SimValidation()
        self._next_token = 1 << 32
        # reply futures currently owed by each serving process, so a kill can
        # break them (TOKEN_IGNORE / broken_promise semantics)
        self._owed: dict[str, list[Promise]] = {}

    # -- topology --
    def new_process(self, address: str, machine_id: str | None = None, dc_id: str = "dc0") -> SimProcess:
        p = SimProcess(self, address, machine_id or address, dc_id)
        self.processes[address] = p
        self._owed.setdefault(address, [])
        return p

    def temp_token(self) -> int:
        self._next_token += 1
        return self._next_token

    # -- fault injection (sim2.actor.cpp:1190-1213, :133-179) --
    def clog_pair(self, a: str, b: str, seconds: float):
        until = self.loop.now() + seconds
        for pair in ((a, b), (b, a)):
            self._clogged_until[pair] = max(self._clogged_until.get(pair, 0.0), until)
        TraceEvent("ClogPair").detail("A", a).detail("B", b).detail("Seconds", seconds).log()

    def partition(self, a: str, b: str):
        self._partitioned.add((a, b))
        self._partitioned.add((b, a))

    def heal(self):
        self._partitioned.clear()
        self._clogged_until.clear()

    def kill(self, address: str, kill_type: str = KillType.KillProcess):
        p = self.processes.get(address)
        if p is None or not p.alive:
            return
        TraceEvent("SimKill", address).detail("KillType", kill_type).log()
        p.alive = False
        for task in p.actors:
            task.cancel()
        p.actors.clear()
        p.handlers.clear()
        for promise in self._owed[address]:
            promise.break_promise()
        self._owed[address].clear()
        if kill_type == KillType.RebootAndDelete:
            p.files.clear()
        else:
            for f in p.files.values():
                f.on_kill()
        if kill_type in (KillType.RebootProcess, KillType.RebootAndDelete):
            self.loop._schedule(
                self.rng.random() * 0.5 + 0.1,
                TaskPriority.DefaultDelay,
                lambda: self.reboot(address),
            )

    def reboot(self, address: str):
        p = self.processes.get(address)
        if p is None or p.alive:
            return
        p.alive = True
        p.reboots += 1
        TraceEvent("SimReboot", address).detail("Reboots", p.reboots).log()
        if p.boot_fn is not None:
            p.boot_fn(p)

    def reboot_dead(self, addresses=None):
        """Reboot every dead process (optionally restricted to `addresses`)
        — the heal path shared by the spec runner's quiesce, region-kill
        workloads, and whole-cluster restart tests."""
        wanted = None if addresses is None else set(addresses)
        for p in list(self.processes.values()):
            if not p.alive and (wanted is None or p.address in wanted):
                self.reboot(p.address)

    # -- file API --
    def open_file(self, process: SimProcess, name: str) -> SimFile:
        if name not in process.files:
            process.files[name] = SimFile(name, self.rng.fork())
        return process.files[name]

    # -- transport --
    def _link_delay(self, src: str, dst: str) -> float | None:
        """None = dropped (partition); otherwise extra delivery delay.

        Clogging DELAYS packets instead of dropping them (Sim2Conn clogs the
        connection; TCP retransmits underneath, sim2.actor.cpp:133-179) — a
        clogged-then-healed link delivers everything late, which is what lets
        version-chained pipelines (resolver prevVersion order, TLog version
        order) drain instead of wedging on a gap. Partitions drop."""
        if (src, dst) in self._partitioned:
            return None
        until = self._clogged_until.get((src, dst))
        if until is not None and until > self.loop.now():
            return until - self.loop.now()
        return 0.0

    def _latency(self) -> float:
        lo, hi = KNOBS.SIM_MIN_LATENCY, KNOBS.SIM_MAX_LATENCY
        return lo + (hi - lo) * self.rng.random()

    def request(self, src: SimProcess, dest: Endpoint, payload: Any,
                priority: int = TaskPriority.DefaultOnMainThread,
                timeout: float | None = -1.0) -> Future:
        """RequestStream::getReply — send `payload`, future of the reply.

        The reply promise traverses the network (fdbrpc/fdbrpc.h:99): the
        callee's handler fulfills it; if the callee is dead at delivery time or
        dies before replying, the caller sees broken_promise.

        A clogged/partitioned link DROPS the packet; without a bound every
        such await would hang its actor forever, so requests carry a default
        timeout (SIM_RPC_TIMEOUT_SECONDS) after which the caller sees
        request_maybe_delivered — the reference surfaces the same through
        connection failure + IFailureMonitor. Pass timeout=None for
        deliberately unbounded waits (watches)."""
        reply = Promise()
        if not src.alive:
            reply.send_error(FDBError("operation_cancelled"))
            return reply.future
        if timeout == -1.0:
            timeout = KNOBS.SIM_RPC_TIMEOUT_SECONDS
        if timeout is not None:
            self.loop._schedule(
                timeout, TaskPriority.DefaultDelay,
                lambda: reply.send_error(FDBError("request_maybe_delivered"))
                if not reply.is_set() else None)

        def deliver():
            dst = self.processes.get(dest.address)
            if dst is None or not dst.alive or dest.token not in dst.handlers:
                # TOKEN_IGNORE_PACKET path -> broken_promise at the caller
                self._send_back(reply, FDBError("broken_promise"), is_error=True)
                return
            self._owed[dest.address].append(reply)

            inner = Promise()

            def on_reply(f: Future):
                try:
                    self._owed[dest.address].remove(reply)
                except ValueError:
                    return  # already broken by a kill
                if f.is_error():
                    self._send_back(reply, f._result, is_error=True)
                else:
                    self._send_back(reply, f._result, is_error=False)

            inner.future.add_callback(on_reply)
            dst.handlers[dest.token](payload, inner)

        extra = self._link_delay(src.address, dest.address)
        if extra is not None:
            self.loop._schedule(extra + self._latency(), priority, deliver)
        # else: partitioned; packet dropped — the caller's timeout or the
        # failure monitor surfaces it
        return reply.future

    def _send_back(self, reply: Promise, result: Any, is_error: bool):
        """Reply travels the network too (with latency); no link check on the
        way back keeps fault semantics simple but still async."""
        def arrive():
            if reply.is_set():
                return
            if is_error:
                reply.send_error(result)
            else:
                reply.send(result)

        self.loop._schedule(self._latency(), TaskPriority.DefaultOnMainThread, arrive)

    def one_way(self, src: SimProcess, dest: Endpoint, payload: Any):
        """Fire-and-forget message (PromiseStream::send semantics)."""
        def deliver():
            dst = self.processes.get(dest.address)
            if dst is None or not dst.alive or dest.token not in dst.handlers:
                return
            dst.handlers[dest.token](payload, Promise())

        if src.alive:
            extra = self._link_delay(src.address, dest.address)
            if extra is not None:
                self.loop._schedule(extra + self._latency(),
                                    TaskPriority.DefaultOnMainThread, deliver)
