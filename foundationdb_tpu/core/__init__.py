"""Deterministic runtime core (the Flow-runtime equivalent).

Reference layer 0+1: flow/flow.h (Future/Promise/actors), flow/Net2.actor.cpp
(single-threaded prioritized event loop), fdbrpc/sim2.actor.cpp (deterministic
simulator: virtual clock, simulated network with latency/clog/partition,
kill/reboot, non-durable files).

The host control plane is Python coroutines over a custom deterministic
scheduler — the analogue of the ACTOR compiler is plain async/await; the
analogue of swapping g_network for Sim2 is constructing an EventLoop with a
virtual clock and a SimNetwork.
"""

from foundationdb_tpu.core.future import (  # noqa: F401
    Future,
    Promise,
    PromiseStream,
    all_of,
    any_of,
)
from foundationdb_tpu.core.eventloop import EventLoop, TaskPriority  # noqa: F401
from foundationdb_tpu.core.sim import SimNetwork, KillType  # noqa: F401
