"""Simulation-only global invariant oracles.

Reference: fdbrpc/sim_validation.cpp — tiny global trackers called from REAL
code paths (e.g. debug_advanceMaxCommittedVersion from the proxy,
MasterProxyServer.actor.cpp:820) that ASSERT cross-process invariants the
distributed protocol is supposed to guarantee. They only observe under the
deterministic simulator (a real deployment has no global vantage point) and
cost nothing when disabled.

Unlike the reference (one process = one simulation, so globals are safe),
several simulated clusters can coexist in one interpreter here, so the
oracle state is attached to each SimNetwork instance; `of(net)` resolves a
network to its oracle, or to a no-op for real transports.

Invariants tracked:
  - acked-commit monotonicity: the set of client-ACKNOWLEDGED commit
    versions is consistent with the master's total order (a new ack below
    an already-acked version is fine — acks race — but a version can never
    be acked twice from different batches).
  - external consistency: a read version HANDED OUT must be >= every commit
    acknowledged before the GRV request was received (strict
    serializability's real-time edge; debug_checkMinCommittedVersion).
"""

from __future__ import annotations


class SimValidation:
    """Per-simulation oracle state (one per SimNetwork)."""

    enabled = True

    def __init__(self):
        self._max_acked = 0
        self._acked_from: dict[int, str] = {}

    def debug_advance_max_committed(self, version: int, who: str = "?"):
        """Called by a proxy when it ACKS a commit at `version` to a client
        (debug_advanceMaxCommittedVersion). Each version is acked by exactly
        one batch on one proxy; a duplicate ack from elsewhere means two
        batches believed they owned the same master-assigned version."""
        prev = self._acked_from.get(version)
        assert prev is None or prev == who, \
            f"version {version} acked by both {prev} and {who}"
        self._acked_from[version] = who
        if version > self._max_acked:
            self._max_acked = version
        # bound memory AND work: over the cap, drop the oldest half by
        # version (a fixed version-distance window prunes nothing when
        # versions advance slowly, turning long dense sims quadratic)
        if len(self._acked_from) > 65536:
            keep = sorted(self._acked_from)[len(self._acked_from) // 2:]
            kept = {v: self._acked_from[v] for v in keep}
            self._acked_from.clear()
            self._acked_from.update(kept)

    def debug_grv_floor(self) -> int:
        """Snapshot the external-consistency floor when a GRV request
        ARRIVES: the reply must be >= this (every commit acked before the
        request)."""
        return self._max_acked

    def debug_check_read_version(self, version: int, floor: int,
                                 who: str = "?"):
        """Called with the GRV reply and the floor snapshotted at arrival
        (debug_checkMinCommittedVersion): handing out less would let a
        client miss a write it was already told succeeded."""
        assert version >= floor, \
            f"{who} handed out read version {version} < acked floor {floor}"


class _Disabled:
    """Real deployments have no global vantage point: every probe no-ops."""

    enabled = False

    def debug_advance_max_committed(self, version, who="?"):
        pass

    def debug_grv_floor(self) -> int:
        return 0

    def debug_check_read_version(self, version, floor, who="?"):
        pass


DISABLED = _Disabled()


def of(net, scope: str = ""):
    """The oracle attached to a network (SimNetwork carries one); no-op for
    real transports. `scope` separates DATABASES sharing one simulation
    (the DR topology runs two live clusters on one SimNetwork): external
    consistency is a per-database invariant — cluster B's acked commits
    must not raise cluster A's GRV floor."""
    base = getattr(net, "validation", None)
    if base is None:
        return DISABLED
    if not scope:
        return base
    scoped = getattr(net, "_validation_scoped", None)
    if scoped is None:
        scoped = net._validation_scoped = {}
    if scope not in scoped:
        scoped[scope] = type(base)()
    return scoped[scope]
