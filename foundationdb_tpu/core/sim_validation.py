"""Simulation-only global invariant oracles.

Reference: fdbrpc/sim_validation.cpp — tiny global trackers called from REAL
code paths (e.g. debug_advanceMaxCommittedVersion from the proxy,
MasterProxyServer.actor.cpp:820) that ASSERT cross-process invariants the
distributed protocol is supposed to guarantee. They only observe under the
deterministic simulator (a real deployment has no global vantage point) and
cost nothing when disabled.

Invariants tracked:
  - acked-commit monotonicity: the set of client-ACKNOWLEDGED commit
    versions is consistent with the master's total order (a new ack below
    an already-acked version is fine — acks race — but a version can never
    be acked twice from different batches).
  - external consistency: a read version HANDED OUT must be >= every commit
    acknowledged before the GRV request was received (strict
    serializability's real-time edge; debug_checkMinCommittedVersion).
"""

from __future__ import annotations

_enabled = False
_max_acked = 0
_acked_from: dict[int, str] = {}


def enable():
    """Turned on by the simulator; real deployments never call this."""
    global _enabled, _max_acked
    _enabled = True
    _max_acked = 0
    _acked_from.clear()


def reset():
    global _max_acked
    _max_acked = 0
    _acked_from.clear()


def is_enabled() -> bool:
    return _enabled


def debug_advance_max_committed(version: int, who: str = "?"):
    """Called by a proxy when it ACKS a commit at `version` to a client
    (debug_advanceMaxCommittedVersion). Each version is acked by exactly one
    batch on one proxy; a duplicate ack from elsewhere means two batches
    believed they owned the same master-assigned version."""
    global _max_acked
    if not _enabled:
        return
    prev = _acked_from.get(version)
    assert prev is None or prev == who, \
        f"version {version} acked by both {prev} and {who}"
    _acked_from[version] = who
    if version > _max_acked:
        _max_acked = version
    # bound memory AND work: over the cap, drop the oldest half by version
    # (a fixed version-distance window prunes nothing when versions advance
    # slowly, turning long dense sims quadratic)
    if len(_acked_from) > 65536:
        keep = sorted(_acked_from)[len(_acked_from) // 2:]
        kept = {v: _acked_from[v] for v in keep}
        _acked_from.clear()
        _acked_from.update(kept)


def debug_grv_floor() -> int:
    """Snapshot the external-consistency floor when a GRV request ARRIVES:
    the reply must be >= this (every commit acked before the request)."""
    return _max_acked if _enabled else 0


def debug_check_read_version(version: int, floor: int, who: str = "?"):
    """Called with the GRV reply and the floor snapshotted at arrival
    (debug_checkMinCommittedVersion): handing out less would let a client
    miss a write it was already told succeeded."""
    if not _enabled:
        return
    assert version >= floor, \
        f"{who} handed out read version {version} < acked floor {floor}"
