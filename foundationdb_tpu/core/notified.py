"""NotifiedVersion: a monotonically increasing value with threshold waiters.

Reference: fdbclient/Notified.h:29 — the ordering primitive of the whole write
pipeline. The resolver orders batches by waiting version.whenAtLeast(prev)
(Resolver.actor.cpp:104), TLogs order commits the same way
(TLogServer.actor.cpp:1168), proxies gate their pipeline phases on it
(MasterProxyServer.actor.cpp:364-366,426-428), and storage servers wake readers
when they catch up (storageserver.actor.cpp:654 waitForVersion).
"""

from __future__ import annotations

import heapq

from foundationdb_tpu.core.future import Future, ready_future


class NotifiedVersion:
    __slots__ = ("_value", "_waiters", "_seq")

    def __init__(self, value: int = 0):
        self._value = value
        self._waiters: list[tuple[int, int, Future]] = []  # (threshold, seq, f)
        self._seq = 0

    def get(self) -> int:
        return self._value

    def when_at_least(self, threshold: int) -> Future:
        if self._value >= threshold:
            return ready_future(self._value)
        f = Future()
        self._seq += 1
        heapq.heappush(self._waiters, (threshold, self._seq, f))
        return f

    def set(self, value: int):
        if value < self._value:
            raise ValueError(f"NotifiedVersion moved backwards: {self._value} -> {value}")
        self._value = value
        while self._waiters and self._waiters[0][0] <= value:
            _, _, f = heapq.heappop(self._waiters)
            if not f.is_ready():
                f._set(value)


class AsyncVar:
    """A mutable value with change notification (flow/genericactors.actor.h
    AsyncVar): readers `await onChange()` to observe the next set(); set with
    an equal value does not fire (the reference's setUnconditional is
    `set_unconditional`)."""

    def __init__(self, value=None):
        self._value = value
        self._waiters: list[Future] = []

    def get(self):
        return self._value

    def on_change(self) -> Future:
        f = Future()
        self._waiters.append(f)
        return f

    def set(self, value):
        if value == self._value:
            return
        self.set_unconditional(value)

    def set_unconditional(self, value):
        self._value = value
        waiters, self._waiters = self._waiters, []
        for f in waiters:
            f._set(value)


class AsyncTrigger:
    """An edge-only signal (flow/genericactors.actor.h AsyncTrigger):
    `await on_trigger()` resumes at the NEXT trigger(); triggers with no
    waiters are not remembered."""

    def __init__(self):
        self._waiters: list[Future] = []

    def on_trigger(self) -> Future:
        f = Future()
        self._waiters.append(f)
        return f

    def trigger(self):
        waiters, self._waiters = self._waiters, []
        for f in waiters:
            f._set(None)
