"""Futures, promises and streams.

Reference: flow/flow.h — SAV<T> single-assignment variable (:351), Future<T>
(:595), Promise<T> (:709), PromiseStream/FutureStream (:760,:837). Error
propagation is by exception (flow/Error.h); `broken_promise` is delivered when
a Promise is dropped unfulfilled, which is how dead servers surface to waiters.

A Future here is a plain awaitable resolved by the EventLoop. It is decoupled
from any particular loop: callbacks fire synchronously on set, and the loop's
task-resume callback reschedules the awaiting actor.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Generator

from foundationdb_tpu.utils.errors import FDBError

_PENDING, _VALUE, _ERROR = 0, 1, 2


class Future:
    __slots__ = ("_state", "_result", "_callbacks")

    def __init__(self):
        self._state = _PENDING
        self._result: Any = None
        self._callbacks: list[Callable[[Future], None]] = []

    # -- inspection --
    def is_ready(self) -> bool:
        return self._state != _PENDING

    def is_error(self) -> bool:
        return self._state == _ERROR

    def get(self) -> Any:
        """Value if ready; raises if error or not ready."""
        if self._state == _VALUE:
            return self._result
        if self._state == _ERROR:
            raise self._result
        raise FDBError("internal_error", "Future.get() on pending future")

    # -- resolution (used by Promise / loop) --
    def _set(self, value: Any):
        if self._state != _PENDING:
            raise FDBError("internal_error", "future set twice")
        self._state = _VALUE
        self._result = value
        self._fire()

    def _set_error(self, error: BaseException):
        if self._state != _PENDING:
            return  # late error after value: drop (matches SAV sendError races)
        self._state = _ERROR
        self._result = error
        self._fire()

    def _fire(self):
        cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            cb(self)

    def add_callback(self, cb: Callable[[Future], None]):
        if self._state != _PENDING:
            cb(self)
        else:
            self._callbacks.append(cb)

    def remove_callback(self, cb):
        try:
            self._callbacks.remove(cb)
        except ValueError:
            pass

    def __await__(self) -> Generator["Future", None, Any]:
        if self._state == _PENDING:
            yield self
        if self._state == _ERROR:
            raise self._result
        if self._state == _PENDING:
            raise FDBError("internal_error", "actor resumed with pending future")
        return self._result


class Promise:
    """Sender side of a Future. Dropping it unfulfilled breaks the future."""

    __slots__ = ("future", "_sent")

    def __init__(self):
        self.future = Future()
        self._sent = False

    def send(self, value: Any = None):
        self._sent = True
        self.future._set(value)

    def send_error(self, error: BaseException):
        self._sent = True
        self.future._set_error(error)

    def is_set(self) -> bool:
        return self.future.is_ready()

    def break_promise(self):
        if not self.future.is_ready():
            self.future._set_error(FDBError("broken_promise"))


def settle_failed(reply: Promise, e: BaseException) -> None:
    """Settle a reply promise from a FAILING spawned handler, just before
    the exception propagates and kills the coroutine. The transport only
    auto-answers raises from synchronous handlers; a spawned delegate that
    dies with its reply unsettled wedges the caller until the full RPC
    timeout (protolint PROTO002). Cancellation maps to broken_promise:
    forwarding operation_cancelled verbatim would make the remote caller
    believe its OWN operation was cancelled and kill actors (see
    ratekeeper._sample's re-raise discipline)."""
    if isinstance(e, FDBError) and e.name == "operation_cancelled":
        e = FDBError("broken_promise", "handler cancelled before reply")
    reply.send_error(e)


def settle_many(settlements) -> None:
    """Settle a batch of promises synchronously, in order.

    `settlements` is a list of (promise, value, error) triples — error is
    None for a value settlement. One native reply batch (a ClientConn.feed
    over a socket read) resolves every future it carries from a single
    call in a single loop tick: each settle fires its callbacks inline,
    and only the awaiting actors' resumes go back through the loop, so
    the per-future schedule hop of settling one-by-one from a coroutine
    disappears. Already-settled promises (request expired, duplicate
    reply) are skipped, matching the reply loop's dedup discipline."""
    for p, value, error in settlements:
        if p.is_set():
            continue
        if error is not None:
            p.send_error(error)
        else:
            p.send(value)


class PromiseStream:
    """Multi-value stream: send() many values; receivers pop() Futures.

    Reference: flow/flow.h:760 PromiseStream / :837 FutureStream. Queueing is
    unbounded; `close(error)` ends the stream (end_of_stream by default).
    """

    __slots__ = ("_queue", "_waiters", "_closed")

    def __init__(self):
        # deques: both ends see O(1) — a saturated stream (thousands of
        # queued commits / GRV waiters) must not turn every pop into a
        # front-shift of the whole backlog
        self._queue: deque[Any] = deque()
        self._waiters: deque[Future] = deque()
        self._closed: BaseException | None = None

    def send(self, value: Any = None):
        if self._closed is not None:
            return
        if self._waiters:
            self._waiters.popleft()._set(value)
        else:
            self._queue.append(value)

    def close(self, error: BaseException | None = None):
        if self._closed is not None:
            return
        self._closed = error or FDBError("end_of_stream")
        for w in self._waiters:
            w._set_error(self._closed)
        self._waiters = deque()

    def pop(self) -> Future:
        """Future of the next value (FIFO among waiters — deterministic)."""
        f = Future()
        if self._queue:
            f._set(self._queue.popleft())
        elif self._closed is not None:
            f._set_error(self._closed)
        else:
            self._waiters.append(f)
        return f

    def __len__(self):
        return len(self._queue)


def ready_future(value: Any = None) -> Future:
    f = Future()
    f._set(value)
    return f


def error_future(error: BaseException) -> Future:
    f = Future()
    f._set_error(error)
    return f


def all_of(futures: list[Future]) -> Future:
    """Resolves with the list of values once all resolve; first error wins.

    Reference: flow/genericactors.actor.h waitForAll.
    """
    out = Future()
    n = len(futures)
    if n == 0:
        out._set([])
        return out
    remaining = [n]

    def on_done(_f):
        if out.is_ready():
            return
        if _f.is_error():
            out._set_error(_f._result)
            return
        remaining[0] -= 1
        if remaining[0] == 0:
            out._set([f.get() for f in futures])

    for f in futures:
        f.add_callback(on_done)
    return out


def any_of(futures: list[Future]) -> Future:
    """Resolves with (index, value) of the first future to resolve."""
    out = Future()

    def on_done(_f):
        if out.is_ready():
            return
        if _f.is_error():
            out._set_error(_f._result)
        else:
            out._set((futures.index(_f), _f._result))

    for f in futures:
        f.add_callback(on_done)
    return out
