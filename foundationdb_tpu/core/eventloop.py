"""Deterministic single-threaded prioritized event loop.

Reference: flow/Net2.actor.cpp — Net2::run (:550) drains a priority queue of
OrderedTasks with 42 named priorities (flow/network.h:31-73); the simulator
(fdbrpc/sim2.actor.cpp) replaces the wall clock with virtual time so a run is a
pure function of the seed.

Ordering contract: runnable items execute in (time, -priority, seq) order.
`seq` is a global monotone counter, so same-time same-priority items run in
schedule order — this is what makes whole-cluster simulation replayable.

The loop runs coroutines ("actors") that await Futures. Cancellation follows
Flow's model: cancelling an actor injects operation_cancelled at its current
wait point (flow/README.md "ACTOR cancellation").
"""

from __future__ import annotations

import heapq
from typing import Any, Coroutine

from foundationdb_tpu.core.future import Future
from foundationdb_tpu.utils.errors import FDBError


class TaskPriority:
    """Subset of flow/network.h task priorities (higher runs first)."""

    Max = 1000000
    Coordination = 8800
    FailureMonitor = 8700
    TLogCommit = 8570
    ProxyCommitDispatch = 8550
    ProxyCommit = 8540
    ResolverResolve = 8530
    ProxyGetConsistentReadVersion = 8500
    DefaultOnMainThread = 7500
    DefaultDelay = 7010
    DefaultYield = 7000
    DataDistribution = 3500
    UpdateStorage = 3000
    Low = 2000
    Min = 1000
    Zero = 0


class ActorTask(Future):
    """A running coroutine; also the Future of its final result.

    Unhandled-error contract (Flow's SAV error delivery, flow/flow.h): an
    actor that dies with an error *nobody is waiting on* must not fail
    silently — the loop reports it loudly (default: raise out of the run
    loop). operation_cancelled is benign (that's how kills reap actors).
    """

    __slots__ = ("_coro", "_loop", "name", "_waiting_on", "_cancelled",
                 "_observed", "_started")

    def __init__(self, loop: "EventLoop", coro: Coroutine, name: str):
        super().__init__()
        self._loop = loop
        self._coro = coro
        self.name = name
        self._waiting_on: Future | None = None
        self._cancelled = False
        self._observed = False
        self._started = False

    def __del__(self):
        # A task whose loop was abandoned before its first step holds a
        # coroutine that never ran; close it so GC doesn't emit
        # "coroutine ... was never awaited" (the silent-task-loss class —
        # the suite runs with that warning promoted to an error).
        if not self._started and not self.is_ready():
            self._coro.close()

    def add_callback(self, cb):
        self._observed = True
        super().add_callback(cb)

    def add_system_callback(self, cb):
        """Bookkeeping callback that does NOT count as observing the result
        (used by SimProcess's actor registry)."""
        super().add_callback(cb)

    # awaiting/getting an already-failed task raises inline without going
    # through add_callback — still counts as observing the error
    def __await__(self):
        self._observed = True
        return super().__await__()

    def get(self):
        self._observed = True
        return super().get()

    def cancel(self):
        """Inject operation_cancelled at the actor's current wait point."""
        if self.is_ready() or self._cancelled:
            return
        self._cancelled = True
        if self._waiting_on is not None:
            self._waiting_on.remove_callback(self._on_waited)
            self._waiting_on = None
        self._loop._schedule(0.0, TaskPriority.DefaultOnMainThread, self._step_cancel)

    def _step_cancel(self):
        if self.is_ready():
            return
        self._started = True
        # If the actor swallows the cancellation (cleanup in an except/finally
        # that awaits), _drive registers on whatever it awaits next.
        self._cancelled = False
        self._drive(lambda: self._coro.throw(FDBError("operation_cancelled")))

    def _start(self):
        self._started = True
        self._step()

    def _step(self):
        if self.is_ready():
            return  # died meanwhile (e.g. a cancel landed between a queued
            # resume and now): a finished coroutine must never be re-driven
        # the resume hot path: _drive(lambda: self._coro.send(None)) costs
        # a closure allocation + an extra frame per actor step, which is
        # measurable at bench rates — inline the send instead
        try:
            waited = self._coro.send(None)
        except StopIteration as stop:
            self._set(stop.value)
            return
        except BaseException as e:  # noqa: BLE001
            self._died(e)
            return
        self._waiting_on = waited
        waited.add_callback(self._on_waited)

    def _drive(self, advance):
        """Advance the coroutine one step; park it on whatever it yields."""
        try:
            waited = advance()
        except StopIteration as stop:
            self._set(stop.value)
            return
        except BaseException as e:  # noqa: BLE001
            self._died(e)
            return
        self._waiting_on = waited
        waited.add_callback(self._on_waited)

    def _died(self, err: BaseException):
        self._set_error(err)
        if not self._observed and not (
                isinstance(err, FDBError) and err.name == "operation_cancelled"):
            # defer one scheduler turn at the lowest priority: a caller
            # that awaits the task in the same virtual instant observes it
            # first; only a genuinely unwatched death reports
            self._loop._schedule(
                0.0, TaskPriority.Zero,
                lambda: None if self._observed
                else self._loop._report_unhandled(self, err))

    def _on_waited(self, fut: Future):
        self._waiting_on = None
        self._loop._schedule(0.0, TaskPriority.DefaultOnMainThread, self._step)


class EventLoop:
    """Deterministic scheduler with a virtual (or wall) clock."""

    def __init__(self, start_time: float = 0.0):
        self._now = start_time
        self._seq = 0
        self._heap: list[tuple[float, int, int, Any]] = []
        self._stopped = False
        # Override to tolerate unobserved actor errors (takes (task, error));
        # None = trace at SevError and raise, crashing the run loop.
        self.on_unhandled_actor_error = None

    def _report_unhandled(self, task: "ActorTask", error: BaseException):
        if self.on_unhandled_actor_error is not None:
            self.on_unhandled_actor_error(task, error)
            return
        from foundationdb_tpu.utils.trace import TraceEvent
        TraceEvent("UnhandledActorError", task.name).detail(
            "Error", repr(error)).log()
        raise error

    # -- clock --
    def now(self) -> float:
        return self._now

    # -- scheduling primitives --
    def _schedule(self, delay: float, priority: int, fn):
        self._seq += 1
        heapq.heappush(self._heap, (self._now + delay, -priority, self._seq, fn))

    def delay(self, seconds: float, priority: int = TaskPriority.DefaultDelay) -> Future:
        f = Future()
        self._schedule(max(0.0, seconds), priority, lambda: f._set(None) if not f.is_ready() else None)
        return f

    def yield_(self, priority: int = TaskPriority.DefaultYield) -> Future:
        return self.delay(0.0, priority)

    def spawn(self, coro: Coroutine, name: str = "actor") -> ActorTask:
        task = ActorTask(self, coro, name)
        self._schedule(0.0, TaskPriority.DefaultOnMainThread, task._start)
        return task

    def stop(self):
        self._stopped = True

    # -- running --
    def run_until_idle(self, max_time: float | None = None) -> float:
        """Drain the queue, advancing virtual time; returns final time."""
        self._stopped = False
        while self._heap and not self._stopped:
            t, negp, seq, fn = heapq.heappop(self._heap)
            if max_time is not None and t > max_time:
                heapq.heappush(self._heap, (t, negp, seq, fn))
                self._now = max_time
                break
            self._now = max(self._now, t)
            fn()
        return self._now

    def run_future(self, fut: Future, max_time: float | None = None) -> Any:
        """Run until `fut` resolves; returns its value (or raises)."""
        if isinstance(fut, ActorTask):
            fut._observed = True  # the caller is watching this actor
        self._stopped = False
        while not fut.is_ready() and self._heap and not self._stopped:
            t, negp, seq, fn = heapq.heappop(self._heap)
            if max_time is not None and t > max_time:
                heapq.heappush(self._heap, (t, negp, seq, fn))  # don't lose it
                raise FDBError("timed_out", "run_future hit max_time")
            self._now = max(self._now, t)
            fn()
        if not fut.is_ready():
            raise FDBError("internal_error", "deadlock: future unresolved and queue empty")
        return fut.get()

    def run_blocking(self, fn) -> Future:
        """Future of fn()'s value, for host-blocking work (e.g. a device
        readback). The deterministic sim runs it inline — virtual time does
        not advance and replay stays exact; RealEventLoop overrides this to
        a worker thread so the loop keeps serving while the host blocks
        (the reference's IThreadPool / onMainThread bridge, flow/flow.h)."""
        out = Future()
        try:
            out._set(fn())
        except BaseException as e:  # noqa: BLE001 — delivered to the awaiter
            out._set_error(e)
        return out

    def timeout(self, fut: Future, seconds: float) -> Future:
        """Future of fut's value, or error timed_out after `seconds`.

        Reference: flow/genericactors.actor.h timeoutError.
        """
        out = Future()

        def on_fut(f: Future):
            if out.is_ready():
                return
            if f.is_error():
                out._set_error(f._result)
            else:
                out._set(f._result)

        fut.add_callback(on_fut)
        self._schedule(
            seconds,
            TaskPriority.DefaultDelay,
            lambda: out._set_error(FDBError("timed_out")) if not out.is_ready() else None,
        )
        return out
