"""Workload library + spec runner: correctness invariants under fault cocktails.

Reference: fdbserver/workloads/workloads.h (:55-72 TestWorkload's
setup/start/check phases), fdbserver/workloads/Cycle.actor.cpp (:27-80 the
serializability ring), RandomClogging.actor.cpp, MachineAttrition.actor.cpp,
and the spec grammar of tests/fast/CycleTest.txt (a correctness workload runs
IN PARALLEL with fault workloads; at the end the cluster quiesces and check()
asserts the invariant). Swizzle-clogging (tests/slow/SwizzledCycleTest.txt,
documentation/sphinx/source/testing.rst): clog a whole set of links, then
unclog in reverse order — a rolling partial partition.

Every workload draws randomness ONLY from the forked DeterministicRandom it
is given, so a failing (seed, spec) pair replays identically.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from foundationdb_tpu.core.future import all_of
from foundationdb_tpu.core.sim import KillType
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.trace import TraceEvent


class Workload:
    """setup() -> start() (runs until stop_at) -> check() after quiesce."""

    name = "workload"

    def init(self, cluster, rng, stop_at: float):
        self.cluster = cluster
        self.rng = rng
        self.stop_at = stop_at

    async def setup(self, db):
        pass

    async def start(self, db):
        pass

    async def check(self, db):
        pass

    def _time_left(self) -> bool:
        return self.cluster.loop.now() < self.stop_at

    async def _commit_resolved(self, db, fn, marker, token):
        """Run fn+commit manually; resolve commit_unknown_result through the
        marker so the model only advances for transactions that landed."""
        for _ in range(200):
            tr = db.create_transaction()
            try:
                overlay = await fn(tr)
                await tr.commit()
                return overlay
            except FDBError as e:
                if e.name == "commit_unknown_result":
                    async def probe(t):
                        return await t.get(marker)
                    if await db.transact(probe, max_retries=500) == token:
                        return overlay
                    continue
                if e.name in ("not_committed", "transaction_too_old",
                              "transaction_throttled",
                              "future_version", "timed_out",
                              "proxies_changed", "cluster_not_fully_recovered",
                              "operation_failed", "wrong_shard_server",
                              "request_maybe_delivered", "broken_promise"):
                    await self.cluster.loop.delay(
                        0.2 * (0.5 + self.rng.random()))
                    continue
                raise
        return None


class CycleWorkload(Workload):
    """N keys form a ring by value; transactional 3-key rotations preserve
    the ring under ANY interleaving iff the system is serializable."""

    name = "Cycle"

    def __init__(self, n_keys: int = 5, prefix: bytes = b"cycle/"):
        self.n = n_keys
        self.prefix = prefix
        self.rotations = 0

    def key(self, i: int) -> bytes:
        return self.prefix + b"%02d" % i

    async def setup(self, db):
        async def fn(tr):
            for i in range(self.n):
                tr.set(self.key(i), b"%02d" % ((i + 1) % self.n))
        await db.transact(fn)

    async def start(self, db):
        while self._time_left():
            async def rotate(tr):
                r = self.rng.randint(0, self.n - 1)
                a = self.key(r)
                b_idx = int(await tr.get(a))
                b = self.key(b_idx)
                c_idx = int(await tr.get(b))
                ck = self.key(c_idx)
                d_idx = int(await tr.get(ck))
                tr.set(a, b"%02d" % c_idx)
                tr.set(b, b"%02d" % d_idx)
                tr.set(ck, b"%02d" % b_idx)
            await db.transact(rotate, max_retries=2000)
            self.rotations += 1
            await self.cluster.loop.delay(0.05 * self.rng.random())

    async def check(self, db):
        async def read_ring(tr):
            seen = set()
            i = 0
            for _ in range(self.n):
                seen.add(i)
                i = int(await tr.get(self.key(i)))
            return i, seen
        i, seen = await db.transact(read_ring, max_retries=1000)
        assert i == 0 and len(seen) == self.n, \
            f"ring broken after {self.rotations} rotations: {seen}"
        assert self.rotations > 0, "workload made no progress"


class RandomCloggingWorkload(Workload):
    """Randomly clog links between cluster processes (RandomClogging)."""

    name = "RandomClogging"

    def __init__(self, interval: float = 2.0, max_seconds: float = 2.5):
        self.interval = interval
        self.max_seconds = max_seconds

    async def start(self, db):
        procs = [p.address for p in self.cluster.worker_procs] + \
                [p.address for p in self.cluster.storage_worker_procs]
        while self._time_left():
            await self.cluster.loop.delay(self.interval * (0.5 + self.rng.random()))
            a = procs[self.rng.randint(0, len(procs) - 1)]
            b = procs[self.rng.randint(0, len(procs) - 1)]
            if a != b:
                self.cluster.net.clog_pair(a, b, self.max_seconds * self.rng.random())


class SwizzleCloggingWorkload(Workload):
    """Clog a random subset of processes' links one at a time, then unclog in
    reverse order ("swizzle", testing.rst) — catches recovery paths that only
    work when failures resolve in FIFO order."""

    name = "SwizzledClogging"

    def __init__(self, interval: float = 5.0):
        self.interval = interval

    async def start(self, db):
        loop = self.cluster.loop
        procs = [p.address for p in self.cluster.worker_procs]
        while self._time_left():
            await loop.delay(self.interval * (0.5 + self.rng.random()))
            subset = [a for a in procs if self.rng.coinflip(0.5)]
            self.rng.shuffle(subset)
            cloggged = []
            for a in subset:
                for b in procs:
                    if a != b:
                        self.cluster.net.clog_pair(a, b, 30.0)
                cloggged.append(a)
                await loop.delay(0.3 * self.rng.random())
            for a in reversed(cloggged):
                # unclog by re-clogging with 0 duration is not possible;
                # heal link-by-link via the clog map
                for b in procs:
                    self.cluster.net._clogged_until.pop((a, b), None)
                    self.cluster.net._clogged_until.pop((b, a), None)
                await loop.delay(0.3 * self.rng.random())


class AttritionWorkload(Workload):
    """Kill/reboot transaction-subsystem processes at random intervals
    (MachineAttrition). With replication > 1, storage workers get HARD
    KILLS too (stay down past the DD failure timeout, forcing redundancy
    healing to re-replicate their shards); single-replica storage only gets
    reboots (the data would otherwise be unrecoverable)."""

    name = "Attrition"

    def __init__(self, interval: float = 6.0):
        self.interval = interval

    async def start(self, db):
        loop = self.cluster.loop
        replicated = getattr(self.cluster.config, "n_replicas", 1) > 1
        while self._time_left():
            await loop.delay(self.interval * (0.5 + self.rng.random()))
            if self.rng.coinflip(0.3):
                victim = self.cluster.storage_worker_procs[
                    self.rng.randint(0, len(self.cluster.storage_worker_procs) - 1)]
                if replicated and self.rng.coinflip(0.5):
                    # permanent(ish) loss: down long enough that the DD
                    # declares the server failed and heals the teams; the
                    # eventual reboot returns it as a spare
                    TraceEvent("AttritionStorageKill", victim.address).log()
                    self.cluster.net.kill(victim.address, KillType.KillProcess)

                    async def reboot_much_later(addr=victim.address):
                        await loop.delay(
                            2.5 * KNOBS.DD_STORAGE_FAILURE_SECONDS
                            + 10.0 * self.rng.random())
                        self.cluster.net.reboot(addr)
                    loop.spawn(reboot_much_later(), name="attritionSReboot")
                    continue
                TraceEvent("AttritionReboot", victim.address).log()
                self.cluster.net.kill(victim.address, KillType.RebootProcess)
            else:
                victim = self.cluster.worker_procs[
                    self.rng.randint(0, len(self.cluster.worker_procs) - 1)]
                TraceEvent("AttritionKill", victim.address).log()
                # hard kill: the process stays DOWN for a while (capacity
                # genuinely lost, recovery must re-place its roles), then an
                # explicit reboot restores the worker
                self.cluster.net.kill(victim.address, KillType.KillProcess)

                async def reboot_later(addr=victim.address):
                    await loop.delay(2.0 + 4.0 * self.rng.random())
                    self.cluster.net.reboot(addr)
                loop.spawn(reboot_later(), name="attritionReboot")


async def quiet_database(c, db, max_wait: float = 120.0,
                         max_tlog_bytes: int = 100_000,
                         max_storage_lag: int = 2_000_000):
    """QuietDatabase (fdbserver/QuietDatabase.actor.cpp): checks may only
    run on a SETTLED cluster — every TLog queue drained below a threshold,
    every storage server's durability lag bounded, and data distribution
    idle (no in-flight relocation) — otherwise invariant checks race the
    pipeline's own catch-up work."""
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.server.interfaces import Token
    loop = c.loop
    deadline = loop.now() + max_wait
    client = db.process
    while loop.now() < deadline:
        cc = c.current_cc()
        if cc is None:
            await loop.delay(0.5)
            continue
        info = cc.dbinfo
        ok = not getattr(cc, "_dd_moving", False)
        worst_log = worst_lag = 0
        last_ep = info.log_epochs[-1] if info.log_epochs else None
        addrs = (list(last_ep.addrs) if last_ep else []) +                 [a for a, _t in info.storages]
        for addr in addrs:
            try:
                st = await loop.timeout(c.net.request(
                    client, Endpoint(addr, Token.QUEUE_STATS), None), 1.0)
                worst_log = max(worst_log, st.queue_bytes)
                worst_lag = max(worst_lag, st.lag_versions)
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
                ok = False
                break
        if ok and worst_log <= max_tlog_bytes \
                and worst_lag <= max_storage_lag:
            TraceEvent("QuietDatabaseDone", "spec") \
                .detail("TLogBytes", worst_log) \
                .detail("StorageLag", worst_lag).log()
            return
        await loop.delay(1.0)
    TraceEvent("QuietDatabaseTimeout", "spec", severity=30).log()


@dataclass
class SpecResult:
    seed: int
    rotations: int
    epochs: int
    elapsed: float


def run_spec(seed: int, workloads: list[Workload] | None = None,
             duration: float = 60.0, buggify: bool = True,
             max_time: float = 600_000.0, cluster_factory=None,
             **cluster_kw) -> SpecResult:
    """Boot a RecoverableCluster, run `workloads` in parallel for `duration`
    virtual seconds, quiesce (heal + wait for a recovered generation), then
    run every workload's check(). The whole run is a pure function of
    (seed, spec): the reference's `fdbserver -r simulation -f spec.txt`.

    `cluster_factory(cluster_seed) -> RecoverableCluster` overrides the
    default flat topology — the randomized harness (testing/simulated_cluster)
    uses it to boot whatever shape the seed drew, including two-region
    clusters built via RecoverableCluster.two_region().
    """
    from foundationdb_tpu.server.cluster import RecoverableCluster
    from foundationdb_tpu.utils.rng import DeterministicRandom

    rng = DeterministicRandom(seed)
    if buggify:
        KNOBS.buggify(rng.fork())
    if workloads is None:
        workloads = [CycleWorkload(), RandomCloggingWorkload(),
                     AttritionWorkload()]

    if cluster_factory is not None:
        c = cluster_factory(rng.randint(0, 1 << 30))
    else:
        cluster_kw.setdefault("n_workers", 5)
        cluster_kw.setdefault("n_proxies", 2)
        cluster_kw.setdefault("n_tlogs", 2)
        cluster_kw.setdefault("n_storage", 2)
        c = RecoverableCluster(seed=rng.randint(0, 1 << 30), **cluster_kw)
    db = c.database()

    async def spec():
        await db.refresh(max_wait=120.0)
        stop_at = c.loop.now() + duration
        for w in workloads:
            w.init(c, rng.fork(), stop_at)
        for w in workloads:
            await w.setup(db)
        await all_of([c.loop.spawn(w.start(db), name=w.name)
                      for w in workloads])
        # quiesce (QuietDatabase): heal every fault, then wait until a CC
        # reaches accepting_commits and transactions flow again
        c.net.heal()
        for p in c.cluster_procs():
            if not p.alive:
                c.net.reboot(p.address)
        for _ in range(600):
            if c.current_cc() is not None:
                try:
                    async def probe(tr):
                        await tr.get(b"\x00quiesce-probe")
                    await db.transact(probe, max_retries=50)
                    break
                except FDBError:
                    pass
            await c.loop.delay(0.5)
        await quiet_database(c, db)
        for w in workloads:
            await w.check(db)

    c.run(c.loop.spawn(spec()), max_time=max_time)
    cyc = next((w for w in workloads if isinstance(w, CycleWorkload)), None)
    cc = c.current_cc()
    return SpecResult(seed=seed,
                      rotations=cyc.rotations if cyc else 0,
                      epochs=cc.dbinfo.epoch if cc else -1,
                      elapsed=c.loop.now())


class ConsistencyCheckWorkload(Workload):
    """Compare every shard's replicas row-for-row at one version
    (fdbserver/workloads/ConsistencyCheck.actor.cpp): after the cluster
    quiesces, all team members must hold identical data."""

    name = "ConsistencyCheck"

    async def check(self, db):
        from foundationdb_tpu.core.sim import Endpoint
        from foundationdb_tpu.server.interfaces import (
            GetKeyValuesRequest, KeySelector, Token)
        await db.refresh()
        cc = self.cluster.current_cc()
        info = cc.dbinfo
        addr_of_tag = {tag: addr for addr, tag in info.storages}
        b = info.shard_boundaries
        shard_tags = info.teams()
        from foundationdb_tpu.utils.errors import FDBError

        async def read_replica(tag: int, lo, hi, version):
            req = GetKeyValuesRequest(
                begin=KeySelector.first_greater_or_equal(lo),
                end=KeySelector.first_greater_or_equal(hi),
                version=version)
            rows = []
            while True:
                # a reply-error here (replica rebooting, version aged out)
                # propagates to the per-shard retry loop below, which
                # re-reads the WHOLE shard at a fresh version — handling it
                # per-page would splice rows from two versions
                reply = await db.process.net.request(  # protolint: ignore[PROTO008]

                    db.process,
                    Endpoint(addr_of_tag[tag], Token.STORAGE_GET_KEY_VALUES),
                    req)
                rows.extend(reply.data)
                if not (reply.more and reply.data):
                    return rows
                req = GetKeyValuesRequest(
                    begin=KeySelector.first_greater_or_equal(
                        reply.data[-1][0] + b"\x00"),
                    end=KeySelector.first_greater_or_equal(hi),
                    version=version)

        for i, team in enumerate(shard_tags):
            lo = b[i]
            hi = b[i + 1] if i + 1 < len(b) else b"\xff" * 16
            # transient read errors (a replica still catching up after a
            # late reboot: future_version; dropped packets; a version aging
            # out mid-check) retry the whole shard at a FRESH version — only
            # a clean same-version comparison may vote
            for attempt in range(60):
                try:
                    tr = db.create_transaction()
                    version = await tr.get_read_version()
                    per_replica = [(tag, await read_replica(tag, lo, hi,
                                                            version))
                                   for tag in team]
                    break
                except FDBError as e:
                    if e.name == "operation_cancelled":
                        raise
                    await self.cluster.loop.delay(1.0)
            else:
                raise AssertionError(
                    f"shard {i}: replicas unreadable for the checker")
            first_tag, first_rows = per_replica[0]
            for tag, rows in per_replica[1:]:
                assert rows == first_rows, \
                    (f"shard {i}: replica tag {tag} diverges from tag "
                     f"{first_tag}: {len(rows)} vs {len(first_rows)} rows")


class ConflictRangeWorkload(Workload):
    """System-level RESOLVER ORACLE (fdbserver/workloads/ConflictRange.actor.cpp):
    transaction A performs 1-3 randomized range reads (random shapes,
    optionally LIMITED and/or REVERSE — the registered conflict range is then
    clipped to the window actually observed — optionally SNAPSHOT, which
    registers nothing); transaction B then commits a random plan of
    sets/clears/range-clears; A commits a write of its own. A's outcome is
    forced: not_committed iff B touched a window A actually registered,
    committed otherwise — snapshot reads are exempt, and keys beyond a
    limit-clipped window are exempt. Every verdict cross-checks the whole
    conflict pipeline — client conflict-range registration (including the
    clipping), proxy range splitting, and the device/sharded/oracle engine's
    decision — against an independent host model, which also validates every
    range read's row set."""

    name = "ConflictRange"

    def __init__(self, n_keys: int = 48, prefix: bytes = b"cr/"):
        self.n = n_keys
        self.prefix = prefix
        self.present: set[int] = set()
        self.checked = 0
        self.conflicts = 0
        self.snapshot_exempt = 0   # B touched a snapshot read: no conflict
        self.clip_exempt = 0       # B touched only beyond a clipped window
        self.clipped_reads = 0

    def key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    async def setup(self, db):
        async def fn(tr):
            for i in range(0, self.n, 2):
                tr.set(self.key(i), b"v%04d" % i)
        await db.transact(fn)
        self.present = set(range(0, self.n, 2))

    # -- draw helpers (all randomness from self.rng: replayable) --

    def _draw_reads(self, rng):
        """1-3 range-read shapes: (lo_i, hi_i, limit, reverse). A limit is
        only drawn strictly below the number of rows present, so the client
        is guaranteed to clip its registered conflict range."""
        reads = []
        for _ in range(rng.randint(1, 3)):
            lo_i = rng.randint(0, self.n - 2)
            hi_i = rng.randint(lo_i + 1, self.n)
            avail = sum(1 for i in self.present if lo_i <= i < hi_i)
            limit = 0
            if avail >= 2 and rng.coinflip(0.4):
                limit = rng.randint(1, avail - 1)
            reads.append((lo_i, hi_i, limit, rng.coinflip(0.3)))
        return reads

    def _draw_plan(self, rng):
        """B's mutation plan, fixed up front so transact() retries replay
        identical (idempotent) mutations."""
        plan = []
        for _ in range(rng.randint(1, 4)):
            r = rng.random()
            if r < 0.5:
                plan.append(("set", rng.randint(0, self.n - 1),
                             rng.randint(0, 1 << 30)))
            elif r < 0.8:
                plan.append(("clear", rng.randint(0, self.n - 1), 0))
            else:
                i = rng.randint(0, self.n - 2)
                plan.append(("clear_range", i, rng.randint(i + 1, self.n)))
        return plan

    def _apply_plan(self, plan):
        for kind, a, b in plan:
            if kind == "set":
                self.present.add(a)
            elif kind == "clear":
                self.present.discard(a)
            else:
                for i in [i for i in self.present if a <= i < b]:
                    self.present.discard(i)

    def _plan_touches(self, plan, lo: bytes, hi: bytes) -> bool:
        for kind, a, b in plan:
            if kind == "clear_range":
                if self.key(a) < hi and lo < self.key(b):
                    return True
            elif lo <= self.key(a) < hi:
                return True
        return False

    def _registered_window(self, lo, hi, limit, reverse, rows):
        """Mirror of Transaction.get_range's conflict registration: a
        satisfied limit clips the window to the span actually observed."""
        if limit and len(rows) == limit:
            self.clipped_reads += 1
            if reverse:
                return (rows[-1][0], hi)
            return (lo, rows[-1][0] + b"\x00")
        return (lo, hi)

    async def _resync(self, db):
        """B's fate unknown (retry budget exhausted): reload the key model
        from the database before judging any further verdicts."""
        async def rd(tr):
            return await tr.get_range(self.key(0), self.key(self.n),
                                      limit=self.n + 1)
        rows = await db.transact(rd, max_retries=500)
        self.present = {int(k[len(self.prefix):]) for k, _v in rows}

    async def start(self, db):
        it = 0
        while self._time_left():
            it += 1
            rng = self.rng
            snapshot = rng.coinflip(0.2)
            reads = self._draw_reads(rng)
            plan = self._draw_plan(rng)
            marker = self.prefix + b"__marker__"
            token = b"t%08d" % it
            trA = db.create_transaction()
            windows = []
            b_touched_any_read = False
            try:
                await trA.get_read_version()
                for lo_i, hi_i, limit, reverse in reads:
                    lo, hi = self.key(lo_i), self.key(hi_i)
                    rows = await trA.get_range(lo, hi, limit=limit,
                                               reverse=reverse,
                                               snapshot=snapshot)
                    want = [self.key(i) for i in sorted(self.present)
                            if lo_i <= i < hi_i]
                    if reverse:
                        want = want[::-1]
                    if limit:
                        want = want[:limit]
                    got = [k for k, _v in rows]
                    assert got == want, \
                        (f"getRange[{lo_i},{hi_i}) limit={limit} "
                         f"reverse={reverse} diverges from model: "
                         f"{got} vs {want}")
                    if self._plan_touches(plan, lo, hi):
                        b_touched_any_read = True
                    windows.append(self._registered_window(
                        lo, hi, limit, reverse, rows))
            except FDBError:
                continue  # clog/recovery noise before B ran: no verdict
            # B commits its plan (idempotent; transact retries replay it)
            async def bfn(tr, plan=plan):
                for kind, a, b, in plan:
                    if kind == "set":
                        tr.set(self.key(a), b"b%08d" % b)
                    elif kind == "clear":
                        tr.clear(self.key(a))
                    else:
                        tr.clear_range(self.key(a), self.key(b))
            try:
                await db.transact(bfn, max_retries=500)
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
                await self._resync(db)
                continue
            self._apply_plan(plan)
            # expectation: conflict iff B touched a REGISTERED window
            touches = (not snapshot) and any(
                self._plan_touches(plan, wl, wh) for wl, wh in windows)
            trA.set(marker, token)
            try:
                await trA.commit()
                committed = True
            except FDBError as e:
                if e.name == "not_committed":
                    committed = False
                elif e.name == "commit_unknown_result":
                    async def probe(tr):
                        return await tr.get(marker)
                    committed = (await db.transact(probe, max_retries=500)
                                 == token)
                else:
                    continue  # infrastructure noise: no verdict
            assert committed == (not touches), \
                (f"resolver verdict wrong: B touched A's registered "
                 f"range={touches}, A committed={committed} (iter {it}, "
                 f"snapshot={snapshot}, reads {reads}, plan {plan})")
            self.checked += 1
            self.conflicts += 0 if committed else 1
            if committed and snapshot and b_touched_any_read:
                self.snapshot_exempt += 1
            if committed and not snapshot and b_touched_any_read:
                # touched a read but no registered window: clip exemption
                self.clip_exempt += 1

    async def check(self, db):
        assert self.checked > 0, "no conflict-range verdicts were checked"
        assert self.conflicts > 0, \
            "workload never produced a conflict (coverage bug)"


class ApiCorrectnessWorkload(Workload):
    """Model-based API conformance (workloads/ApiCorrectness.actor.cpp):
    a single writer drives random set/clear/clear_range/atomic-add ops plus
    get/get_range/get_key reads, mirroring every committed mutation into a
    host dict; every read must match the model exactly. Composable with
    clogging: commit_unknown_result is resolved through a per-transaction
    marker before the model advances."""

    name = "ApiCorrectness"

    def __init__(self, n_keys: int = 60, prefix: bytes = b"api/"):
        self.n = n_keys
        self.prefix = prefix
        self.model: dict[bytes, bytes] = {}
        self.txns = 0

    def key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    def _apply(self, model, ops):
        from foundationdb_tpu.utils.types import MutationType, apply_atomic_op
        for op in ops:
            kind = op[0]
            if kind == "set":
                model[op[1]] = op[2]
            elif kind == "clear":
                model.pop(op[1], None)
            elif kind == "clear_range":
                for k in [k for k in model if op[1] <= k < op[2]]:
                    del model[k]
            elif kind == "add":
                model[op[1]] = apply_atomic_op(
                    MutationType.ADD_VALUE, model.get(op[1]), op[2])

    async def start(self, db):
        from foundationdb_tpu.server.interfaces import KeySelector
        from foundationdb_tpu.utils.types import MutationType
        it = 0
        while self._time_left():
            it += 1
            rng = self.rng
            ops = []
            for _ in range(rng.randint(1, 6)):
                r = rng.random()
                k = self.key(rng.randint(0, self.n - 1))
                if r < 0.45:
                    ops.append(("set", k, b"v%06d" % rng.randint(0, 1 << 20)))
                elif r < 0.6:
                    ops.append(("clear", k))
                elif r < 0.75:
                    i = rng.randint(0, self.n - 2)
                    j = rng.randint(i + 1, self.n)
                    ops.append(("clear_range", self.key(i), self.key(j)))
                else:
                    ops.append(("add", k,
                                rng.randint(1, 1000).to_bytes(8, "little")))
            marker = self.prefix + b"__marker__"
            token = b"t%08d" % it

            async def fn(tr, ops=ops, token=token):
                overlay = dict(self.model)
                self._apply(overlay, ops)
                for op in ops:
                    if op[0] == "set":
                        tr.set(op[1], op[2])
                    elif op[0] == "clear":
                        tr.clear(op[1])
                    elif op[0] == "clear_range":
                        tr.clear_range(op[1], op[2])
                    else:
                        tr.atomic_op(MutationType.ADD_VALUE, op[1], op[2])
                # reads through the RYW overlay must equal the model
                for _ in range(2):
                    k = self.key(self.rng.randint(0, self.n - 1))
                    got = await tr.get(k)
                    want = overlay.get(k)
                    assert got == want, f"get({k}) = {got}, model {want}"
                i = self.rng.randint(0, self.n - 2)
                j = self.rng.randint(i + 1, self.n)
                rows = await tr.get_range(self.key(i), self.key(j))
                want_rows = sorted((k, v) for k, v in overlay.items()
                                   if self.key(i) <= k < self.key(j)
                                   and not k.endswith(b"__marker__"))
                got_rows = [(k, v) for k, v in rows
                            if not k.endswith(b"__marker__")]
                assert got_rows == want_rows, \
                    f"get_range[{i},{j}) diverges from model"
                # selector read: first key at-or-after a random point
                k = self.key(self.rng.randint(0, self.n - 1))
                got_k = await tr.get_key(KeySelector.first_greater_or_equal(k))
                cand = sorted(kk for kk in overlay if kk >= k)
                if cand and cand[0] < self.prefix + b"\xff":
                    assert got_k == cand[0], \
                        f"get_key(>={k}) = {got_k}, model {cand[0]}"
                tr.set(marker, token)
                return overlay

            try:
                overlay = await self._commit_resolved(db, fn, marker, token)
            except FDBError:
                continue  # infrastructure noise; model unchanged
            if overlay is not None:
                overlay.pop(marker, None)
                self.model = overlay
                self.txns += 1

    async def check(self, db):
        assert self.txns > 0, "no API transactions committed"
        async def read_all(tr):
            return await tr.get_range(self.prefix, self.prefix + b"\xff",
                                      limit=10_000)
        rows = await db.transact(read_all, max_retries=1000)
        got = {k: v for k, v in rows if not k.endswith(b"__marker__")}
        want = dict(self.model)
        assert got == want, \
            (f"final state diverges from model: {len(got)} vs {len(want)} "
             f"rows after {self.txns} txns")


class WriteDuringReadWorkload(Workload):
    """RYW-overlay conformance under interleaved reads and writes INSIDE one
    transaction (workloads/WriteDuringRead.actor.cpp): after every mutation,
    plain and snapshot reads must both see the overlay state (snapshot reads
    skip conflict registration, not the overlay); aborted transactions must
    leave no trace."""

    name = "WriteDuringRead"

    def __init__(self, n_keys: int = 30, prefix: bytes = b"wdr/"):
        self.n = n_keys
        self.prefix = prefix
        self.model: dict[bytes, bytes] = {}
        self.txns = 0

    def key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    async def start(self, db):
        from foundationdb_tpu.utils.types import MutationType, apply_atomic_op
        it = 0
        while self._time_left():
            it += 1
            rng = self.rng
            commit_it = rng.coinflip(0.6)
            marker = self.prefix + b"__marker__"
            token = b"t%08d" % it
            steps = rng.randint(2, 8)
            plan = [rng.randint(0, 1 << 30) for _ in range(steps)]

            async def fn(tr, plan=plan, token=token):
                overlay = dict(self.model)
                for step in plan:
                    srng = step
                    k = self.key(srng % self.n)
                    kind = (srng >> 8) % 4
                    if kind == 0:
                        v = b"w%08d" % (srng % 10_000_019)
                        tr.set(k, v)
                        overlay[k] = v
                    elif kind == 1:
                        tr.clear(k)
                        overlay.pop(k, None)
                    elif kind == 2:
                        d = (1 + srng % 999).to_bytes(8, "little")
                        tr.atomic_op(MutationType.ADD_VALUE, k, d)
                        overlay[k] = apply_atomic_op(
                            MutationType.ADD_VALUE, overlay.get(k), d)
                    # read-after-write, both plain and snapshot
                    got = await tr.get(k)
                    assert got == overlay.get(k), \
                        f"RYW get({k}) = {got}, overlay {overlay.get(k)}"
                    got_s = await tr.get(k, snapshot=True)
                    assert got_s == overlay.get(k), \
                        f"snapshot get({k}) = {got_s}, overlay {overlay.get(k)}"
                rows = await tr.get_range(self.prefix, self.prefix + b"\xf0")
                want = sorted((kk, vv) for kk, vv in overlay.items()
                              if not kk.endswith(b"__marker__"))
                got_rows = [(kk, vv) for kk, vv in rows
                            if not kk.endswith(b"__marker__")]
                assert got_rows == want, "RYW range diverges from overlay"
                tr.set(marker, token)
                return overlay

            if not commit_it:
                # run and abandon: an uncommitted transaction's writes must
                # never become visible
                tr = db.create_transaction()
                try:
                    await fn(tr)
                except FDBError:
                    pass
                tr.reset()
                continue
            try:
                overlay = await self._commit_resolved(db, fn, marker, token)
            except FDBError:
                continue
            if overlay is not None:
                overlay.pop(marker, None)
                self.model = overlay
                self.txns += 1

    async def check(self, db):
        assert self.txns > 0, "no write-during-read transactions committed"
        async def read_all(tr):
            return await tr.get_range(self.prefix, self.prefix + b"\xff",
                                      limit=10_000)
        rows = await db.transact(read_all, max_retries=1000)
        got = {k: v for k, v in rows if not k.endswith(b"__marker__")}
        assert got == dict(self.model), "abandoned writes leaked or state lost"


class AtomicOpsWorkload(Workload):
    """Atomic-op consistency under retries and faults
    (workloads/AtomicOps.actor.cpp): every transaction atomically ADDs a
    delta to one of K counters AND writes a VERSIONSTAMPED log row carrying
    the same delta — the two ride one commit, so even a duplicated
    commit_unknown_result retry keeps the invariant sum(logs) == counter."""

    name = "AtomicOps"

    def __init__(self, n_counters: int = 4, prefix: bytes = b"aops/"):
        self.k = n_counters
        self.prefix = prefix
        self.attempted = 0

    async def start(self, db):
        from foundationdb_tpu.utils.types import MutationType
        while self._time_left():
            rng = self.rng
            c = rng.randint(0, self.k - 1)
            d = rng.randint(1, 1000)

            async def fn(tr, c=c, d=d):
                tr.atomic_op(MutationType.ADD_VALUE,
                             self.prefix + b"sum/%02d" % c,
                             d.to_bytes(8, "little"))
                # log key gets the commit versionstamp: EVERY application
                # (including a duplicated retry) produces its own row
                body = self.prefix + b"log/%02d/" % c + b"\x00" * 10
                key = body + (len(body) - 10).to_bytes(4, "little")
                tr.atomic_op(MutationType.SET_VERSIONSTAMPED_KEY, key,
                             d.to_bytes(8, "little"))
            try:
                await db.transact(fn, max_retries=1000)
                self.attempted += 1
            except FDBError:
                pass
            await self.cluster.loop.delay(0.02 * self.rng.random())

    async def check(self, db):
        assert self.attempted > 0, "no atomic transactions ran"
        async def read_all(tr):
            sums = {}
            logs = {}
            for c in range(self.k):
                v = await tr.get(self.prefix + b"sum/%02d" % c)
                sums[c] = int.from_bytes(v or b"", "little")
                rows = await tr.get_range(self.prefix + b"log/%02d/" % c,
                                          self.prefix + b"log/%02d0" % c,
                                          limit=100_000)
                logs[c] = sum(int.from_bytes(v, "little") for _k, v in rows)
            return sums, logs
        sums, logs = await db.transact(read_all, max_retries=1000)
        for c in range(self.k):
            assert sums[c] == logs[c], \
                (f"counter {c}: atomic sum {sums[c]} != logged sum "
                 f"{logs[c]} — an atomic op was lost or half-applied")
        assert sum(sums.values()) > 0, "no atomic op landed"


class RandomMoveKeysWorkload(Workload):
    """Drive shard splits/moves/merges WHILE data workloads run
    (workloads/RandomMoveKeys.actor.cpp): correctness must survive layouts
    changing under live traffic; the composed Cycle + ConsistencyCheck
    workloads assert it."""

    name = "RandomMoveKeys"

    def __init__(self, interval: float = 3.0):
        self.interval = interval
        self.moves = 0

    async def start(self, db):
        loop = self.cluster.loop
        while self._time_left():
            await loop.delay(self.interval * (0.5 + self.rng.random()))
            cc = self.cluster.current_cc()
            if cc is None or not getattr(cc, "_initial_meta_done", False):
                continue
            info = cc.dbinfo
            b = list(info.shard_boundaries)
            teams = [list(t) for t in info.teams()]
            try:
                if len(b) > 1 and self.rng.coinflip(0.35):
                    # merge a random same-team boundary if one exists
                    cands = [i for i in range(len(b) - 1)
                             if teams[i] == teams[i + 1]]
                    if not cands:
                        continue
                    i = cands[self.rng.randint(0, len(cands) - 1)]
                    await cc._merge(i)
                else:
                    i = self.rng.randint(0, len(b) - 1)
                    lo = b[i]
                    hi = b[i + 1] if i + 1 < len(b) else None
                    async def sample(tr):
                        return await tr.get_range(
                            lo or b"\x00", hi or b"\xf0", limit=50)
                    rows = await db.transact(sample, max_retries=50)
                    if len(rows) < 2:
                        continue
                    split = rows[len(rows) // 2][0]
                    if split <= lo or (hi is not None and split >= hi):
                        continue
                    await cc._split_and_move(i, split)
                self.moves += 1
            except (FDBError, AssertionError):
                continue  # moves legitimately race recoveries/other moves

    async def check(self, db):
        assert self.moves > 0, "no shard was ever moved"


class IncrementWorkload(Workload):
    """Atomic counter increments with exact accounting
    (workloads/Increment.actor.cpp): every CONFIRMED transaction added
    exactly 1 to one of K counters; a per-transaction marker resolves
    commit_unknown_result, so at check() the counter total equals the
    confirmed count exactly — lost or doubled increments both fail."""

    name = "Increment"

    def __init__(self, n_counters: int = 5, prefix: bytes = b"incr/"):
        self.k = n_counters
        self.prefix = prefix
        self.confirmed = 0

    async def start(self, db):
        from foundationdb_tpu.utils.types import MutationType
        it = 0
        while self._time_left():
            it += 1
            c = self.rng.randint(0, self.k - 1)
            marker = self.prefix + b"__m__"
            token = b"t%08d" % it

            async def fn(tr, c=c, token=token):
                tr.atomic_op(MutationType.ADD_VALUE,
                             self.prefix + b"c%02d" % c,
                             (1).to_bytes(8, "little"))
                tr.set(marker, token)
                return True
            try:
                if await self._commit_resolved(db, fn, marker, token):
                    self.confirmed += 1
            except FDBError:
                pass
            await self.cluster.loop.delay(0.01 * self.rng.random())

    async def check(self, db):
        assert self.confirmed > 0
        async def rd(tr):
            total = 0
            for c in range(self.k):
                v = await tr.get(self.prefix + b"c%02d" % c)
                total += int.from_bytes(v or b"", "little")
            return total
        total = await db.transact(rd, max_retries=1000)
        assert total == self.confirmed, \
            (f"increment accounting broken: counters sum {total}, "
             f"confirmed {self.confirmed}")


class SelectorCorrectnessWorkload(Workload):
    """Key-selector resolution vs a host model
    (workloads/SelectorCorrectness.actor.cpp): a FIXED key set, then random
    (or_equal, offset) selectors resolved by the database must match the
    model's walk over the sorted keys, including selectors inside
    uncommitted-write overlays."""

    name = "SelectorCorrectness"

    def __init__(self, n_keys: int = 20, prefix: bytes = b"sel/"):
        self.n = n_keys
        self.prefix = prefix
        self.checked = 0

    def key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    async def setup(self, db):
        async def fn(tr):
            for i in range(0, self.n, 2):  # only even keys exist
                tr.set(self.key(i), b"v")
        await db.transact(fn)

    def _model_resolve(self, keys, base, or_equal, offset):
        """The selector contract: start at the first key > base (or >= if
        not or_equal... the reference defines or_equal on the BASE), then
        move offset-1 forward / -offset back (workloads SelectorCorrectness
        uses the same arithmetic)."""
        import bisect
        if offset >= 1:
            start = bisect.bisect_right(keys, base) if or_equal \
                else bisect.bisect_left(keys, base)
            i = start + (offset - 1)
            if i < len(keys):
                return keys[i]
            return b"<end>"
        start = bisect.bisect_right(keys, base) if or_equal \
            else bisect.bisect_left(keys, base)
        i = start - (1 - offset)
        if i >= 0:
            return keys[i]
        return b"<begin>"

    async def start(self, db):
        from foundationdb_tpu.server.interfaces import KeySelector
        keys = [self.key(i) for i in range(0, self.n, 2)]
        while self._time_left():
            base = self.key(self.rng.randint(0, self.n - 1))
            or_equal = self.rng.coinflip(0.5)
            offset = self.rng.randint(-2, 3)
            if offset == 0:
                offset = 1

            async def fn(tr, base=base, or_equal=or_equal, offset=offset):
                got = await tr.get_key(KeySelector(key=base,
                                                  or_equal=or_equal,
                                                  offset=offset))
                if not got.startswith(self.prefix):
                    got = b"<end>" if got > self.prefix else b"<begin>"
                want = self._model_resolve(keys, base, or_equal, offset)
                assert got == want, \
                    (f"selector({base}, or_equal={or_equal}, "
                     f"offset={offset}) = {got}, model {want}")
            try:
                tr = db.create_transaction()
                await fn(tr)
                tr.reset()
                self.checked += 1
            except FDBError:
                pass
            await self.cluster.loop.delay(0.01 * self.rng.random())

    async def check(self, db):
        assert self.checked > 10, f"only {self.checked} selectors checked"


class WatchesWorkload(Workload):
    """Watch semantics (workloads/Watches.actor.cpp): a watch on a key
    resolves when (and only when) the value changes; a watch armed on the
    CURRENT value does not fire spuriously."""

    name = "Watches"

    def __init__(self, prefix: bytes = b"watch/"):
        self.prefix = prefix
        self.fired = 0

    async def start(self, db):
        loop = self.cluster.loop
        it = 0
        while self._time_left():
            it += 1
            k = self.prefix + b"%02d" % self.rng.randint(0, 4)
            new_val = b"w%06d" % it

            # arm the watch (watch() registers at current value)
            tr = db.create_transaction()
            try:
                fut = await tr.watch(k)
            except FDBError:
                await loop.delay(0.2)
                continue

            async def write(tr2, k=k, new_val=new_val):
                tr2.set(k, new_val)
            try:
                await db.transact(write, max_retries=500)
            except FDBError:
                continue
            try:
                await loop.timeout(fut, 15.0)
                self.fired += 1
            except FDBError:
                pass  # watch lost to a recovery: the client re-arms
            await loop.delay(0.05 * self.rng.random())

    async def check(self, db):
        assert self.fired > 3, f"only {self.fired} watches fired"


class VersionStampWorkload(Workload):
    """Versionstamped keys (workloads/VersionStamp.actor.cpp): stamped keys
    materialize with the COMMIT version big-endian in the placeholder, so
    they sort in commit order and decode back to the version the commit
    reported."""

    name = "VersionStamp"

    def __init__(self, prefix: bytes = b"vs/"):
        self.prefix = prefix
        self.stamps: list[tuple[int, bytes]] = []  # (committed_version, tag)

    async def start(self, db):
        from foundationdb_tpu.utils.types import MutationType
        it = 0
        while self._time_left():
            it += 1
            tag = b"%06d" % it
            body = self.prefix + b"\x00" * 10
            key = body + (len(self.prefix)).to_bytes(4, "little")
            tr = db.create_transaction()
            try:
                tr.atomic_op(MutationType.SET_VERSIONSTAMPED_KEY, key, tag)
                await tr.commit()
                self.stamps.append((tr.committed_version, tag))
            except FDBError:
                pass
            await self.cluster.loop.delay(0.02 * self.rng.random())

    async def check(self, db):
        assert len(self.stamps) > 5
        async def rd(tr):
            return await tr.get_range(self.prefix, self.prefix + b"\xff",
                                      limit=100_000)
        rows = await db.transact(rd, max_retries=1000)
        by_tag = {}
        for k, v in rows:
            stamp = k[len(self.prefix):]
            version = int.from_bytes(stamp[:8], "big")
            by_tag.setdefault(v, []).append(version)
        versions_in_key_order = [
            int.from_bytes(k[len(self.prefix):][:8], "big") for k, _v in rows]
        assert versions_in_key_order == sorted(versions_in_key_order), \
            "stamped keys not in commit order"
        for committed, tag in self.stamps:
            assert tag in by_tag, f"stamped row for {tag} missing"
            assert committed in by_tag[tag], \
                (f"stamp for {tag}: committed_version {committed} not in "
                 f"{by_tag[tag]} (stamp != reported commit version)")
