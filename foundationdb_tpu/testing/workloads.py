"""Workload library + spec runner: correctness invariants under fault cocktails.

Reference: fdbserver/workloads/workloads.h (:55-72 TestWorkload's
setup/start/check phases), fdbserver/workloads/Cycle.actor.cpp (:27-80 the
serializability ring), RandomClogging.actor.cpp, MachineAttrition.actor.cpp,
and the spec grammar of tests/fast/CycleTest.txt (a correctness workload runs
IN PARALLEL with fault workloads; at the end the cluster quiesces and check()
asserts the invariant). Swizzle-clogging (tests/slow/SwizzledCycleTest.txt,
documentation/sphinx/source/testing.rst): clog a whole set of links, then
unclog in reverse order — a rolling partial partition.

Every workload draws randomness ONLY from the forked DeterministicRandom it
is given, so a failing (seed, spec) pair replays identically.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from foundationdb_tpu.core.future import all_of
from foundationdb_tpu.core.sim import KillType
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.trace import TraceEvent


class Workload:
    """setup() -> start() (runs until stop_at) -> check() after quiesce."""

    name = "workload"

    def init(self, cluster, rng, stop_at: float):
        self.cluster = cluster
        self.rng = rng
        self.stop_at = stop_at

    async def setup(self, db):
        pass

    async def start(self, db):
        pass

    async def check(self, db):
        pass

    def _time_left(self) -> bool:
        return self.cluster.loop.now() < self.stop_at


class CycleWorkload(Workload):
    """N keys form a ring by value; transactional 3-key rotations preserve
    the ring under ANY interleaving iff the system is serializable."""

    name = "Cycle"

    def __init__(self, n_keys: int = 5, prefix: bytes = b"cycle/"):
        self.n = n_keys
        self.prefix = prefix
        self.rotations = 0

    def key(self, i: int) -> bytes:
        return self.prefix + b"%02d" % i

    async def setup(self, db):
        async def fn(tr):
            for i in range(self.n):
                tr.set(self.key(i), b"%02d" % ((i + 1) % self.n))
        await db.transact(fn)

    async def start(self, db):
        while self._time_left():
            async def rotate(tr):
                r = self.rng.randint(0, self.n - 1)
                a = self.key(r)
                b_idx = int(await tr.get(a))
                b = self.key(b_idx)
                c_idx = int(await tr.get(b))
                ck = self.key(c_idx)
                d_idx = int(await tr.get(ck))
                tr.set(a, b"%02d" % c_idx)
                tr.set(b, b"%02d" % d_idx)
                tr.set(ck, b"%02d" % b_idx)
            await db.transact(rotate, max_retries=2000)
            self.rotations += 1
            await self.cluster.loop.delay(0.05 * self.rng.random())

    async def check(self, db):
        async def read_ring(tr):
            seen = set()
            i = 0
            for _ in range(self.n):
                seen.add(i)
                i = int(await tr.get(self.key(i)))
            return i, seen
        i, seen = await db.transact(read_ring, max_retries=1000)
        assert i == 0 and len(seen) == self.n, \
            f"ring broken after {self.rotations} rotations: {seen}"
        assert self.rotations > 0, "workload made no progress"


class RandomCloggingWorkload(Workload):
    """Randomly clog links between cluster processes (RandomClogging)."""

    name = "RandomClogging"

    def __init__(self, interval: float = 2.0, max_seconds: float = 2.5):
        self.interval = interval
        self.max_seconds = max_seconds

    async def start(self, db):
        procs = [p.address for p in self.cluster.worker_procs] + \
                [p.address for p in self.cluster.storage_worker_procs]
        while self._time_left():
            await self.cluster.loop.delay(self.interval * (0.5 + self.rng.random()))
            a = procs[self.rng.randint(0, len(procs) - 1)]
            b = procs[self.rng.randint(0, len(procs) - 1)]
            if a != b:
                self.cluster.net.clog_pair(a, b, self.max_seconds * self.rng.random())


class SwizzleCloggingWorkload(Workload):
    """Clog a random subset of processes' links one at a time, then unclog in
    reverse order ("swizzle", testing.rst) — catches recovery paths that only
    work when failures resolve in FIFO order."""

    name = "SwizzledClogging"

    def __init__(self, interval: float = 5.0):
        self.interval = interval

    async def start(self, db):
        loop = self.cluster.loop
        procs = [p.address for p in self.cluster.worker_procs]
        while self._time_left():
            await loop.delay(self.interval * (0.5 + self.rng.random()))
            subset = [a for a in procs if self.rng.coinflip(0.5)]
            self.rng.shuffle(subset)
            cloggged = []
            for a in subset:
                for b in procs:
                    if a != b:
                        self.cluster.net.clog_pair(a, b, 30.0)
                cloggged.append(a)
                await loop.delay(0.3 * self.rng.random())
            for a in reversed(cloggged):
                # unclog by re-clogging with 0 duration is not possible;
                # heal link-by-link via the clog map
                for b in procs:
                    self.cluster.net._clogged_until.pop((a, b), None)
                    self.cluster.net._clogged_until.pop((b, a), None)
                await loop.delay(0.3 * self.rng.random())


class AttritionWorkload(Workload):
    """Kill/reboot transaction-subsystem processes at random intervals
    (MachineAttrition). With replication > 1, storage workers get HARD
    KILLS too (stay down past the DD failure timeout, forcing redundancy
    healing to re-replicate their shards); single-replica storage only gets
    reboots (the data would otherwise be unrecoverable)."""

    name = "Attrition"

    def __init__(self, interval: float = 6.0):
        self.interval = interval

    async def start(self, db):
        loop = self.cluster.loop
        replicated = getattr(self.cluster.config, "n_replicas", 1) > 1
        while self._time_left():
            await loop.delay(self.interval * (0.5 + self.rng.random()))
            if self.rng.coinflip(0.3):
                victim = self.cluster.storage_worker_procs[
                    self.rng.randint(0, len(self.cluster.storage_worker_procs) - 1)]
                if replicated and self.rng.coinflip(0.5):
                    # permanent(ish) loss: down long enough that the DD
                    # declares the server failed and heals the teams; the
                    # eventual reboot returns it as a spare
                    TraceEvent("AttritionStorageKill", victim.address).log()
                    self.cluster.net.kill(victim.address, KillType.KillProcess)

                    async def reboot_much_later(addr=victim.address):
                        await loop.delay(
                            2.5 * KNOBS.DD_STORAGE_FAILURE_SECONDS
                            + 10.0 * self.rng.random())
                        self.cluster.net.reboot(addr)
                    loop.spawn(reboot_much_later(), name="attritionSReboot")
                    continue
                TraceEvent("AttritionReboot", victim.address).log()
                self.cluster.net.kill(victim.address, KillType.RebootProcess)
            else:
                victim = self.cluster.worker_procs[
                    self.rng.randint(0, len(self.cluster.worker_procs) - 1)]
                TraceEvent("AttritionKill", victim.address).log()
                # hard kill: the process stays DOWN for a while (capacity
                # genuinely lost, recovery must re-place its roles), then an
                # explicit reboot restores the worker
                self.cluster.net.kill(victim.address, KillType.KillProcess)

                async def reboot_later(addr=victim.address):
                    await loop.delay(2.0 + 4.0 * self.rng.random())
                    self.cluster.net.reboot(addr)
                loop.spawn(reboot_later(), name="attritionReboot")


@dataclass
class SpecResult:
    seed: int
    rotations: int
    epochs: int
    elapsed: float


def run_spec(seed: int, workloads: list[Workload] | None = None,
             duration: float = 60.0, buggify: bool = True,
             max_time: float = 600_000.0, **cluster_kw) -> SpecResult:
    """Boot a RecoverableCluster, run `workloads` in parallel for `duration`
    virtual seconds, quiesce (heal + wait for a recovered generation), then
    run every workload's check(). The whole run is a pure function of
    (seed, spec): the reference's `fdbserver -r simulation -f spec.txt`.
    """
    from foundationdb_tpu.server.cluster import RecoverableCluster
    from foundationdb_tpu.utils.rng import DeterministicRandom

    rng = DeterministicRandom(seed)
    if buggify:
        KNOBS.buggify(rng.fork())
    if workloads is None:
        workloads = [CycleWorkload(), RandomCloggingWorkload(),
                     AttritionWorkload()]

    cluster_kw.setdefault("n_workers", 5)
    cluster_kw.setdefault("n_proxies", 2)
    cluster_kw.setdefault("n_tlogs", 2)
    cluster_kw.setdefault("n_storage", 2)
    c = RecoverableCluster(seed=rng.randint(0, 1 << 30), **cluster_kw)
    db = c.database()

    async def spec():
        await db.refresh(max_wait=120.0)
        stop_at = c.loop.now() + duration
        for w in workloads:
            w.init(c, rng.fork(), stop_at)
        for w in workloads:
            await w.setup(db)
        await all_of([c.loop.spawn(w.start(db), name=w.name)
                      for w in workloads])
        # quiesce (QuietDatabase): heal every fault, then wait until a CC
        # reaches accepting_commits and transactions flow again
        c.net.heal()
        for p in c.worker_procs + c.storage_worker_procs + c.coord_procs:
            if not p.alive:
                c.net.reboot(p.address)
        for _ in range(600):
            if c.current_cc() is not None:
                try:
                    async def probe(tr):
                        await tr.get(b"\x00quiesce-probe")
                    await db.transact(probe, max_retries=50)
                    break
                except FDBError:
                    pass
            await c.loop.delay(0.5)
        for w in workloads:
            await w.check(db)

    c.run(c.loop.spawn(spec()), max_time=max_time)
    cyc = next((w for w in workloads if isinstance(w, CycleWorkload)), None)
    cc = c.current_cc()
    return SpecResult(seed=seed,
                      rotations=cyc.rotations if cyc else 0,
                      epochs=cc.dbinfo.epoch if cc else -1,
                      elapsed=c.loop.now())


class ConsistencyCheckWorkload(Workload):
    """Compare every shard's replicas row-for-row at one version
    (fdbserver/workloads/ConsistencyCheck.actor.cpp): after the cluster
    quiesces, all team members must hold identical data."""

    name = "ConsistencyCheck"

    async def check(self, db):
        from foundationdb_tpu.core.sim import Endpoint
        from foundationdb_tpu.server.interfaces import (
            GetKeyValuesRequest, KeySelector, Token)
        await db.refresh()
        cc = self.cluster.current_cc()
        info = cc.dbinfo
        addr_of_tag = {tag: addr for addr, tag in info.storages}
        b = info.shard_boundaries
        shard_tags = info.teams()
        from foundationdb_tpu.utils.errors import FDBError

        async def read_replica(tag: int, lo, hi, version):
            req = GetKeyValuesRequest(
                begin=KeySelector.first_greater_or_equal(lo),
                end=KeySelector.first_greater_or_equal(hi),
                version=version)
            rows = []
            while True:
                reply = await db.process.net.request(
                    db.process,
                    Endpoint(addr_of_tag[tag], Token.STORAGE_GET_KEY_VALUES),
                    req)
                rows.extend(reply.data)
                if not (reply.more and reply.data):
                    return rows
                req = GetKeyValuesRequest(
                    begin=KeySelector.first_greater_or_equal(
                        reply.data[-1][0] + b"\x00"),
                    end=KeySelector.first_greater_or_equal(hi),
                    version=version)

        for i, team in enumerate(shard_tags):
            lo = b[i]
            hi = b[i + 1] if i + 1 < len(b) else b"\xff" * 16
            # transient read errors (a replica still catching up after a
            # late reboot: future_version; dropped packets; a version aging
            # out mid-check) retry the whole shard at a FRESH version — only
            # a clean same-version comparison may vote
            for attempt in range(60):
                try:
                    tr = db.create_transaction()
                    version = await tr.get_read_version()
                    per_replica = [(tag, await read_replica(tag, lo, hi,
                                                            version))
                                   for tag in team]
                    break
                except FDBError as e:
                    if e.name == "operation_cancelled":
                        raise
                    await self.cluster.loop.delay(1.0)
            else:
                raise AssertionError(
                    f"shard {i}: replicas unreadable for the checker")
            first_tag, first_rows = per_replica[0]
            for tag, rows in per_replica[1:]:
                assert rows == first_rows, \
                    (f"shard {i}: replica tag {tag} diverges from tag "
                     f"{first_tag}: {len(rows)} vs {len(first_rows)} rows")
