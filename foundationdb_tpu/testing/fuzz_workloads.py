"""Fuzz workload battery: the highest-value reference workloads we were
missing, ported onto the Workload/spec machinery.

Reference: fdbserver/workloads/ApiCorrectness.actor.cpp (random API ops vs an
in-memory model), Serializability.actor.cpp (concurrent histories replayed in
commit order), RYWPerformance/RyowCorrectness.actor.cpp (read-your-writes
overlay vs model), ChangeConfig.actor.cpp (live `configure` churn mid-load),
RemoveServersSafely.actor.cpp (exclusion drains before a kill), KillRegion
(configuration.rst region failover), and BackupToDBCorrectness /
BackupCorrectness.actor.cpp (live backup + restore byte-diff under faults).

Every workload draws randomness ONLY from its forked DeterministicRandom and
advances its host-side model ONLY for transactions proven to have landed
(marker probe via Workload._commit_resolved), so a failing (seed, spec) pair
replays identically.
"""

from __future__ import annotations

from foundationdb_tpu.testing.workloads import Workload
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.trace import TraceEvent
from foundationdb_tpu.utils.types import MutationType, apply_atomic_op

# atomic ops the fuzzers draw from (key-valued ops only: the versionstamp
# ops need placeholder-offset trailers and are covered by VersionStamp /
# Serializability's history rows)
_FUZZ_ATOMICS = (
    MutationType.ADD_VALUE, MutationType.AND, MutationType.OR,
    MutationType.XOR, MutationType.MAX, MutationType.MIN,
    MutationType.BYTE_MIN, MutationType.BYTE_MAX,
    MutationType.APPEND_IF_FITS,
)


class FuzzApiCorrectnessWorkload(Workload):
    """Random API ops (set/clear/clear_range/atomic-ops) committed against a
    host-side model dict (workloads/ApiCorrectness.actor.cpp). The model
    advances only for commits proven to have landed; interleaved read passes
    and the final check assert db == model byte-for-byte."""

    name = "FuzzApiCorrectness"

    def __init__(self, n_keys: int = 32, prefix: bytes = b"fuzz/"):
        self.n = n_keys
        self.prefix = prefix
        self.model: dict[bytes, bytes] = {}
        self.committed = 0
        self.reads_checked = 0
        self.atomics = 0

    def key(self, i: int) -> bytes:
        return self.prefix + b"%03d" % i

    def _draw_plan(self, rng):
        plan = []
        for _ in range(rng.randint(1, 5)):
            r = rng.random()
            i = rng.randint(0, self.n - 1)
            if r < 0.40:
                plan.append(("set", i, b"v%08d" % rng.randint(0, 1 << 26)))
            elif r < 0.55:
                plan.append(("clear", i, b""))
            elif r < 0.65:
                j = rng.randint(i, self.n)
                plan.append(("clear_range", i, b"%03d" % j))
            else:
                op = _FUZZ_ATOMICS[rng.randint(0, len(_FUZZ_ATOMICS) - 1)]
                width = (1, 4, 8)[rng.randint(0, 2)]
                operand = rng.randint(0, (1 << (8 * width)) - 1) \
                    .to_bytes(width, "little")
                plan.append(("atomic", i, (op, operand)))
        return plan

    def _apply_to_model(self, plan):
        for kind, i, arg in plan:
            k = self.key(i)
            if kind == "set":
                self.model[k] = arg
            elif kind == "clear":
                self.model.pop(k, None)
            elif kind == "clear_range":
                hi = self.prefix + arg
                for kk in [kk for kk in self.model if k <= kk < hi]:
                    del self.model[kk]
            else:
                op, operand = arg
                self.model[k] = apply_atomic_op(op, self.model.get(k),
                                                operand)

    async def _resync(self, db):
        async def rd(tr):
            return await tr.get_range(self.prefix, self.prefix + b"\xff",
                                      limit=self.n * 4)
        rows = await db.transact(rd, max_retries=500)
        self.model = {k: v for k, v in rows
                      if not k.endswith(b"__marker__")}

    async def start(self, db):
        marker = self.prefix + b"__marker__"
        it = 0
        while self._time_left():
            it += 1
            rng = self.rng
            if rng.coinflip(0.3):
                # read pass: point + range reads must match the model
                lo_i = rng.randint(0, self.n - 1)
                hi_i = rng.randint(lo_i + 1, self.n)

                async def rd(tr, lo_i=lo_i, hi_i=hi_i):
                    pt = await tr.get(self.key(lo_i))
                    rows = await tr.get_range(self.key(lo_i),
                                              self.prefix + b"%03d" % hi_i)
                    return pt, rows
                try:
                    pt, rows = await db.transact(rd, max_retries=500)
                except FDBError:
                    continue
                want_pt = self.model.get(self.key(lo_i))
                want = sorted((k, v) for k, v in self.model.items()
                              if self.key(lo_i) <= k < self.prefix
                              + b"%03d" % hi_i)
                assert pt == want_pt and list(rows) == want, \
                    (f"fuzz read diverged from model (iter {it}): "
                     f"{pt!r}/{rows} vs {want_pt!r}/{want}")
                self.reads_checked += 1
                continue
            plan = self._draw_plan(rng)
            token = b"t%08d" % it

            async def fn(tr, plan=plan, token=token):
                for kind, i, arg in plan:
                    k = self.key(i)
                    if kind == "set":
                        tr.set(k, arg)
                    elif kind == "clear":
                        tr.clear(k)
                    elif kind == "clear_range":
                        tr.clear_range(k, self.prefix + arg)
                    else:
                        tr.atomic_op(arg[0], k, arg[1])
                tr.set(marker, token)
                return True
            landed = await self._commit_resolved(db, fn, marker, token)
            if landed:
                self._apply_to_model(plan)
                self.committed += 1
                self.atomics += sum(1 for kind, _i, _a in plan
                                    if kind == "atomic")
            else:
                await self._resync(db)
            await self.cluster.loop.delay(0.02 * rng.random())

    async def check(self, db):
        assert self.committed > 0, "no fuzz transaction landed"
        assert self.reads_checked > 0, "no read pass ran (coverage bug)"
        assert self.atomics > 0, "no atomic op drawn (coverage bug)"

        async def rd(tr):
            return await tr.get_range(self.prefix, self.prefix + b"\xff",
                                      limit=self.n * 4)
        rows = await db.transact(rd, max_retries=1000)
        got = {k: v for k, v in rows if not k.endswith(b"__marker__")}
        assert got == self.model, \
            (f"final state diverged from model after {self.committed} "
             f"commits: missing={set(self.model) - set(got)} "
             f"extra={set(got) - set(self.model)} "
             f"diff={[k for k in got if self.model.get(k) != got[k]]}")


class ZipfianHotKeyWorkload(Workload):
    """Concurrent read-modify-write increments over a zipfian-skewed key
    population (rank 0 is the hot key): the contention generator behind the
    conflict-hotspot loop. Every landed commit adds exactly ONE to its key,
    so after quiesce each counter must equal the host-side count of proven
    commits — serializability under sustained write-write conflict. The skew
    concentrates conflicts on a narrow range, driving the resolver's
    hot-range sketch, the ratekeeper's throttle list and the proxy's
    transaction_throttled rejections (all retried inside _commit_resolved),
    so the spec exercises the whole contention-management loop under the
    same fault battery as every other spec."""

    name = "ZipfianHotKey"

    def __init__(self, n_keys: int = 16, n_actors: int = 6,
                 theta: float = 1.2, prefix: bytes = b"zipf/"):
        self.n = n_keys
        self.n_actors = n_actors
        self.prefix = prefix
        # zipfian CDF over ranks: P(rank i) ~ 1/(i+1)^theta
        w = [1.0 / float(i + 1) ** theta for i in range(n_keys)]
        tot = sum(w)
        acc = 0.0
        self.cdf = []
        for x in w:
            acc += x
            self.cdf.append(acc / tot)
        self.model = [0] * n_keys
        self.committed = 0
        self.attempts = 0

    def key(self, i: int) -> bytes:
        return self.prefix + b"%03d" % i

    def _draw_key(self, rng) -> int:
        r = rng.random()
        for i, c in enumerate(self.cdf):
            if r <= c:
                return i
        return self.n - 1

    async def _actor(self, db, aid: int, rng):
        marker = self.prefix + b"__marker%02d__" % aid
        it = 0
        while self._time_left():
            it += 1
            i = self._draw_key(rng)
            token = b"a%02d-%06d" % (aid, it)

            async def fn(tr, i=i, token=token):
                self.attempts += 1
                v = await tr.get(self.key(i))
                tr.set(self.key(i), b"%d" % (int(v or b"0") + 1))
                tr.set(marker, token)
                return True

            landed = await self._commit_resolved(db, fn, marker, token)
            if landed:
                self.model[i] += 1
                self.committed += 1
            await self.cluster.loop.delay(0.01 * rng.random())

    async def start(self, db):
        # one forked rng per actor, drawn up front: the actors interleave on
        # the deterministic sim loop, so per-actor streams keep the whole
        # run a pure function of the seed
        rngs = [self.rng.fork() for _ in range(self.n_actors)]
        tasks = [self.cluster.loop.spawn(self._actor(db, a, rngs[a]),
                                         f"zipf{a}")
                 for a in range(self.n_actors)]
        for t in tasks:
            await t

    async def check(self, db):
        assert self.committed > 0, "no zipfian increment landed"
        assert self.attempts > self.committed, \
            "no retry pressure: the hot key never drew a conflict"

        async def rd(tr):
            return await tr.get_range(self.prefix, self.prefix + b"\xff",
                                      limit=self.n * 4)
        rows = await db.transact(rd, max_retries=1000)
        got = {k: v for k, v in rows if b"__marker" not in k}
        want = {self.key(i): b"%d" % c
                for i, c in enumerate(self.model) if c}
        assert got == want, (
            f"counters diverged from proven-commit counts after "
            f"{self.committed} commits / {self.attempts} attempts: "
            f"got={got} want={want}")


class ZipfianReadHotspotWorkload(Workload):
    """Read-heavy zipfian skew against replicated storage: writer actors
    RMW-increment a narrow hot key set (proven via _commit_resolved markers,
    so the host ledger counts exactly the landed commits) while reader
    actors hammer the same keys through the client's replica-balanced read
    path and the storage-side versioned hot-key cache.

    The readers keep, per key, the highest-version observation seen so far
    and compare every new (read_version, counter) pair against it:

      v2 == v1  =>  c2 == c1   (two reads at one version must agree — a
                                divergent replica or a stale cache entry
                                surfaces here)
      v2 >  v1  =>  c2 >= c1   (counters only grow; a lower counter at a
                                higher version is a lost or stale read)
      v2 <  v1  =>  c2 <= c1   (a read at an OLDER version returning a
                                newer counter means a replica or cache
                                served data from the future)

    Because the battery runs clogging + attrition, the observations span
    shard moves, replica catch-up after recoveries, and cache
    invalidation/rebuild — exactly the windows where a fencing bug would
    leak a wrong-version value. After quiesce, the final counters must
    equal the proven-commit ledger, and (when the cache knob is on) the
    storage roles must report cache hits: the hot path actually engaged."""

    name = "ZipfianReadHotspot"

    def __init__(self, n_keys: int = 8, n_writers: int = 2,
                 n_readers: int = 4, theta: float = 1.2,
                 prefix: bytes = b"zrh/"):
        self.n = n_keys
        self.n_writers = n_writers
        self.n_readers = n_readers
        self.prefix = prefix
        w = [1.0 / float(i + 1) ** theta for i in range(n_keys)]
        tot = sum(w)
        acc = 0.0
        self.cdf = []
        for x in w:
            acc += x
            self.cdf.append(acc / tot)
        self.model = [0] * n_keys
        self.committed = 0
        self.reads = 0
        self.distinct_versions = 0
        self.cache_hits_seen = 0
        # per-key highest-version observation: key index -> (version, count)
        self._best: dict[int, tuple[int, int]] = {}

    def key(self, i: int) -> bytes:
        return self.prefix + b"%03d" % i

    def _draw_key(self, rng) -> int:
        r = rng.random()
        for i, c in enumerate(self.cdf):
            if r <= c:
                return i
        return self.n - 1

    async def setup(self, db):
        async def fn(tr):
            for i in range(self.n):
                tr.set(self.key(i), b"0")
        await db.transact(fn)

    def _observe(self, i: int, version: int, count: int):
        """Fold one (read_version, counter) sighting into the per-key
        monotonicity invariant."""
        prev = self._best.get(i)
        if prev is None:
            self._best[i] = (version, count)
            return
        v1, c1 = prev
        if version == v1:
            assert count == c1, (
                f"replica/cache divergence on key {i}: two reads at "
                f"version {version} returned {c1} and {count}")
        elif version > v1:
            assert count >= c1, (
                f"stale read on key {i}: version {version} > {v1} but "
                f"counter went {c1} -> {count}")
            if count > c1:
                self.distinct_versions += 1
            self._best[i] = (version, count)
        else:
            assert count <= c1, (
                f"future leak on key {i}: version {version} < {v1} but "
                f"counter {count} > {c1} seen at the newer version")

    async def _writer(self, db, aid: int, rng):
        marker = self.prefix + b"__marker%02d__" % aid
        it = 0
        while self._time_left():
            it += 1
            i = self._draw_key(rng)
            token = b"w%02d-%06d" % (aid, it)

            async def fn(tr, i=i, token=token):
                v = await tr.get(self.key(i))
                tr.set(self.key(i), b"%d" % (int(v or b"0") + 1))
                tr.set(marker, token)
                return True

            if await self._commit_resolved(db, fn, marker, token):
                self.model[i] += 1
                self.committed += 1
            await self.cluster.loop.delay(0.05 * (0.5 + rng.random()))

    async def _reader(self, db, rng):
        retryable = ("transaction_too_old", "future_version", "timed_out",
                     "transaction_throttled", "proxies_changed",
                     "cluster_not_fully_recovered", "operation_failed",
                     "wrong_shard_server", "request_maybe_delivered",
                     "broken_promise", "all_alternatives_failed")
        while self._time_left():
            ks = sorted({self._draw_key(rng)
                         for _ in range(rng.randint(1, 4))})
            tr = db.create_transaction()
            try:
                vals = await tr.get_many([self.key(i) for i in ks],
                                         snapshot=True)
                version = await tr.get_read_version()
            except FDBError as e:
                if e.name in retryable:
                    await self.cluster.loop.delay(
                        0.1 * (0.5 + rng.random()))
                    continue
                raise
            for i, val in zip(ks, vals):
                self._observe(i, version, int(val or b"0"))
            self.reads += len(ks)
            await self.cluster.loop.delay(0.01 * rng.random())

    def _sample_cache_hits(self) -> int:
        from foundationdb_tpu.server.storage import StorageServer
        hits = 0
        for p in self.cluster.storage_worker_procs:
            w = getattr(p, "worker", None)
            if w is None or not p.alive:
                continue
            for role in w.roles.values():
                # rc.hits is the live tally; the CounterCollection copy only
                # syncs on a STORAGE_METRICS fetch, so read the source
                if isinstance(role, StorageServer) \
                        and role._read_cache is not None:
                    hits += role._read_cache.hits
        return hits

    async def _cache_monitor(self):
        """Attrition + the quiesce recovery re-create storage roles (fresh
        counter collections), so the post-quiesce ledger can legitimately
        read 0: sample the live roles DURING the run and keep the peak."""
        while self._time_left():
            self.cache_hits_seen = max(self.cache_hits_seen,
                                       self._sample_cache_hits())
            await self.cluster.loop.delay(0.5)

    async def start(self, db):
        rngs = [self.rng.fork()
                for _ in range(self.n_writers + self.n_readers)]
        tasks = [self.cluster.loop.spawn(self._writer(db, a, rngs[a]),
                                         f"zrhW{a}")
                 for a in range(self.n_writers)]
        tasks += [self.cluster.loop.spawn(
                      self._reader(db, rngs[self.n_writers + r]),
                      f"zrhR{r}")
                  for r in range(self.n_readers)]
        tasks.append(self.cluster.loop.spawn(self._cache_monitor(),
                                             "zrhCache"))
        for t in tasks:
            await t

    async def check(self, db):
        from foundationdb_tpu.utils.knobs import KNOBS
        assert self.committed > 0, "no hot-key increment landed"
        assert self.reads > 0, "readers made no progress"
        assert self.distinct_versions > 0, \
            "readers never saw a counter advance: no read/write overlap"

        async def rd(tr):
            return await tr.get_range(self.prefix, self.prefix + b"\xff",
                                      limit=self.n * 4)
        rows = await db.transact(rd, max_retries=1000)
        got = {k: v for k, v in rows if b"__marker" not in k}
        want = {self.key(i): b"%d" % c for i, c in enumerate(self.model)}
        assert got == want, (
            f"final counters diverged from the proven-commit ledger after "
            f"{self.committed} commits / {self.reads} reads: "
            f"got={got} want={want}")

        # the cache must have ENGAGED when the knob is on (the spec pins
        # hot-rate/sample knobs so the skew crosses the sketch's bar);
        # buggify can flip the knob off, in which case hits stay 0 by design
        if KNOBS.READ_CACHE_ENABLED:
            hits = max(self.cache_hits_seen, self._sample_cache_hits())
            assert hits > 0, (
                f"read cache never hit across {self.reads} skewed reads "
                f"with READ_CACHE_ENABLED on")


class SerializabilityWorkload(Workload):
    """Concurrent register transactions leave a versionstamped history row
    per commit recording (reads seen, writes made); after quiesce the rows —
    sorted by key, i.e. by commit version — must replay as a SERIAL history
    against a model (workloads/Serializability.actor.cpp). Each transaction's
    recorded reads must equal the model state at its commit point: exactly
    the strict-serializability guarantee the resolver enforces."""

    name = "Serializability"

    def __init__(self, n_regs: int = 8, prefix: bytes = b"ser/"):
        self.k = n_regs
        self.prefix = prefix
        self.attempted = 0

    def reg(self, i: int) -> bytes:
        return self.prefix + b"r%02d" % i

    async def setup(self, db):
        async def fn(tr):
            for i in range(self.k):
                tr.set(self.reg(i), b"%08d" % 0)
        await db.transact(fn)

    async def start(self, db):
        while self._time_left():
            rng = self.rng
            n_read = rng.randint(1, 3)
            read_idx = sorted({rng.randint(0, self.k - 1)
                               for _ in range(n_read)})
            write_idx = sorted({read_idx[rng.randint(0, len(read_idx) - 1)],
                                rng.randint(0, self.k - 1)})
            salt = rng.randint(0, 1 << 20)

            async def fn(tr, read_idx=read_idx, write_idx=write_idx,
                         salt=salt):
                vals = []
                for i in read_idx:
                    vals.append(int(await tr.get(self.reg(i))))
                newv = (sum(vals) * 31 + salt) % 100_000_000
                for i in write_idx:
                    tr.set(self.reg(i), b"%08d" % newv)
                rec = b"r=" + b",".join(
                    b"%02d:%08d" % (i, v)
                    for i, v in zip(read_idx, vals)) + \
                    b";w=" + b",".join(b"%02d" % i for i in write_idx) + \
                    b";v=%08d" % newv
                # history key gets the commit versionstamp: rows sort in
                # commit order, and even a duplicated unknown-result retry
                # produces its own (still serially-consistent) row
                body = self.prefix + b"h/" + b"\x00" * 10
                key = body + (len(body) - 10).to_bytes(4, "little")
                tr.atomic_op(MutationType.SET_VERSIONSTAMPED_KEY, key, rec)
            try:
                await db.transact(fn, max_retries=1000)
                self.attempted += 1
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
            await self.cluster.loop.delay(0.02 * self.rng.random())

    async def check(self, db):
        assert self.attempted > 0, "no serializability transaction ran"

        async def rd(tr):
            regs = [int(await tr.get(self.reg(i))) for i in range(self.k)]
            hist = await tr.get_range(self.prefix + b"h/",
                                      self.prefix + b"h0", limit=1_000_000)
            return regs, hist
        regs, hist = await db.transact(rd, max_retries=1000)
        assert hist, "no history row committed"
        model = [0] * self.k
        for n, (_key, rec) in enumerate(hist):
            r_part, w_part, v_part = rec.split(b";")
            for item in r_part[2:].split(b","):
                i, v = item.split(b":")
                assert model[int(i)] == int(v), \
                    (f"history row {n} read reg {int(i)}={int(v)} but the "
                     f"serial replay has {model[int(i)]}: the concurrent "
                     f"history is NOT equivalent to commit order")
            newv = int(v_part[2:])
            for i in w_part[2:].split(b","):
                model[int(i)] = newv
        assert regs == model, \
            f"final registers {regs} != serial replay {model}"


class RyowCorrectnessWorkload(Workload):
    """A single transaction interleaves writes (set/clear/clear_range) with
    reads (get/get_range); every read must see the transaction's OWN prior
    writes overlaid on the committed state (workloads/RyowCorrectness
    pattern). The committed model advances only for proven commits."""

    name = "RyowCorrectness"

    def __init__(self, n_keys: int = 24, prefix: bytes = b"ryow/"):
        self.n = n_keys
        self.prefix = prefix
        self.model: dict[bytes, bytes] = {}
        self.committed = 0
        self.ryw_hits = 0  # reads that observed an own-write

    def key(self, i: int) -> bytes:
        return self.prefix + b"%03d" % i

    def _draw_ops(self, rng):
        ops = []
        written: set[int] = set()
        hits = 0
        for _ in range(rng.randint(4, 10)):
            r = rng.random()
            i = rng.randint(0, self.n - 1)
            if r < 0.30:
                ops.append(("set", i, b"w%08d" % rng.randint(0, 1 << 26)))
                written.add(i)
            elif r < 0.42:
                ops.append(("clear", i, 0))
                written.add(i)
            elif r < 0.52:
                j = rng.randint(i + 1, self.n)
                ops.append(("clear_range", i, j))
                written.update(range(i, j))
            elif r < 0.80:
                if written and rng.coinflip(0.6):
                    i = sorted(written)[rng.randint(0, len(written) - 1)]
                    hits += 1
                ops.append(("get", i, 0))
            else:
                j = rng.randint(i + 1, self.n)
                ops.append(("get_range", i, j))
                if any(i <= w < j for w in written):
                    hits += 1
        return ops, hits

    async def _resync(self, db):
        async def rd(tr):
            return await tr.get_range(self.prefix, self.prefix + b"\xff",
                                      limit=self.n * 4)
        rows = await db.transact(rd, max_retries=500)
        self.model = {k: v for k, v in rows
                      if not k.endswith(b"__marker__")}

    async def start(self, db):
        marker = self.prefix + b"__marker__"
        it = 0
        while self._time_left():
            it += 1
            rng = self.rng
            ops, hits = self._draw_ops(rng)
            token = b"t%08d" % it

            async def fn(tr, ops=ops, token=token):
                ov = dict(self.model)
                for kind, a, b in ops:
                    k = self.key(a)
                    if kind == "set":
                        tr.set(k, b)
                        ov[k] = b
                    elif kind == "clear":
                        tr.clear(k)
                        ov.pop(k, None)
                    elif kind == "clear_range":
                        hi = self.key(b)
                        tr.clear_range(k, hi)
                        for kk in [kk for kk in ov if k <= kk < hi]:
                            del ov[kk]
                    elif kind == "get":
                        got = await tr.get(k)
                        assert got == ov.get(k), \
                            (f"RYW get({k!r}) = {got!r}, overlay says "
                             f"{ov.get(k)!r} (ops {ops})")
                    else:
                        hi = self.key(b)
                        rows = await tr.get_range(k, hi)
                        want = sorted((kk, vv) for kk, vv in ov.items()
                                      if k <= kk < hi)
                        assert list(rows) == want, \
                            (f"RYW get_range[{k!r},{hi!r}) = {rows}, "
                             f"overlay says {want} (ops {ops})")
                tr.set(marker, token)
                return ov
            ov = await self._commit_resolved(db, fn, marker, token)
            if ov is not None:
                self.model = ov
                self.committed += 1
                self.ryw_hits += hits
            else:
                await self._resync(db)
            await self.cluster.loop.delay(0.02 * rng.random())

    async def check(self, db):
        assert self.committed > 0, "no RYW transaction landed"
        assert self.ryw_hits > 0, \
            "no read ever observed an own-write (coverage bug)"

        async def rd(tr):
            return await tr.get_range(self.prefix, self.prefix + b"\xff",
                                      limit=self.n * 4)
        rows = await db.transact(rd, max_retries=1000)
        got = {k: v for k, v in rows if not k.endswith(b"__marker__")}
        assert got == self.model, "final state diverged from RYW model"


class ChangeConfigWorkload(Workload):
    """Live `configure` churn while data workloads run
    (workloads/ChangeConfig.actor.cpp): the txn-subsystem shape (proxies /
    tlogs / resolvers) is rewritten mid-load; each change makes the CC
    trigger a recovery onto the new shape and traffic must ride through."""

    name = "ChangeConfig"

    def __init__(self, interval: float = 6.0):
        self.interval = interval
        self.changes = 0
        self.last: dict = {}

    async def start(self, db):
        from foundationdb_tpu.client import management
        loop = self.cluster.loop
        # recruitment needs max(n_proxies, n_resolvers) stateless workers
        # plus tlog hosts: cap the draw so a change can always recruit
        nw = len(getattr(self.cluster, "worker_procs", [])) or 5
        hi = max(1, min(3, nw - 2))
        while self._time_left():
            await loop.delay(self.interval * (0.5 + self.rng.random()))
            r = self.rng.random()
            if r < 0.4:
                params = {"n_proxies": self.rng.randint(1, hi)}
            elif r < 0.8:
                params = {"n_tlogs": self.rng.randint(1, hi)}
            else:
                params = {"n_resolvers": self.rng.randint(1, min(2, hi))}
            try:
                await management.configure(db, **params)
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
                continue
            self.changes += 1
            self.last.update(params)
            TraceEvent("ChangeConfigApplied", "workload") \
                .detail("Params", str(params)).log()

    async def check(self, db):
        from foundationdb_tpu.client import management
        assert self.changes > 0, "no configure ever committed"
        conf = await management.get_configuration(db)
        for k, v in self.last.items():
            assert conf.get(k) == v, \
                f"\\xff/conf lost {k}: wanted {v}, holds {conf.get(k)}"
        # the cluster must converge onto the last written shape (the CC
        # reads conf each DD round and recovers into it)
        want_proxies = self.last.get("n_proxies")
        if want_proxies is not None:
            for _ in range(240):
                cc = self.cluster.current_cc()
                if cc is not None \
                        and len(cc.dbinfo.proxies) == want_proxies:
                    break
                await self.cluster.loop.delay(0.5)
            cc = self.cluster.current_cc()
            assert cc is not None \
                and len(cc.dbinfo.proxies) == want_proxies, \
                (f"cluster never recovered onto n_proxies={want_proxies}: "
                 f"{len(cc.dbinfo.proxies) if cc else None}")


class RemoveServersSafelyWorkload(Workload):
    """Exclude a storage worker under load, wait for the DD to drain every
    shard off it, kill it (now safe: it holds no data), then include it back
    (workloads/RemoveServersSafely.actor.cpp). Requires spare storage
    workers so healing has somewhere to re-replicate."""

    name = "RemoveServersSafely"

    def __init__(self, drain_wait: float = 90.0):
        self.drain_wait = drain_wait
        self.excluded = 0
        self.drained = 0

    async def start(self, db):
        from foundationdb_tpu.client import management
        from foundationdb_tpu.core.sim import KillType
        c = self.cluster
        loop = c.loop
        while self._time_left():
            await loop.delay(2.0 + 3.0 * self.rng.random())
            cc = c.current_cc()
            if cc is None:
                continue
            storages = cc.dbinfo.storages
            if len({a for a, _t in storages}) < 2:
                continue
            victim = storages[self.rng.randint(0, len(storages) - 1)][0]
            try:
                await management.exclude_servers(db, [victim])
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise
                continue
            self.excluded += 1
            deadline = loop.now() + self.drain_wait
            drained = False
            while loop.now() < deadline:
                await loop.delay(0.5)
                cc = c.current_cc()
                if cc is None:
                    continue
                info = cc.dbinfo
                victim_tags = {t for a, t in info.storages if a == victim}
                if victim_tags and not any(
                        t in team for t in victim_tags
                        for team in info.teams()):
                    drained = True
                    break
            if drained:
                self.drained += 1
                # now the kill is safe: the server holds no shard
                proc = c.net.processes.get(victim)
                if proc is not None and proc.alive:
                    c.net.kill(victim, KillType.RebootProcess)
                TraceEvent("RemovedServerSafely", "workload") \
                    .detail("Victim", victim).log()
            try:
                await management.include_servers(db, [victim])
            except FDBError as e:
                if e.name == "operation_cancelled":
                    raise

    async def check(self, db):
        assert self.excluded > 0, "no exclusion was ever written"
        assert self.drained > 0, \
            "no exclusion ever drained (DD healing never completed)"


class KillRegionWorkload(Workload):
    """Region loss under load (configuration.rst regions; the KillRegion
    test spec): kill every process in one datacenter — standby, satellite,
    or the PRIMARY itself (the satellite log means no acked commit is lost)
    — let the survivors fail over, then reboot the region and let it
    rejoin. Requires a two-region cluster."""

    name = "KillRegion"

    def __init__(self, first_delay: float = 6.0):
        self.first_delay = first_delay
        self.kills = 0
        self.killed_dcs: list[str] = []

    async def start(self, db):
        c = self.cluster
        loop = c.loop
        await loop.delay(self.first_delay)
        while self._time_left():
            r = self.rng.random()
            dc = "dc1" if r < 0.4 else ("sat0" if r < 0.7 else "dc0")
            victims = [p for p in c.net.processes.values()
                       if p.dc_id == dc and p.alive]
            if victims:
                TraceEvent("KillRegion", "workload").detail("DC", dc).log()
                c.kill_dc(dc)
                self.kills += 1
                self.killed_dcs.append(dc)
            await loop.delay(6.0 + 6.0 * self.rng.random())
            c.net.reboot_dead([p.address for p in victims])
            await loop.delay(4.0 + 4.0 * self.rng.random())

    async def check(self, db):
        assert self.kills > 0, "no region was ever killed"


class BackupUnderAttritionWorkload(Workload):
    """Live backup while the spec's fault workloads kill and clog the
    cluster (BackupCorrectness.actor.cpp under Attrition): snapshot chunks +
    the mutation-log tee run to completion through the faults; check()
    restores into a fresh cluster on the same simulation and byte-diffs this
    workload's keyspace against the source. The writer quiesces BEFORE the
    backup stops, so the source's final bk/ rows ARE the end-version truth
    (no pinned-version read racing the MVCC window)."""

    name = "BackupAttrition"

    def __init__(self, n_keys: int = 40, chunks: int = 3,
                 prefix: bytes = b"bk/"):
        self.n = n_keys
        self.chunks = chunks
        self.prefix = prefix
        self.container = None
        self.end_version = 0
        self.writes = 0

    async def setup(self, db):
        async def fn(tr):
            for i in range(self.n):
                tr.set(self.prefix + b"%03d" % i, b"v%d" % i)
        await db.transact(fn, max_retries=500)

    async def start(self, db):
        from foundationdb_tpu.backup import BackupAgent, BackupContainer
        loop = self.cluster.loop
        self.container = BackupContainer()
        agent = BackupAgent(db, self.container, chunks=self.chunks)
        await agent.start()

        state = {"stop": False}

        async def writer():
            n = 0
            while not state["stop"]:
                async def w(tr, n=n):
                    tr.set(self.prefix + b"%03d" % (n % self.n),
                           b"updated%d" % n)
                    if n % 5 == 0:
                        tr.clear(self.prefix + b"%03d"
                                 % ((n * 7) % self.n))
                    tr.atomic_op(MutationType.ADD_VALUE,
                                 self.prefix + b"counter",
                                 (1).to_bytes(8, "little"))
                try:
                    await db.transact(w, max_retries=500)
                    self.writes += 1
                except FDBError as e:
                    if e.name == "operation_cancelled":
                        raise
                n += 1
                await loop.delay(0.1)
        wtask = loop.spawn(writer(), name="bkWriter")

        a1 = loop.spawn(agent.run_agent(), name="bkAgent")
        tailer = loop.spawn(agent.run_log_tailer(), name="bkTailer")
        await a1
        await loop.delay(1.0)  # a few more teed writes past the snapshot
        state["stop"] = True
        await wtask  # writer fully quiesced BEFORE the backup's end version
        self.end_version = await agent.stop()
        await tailer

    async def check(self, db):
        from foundationdb_tpu.backup import RestoreAgent
        from foundationdb_tpu.server.cluster import SimCluster
        assert self.writes > 0, "no live writes landed during the backup"
        assert self.end_version > 0, "backup never produced an end version"
        c = self.cluster

        async def rd(tr):
            return await tr.get_range(self.prefix, self.prefix + b"\xff")
        truth = await db.transact(rd, max_retries=1000)

        dst = SimCluster(seed=self.rng.randint(0, 1 << 30), n_proxies=1,
                         n_resolvers=1, n_tlogs=1, n_storage=1,
                         loop=c.loop, net=c.net, name_prefix="bkrestore-")
        db2 = dst.database()
        await RestoreAgent(db2, self.container).restore()
        got = await db2.transact(rd, max_retries=500)
        assert got == truth, (
            f"restore mismatch on {self.prefix!r}: {len(got)} vs "
            f"{len(truth)} rows; missing={set(dict(truth)) - set(dict(got))} "
            f"extra={set(dict(got)) - set(dict(truth))}")
