"""Randomized simulation harness: seed -> ClusterDraw -> spec -> repro line.

Reference: fdbserver/SimulatedCluster.actor.cpp:1239 (simulationSetupAndRun)
— the simulator NEVER runs on a fixed cluster. Every seed draws a random
topology (process / proxy / resolver / tlog counts), replication mode,
storage engine, conflict backend, and a buggified knob subset; the spec's
workloads then run against whatever came up. Fault coverage comes from
randomizing the ENVIRONMENT, not just the fault schedule ("Torturing
Databases for Fun and Profit", OSDI '14).

Specs are organized into graded tiers mirroring the reference's
tests/fast|slow/ split: the fast tier runs as a seeded sweep inside tier-1
CI; the slow tier sits behind the `slow` pytest marker.

Every failure prints a ONE-LINE REPRO command: the draw is a pure function
of the seed, so `python -m foundationdb_tpu.testing.simulated_cluster
--seed N --spec NAME` replays the identical cluster, knobs, faults, and
workload schedule.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from foundationdb_tpu.testing import fuzz_workloads as F
from foundationdb_tpu.testing import workloads as W
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom

# static batch shapes for the JAX conflict engines: small enough to compile
# in test time, identical across draws so every device/sharded draw in one
# process shares the jit cache (tests/test_fault_cocktail.py idiom)
_ACCEL_FAST_SHAPE = {
    "CONFLICT_BATCH_TXNS": 16,
    "CONFLICT_BATCH_READS_PER_TXN": 2,
    "CONFLICT_BATCH_WRITES_PER_TXN": 2,
    "CONFLICT_STATE_CAPACITY": 2048,
}

DEFAULT_BACKENDS = ("oracle", "device", "sharded")
DEFAULT_ENGINES = ("memory", "ssd", "redwood")

# sharded draws must run the real SPMD mesh even on the CPU platform
# (CPU_FALLBACK="host" would silently degrade them to the host oracle and
# the sim would never exercise the shard_map path); 2 shards keeps the
# mesh program small while still crossing a cut boundary, and the sweep's
# conftest-forced host device count (8) always covers it
_SHARDED_SIM_SHAPE = {
    "CONFLICT_NUM_SHARDS": 2,
    "CONFLICT_CPU_FALLBACK": "jax",
}


def _ensure_mesh_devices():
    """Sharded draws need CONFLICT_NUM_SHARDS jax devices. Under pytest the
    conftest forces 8 host-platform CPU devices; a CLI repro process must
    force them here instead — possible only before jax initializes. If jax
    is already imported with fewer devices, shrink the mesh width instead:
    a 1-wide mesh still exercises the shard_map path, and decisions are
    identical at any width."""
    import os
    import sys
    if "jax" not in sys.modules:
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if not f.startswith(
                     "--xla_force_host_platform_device_count")]
        flags.append("--xla_force_host_platform_device_count=8")
        os.environ["XLA_FLAGS"] = " ".join(flags)
        return
    import jax
    avail = len(jax.devices())
    if 0 < avail < int(KNOBS.CONFLICT_NUM_SHARDS):
        KNOBS.set("CONFLICT_NUM_SHARDS", avail)

# redwood draws shrink the engine's budgets so test-scale datasets actually
# flush and compact (at the production defaults a 25s spec never fills the
# 4MB memtable and the LSM path would go unexercised)
_REDWOOD_SIM_SHAPE = {
    "REDWOOD_MEMTABLE_BYTES": 2_048,
    "REDWOOD_BLOCK_BYTES": 512,
    "REDWOOD_COMPACTION_FAN_IN": 2,
}


@dataclass(frozen=True)
class ClusterDraw:
    """Everything SimulatedCluster randomizes per seed, as one record. A
    pure function of the seed (see draw()): the repro line only needs the
    seed, the rest is documentation for the human reading the failure."""

    seed: int
    replication: str       # "single" | "double" | "two_region"
    storage_engine: str    # "memory" | "ssd" | "redwood"
    conflict_backend: str  # "oracle" | "device" | "sharded"
    n_workers: int
    n_proxies: int
    n_resolvers: int
    n_tlogs: int
    n_storage: int
    n_replicas: int
    spare_storage: int     # storage workers beyond n_storage * n_replicas
    knobs: tuple           # sorted (name, value) buggified subset

    @classmethod
    def draw(cls, seed: int,
             allow_backends: tuple = DEFAULT_BACKENDS,
             allow_engines: tuple = DEFAULT_ENGINES,
             allow_two_region: bool = True,
             buggify_probability: float = 0.25) -> "ClusterDraw":
        """The per-seed environment draw (SimulatedCluster.actor.cpp:1239).
        Pure: same (seed, allow-lists) -> same draw, no global state read
        beyond the static knob registry."""
        rng = DeterministicRandom(seed)
        r = rng.random()
        if allow_two_region and r < 0.25:
            replication = "two_region"
        elif r < 0.60:
            replication = "double"
        else:
            replication = "single"
        engine = allow_engines[rng.randint(0, len(allow_engines) - 1)]
        backend = allow_backends[rng.randint(0, len(allow_backends) - 1)]
        knobs = tuple(sorted(KNOBS.draw_buggified(
            rng.fork(), probability=buggify_probability).items()))
        if replication == "two_region":
            # the dual-region layout fixes the txn-subsystem shape
            # (RecoverableCluster.two_region); the seed still draws the
            # storage width
            return cls(seed=seed, replication=replication,
                       storage_engine=engine, conflict_backend=backend,
                       n_workers=6, n_proxies=1, n_resolvers=1, n_tlogs=1,
                       n_storage=rng.randint(1, 2), n_replicas=1,
                       spare_storage=0, knobs=knobs)
        n_replicas = 2 if replication == "double" else 1
        n_proxies = rng.randint(1, 3)
        n_resolvers = rng.randint(1, 2)
        n_tlogs = rng.randint(1, 3)
        n_storage = rng.randint(1, 3)
        spare = rng.randint(0, 1)
        n_workers = max(5, max(n_proxies, n_resolvers) + n_tlogs + 2)
        return cls(seed=seed, replication=replication,
                   storage_engine=engine, conflict_backend=backend,
                   n_workers=n_workers, n_proxies=n_proxies,
                   n_resolvers=n_resolvers, n_tlogs=n_tlogs,
                   n_storage=n_storage, n_replicas=n_replicas,
                   spare_storage=spare, knobs=knobs)

    # -- identity --

    def topology(self) -> tuple:
        return (self.n_workers, self.n_proxies, self.n_resolvers,
                self.n_tlogs, self.n_storage, self.n_replicas,
                self.spare_storage)

    def distinct_tuple(self) -> tuple:
        """(topology, replication, engine, knobs): the axes the sweep must
        demonstrably vary across seeds."""
        return (self.topology(), self.replication, self.storage_engine,
                self.conflict_backend, self.knobs)

    def summary(self) -> str:
        kn = ",".join(f"{k}={v}" for k, v in self.knobs) or "-"
        return (f"{self.replication}/{self.storage_engine}/"
                f"{self.conflict_backend} workers={self.n_workers} "
                f"proxies={self.n_proxies} resolvers={self.n_resolvers} "
                f"tlogs={self.n_tlogs} "
                f"storage={self.n_storage}x{self.n_replicas}"
                f"+{self.spare_storage} knobs[{kn}]")

    def repro_line(self, spec_name: str, duration: float) -> str:
        return (f"python -m foundationdb_tpu.testing.simulated_cluster "
                f"--seed {self.seed} --spec {spec_name} "
                f"--duration {duration:g}  # drew: {self.summary()}")

    # -- realization --

    def apply_knobs(self):
        """Install the draw into the global knob bank (caller saves and
        restores around the run): buggified subset first, then the engine /
        backend picks, then the accelerator fast shapes (which must win so
        device draws share one compiled batch shape)."""
        for k, v in self.knobs:
            KNOBS.set(k, v)
        KNOBS.set("STORAGE_ENGINE", self.storage_engine)
        KNOBS.set("CONFLICT_BACKEND", self.conflict_backend)
        if self.conflict_backend in ("device", "sharded"):
            for k, v in _ACCEL_FAST_SHAPE.items():
                KNOBS.set(k, v)
        if self.conflict_backend == "sharded":
            for k, v in _SHARDED_SIM_SHAPE.items():
                KNOBS.set(k, v)
            _ensure_mesh_devices()
        if self.storage_engine == "redwood":
            for k, v in _REDWOOD_SIM_SHAPE.items():
                KNOBS.set(k, v)

    def factory(self) -> Callable:
        """cluster_factory for run_spec: boots the drawn shape."""
        from foundationdb_tpu.server.cluster import RecoverableCluster

        def make(cluster_seed: int):
            if self.replication == "two_region":
                c = RecoverableCluster.two_region(
                    seed=cluster_seed, n_storage=self.n_storage,
                    n_replicas=self.n_replicas)
                # pre-create the client OUTSIDE every killable region, so a
                # KillRegion on the primary doesn't take the workload driver
                # down with it (tests/test_tworegion.py idiom)
                c.net.new_process("client:0", dc_id="client")
                return c
            return RecoverableCluster(
                seed=cluster_seed, n_workers=self.n_workers,
                n_proxies=self.n_proxies, n_resolvers=self.n_resolvers,
                n_tlogs=self.n_tlogs, n_storage=self.n_storage,
                n_replicas=self.n_replicas,
                n_storage_workers=(self.n_storage * self.n_replicas
                                   + self.spare_storage))
        return make


# ---------------------------------------------------------------------------
# graded spec tiers (the reference's tests/fast/ vs tests/slow/ split)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Spec:
    """One named test spec: a workload battery + what it needs from the
    drawn cluster (tests/fast/CycleTest.txt etc. as data)."""

    name: str
    tier: str                  # "fast" | "slow"
    build: Callable            # (rng) -> list[Workload]
    duration: float = 25.0
    needs: str = ""            # "" | "flat" | "two_region" | "spare_storage"
    knobs: tuple = ()          # (name, value) overrides the spec REQUIRES
    # (applied after the draw's, since the spec can't pass without them)

    def compatible(self, draw: ClusterDraw) -> bool:
        if self.needs == "two_region":
            return draw.replication == "two_region"
        if self.needs == "flat":
            return draw.replication != "two_region"
        if self.needs == "spare_storage":
            # exclusion drain moves the victim's replicas onto the spare —
            # it needs a replacement worker AND double replication (the
            # team stays readable while DD re-replicates the drained copy)
            return draw.replication == "double" and draw.spare_storage > 0
        return True


def _cycle_battery(rng):
    return [W.CycleWorkload(), W.ConsistencyCheckWorkload(),
            W.RandomCloggingWorkload(), W.AttritionWorkload()]


def _fuzz_api_battery(rng):
    return [F.FuzzApiCorrectnessWorkload(), W.CycleWorkload(),
            W.RandomCloggingWorkload(), W.AttritionWorkload()]


def _zipfian_hotkey_battery(rng):
    # the contention loop through a recovery: zipfian RMW hammering plus
    # attrition (worker kills -> full recoveries) and clogging
    return [F.ZipfianHotKeyWorkload(), W.RandomCloggingWorkload(),
            W.AttritionWorkload()]


def _zipfian_read_hotspot_battery(rng):
    # the read scale-out loop through faults: skewed readers asserting
    # version-consistency across every replica + the versioned hot-key
    # cache, while clogging forces hedged fail-overs and attrition forces
    # replica catch-up / cache rebuild after recoveries
    return [F.ZipfianReadHotspotWorkload(), W.RandomCloggingWorkload(),
            W.AttritionWorkload()]


def _serializability_battery(rng):
    return [F.SerializabilityWorkload(), W.RandomCloggingWorkload(),
            W.AttritionWorkload()]


def _ryow_battery(rng):
    return [F.RyowCorrectnessWorkload(), W.RandomCloggingWorkload()]


def _conflict_range_battery(rng):
    return [W.ConflictRangeWorkload(), W.RandomCloggingWorkload()]


def _change_config_battery(rng):
    return [W.CycleWorkload(), F.ChangeConfigWorkload(),
            W.RandomCloggingWorkload()]


def _remove_servers_battery(rng):
    return [W.CycleWorkload(), F.RemoveServersSafelyWorkload(),
            W.RandomCloggingWorkload()]


def _kill_region_battery(rng):
    return [W.CycleWorkload(), F.KillRegionWorkload(),
            W.RandomCloggingWorkload()]


def _backup_attrition_battery(rng):
    return [F.BackupUnderAttritionWorkload(), W.CycleWorkload(),
            W.RandomCloggingWorkload(), W.AttritionWorkload()]


def _swizzled_battery(rng):
    return [W.CycleWorkload(), F.FuzzApiCorrectnessWorkload(),
            W.ConflictRangeWorkload(), W.ConsistencyCheckWorkload(),
            W.SwizzleCloggingWorkload(), W.AttritionWorkload()]


def _two_region_fuzz_battery(rng):
    return [F.FuzzApiCorrectnessWorkload(), F.KillRegionWorkload(),
            W.RandomCloggingWorkload()]


SPECS: dict[str, Spec] = {s.name: s for s in [
    Spec("cycle", "fast", _cycle_battery),
    Spec("fuzz-api", "fast", _fuzz_api_battery),
    # needs=flat: under two_region + attrition this workload's per-key
    # commit ledger catches an acked-commit rollback across recovery (see
    # ROADMAP "two-region durability under attrition") — a pre-existing
    # exposure, tracked separately from the contention loop this spec pins
    Spec("zipfian-hotkey", "fast", _zipfian_hotkey_battery, needs="flat",
         # the throttle loop must ENGAGE at test scale: lower the conflict
         # threshold so the zipfian hot range crosses it within the run
         knobs=(("RK_THROTTLE_CONFLICT_RATE", 4.0),
                ("RK_THROTTLE_RELEASE_TPS", 8.0))),
    # needs=flat for the same acked-commit-rollback exposure as
    # zipfian-hotkey; under a "double" draw the readers exercise the
    # hedged multi-replica path, under "single" the same invariants pin
    # the cache alone. The knobs force the hot-range sketch to flag the
    # zipfian prefix within the run so the versioned cache engages.
    Spec("zipfian-read-hotspot", "fast", _zipfian_read_hotspot_battery,
         needs="flat",
         knobs=(("READ_CACHE_HOT_RATE", 1.0),
                ("READ_CACHE_REFRESH", 0.25),
                ("READ_CACHE_SAMPLE", 1))),
    Spec("serializability", "fast", _serializability_battery),
    Spec("ryow", "fast", _ryow_battery),
    Spec("conflict-range", "fast", _conflict_range_battery),
    Spec("change-config", "fast", _change_config_battery, needs="flat"),
    Spec("remove-servers", "fast", _remove_servers_battery,
         needs="spare_storage",
         knobs=(("DD_INTERVAL_SECONDS", 1.0),
                ("DD_STORAGE_FAILURE_SECONDS", 4.0))),
    Spec("kill-region", "fast", _kill_region_battery, needs="two_region"),
    Spec("backup-attrition", "slow", _backup_attrition_battery,
         duration=35.0, needs="flat"),
    Spec("swizzled-battery", "slow", _swizzled_battery, duration=60.0),
    Spec("two-region-fuzz", "slow", _two_region_fuzz_battery,
         duration=40.0, needs="two_region"),
]}

FAST_SPECS = [s for s in SPECS.values() if s.tier == "fast"]
SLOW_SPECS = [s for s in SPECS.values() if s.tier == "slow"]


@dataclass
class RandomizedResult:
    seed: int
    spec: str
    draw: ClusterDraw
    result: W.SpecResult


class SpecFailure(AssertionError):
    """A randomized spec failed; str() carries the one-line repro command
    (so pytest's report shows exactly how to replay the seed)."""


def run_randomized_spec(seed: int, spec: Spec | str | None = None,
                        tier: str = "fast", duration: float | None = None,
                        allow_backends: tuple = DEFAULT_BACKENDS,
                        allow_engines: tuple = DEFAULT_ENGINES,
                        allow_two_region: bool = True,
                        max_time: float = 600_000.0) -> RandomizedResult:
    """The harness entry point: draw the cluster from the seed, pick (or
    take) a spec, boot run_spec on the drawn cluster, and print a one-line
    repro command on ANY failure. Restores the global knob bank afterward."""
    draw = ClusterDraw.draw(seed, allow_backends=allow_backends,
                            allow_engines=allow_engines,
                            allow_two_region=allow_two_region)
    rng = DeterministicRandom(seed ^ 0x5BEC)
    if isinstance(spec, str):
        spec = SPECS[spec]
    if spec is None:
        cands = [s for s in SPECS.values()
                 if s.tier == tier and s.compatible(draw)]
        spec = cands[rng.randint(0, len(cands) - 1)]
    elif not spec.compatible(draw):
        raise ValueError(
            f"spec {spec.name!r} needs {spec.needs!r} but seed {seed} "
            f"drew {draw.replication}: pick a seed whose draw fits")
    dur = spec.duration if duration is None else duration
    saved = dict(KNOBS._values)
    try:
        draw.apply_knobs()
        for k, v in spec.knobs:
            KNOBS.set(k, v)
        workloads = spec.build(rng.fork())
        try:
            result = W.run_spec(seed, workloads=workloads, duration=dur,
                                buggify=False, max_time=max_time,
                                cluster_factory=draw.factory())
        except (AssertionError, Exception) as e:  # noqa: B014 — repro line
            # on EVERY failure class, then re-raise with it attached
            line = draw.repro_line(spec.name, dur)
            print(f"\n*** simulation spec failed — repro:\n    {line}",
                  flush=True)
            raise SpecFailure(
                f"spec {spec.name!r} failed under draw "
                f"[{draw.summary()}]: {e}\n  repro: {line}") from e
    finally:
        KNOBS._values.clear()
        KNOBS._values.update(saved)
    return RandomizedResult(seed=seed, spec=spec.name, draw=draw,
                            result=result)


def sweep(seeds, tier: str = "fast",
          wall_clock_budget: float | None = None,
          **kw) -> list[RandomizedResult]:
    """Run a seeded sweep of the tier, optionally wall-clock-capped (CI's
    bounded fast-tier sweep). Seeds beyond the budget are skipped — callers
    assert a minimum completed count, so a too-slow environment fails
    loudly instead of hanging."""
    import time
    t0 = time.monotonic()
    out: list[RandomizedResult] = []
    for s in seeds:
        if wall_clock_budget is not None \
                and time.monotonic() - t0 > wall_clock_budget:
            break
        out.append(run_randomized_spec(s, tier=tier, **kw))
    return out


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description="Replay one randomized simulation spec (the repro "
                    "command printed by a failing sweep).")
    ap.add_argument("--seed", type=int, required=True)
    ap.add_argument("--spec", default=None,
                    choices=sorted(SPECS), help="spec name; default: the "
                    "seed's own tier draw")
    ap.add_argument("--tier", default="fast", choices=("fast", "slow"))
    ap.add_argument("--duration", type=float, default=None)
    args = ap.parse_args(argv)
    r = run_randomized_spec(args.seed, spec=args.spec, tier=args.tier,
                            duration=args.duration)
    print(f"OK seed={r.seed} spec={r.spec} [{r.draw.summary()}] "
          f"epochs={r.result.epochs} elapsed={r.result.elapsed:.1f}s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
