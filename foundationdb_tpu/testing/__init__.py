from foundationdb_tpu.testing.workloads import (  # noqa: F401
    ApiCorrectnessWorkload, AtomicOpsWorkload, AttritionWorkload,
    ConflictRangeWorkload, ConsistencyCheckWorkload, CycleWorkload,
    RandomCloggingWorkload, RandomMoveKeysWorkload, SwizzleCloggingWorkload,
    WriteDuringReadWorkload, run_spec)
