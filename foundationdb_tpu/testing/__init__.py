from foundationdb_tpu.testing.workloads import (  # noqa: F401
    AttritionWorkload, ConsistencyCheckWorkload, CycleWorkload,
    RandomCloggingWorkload, SwizzleCloggingWorkload, run_spec)
