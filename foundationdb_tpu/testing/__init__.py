from foundationdb_tpu.testing.workloads import (  # noqa: F401
    ApiCorrectnessWorkload, AtomicOpsWorkload, AttritionWorkload,
    ConflictRangeWorkload, ConsistencyCheckWorkload, CycleWorkload,
    IncrementWorkload, RandomCloggingWorkload, RandomMoveKeysWorkload,
    SelectorCorrectnessWorkload, SwizzleCloggingWorkload,
    VersionStampWorkload, WatchesWorkload, WriteDuringReadWorkload,
    run_spec)

from foundationdb_tpu.testing.fuzz_workloads import (  # noqa: F401
    BackupUnderAttritionWorkload, ChangeConfigWorkload,
    FuzzApiCorrectnessWorkload, KillRegionWorkload,
    RemoveServersSafelyWorkload, RyowCorrectnessWorkload,
    SerializabilityWorkload)

from foundationdb_tpu.testing.simulated_cluster import (  # noqa: F401
    FAST_SPECS, SLOW_SPECS, SPECS, ClusterDraw, RandomizedResult, Spec,
    SpecFailure, run_randomized_spec, sweep)
