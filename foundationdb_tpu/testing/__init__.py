from foundationdb_tpu.testing.workloads import (  # noqa: F401
    ApiCorrectnessWorkload, AtomicOpsWorkload, AttritionWorkload,
    ConflictRangeWorkload, ConsistencyCheckWorkload, CycleWorkload,
    IncrementWorkload, RandomCloggingWorkload, RandomMoveKeysWorkload,
    SelectorCorrectnessWorkload, SwizzleCloggingWorkload,
    VersionStampWorkload, WatchesWorkload, WriteDuringReadWorkload,
    run_spec)
