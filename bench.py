"""North-star benchmark: resolver conflict-detection throughput on device.

Mirrors the reference's in-binary microbench skipListTest()
(fdbserver/SkipList.cpp:1412-1502): batches of transactions each carrying one
read range and one write range over a shared keyspace, processed in commit
order; the metric is committed transactions per second through the conflict
engine (the resolver's hot loop, Resolver.actor.cpp:153).

Baseline: the reference ships no committed number for skipListTest (it prints
Mtransactions/s at run time; BASELINE.md). Public figures for the CPU SkipList
put it on the order of 1M txns/s on one core (the single-threaded resolver,
SkipList.cpp:42 disables the parallel path); vs_baseline is computed against
BASELINE_TXNS_PER_SEC = 1.0e6.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_TXNS_PER_SEC = 1.0e6

# skipListTest shape: 500 batches x 5000 ranges; here T txns/batch with one
# read + one write range each.
TXNS_PER_BATCH = 4096
N_BATCHES = 100
WARMUP_BATCHES = 10
KEYSPACE = 2_000_000  # contended: repeated keys across batches
PIPELINE_DEPTH = 8  # outstanding device batches (proxy-style pipelining)


def _make_batches(seed: int = 0):
    from foundationdb_tpu.ops.batch import TxnConflictInfo

    rng = np.random.RandomState(seed)
    batches = []
    version = 1_000_000
    for _ in range(N_BATCHES + WARMUP_BATCHES):
        lo = rng.randint(0, KEYSPACE, size=TXNS_PER_BATCH)
        span = rng.randint(1, 1000, size=TXNS_PER_BATCH)
        wlo = rng.randint(0, KEYSPACE, size=TXNS_PER_BATCH)
        wspan = rng.randint(1, 1000, size=TXNS_PER_BATCH)
        stale = rng.randint(0, 2_000_000, size=TXNS_PER_BATCH)
        txns = []
        for t in range(TXNS_PER_BATCH):
            rb = int(lo[t]).to_bytes(8, "big")
            re = int(lo[t] + span[t]).to_bytes(8, "big")
            wb = int(wlo[t]).to_bytes(8, "big")
            we = int(wlo[t] + wspan[t]).to_bytes(8, "big")
            txns.append(TxnConflictInfo(
                read_snapshot=version - int(stale[t]) % 900_000,
                read_ranges=[(rb, re)],
                write_ranges=[(wb, we)],
            ))
        batches.append((txns, version))
        version += 10_000
    return batches


def main():
    from foundationdb_tpu.ops.batch import COMMITTED
    from foundationdb_tpu.ops.conflict import DeviceConflictSet

    cs = DeviceConflictSet(
        capacity=1 << 15, txns=TXNS_PER_BATCH,
        reads_per_txn=1, writes_per_txn=1)
    batches = _make_batches()

    committed = 0
    for txns, version in batches[:WARMUP_BATCHES]:
        cs.detect(txns, version)

    from collections import deque
    t0 = time.perf_counter()
    total = 0
    pending: deque = deque()
    for txns, version in batches[WARMUP_BATCHES:]:
        pending.append(cs.detect_async(txns, version))
        if len(pending) >= PIPELINE_DEPTH:
            statuses = pending.popleft().result()
            total += len(statuses)
            committed += sum(1 for s in statuses if s == COMMITTED)
    while pending:
        statuses = pending.popleft().result()
        total += len(statuses)
        committed += sum(1 for s in statuses if s == COMMITTED)
    dt = time.perf_counter() - t0

    txns_per_sec = total / dt
    print(json.dumps({
        "metric": "resolver_conflict_txns_per_sec",
        "value": round(txns_per_sec, 1),
        "unit": "txns/s",
        "vs_baseline": round(txns_per_sec / BASELINE_TXNS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
