"""North-star benchmark: resolver conflict-detection throughput on device.

Mirrors the reference's in-binary microbench skipListTest()
(fdbserver/SkipList.cpp:1412-1502): batches of transactions each carrying one
read range and one write range over a 20M-key keyspace (span 1-10, the
reference's randomInt(0,20000000) / key+1+randomInt(0,10) shape), processed in
commit order with a history window holding ~8 batches (~131k txns — the
reference's window is 50 batches x 2500 txns = 125k). The metric is
transactions per second through the conflict engine.

Methodology parity: skipListTest pre-generates all test data in RAM before the
timed loop and then times addTransaction+detectConflicts per batch. Here all
batches are pre-encoded and pre-staged in device HBM (untimed), and the timed
region runs the engine itself — conflict_scan dispatches that carry the
version-history state on device across batches, with one host sync at the end.
Committed counts come back per batch; the run asserts the state never
overflowed (an overflowed/poisoned state would conflict everything and cheat
the merge cost).

Baseline: the reference ships no committed number for skipListTest and cannot
be built here (its actor compiler needs a C# toolchain, absent from this
image). The baseline is therefore MEASURED at bench time: a faithful C
implementation of the SkipList algorithm (native/skiplist_baseline.c —
level-max-annotated skiplist, 16-way interleaved queries, striped merge,
incremental GC) is compiled and run on this machine with the same workload
shape and batch size. To stay conservative, vs_baseline divides by
max(measured C txns/s, 1.0e6) — the 1.0e6 floor being the order-of-magnitude
suggested by public figures for the CPU SkipList on one core (single-
threaded: SkipList.cpp:42 disables the parallel path).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

BASELINE_FLOOR_TXNS_PER_SEC = 1.0e6


def measure_cpu_baseline(txns_per_batch: int) -> dict:
    """Compile + run the C SkipList baseline on THIS machine (same workload
    shape, same batch size, ~125k-txn history window). Returns
    {"txns_per_sec": float, ...} or {"error": str}."""
    import subprocess
    import tempfile
    src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "foundationdb_tpu", "native", "skiplist_baseline.c")
    # per-run private tempfile: a fixed predictable path in a shared tmp
    # dir could be pre-planted or raced by a concurrent bench
    fd, exe = tempfile.mkstemp(prefix="fdbtpu_skb_")
    os.close(fd)
    try:
        cc = os.environ.get("CC", "cc")
        proc = subprocess.run(
            [cc, "-O3", "-march=native", "-o", exe, src],
            capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            return {"error": proc.stderr[-500:]}
        n_batches = max(10, 1_250_000 // txns_per_batch)
        proc = subprocess.run([exe, str(txns_per_batch), str(n_batches)],
                              capture_output=True, text=True, timeout=300)
        if proc.returncode != 0:
            return {"error": proc.stderr[-500:]}
        return json.loads(proc.stdout.strip())
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"}
    finally:
        try:
            os.unlink(exe)
        except OSError:
            pass

TXNS_PER_BATCH = 16384
N_BATCHES = 200
CHUNK = 100  # batches per conflict_scan dispatch (fixed shape: compile once)
KEYSPACE = 20_000_000  # reference: randomInt(0, 20000000)
MAX_SPAN = 10  # reference: key + 1 + randomInt(0, 10)
CAPACITY = 1 << 18
KEY_BYTES = 16  # reference setK keys (SkipList.cpp:913)
WINDOW = 5_000_000  # MAX_WRITE_TRANSACTION_LIFE_VERSIONS (Knobs.cpp:30-34)
VERSION_STEP = WINDOW // 8  # ~8 batches (~131k txns) of history in the window


def _encode_batches(n_batches: int, seed: int, version0: int):
    """Vectorized batch construction mirroring the reference's setK keys
    EXACTLY (SkipList.cpp:909-922): 16-byte keys, 12 '.' bytes then the
    4-byte big-endian integer. The engine runs at key_bytes=16 (5 limbs) —
    the honest width for this workload, just as the CPU skiplist's memcmp
    cost is set by these same 16 bytes. Returns a stacked batch dict (numpy,
    leading axis n_batches) matching conflict_step's batch layout."""
    assert KEY_BYTES >= 16, "keys_to_limbs hard-codes the 16-byte setK layout"
    L = KEY_BYTES // 4 + 1  # 5
    DOT = 0x2E2E2E2E  # '....'

    T = TXNS_PER_BATCH
    rng = np.random.RandomState(seed)

    def keys_to_limbs(v):  # v: (n, T) int64 ints in [0, KEYSPACE+MAX_SPAN]
        out = np.zeros((v.shape[0], L, T), dtype=np.uint32)
        out[:, 0, :] = DOT
        out[:, 1, :] = DOT
        out[:, 2, :] = DOT
        out[:, 3, :] = v.astype(np.uint32)  # big-endian int, bytes 12..16
        out[:, L - 1, :] = 16  # every setK key is exactly 16 bytes
        return out

    n = n_batches
    rlo = rng.randint(0, KEYSPACE, size=(n, T)).astype(np.int64)
    rspan = 1 + rng.randint(0, MAX_SPAN, size=(n, T)).astype(np.int64)
    wlo = rng.randint(0, KEYSPACE, size=(n, T)).astype(np.int64)
    wspan = 1 + rng.randint(0, MAX_SPAN, size=(n, T)).astype(np.int64)

    versions = version0 + VERSION_STEP * np.arange(1, n + 1, dtype=np.int64)
    # max staleness, like the reference (read_snapshot=i, detect at i+50 with
    # newOldestVersion=i): every committed write in the window conflicts
    snapshots = (versions - WINDOW).astype(np.int32)  # (n,)

    batch = {
        "rb": keys_to_limbs(rlo),
        "re": keys_to_limbs(rlo + rspan),
        "wb": keys_to_limbs(wlo),
        "we": keys_to_limbs(wlo + wspan),
        "rtxn": np.broadcast_to(np.arange(T, dtype=np.int32), (n, T)).copy(),
        "wtxn": np.broadcast_to(np.arange(T, dtype=np.int32), (n, T)).copy(),
        "snapshot": np.broadcast_to(snapshots[:, None], (n, T)).astype(np.int32).copy(),
        "txn_valid": np.ones((n, T), dtype=bool),
        "commit_version": versions.astype(np.int32),
        "advance_floor": np.ones(n, dtype=bool),
    }
    return batch


def run_e2e(accelerator_ok: bool = True) -> dict:
    """Run the end-to-end bench for BOTH conflict backends in a SUBPROCESS,
    before this process initializes jax: the device-backend e2e gives its
    txn server the accelerator, which must not already be held here (one
    TPU client per device). Returns {"oracle": {...}, "device": {...}} or
    {"error": ...}."""
    import subprocess
    import sys
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "bench_e2e.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if not accelerator_ok:
        # the device-backend e2e still exercises the device-engine serving
        # path, on the CPU backend — reported as such
        env["FDBTPU_E2E_FORCE_CPU"] = "1"
    out = {}
    # one subprocess per backend: a hung/failed device run (e.g. the remote
    # accelerator refusing a second client) must not take the oracle
    # numbers down with it
    for backend in ("oracle", "device"):
        try:
            proc = subprocess.run(
                [sys.executable, script, backend],
                capture_output=True, text=True, timeout=1500, env=env)
            if proc.returncode != 0:
                out[backend] = {"error": proc.stderr[-600:]}
            else:
                out[backend] = json.loads(proc.stdout)
        except Exception as e:  # noqa: BLE001
            out[backend] = {"error": f"{type(e).__name__}: {e}"}
    return out


def run_kernel(T: int, n_batches: int, chunk: int,
               capacity: int | None = None) -> dict:
    """One timed kernel measurement at `T` txns/batch (see module doc)."""
    global TXNS_PER_BATCH
    import jax
    # persistent compile cache: the scan programs are large; without this
    # every bench run pays the full XLA compile again
    jax.config.update("jax_compilation_cache_dir", "/tmp/fdb_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from foundationdb_tpu.ops.conflict import (
        ConflictShapes, _compiled_scan, init_state)
    from foundationdb_tpu.utils.knobs import KNOBS

    from foundationdb_tpu.utils.jaxenv import ensure_platform_honored
    ensure_platform_honored()
    TXNS_PER_BATCH = T  # _encode_batches reads it
    # strided: 1 read + 1 write per txn, the skipListTest shape — the
    # range->txn map compiles to reshapes instead of per-eval scatters
    shapes = ConflictShapes(capacity=capacity or CAPACITY, txns=T,
                            reads=T, writes=T,
                            key_bytes=KEY_BYTES, strided=True)
    scan = _compiled_scan(shapes, KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS)

    # pre-stage everything in HBM (untimed, like skipListTest's RAM test data)
    warm_np = _encode_batches(chunk, seed=1, version0=WINDOW)
    v0 = WINDOW + chunk * VERSION_STEP
    main_np = _encode_batches(n_batches, seed=2, version0=v0)
    warm = jax.device_put(warm_np)
    chunks = []
    for c in range(0, n_batches, chunk):
        chunks.append(jax.device_put(
            {k: v[c:c + chunk] for k, v in main_np.items()}))
    state = init_state(shapes, oldest=0)

    # warmup: compiles the fixed-chunk scan and fills the window with history
    state, _stat, _comm, ovf = scan(state, warm)
    assert not bool(np.asarray(ovf).any()), "state overflow during warmup"

    t0 = time.perf_counter()
    comms, ovfs = [], []
    for ch in chunks:
        state, _statuses, comm, ovf = scan(state, ch)
        comms.append(comm)
        ovfs.append(ovf)
    comm_np = np.concatenate([np.asarray(c) for c in comms])  # the sync
    dt = time.perf_counter() - t0

    ovf_np = np.concatenate([np.asarray(o) for o in ovfs])
    assert not ovf_np.any(), "conflict state overflowed; CAPACITY too small"
    total = n_batches * T
    committed = int(comm_np.sum())

    txns_per_sec = total / dt
    cpu = measure_cpu_baseline(T)
    cpu_measured = cpu.get("txns_per_sec", 0.0)
    # vs_baseline stays the CONSERVATIVE ratio (denominator = max(measured,
    # floor)), but the two inputs are reported as their own explicit ratios:
    # on hosts where the measured C skiplist lands under the 1.0e6 floor, the
    # floor silently diluted the only number shown. baseline_source names
    # which denominator vs_baseline actually used.
    baseline = max(cpu_measured, BASELINE_FLOOR_TXNS_PER_SEC)
    return {
        "value": round(txns_per_sec, 1),
        "vs_baseline": round(txns_per_sec / baseline, 3),
        "vs_cpu_measured": (round(txns_per_sec / cpu_measured, 3)
                            if cpu_measured > 0 else None),
        "vs_floor_1e6": round(txns_per_sec / BASELINE_FLOOR_TXNS_PER_SEC, 3),
        "baseline_source": ("cpu_measured"
                            if cpu_measured >= BASELINE_FLOOR_TXNS_PER_SEC
                            else "floor_1e6"),
        "committed_frac": round(committed / total, 4),
        "batches": n_batches,
        "txns_per_batch": T,
        "baseline_txns_per_sec": round(baseline, 1),
        "baseline_cpu_measured": cpu,
    }


def run_kernel_ab(T: int, n_batches: int = 8,
                  capacity: int | None = None) -> dict:
    """A/B the intra-batch evaluator at one batch size: "legacy" (dense
    overlap matrix + unbounded while_loop fixpoint, the pre-overhaul path)
    vs "scan" (sorted per-level prefix scans, bounded sweeps). Same
    pre-staged batches, same state trajectory; reports ms/step for each and
    the reduction factor. `python bench.py --ab T [n_batches] [capacity]`."""
    global TXNS_PER_BATCH
    import jax
    jax.config.update("jax_compilation_cache_dir", "/tmp/fdb_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from foundationdb_tpu.ops.conflict import (
        ConflictShapes, _compiled_step, init_state)
    from foundationdb_tpu.utils.jaxenv import ensure_platform_honored
    from foundationdb_tpu.utils.knobs import KNOBS
    ensure_platform_honored()
    TXNS_PER_BATCH = T
    shapes = ConflictShapes(capacity=capacity or CAPACITY, txns=T,
                            reads=T, writes=T,
                            key_bytes=KEY_BYTES, strided=True)
    batches_np = _encode_batches(n_batches, seed=3, version0=WINDOW)
    staged = [jax.device_put({k: v[i] for k, v in batches_np.items()})
              for i in range(n_batches)]
    out = {"txns_per_batch": T, "batches": n_batches,
           "backend": jax.default_backend()}
    for mode in ("scan", "legacy"):
        step = _compiled_step(shapes,
                              KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS,
                              mode, 0)
        state = init_state(shapes, oldest=0)
        state, st, _info = step(state, staged[0])  # compile + window fill
        jax.block_until_ready(st)
        t0 = time.perf_counter()
        last = st
        for b in staged[1:]:
            state, last, _info = step(state, b)
        jax.block_until_ready(last)
        out[mode + "_ms_per_step"] = round(
            1e3 * (time.perf_counter() - t0) / max(1, n_batches - 1), 2)
    out["step_time_reduction"] = round(
        out["legacy_ms_per_step"] / out["scan_ms_per_step"], 2)
    return out


def _encode_spread_batches(n_batches: int, seed: int, version0: int, T: int):
    """Batches for the SHARDED engine: same workload shape as
    _encode_batches (1 read + 1 write range per txn, span 1-10, windowed
    snapshots) but with the key integer scaled into the FIRST limb. The
    sharded engine partitions on the leading 4 key bytes; setK's '....'
    prefix would land every key on shard 0 and measure nothing but the
    combine. Keys are the default 24-byte width (the only width the sharded
    step supports)."""
    from foundationdb_tpu.utils import keys as keylib
    L = keylib.NUM_LIMBS
    DOT = 0x2E2E2E2E  # '....'
    # multiply preserves order, spreads [0, KEYSPACE+MAX_SPAN] across uint32
    scale = (1 << 32) // (KEYSPACE + MAX_SPAN + 1)
    rng = np.random.RandomState(seed)

    def keys_to_limbs(v):  # v: (n, T) int64 ints in [0, KEYSPACE+MAX_SPAN]
        out = np.zeros((v.shape[0], L, T), dtype=np.uint32)
        out[:, 0, :] = (v * scale).astype(np.uint32)
        for limb in range(1, L - 1):
            out[:, limb, :] = DOT
        out[:, L - 1, :] = keylib.KEY_BYTES
        return out

    n = n_batches
    rlo = rng.randint(0, KEYSPACE, size=(n, T)).astype(np.int64)
    rspan = 1 + rng.randint(0, MAX_SPAN, size=(n, T)).astype(np.int64)
    wlo = rng.randint(0, KEYSPACE, size=(n, T)).astype(np.int64)
    wspan = 1 + rng.randint(0, MAX_SPAN, size=(n, T)).astype(np.int64)
    versions = version0 + VERSION_STEP * np.arange(1, n + 1, dtype=np.int64)
    snapshots = (versions - WINDOW).astype(np.int32)
    return {
        "rb": keys_to_limbs(rlo),
        "re": keys_to_limbs(rlo + rspan),
        "wb": keys_to_limbs(wlo),
        "we": keys_to_limbs(wlo + wspan),
        "rtxn": np.broadcast_to(np.arange(T, dtype=np.int32), (n, T)).copy(),
        "wtxn": np.broadcast_to(np.arange(T, dtype=np.int32), (n, T)).copy(),
        "snapshot": np.broadcast_to(
            snapshots[:, None], (n, T)).astype(np.int32).copy(),
        "txn_valid": np.ones((n, T), dtype=bool),
        "commit_version": versions.astype(np.int32),
        "advance_floor": np.ones(n, dtype=bool),
    }


def run_sharded_kernel(T: int, n_batches: int, n_devices: int,
                       capacity: int | None = None) -> dict:
    """Kernel-scaling measurement: the sharded SPMD conflict step over an
    `n_devices`-wide mesh, per-batch dispatch with one host sync at the end
    (same methodology as run_kernel, minus the chunked scan — the sharded
    step is one batch per dispatch, as served by the resolver)."""
    import jax
    jax.config.update("jax_compilation_cache_dir", "/tmp/fdb_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from foundationdb_tpu.ops.conflict import ConflictShapes
    from foundationdb_tpu.parallel.sharded_conflict import (
        init_sharded_state, make_resolver_mesh, sharded_conflict_step)
    from foundationdb_tpu.utils import keys as keylib
    from foundationdb_tpu.utils.jaxenv import ensure_platform_honored
    from foundationdb_tpu.utils.knobs import KNOBS
    ensure_platform_honored()
    avail = len(jax.devices())
    if n_devices > avail:
        return {"error": f"{n_devices} devices requested, {avail} attached",
                "n_devices": n_devices}
    shapes = ConflictShapes(capacity=capacity or CAPACITY, txns=T,
                            reads=T, writes=T,
                            key_bytes=keylib.KEY_BYTES, strided=True)
    mesh = make_resolver_mesh(n_devices)
    # full sandwich rounds, like ShardedDeviceConflictSet: the early-out
    # cond makes unused rounds ~free once the bounds pinch
    step = sharded_conflict_step(mesh, shapes,
                                 KNOBS.MAX_WRITE_TRANSACTION_LIFE_VERSIONS,
                                 "scan", T // 2 + 1)
    warm_np = _encode_spread_batches(1, seed=1, version0=WINDOW, T=T)
    main_np = _encode_spread_batches(
        n_batches, seed=2, version0=WINDOW + VERSION_STEP, T=T)
    warm = jax.device_put({k: v[0] for k, v in warm_np.items()})
    staged = [jax.device_put({k: v[i] for k, v in main_np.items()})
              for i in range(n_batches)]
    state = init_sharded_state(shapes, n_devices, oldest=0, mesh=mesh)

    state, st, info = step(state, warm)  # compile + first window fill
    jax.block_until_ready(st)
    t0 = time.perf_counter()
    comms, ovfs = [], []
    for b in staged:
        state, st, info = step(state, b)
        comms.append(info["committed"])
        ovfs.append(info["overflow"])
    comm_np = np.array([np.asarray(c) for c in comms])  # the sync
    dt = time.perf_counter() - t0
    assert not any(bool(np.asarray(o).any()) for o in ovfs), \
        "conflict state overflowed; capacity too small"
    total = n_batches * T
    return {
        "n_devices": n_devices,
        "value": round(total / dt, 1),
        "ms_per_batch": round(1e3 * dt / n_batches, 2),
        "committed_frac": round(int(comm_np.sum()) / total, 4),
        "txns_per_batch": T,
        "batches": n_batches,
        "backend": jax.default_backend(),
    }


def run_devices_sweep(counts=(1, 2, 4, 8), T: int = 512,
                      n_batches: int = 8, capacity: int = 1 << 14,
                      accelerator_ok: bool = False,
                      timeout: float = 900.0) -> dict:
    """`--devices` sweep: one SUBPROCESS per device count (a jax client pins
    its device view at init, so each count needs a fresh process). Without an
    accelerator the counts are forced host-platform CPU devices
    (--xla_force_host_platform_device_count): that validates the SPMD path
    and decision parity at every width, but all "devices" share the same
    cores — wall-clock scaling is NOT expected there and the rows say so."""
    import subprocess
    import sys
    script = os.path.abspath(__file__)
    rows = []
    base = None
    for n in counts:
        env = dict(os.environ)
        if not accelerator_ok:
            env["JAX_PLATFORMS"] = "cpu"
            flags = [f for f in env.get("XLA_FLAGS", "").split()
                     if not f.startswith(
                         "--xla_force_host_platform_device_count")]
            flags.append(f"--xla_force_host_platform_device_count={n}")
            env["XLA_FLAGS"] = " ".join(flags)
            env.pop("PALLAS_AXON_POOL_IPS", None)
        cmd = [sys.executable, script, "--sharded-kernel", str(T),
               str(n_batches), str(n), str(capacity)]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout, env=env)
            if proc.returncode == 0:
                row = json.loads(proc.stdout.strip().splitlines()[-1])
            else:
                row = {"n_devices": n, "error": proc.stderr[-400:]}
        except Exception as e:  # noqa: BLE001
            row = {"n_devices": n, "error": f"{type(e).__name__}: {e}"}
        if row.get("value"):
            if base is None:
                base = row
            row["speedup_vs_1dev"] = round(row["value"] / base["value"], 3)
            row["per_device_efficiency"] = round(
                row["value"] / (n * base["value"]), 3)
            if base.get("committed_frac"):
                row["committed_frac_parity"] = round(
                    row["committed_frac"] / base["committed_frac"], 4)
        rows.append(row)
    return {
        "txns_per_batch": T,
        "batches": n_batches,
        "capacity": capacity,
        "cpu_host_devices": not accelerator_ok,
        "rows": rows,
    }


def probe_accelerator(timeout: float = 180.0) -> bool:
    """Can a fresh process attach the accelerator at all? A wedged remote
    runtime hangs the attach indefinitely; probing once in a throwaway
    subprocess (utils/jaxenv.probe_backend — shared with the resolver's
    bounded discovery) lets every later stage choose CPU up front instead
    of each burning its own watchdog."""
    from foundationdb_tpu.utils.jaxenv import probe_backend
    ok, _backend = probe_backend(timeout)
    return ok


def run_kernel_watchdogged(T: int, n_batches: int, chunk: int,
                           timeout: float = 900.0,
                           accelerator_ok: bool = True) -> dict:
    """run_kernel in a SUBPROCESS with a deadline, falling back to the CPU
    backend on failure: a wedged remote accelerator runtime (or a hung
    attach) must degrade the measurement, never hang or sink the bench."""
    import subprocess
    import sys
    script = os.path.abspath(__file__)
    attempts = (({}, "default"), ({"JAX_PLATFORMS": "cpu"}, "cpu-fallback"))
    if not accelerator_ok:
        attempts = (({"JAX_PLATFORMS": "cpu"}, "cpu-fallback"),)
    for env_extra, label in attempts:
        env = dict(os.environ, **env_extra)
        kT, kn, kc = T, n_batches, chunk
        if label == "cpu-fallback":
            # an emergency measurement, not the headline: the full-size scan
            # (2^18-capacity sorts x hundreds of batches) is hopeless on one
            # CPU core — shrink to something that finishes and mark it
            kT, kn, kc = min(T, 512), 10, 5
        try:
            cmd = [sys.executable, script, "--kernel", str(kT),
                   str(kn), str(kc)]
            if label == "cpu-fallback":
                cmd.append(str(1 << 14))  # capacity shrinks with the load
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout, env=env)
            if proc.returncode == 0:
                out = json.loads(proc.stdout.strip().splitlines()[-1])
                if label != "default":
                    out["backend_fallback"] = label
                    if (kT, kn, kc) != (T, n_batches, chunk):
                        out["scaled_down_from"] = {"txns_per_batch": T,
                                                   "batches": n_batches}
                return out
            err = proc.stderr[-500:]
        except Exception as e:  # noqa: BLE001
            err = f"{type(e).__name__}: {e}"
    return {"error": err, "value": 0.0, "vs_baseline": 0.0,
            "txns_per_batch": T}


def main():
    acc_ok = probe_accelerator()
    # e2e FIRST (and in subprocesses): the parent must not hold the TPU yet
    e2e = None
    if os.environ.get("FDB_TPU_BENCH_E2E", "1") != "0":
        e2e = run_e2e(acc_ok)

    r16 = run_kernel_watchdogged(16384, N_BATCHES, CHUNK,
                                 accelerator_ok=acc_ok)
    # the 32768-point (round-3 gate: >= 1.5x at the doubled batch size)
    r32 = run_kernel_watchdogged(32768, 100, 50, accelerator_ok=acc_ok)
    # sharded-engine device-count scaling (subprocess per count; CPU
    # host-platform devices when the accelerator is unavailable)
    sweep = run_devices_sweep(accelerator_ok=acc_ok)
    out = {
        "metric": "resolver_conflict_txns_per_sec",
        "unit": "txns/s",
        **r16,
        "batch_32768": r32,
        "kernel_scaling": sweep,
    }
    if not acc_ok:
        out["accelerator_unavailable"] = True
    # end-to-end pipeline numbers (real TCP transport, separate server
    # processes, concurrent multi-process clients — BASELINE.md methodology
    # at a saturating concurrency; ran before the kernel bench, see
    # run_e2e). Both conflict backends are reported: "device" serves live
    # commits through the TPU engine, "oracle" through the host engine.
    if e2e is not None:
        out["e2e"] = e2e
    print(json.dumps(out))


if __name__ == "__main__":
    import sys
    if len(sys.argv) >= 5 and sys.argv[1] == "--kernel":
        cap = int(sys.argv[5]) if len(sys.argv) > 5 else None
        print(json.dumps(run_kernel(int(sys.argv[2]), int(sys.argv[3]),
                                    int(sys.argv[4]), capacity=cap)))
        sys.exit(0)
    if len(sys.argv) >= 5 and sys.argv[1] == "--sharded-kernel":
        cap = int(sys.argv[5]) if len(sys.argv) > 5 else None
        print(json.dumps(run_sharded_kernel(
            int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
            capacity=cap)))
        sys.exit(0)
    if len(sys.argv) >= 2 and sys.argv[1] == "--devices":
        counts = tuple(int(x) for x in sys.argv[2:]) or (1, 2, 4, 8)
        print(json.dumps(run_devices_sweep(
            counts, accelerator_ok=probe_accelerator())))
        sys.exit(0)
    if len(sys.argv) >= 3 and sys.argv[1] == "--ab":
        nb = int(sys.argv[3]) if len(sys.argv) > 3 else 8
        cap = int(sys.argv[4]) if len(sys.argv) > 4 else None
        print(json.dumps(run_kernel_ab(int(sys.argv[2]), n_batches=nb,
                                       capacity=cap)))
        sys.exit(0)
    main()
