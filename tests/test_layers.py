"""Tuple/subspace/directory layers, status JSON, fdbcli, counters.

Reference: bindings/python/fdb/tuple.py + design/tuple.md (order-preserving
tuple format), subspace_impl.py, directory_impl.py,
fdbserver/Status.actor.cpp clusterGetStatus, fdbcli/fdbcli.actor.cpp,
flow/Stats.h Counter/CounterCollection.
"""

import pytest

from foundationdb_tpu.layers import tuple as T
from foundationdb_tpu.layers.directory import DirectoryLayer
from foundationdb_tpu.layers.subspace import Subspace
from foundationdb_tpu.server.cluster import RecoverableCluster, SimCluster
from foundationdb_tpu.tools.fdbcli import FdbCli
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom
from foundationdb_tpu.utils.stats import Counter, CounterCollection


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


# -- tuple layer --

def test_tuple_roundtrip():
    cases = [
        (),
        (None,),
        (b"bytes", "string", 0, 1, -1, 255, -255, 65536, -65536,
         (1 << 60), -(1 << 60)),
        (3.14, -3.14, 0.0, float("inf"), float("-inf")),
        (True, False),
        (("nested", (None, b"\x00deep\x00")), "after"),
        (b"\x00\x01\xff", "uniécode"),
    ]
    for t in cases:
        assert T.unpack(T.pack(t)) == t, t


def test_tuple_order_preserving():
    """Packed byte order must equal logical element order — the property
    every layer's range scans rest on."""
    rng = DeterministicRandom(5)

    def rand_elem(depth=0):
        k = rng.randint(0, 5 if depth else 6)
        if k == 0:
            return None
        if k == 1:
            return rng.random_bytes(rng.randint(0, 6))
        if k == 2:
            return "".join(chr(97 + rng.randint(0, 25))
                           for _ in range(rng.randint(0, 5)))
        if k == 3:
            return rng.randint(-(1 << 40), 1 << 40)
        if k == 4:
            return rng.random() * 2000 - 1000
        if k == 5:
            return bool(rng.coinflip())
        return tuple(rand_elem(depth + 1) for _ in range(rng.randint(0, 3)))

    def type_rank(e):
        # the format's cross-type order (by type code): null(0x00) <
        # bytes(0x01) < str(0x02) < nested(0x05) < int(0x0c..) <
        # double(0x21) < false(0x26) < true(0x27)
        if e is None:
            return 0
        if isinstance(e, bytes):
            return 1
        if isinstance(e, str):
            return 2
        if isinstance(e, tuple):
            return 3
        if isinstance(e, bool):
            return 6
        if isinstance(e, int):
            return 4
        return 5

    def logical_key(t):
        return tuple((type_rank(e),
                      logical_key(e) if isinstance(e, tuple)
                      else (e if not isinstance(e, bool) else int(e)))
                     for e in t)

    tuples = [tuple(rand_elem() for _ in range(rng.randint(0, 3)))
              for _ in range(300)]
    by_packed = sorted(tuples, key=lambda t: T.pack(t))
    by_logic = sorted(tuples, key=logical_key)
    assert [T.pack(t) for t in by_packed] == [T.pack(t) for t in by_logic]


def test_tuple_range():
    lo, hi = T.range_of(("users",))
    assert lo < T.pack(("users", 1)) < hi
    assert lo < T.pack(("users", "zz", "deep")) < hi
    assert not (lo < T.pack(("userz",)) < hi)


# -- subspace --

def test_subspace():
    users = Subspace(("app", "users"))
    k = users.pack((42, "bob"))
    assert users.contains(k)
    assert users.unpack(k) == (42, "bob")
    sub = users[42]
    assert sub.contains(users.pack((42, "x")))
    lo, hi = users.range()
    assert lo < k < hi
    with pytest.raises(ValueError):
        users.unpack(b"not-in-subspace")


# -- directory --

def test_directory_layer():
    c = SimCluster(seed=9)
    db = c.database()
    dl = DirectoryLayer()

    async def t():
        async def mk(tr):
            d = await dl.create_or_open(tr, ("app", "events"))
            tr.set(d.pack((1,)), b"first")
            return d
        d = await db.transact(mk)

        async def reopen(tr):
            return await dl.create_or_open(tr, ("app", "events"))
        d2 = await db.transact(reopen)
        assert d2.key == d.key, "reopen must return the same prefix"

        async def read(tr):
            return await tr.get(d.pack((1,)))
        assert await db.transact(read) == b"first"

        async def other(tr):
            return await dl.create_or_open(tr, ("app", "users"))
        d3 = await db.transact(other)
        assert d3.key != d.key

        async def ls(tr):
            return await dl.list(tr, ("app",))
        assert sorted(await db.transact(ls)) == ["events", "users"]

        async def rm(tr):
            return await dl.remove(tr, ("app", "events"))
        assert await db.transact(rm)
        async def gone(tr):
            return (await dl.open(tr, ("app", "events")),
                    await tr.get(d.pack((1,))))
        node, val = await db.transact(gone)
        assert node is None and val is None

    c.run(c.loop.spawn(t()), max_time=10_000.0)


# -- counters --

def test_counters():
    cc = CounterCollection("ProxyStats", "proxy:0")
    commits = cc.counter("Commits")
    commits += 5
    conflicts = Counter("Conflicts", cc)
    conflicts.increment(2)
    assert cc.as_dict() == {"Commits": 5, "Conflicts": 2}
    cc.trace(now=10.0)
    commits += 5
    cc.trace(now=12.0)  # rate = 5/2
    assert commits.rate_since_dump(2.0) == 0.0  # just dumped


# -- status + fdbcli --

def test_status_and_fdbcli():
    c = RecoverableCluster(seed=77, n_workers=4, n_proxies=2, n_tlogs=2,
                           n_storage=2)
    db = c.database()

    async def boot():
        await db.refresh()
    c.run(c.loop.spawn(boot()), max_time=60_000.0)

    cli = FdbCli(c, db)
    assert any("ERROR: writemode" in line for line in cli.execute("set a 1"))
    cli.execute("writemode on")
    assert cli.execute("set hello world") == ["Committed"]
    assert cli.execute("get hello") == ["`hello' is `world'"]
    cli.execute("set hellp x")
    out = cli.execute("getrange hell hellz 10")
    assert "`hello' is `world'" in out[1]
    assert any("hellp" in line for line in out)
    cli.execute("clear hellp")
    assert cli.execute("get hellp") == ["`hellp': not found"]

    out = cli.execute("status")
    assert any("accepting_commits" in line for line in out)
    assert any("Storage servers - 2" in line for line in out)

    async def status_json():
        return await db.get_status()
    status = c.run(c.loop.spawn(status_json()), max_time=60_000.0)
    cl = status["cluster"]
    assert cl["recovery_state"]["name"] == "accepting_commits"
    assert len(cl["layers"]["proxies"]) == 2
    assert len(cl["layers"]["storages"]) == 2
    assert "transactions_per_second_limit" in cl["qos"]


class TestRecipes:
    """Layer recipes (design-recipes docs): counters, queues, secondary
    indexes as plain transactions over subspaces."""

    def _cluster(self):
        from foundationdb_tpu.server.cluster import SimCluster
        from foundationdb_tpu.utils.knobs import KNOBS
        KNOBS.set("CONFLICT_BACKEND", "oracle")
        c = SimCluster(seed=33)
        return c, c.database()

    def test_counter_concurrent_adds_never_conflict(self):
        from foundationdb_tpu.layers.recipes import Counter
        from foundationdb_tpu.layers.subspace import Subspace
        c, db = self._cluster()
        ctr = Counter(Subspace(("ctr",)))

        async def one(delta):
            async def fn(tr):
                ctr.add(tr, delta)
            await db.transact(fn)

        async def t():
            from foundationdb_tpu.core.future import all_of
            await all_of([c.loop.spawn(one(i + 1), name=f"a{i}")
                          for i in range(20)])
            async def rd(tr):
                return await ctr.value(tr)
            assert await db.transact(rd) == sum(range(1, 21))
        c.run(c.loop.spawn(t()), max_time=600.0)

    def test_queue_fifo_under_concurrent_pushers(self):
        from foundationdb_tpu.layers.recipes import Queue
        from foundationdb_tpu.layers.subspace import Subspace
        c, db = self._cluster()
        q = Queue(Subspace(("q",)))

        async def t():
            for i in range(6):
                async def push(tr, i=i):
                    q.push(tr, b"item%d" % i)
                await db.transact(push)
            # FIFO: versionstamped keys order by commit version
            got = []
            for _ in range(6):
                async def pop(tr):
                    return await q.pop(tr)
                got.append(await db.transact(pop))
            assert got == [b"item%d" % i for i in range(6)]
            async def empty(tr):
                return await q.pop(tr)
            assert await db.transact(empty) is None
        c.run(c.loop.spawn(t()), max_time=600.0)

    def test_index_stays_consistent_through_updates(self):
        from foundationdb_tpu.layers.recipes import Index
        from foundationdb_tpu.layers.subspace import Subspace
        c, db = self._cluster()
        ix = Index(Subspace(("rows",)), Subspace(("by_city",)))

        async def t():
            async def w1(tr):
                await ix.set(tr, "alice", b"a-data", "tokyo")
                await ix.set(tr, "bob", b"b-data", "paris")
                await ix.set(tr, "carol", b"c-data", "tokyo")
            await db.transact(w1)
            async def q1(tr):
                return await ix.query(tr, "tokyo")
            assert sorted(await db.transact(q1)) == ["alice", "carol"]
            # moving alice to paris atomically updates row + both entries
            async def w2(tr):
                await ix.set(tr, "alice", b"a2", "paris")
            await db.transact(w2)
            async def q2(tr):
                return (await ix.query(tr, "tokyo"),
                        sorted(await ix.query(tr, "paris")),
                        await ix.get(tr, "alice"))
            tokyo, paris, alice = await db.transact(q2)
            assert tokyo == ["carol"]
            assert paris == ["alice", "bob"]
            assert alice == b"a2"
        c.run(c.loop.spawn(t()), max_time=600.0)
