"""CHANGES.md row-alignment gate (scripts/changes_check.py): the newest
`PR <n>:` row must match the `# ISSUE <n>` header — run here so tier-1
fails a PR that forgot (or placeholder-backfilled) its CHANGES row."""

import importlib.util
import os

_SCRIPT = os.path.join(os.path.dirname(__file__), "..", "scripts",
                       "changes_check.py")
_spec = importlib.util.spec_from_file_location("changes_check", _SCRIPT)
changes_check = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(changes_check)


def test_parsers():
    assert changes_check.issue_number("# ISSUE 16 · [x] title\n") == 16
    assert changes_check.issue_number("no header") is None
    text = "PR 1: a\nPR 2: b\nsome prose\nPR 10: c\n"
    assert changes_check.newest_changes_row(text) == 10
    assert changes_check.newest_changes_row("prose only") is None


def test_misaligned_rows_fail(tmp_path):
    issue = tmp_path / "ISSUE.md"
    changes = tmp_path / "CHANGES.md"
    issue.write_text("# ISSUE 16 · title\n")
    changes.write_text("PR 15: old row\n")
    assert changes_check.main([str(issue), str(changes)]) == 1
    changes.write_text("PR 15: old row\nPR 16: this PR\n")
    assert changes_check.main([str(issue), str(changes)]) == 0
    # no ISSUE.md (post-merge checkouts): nothing to align, pass
    assert changes_check.main([str(tmp_path / "gone.md"),
                               str(changes)]) == 0


def test_live_repo_rows_are_aligned():
    assert changes_check.main([]) == 0
