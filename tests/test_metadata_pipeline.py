"""Metadata through the commit pipeline: \\xff system keyspace, txnStateStore
on proxies, state transactions resolved by all resolvers and applied by every
proxy in version order.

Reference: MasterProxyServer.actor.cpp:452-489,540 (state-mutation apply),
ResolutionRequestBuilder :307-311 (state txns to all resolvers),
Resolver.actor.cpp:170-224 (retained state txns), ApplyMetadataMutation.h,
SystemData.cpp.
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.server import systemdata
from foundationdb_tpu.server.cluster import SimCluster
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.types import Mutation, MutationType


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield
    KNOBS.reset()


def test_txn_state_store_semantics():
    s = systemdata.TxnStateStore([(b"\xff/a", b"1"), (b"\xff/c", b"3")])
    s.apply(Mutation(MutationType.SET_VALUE, b"\xff/b", b"2"))
    assert [k for k, _ in s.snapshot()] == [b"\xff/a", b"\xff/b", b"\xff/c"]
    s.apply(Mutation(MutationType.CLEAR_RANGE, b"\xff/a", b"\xff/b\x00"))
    assert s.snapshot() == [(b"\xff/c", b"3")]


def test_keyservers_codec_roundtrip():
    b = [b"", b"\x40", b"\x80"]
    t = [[0, 1], [2], [0, 3]]
    snap = systemdata.build_keyservers_snapshot(b, t)
    b2, t2 = systemdata.parse_keyservers(snap)
    assert (b2, t2) == (b, t)


def test_metadata_txn_propagates_to_all_proxies():
    """A \\xff/keyServers mutation committed through proxy A must reach
    proxy B's txnStateStore (via the resolver's retained state txns) and
    update B's routing map — in version order, before B routes any later
    batch."""
    c = SimCluster(seed=3, n_proxies=2, n_resolvers=2, n_tlogs=1, n_storage=2)
    db = c.database()

    async def t():
        pa, pb = c.proxies[0], c.proxies[1]
        # both proxies start with the same derived map
        assert pa.shards.boundaries == pb.shards.boundaries

        # commit a metadata txn through proxy A only: add boundary 0x60
        # with the (already valid) tag of the shard it splits
        old_tags = pa.shards.tags_for_key(b"\x60")
        tr = db.create_transaction()
        tr.set(systemdata.keyservers_key(b"\x60"),
               systemdata.encode_tags(old_tags))
        await tr.commit()
        v_meta = tr.committed_version
        assert b"\x60" in pa.shards.boundaries  # A applied its own batch

        # drive ONE batch through proxy B explicitly: B must apply A's state
        # mutation (from the resolver's retained window) BEFORE routing it
        from foundationdb_tpu.core.sim import Endpoint
        from foundationdb_tpu.server.interfaces import (
            CommitTransactionRequest, Token)
        client = c.net.processes["client:0"]
        await c.net.request(
            client, Endpoint(pb.process.address, Token.PROXY_COMMIT),
            CommitTransactionRequest(
                read_snapshot=v_meta, read_conflict_ranges=[],
                write_conflict_ranges=[(b"user-key", b"user-key\x00")],
                mutations=[Mutation(MutationType.SET_VALUE, b"user-key",
                                    b"v")]))
        assert b"\x60" in pb.shards.boundaries, "state txn never reached B"
        assert pb.txn_state_version >= v_meta

        # the metadata row is ALSO stored like normal data: readable
        tr4 = db.create_transaction()
        got = await tr4.get(systemdata.keyservers_key(b"\x60"))
        assert got == systemdata.encode_tags(old_tags)

    c.run(c.loop.spawn(t()), max_time=600.0)


def test_metadata_txn_conflict_detection():
    """Metadata txns are conflict-checked like any other: two txns writing
    the same \\xff key from the same snapshot -> second conflicts."""
    c = SimCluster(seed=4, n_proxies=1, n_resolvers=2, n_tlogs=1, n_storage=1)
    db = c.database()

    async def t():
        k = systemdata.keyservers_key(b"\x70")
        tr1 = db.create_transaction()
        tr2 = db.create_transaction()
        v1 = await tr1.get(k)
        v2 = await tr2.get(k)
        assert v1 is None and v2 is None
        tr1.set(k, systemdata.encode_tags([0]))
        tr2.set(k, systemdata.encode_tags([0]))
        await tr1.commit()
        from foundationdb_tpu.utils.errors import FDBError
        with pytest.raises(FDBError) as ei:
            await tr2.commit()
        assert ei.value.name == "not_committed"

    c.run(c.loop.spawn(t()), max_time=600.0)
