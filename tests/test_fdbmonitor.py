"""fdbmonitor: spawn, restart-with-backoff, conf hot-reload
(fdbmonitor/fdbmonitor.cpp behaviors, driven against real OS processes).
"""

from __future__ import annotations

import json
import os
import sys
import time

from foundationdb_tpu.tools.fdbmonitor import FdbMonitor


def _write_conf(path, sections, restart_delay=0.2):
    lines = ["[general]", f"restart_delay = {restart_delay}",
             "restart_delay_reset = 5"]
    for name, spec in sections.items():
        lines += [f"[server.{name}]", f"spec = {spec}"]
    path.write_text("\n".join(lines) + "\n")


def _spec_file(tmp_path, name, port, exit_after=None):
    """A server_main-shaped spec; server_main with no roles just listens."""
    spec = {"listen": f"127.0.0.1:{port}", "data_dir": str(tmp_path / name),
            "knobs": {}, "roles": []}
    p = tmp_path / f"{name}.json"
    p.write_text(json.dumps(spec))
    return str(p)


def test_monitor_starts_restarts_and_reloads(tmp_path):
    import socket

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    conf = tmp_path / "monitor.conf"
    _write_conf(conf, {"a": _spec_file(tmp_path, "a", free_port())})
    mon = FdbMonitor(str(conf), out=open(os.devnull, "w"))
    try:
        # start
        mon.poll_once()
        c = mon.children["server.a"]
        assert c.proc is not None and c.proc.poll() is None

        # kill it -> restart scheduled with backoff, then restarted
        c.proc.kill()
        c.proc.wait()
        mon.poll_once()
        assert c.proc is None and c.backoff > 0
        deadline = time.time() + 10
        while time.time() < deadline and c.proc is None:
            time.sleep(0.1)
            mon.poll_once()
        assert c.proc is not None and c.proc.poll() is None, "never restarted"

        # conf reload: add a second server, drop the first
        time.sleep(0.05)
        _write_conf(conf, {"b": _spec_file(tmp_path, "b", free_port())})
        os.utime(conf)  # ensure mtime moves even on coarse filesystems
        mon.poll_once()
        assert "server.a" not in mon.children
        assert "server.b" in mon.children
        deadline = time.time() + 10
        b = mon.children["server.b"]
        while time.time() < deadline and b.proc is None:
            time.sleep(0.1)
            mon.poll_once()
        assert b.proc is not None and b.proc.poll() is None
    finally:
        for c in list(mon.children.values()):
            mon.stop_child(c)
