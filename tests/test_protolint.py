"""protolint: the PROTO rule family's own tests + tier-1 enforcement.

Mirrors test_devlint.py's three layers:
  1. Per-rule good/bad snippet fixtures for PROTO001..PROTO008.
  2. Regressions against the PRE-fix shapes of the real violations this PR
     fixed (the resolver/tlog/storage fence-await that dies with its reply
     unsettled, the clustercontroller cancel re-raise, the dead
     MASTER_GET_CURRENT_VERSION handler) — the linter must catch each one
     as it was actually written.
  3. Enforcement: the proto family over the full default target set must
     be clean against the committed baseline, and the Python<->C schema
     parity gate must trip when a field is added to only one side
     (demonstrated by mutating a copy of either registry).

The token census itself is also asserted here (uniqueness + density):
token ints share one per-process routing namespace, so a duplicate
silently routes frames to whichever handler registered last.
"""

from __future__ import annotations

import dataclasses
import os
import textwrap

from foundationdb_tpu.analysis import flowlint, protolint
from foundationdb_tpu.analysis.__main__ import main as flowlint_main

SERVER_PATH = "foundationdb_tpu/server/snippet.py"
CLIENT_PATH = "foundationdb_tpu/client/snippet.py"


def lint(source: str, path: str = SERVER_PATH):
    """Run only the proto family so flow/dev findings can't muddy
    assertions."""
    return flowlint.analyze_source(textwrap.dedent(source), path,
                                   flowlint.active_rules("proto"))


def only(findings, code: str):
    return [f for f in findings if f.rule == code]


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------- PROTO001

def test_proto001_flags_duplicate_token_ints():
    findings = only(lint("""
        class Token:
            PING = 1
            PONG = 1

        class Role:
            def start(self, net):
                net.register(Token.PING, self._on)
                net.register(Token.PONG, self._on)

            def _on(self, req, reply):
                reply.send(1)

        class Client:
            async def call(self, net, addr):
                a = await net.request(net.process, Endpoint(addr, Token.PING), 1)
                b = await net.request(net.process, Endpoint(addr, Token.PONG), 1)
                return a + b
    """), "PROTO001")
    assert [f.detail for f in findings] == ["Token.PONG"]
    assert "routes frames" in findings[0].message


def test_proto001_flags_sent_but_never_registered():
    findings = only(lint("""
        class Token:
            PING = 1

        class Client:
            async def call(self, net, addr):
                return await net.request(
                    net.process, Endpoint(addr, Token.PING), 1)
    """), "PROTO001")
    assert [f.detail for f in findings] == ["Token.PING"]
    assert "broken_promise" in findings[0].message


def test_proto001_flags_registered_but_unreachable():
    # the pre-fix shape of master's MASTER_GET_CURRENT_VERSION: a handler
    # registered for a token no send site (direct or indirect) can reach
    findings = only(lint("""
        class Token:
            GET_VERSION = 4

        class Master:
            def start(self, net):
                net.register(Token.GET_VERSION, self._on_get_version)

            def _on_get_version(self, req, reply):
                reply.send(self.version)
    """), "PROTO001")
    assert [f.detail for f in findings] == ["Token.GET_VERSION"]
    assert "dead handler" in findings[0].message


def test_proto001_flags_declared_dead_token():
    findings = only(lint("""
        class Token:
            NEVER_USED = 77
    """), "PROTO001")
    assert [f.detail for f in findings] == ["Token.NEVER_USED"]
    assert "dead protocol surface" in findings[0].message


def test_proto001_indirect_token_ref_counts_as_reachable():
    # Token.PING never appears inside an Endpoint ctor, but it is passed
    # through a helper that picks the destination (the real client's
    # _pick_proxy(Token.PROXY_COMMIT) pattern) — must stay quiet
    findings = only(lint("""
        class Token:
            PING = 1

        class Role:
            def start(self, net):
                net.register(Token.PING, self._on)

            def _on(self, req, reply):
                reply.send(1)

        class Client:
            async def call(self):
                return await self._pick_proxy(Token.PING, 1)
    """), "PROTO001")
    assert findings == []


# ---------------------------------------------------------------- PROTO002

def test_proto002_flags_early_return_without_settle():
    findings = only(lint("""
        class Token:
            GO = 1

        class Role:
            def start(self, net):
                net.register(Token.GO, self._go)

            def _go(self, req, reply):
                if req.locked:
                    return
                reply.send(1)
    """), "PROTO002")
    assert [f.detail for f in findings] == ["return-unsettled"]


def test_proto002_flags_unguarded_await_in_spawned_coroutine():
    """The pre-fix resolver/tlog/storage shape: the handler spawns a
    delegate, and the delegate's fence-await (when_at_least) can raise or
    be cancelled while the reply is unsettled — the transport only answers
    raises from SYNC handlers, so the caller wedges until RPC timeout."""
    findings = only(lint("""
        class Token:
            RESOLVE = 1

        class Resolver:
            def start(self, net):
                net.register(Token.RESOLVE, self._on_resolve)

            def _on_resolve(self, req, reply):
                self.loop.spawn(self._resolve_batch(req, reply))

            async def _resolve_batch(self, req, reply):
                await self.version.when_at_least(req.prev_version)
                reply.send(self.resolve(req))
    """), "PROTO002")
    assert [f.detail for f in findings] == ["raise-unsettled"]
    assert findings[0].symbol.endswith("_resolve_batch")


def test_proto002_settling_try_makes_the_await_quiet():
    # the post-fix shape: try/except FDBError -> send_error + re-raise
    findings = only(lint("""
        class Token:
            RESOLVE = 1

        class Resolver:
            def start(self, net):
                net.register(Token.RESOLVE, self._on_resolve)

            def _on_resolve(self, req, reply):
                self.loop.spawn(self._resolve_batch(req, reply))

            async def _resolve_batch(self, req, reply):
                try:
                    await self.version.when_at_least(req.prev_version)
                except FDBError as e:
                    reply.send_error(e)
                    raise
                reply.send(self.resolve(req))
    """), "PROTO002")
    assert findings == []


def test_proto002_sync_handler_raise_is_quiet():
    # raises from a synchronous handler are answered by the transport
    # (unknown_error) — only spawned-coroutine raises wedge the caller
    findings = only(lint("""
        class Token:
            GO = 1

        class Role:
            def start(self, net):
                net.register(Token.GO, self._go)

            def _go(self, req, reply):
                if req.bad:
                    raise ValueError("nope")
                reply.send(1)
    """), "PROTO002")
    assert findings == []


def test_proto002_interprocedural_three_hops():
    # handler -> spawn -> delegate -> helper; the helper falls off the end
    # with the reply unsettled on one branch, three calls from the register
    findings = only(lint("""
        class Token:
            GO = 1

        class Role:
            def start(self, net):
                net.register(Token.GO, self._go)

            def _go(self, req, reply):
                self.loop.spawn(self._work(req, reply))

            async def _work(self, req, reply):
                await self._finish(req, reply)

            async def _finish(self, req, reply):
                if req.ok:
                    reply.send(1)
    """), "PROTO002")
    assert [f.detail for f in findings] == ["fall-unsettled"]
    assert findings[0].symbol.endswith("_finish")


def test_proto002_prefix_cc_cancel_reraise():
    """Pre-fix ClusterController._get_status shape: the qos except-branch
    re-raised operation_cancelled without settling the reply first."""
    findings = only(lint("""
        class Token:
            GET_STATUS = 1

        class ClusterController:
            def start(self, net):
                net.register(Token.GET_STATUS, self._on_get_status)

            def _on_get_status(self, req, reply):
                self.loop.spawn(self._get_status(req, reply))

            async def _get_status(self, req, reply):
                try:
                    qos = await self._qos_snapshot()
                except FDBError as e:
                    if e.name == "operation_cancelled":
                        raise
                    qos = None
                reply.send(qos)
    """), "PROTO002")
    assert [f.detail for f in findings] == ["raise-unsettled"]


# ---------------------------------------------------------------- PROTO003

def test_proto003_flags_inconsistent_request_types():
    findings = only(lint("""
        from dataclasses import dataclass

        class Token:
            PING = 1

        @dataclass
        class PingRequest:
            x: int

        @dataclass
        class OtherRequest:
            y: int

        class Client:
            async def a(self, net, addr):
                return await net.request(
                    net.process, Endpoint(addr, Token.PING), PingRequest(1))

            async def b(self, net, addr):
                return await net.request(
                    net.process, Endpoint(addr, Token.PING), OtherRequest(2))
    """), "PROTO003")
    assert len(findings) == 1
    assert "inconsistent request types" in findings[0].message


def test_proto003_flags_handler_annotation_mismatch():
    findings = only(lint("""
        from dataclasses import dataclass

        class Token:
            PING = 1

        @dataclass
        class PingRequest:
            x: int

        @dataclass
        class OtherRequest:
            y: int

        class Role:
            def start(self, net):
                net.register(Token.PING, self._on_ping)

            def _on_ping(self, req: OtherRequest, reply):
                reply.send(req.y)

        class Client:
            async def call(self, net, addr):
                return await net.request(
                    net.process, Endpoint(addr, Token.PING), PingRequest(1))
    """), "PROTO003")
    assert len(findings) == 1
    assert "OtherRequest" in findings[0].message
    assert "PingRequest" in findings[0].message


def test_proto003_flags_inconsistent_reply_types():
    findings = only(lint("""
        from dataclasses import dataclass

        class Token:
            PING = 1

        @dataclass
        class PongReply:
            x: int

        @dataclass
        class AckReply:
            ok: bool

        class Role:
            def start(self, net):
                net.register(Token.PING, self._on_ping)

            def _on_ping(self, req, reply):
                if req:
                    reply.send(PongReply(1))
                else:
                    reply.send(AckReply(True))
    """), "PROTO003")
    assert len(findings) == 1
    assert "inconsistent reply types" in findings[0].message


# ---------------------------------------------------------------- PROTO004

def test_proto004_flags_unregistered_payload_crossing_transport():
    findings = only(lint("""
        from dataclasses import dataclass

        class Token:
            PING = 1

        @dataclass
        class PingRequest:
            x: int

        @dataclass
        class SneakyRequest:
            y: int

        def _register_all():
            return (
                (1, PingRequest),
            )

        class Client:
            async def call(self, net, addr):
                return await net.request(
                    net.process, Endpoint(addr, Token.PING),
                    SneakyRequest(2))
    """), "PROTO004")
    assert [f.detail for f in findings] == ["SneakyRequest"]
    assert "WireError" in findings[0].message


def test_proto004_flags_duplicate_wire_id():
    findings = only(lint("""
        from dataclasses import dataclass

        @dataclass
        class PingRequest:
            x: int

        @dataclass
        class PongReply:
            y: int

        def _register_all():
            return (
                (1, PingRequest),
                (1, PongReply),
            )
    """), "PROTO004")
    assert [f.detail for f in findings] == ["id:1"]
    assert "wire format" in findings[0].message


def test_proto004_flags_unregistered_dataclass_field_type():
    findings = only(lint("""
        from dataclasses import dataclass

        @dataclass
        class Secret:
            blob: bytes

        @dataclass
        class PingRequest:
            inner: Secret

        def _register_all():
            return (
                (1, PingRequest),
            )
    """), "PROTO004")
    assert [f.detail for f in findings] == ["PingRequest.inner"]
    assert "no wire-registry entry" in findings[0].message


def test_proto004_registered_payloads_are_quiet():
    findings = only(lint("""
        from dataclasses import dataclass

        class Token:
            PING = 1

        @dataclass
        class PingRequest:
            x: int

        def _register_all():
            return (
                (1, PingRequest),
            )

        class Role:
            def start(self, net):
                net.register(Token.PING, self._on_ping)

            def _on_ping(self, req, reply):
                reply.send(req.x)

        class Client:
            async def call(self, net, addr):
                return await net.request(
                    net.process, Endpoint(addr, Token.PING), PingRequest(1))
    """), "PROTO004")
    assert findings == []


# ---------------------------------------------------------------- PROTO005

def _real_c_source() -> str:
    path = os.path.join(flowlint.default_target(), "native", "fdb_native.c")
    with open(path, encoding="utf-8") as f:
        return f.read()


def _real_py_view():
    from foundationdb_tpu.server import interfaces
    names = ("GetValuesReply", "GetKeyValuesReply")
    py_fields = {n: [f.name for f in dataclasses.fields(getattr(interfaces, n))]
                 for n in names}
    return py_fields, set(names)


def test_proto005_parser_reads_the_real_emitters():
    schemas = {s.name: s for s in protolint.parse_c_schemas(_real_c_source())}
    assert schemas["GetValuesReply"].fields == ["results"]
    assert schemas["GetValuesReply"].emit_count == 1
    assert schemas["GetKeyValuesReply"].fields == ["data", "more", "version"]
    assert schemas["GetKeyValuesReply"].emit_count == 3


def test_proto005_parity_holds_on_the_real_tree():
    py_fields, registered = _real_py_view()
    problems = protolint.c_parity_problems(
        protolint.parse_c_schemas(_real_c_source()), py_fields, registered)
    assert problems == []


def test_proto005_trips_when_python_gains_a_field():
    """THE acceptance gate: add a field to only the Python side and the
    parity rule must fail the build."""
    py_fields, registered = _real_py_view()
    py_fields["GetValuesReply"] = py_fields["GetValuesReply"] + ["shard_hint"]
    problems = protolint.c_parity_problems(
        protolint.parse_c_schemas(_real_c_source()), py_fields, registered)
    messages = [m for s, m in problems if s.name == "GetValuesReply"]
    assert any("mis-fills" in m for m in messages)
    assert any("hard-codes a field count" in m for m in messages)


def test_proto005_trips_when_c_gains_a_field():
    # mutate a COPY of the C registry: the schema comment grows a field the
    # Python dataclass doesn't have
    src = _real_c_source().replace(
        "GetValuesReply { results", "GetValuesReply { shard_hint, results", 1)
    assert src != _real_c_source()
    py_fields, registered = _real_py_view()
    problems = protolint.c_parity_problems(
        protolint.parse_c_schemas(src), py_fields, registered)
    messages = [m for s, m in problems if s.name == "GetValuesReply"]
    assert any("mis-fills" in m for m in messages)


def test_proto005_trips_on_emit_count_drift():
    schema = protolint.CSchema(name="GetValuesReply", fields=["results"],
                               line=1, emit_count=2)
    problems = protolint.c_parity_problems(
        [schema], {"GetValuesReply": ["results"]}, {"GetValuesReply"})
    assert len(problems) == 1
    assert "hard-codes a field count of 2" in problems[0][1]


def test_proto005_trips_on_schema_with_no_dataclass():
    schema = protolint.CSchema(name="Phantom", fields=["x"], line=1,
                               emit_count=None)
    problems = protolint.c_parity_problems([schema], {}, {"Phantom"})
    assert len(problems) == 1
    assert "no matching Python dataclass" in problems[0][1]


def test_proto005_unregistered_braces_are_ignored():
    # prose with braces in a comment must not produce phantom schemas
    schema = protolint.CSchema(name="whatever", fields=["looks", "like"],
                               line=1, emit_count=None)
    assert protolint.c_parity_problems([schema], {}, {"GetValuesReply"}) == []


# ---------------------------------------------------------------- PROTO006

def test_proto006_flags_unbounded_remote_wait():
    findings = only(lint("""
        class Client:
            async def call(self, net, ep):
                return await net.request(net.process, ep, 1, timeout=None)
    """, CLIENT_PATH), "PROTO006")
    assert [f.detail for f in findings] == ["timeout=None"]


def test_proto006_loop_timeout_wrapper_is_quiet():
    findings = only(lint("""
        class Client:
            async def call(self, net, ep):
                return await self.loop.timeout(
                    5.0, net.request(net.process, ep, 1, timeout=None))
    """, CLIENT_PATH), "PROTO006")
    assert findings == []


def test_proto006_default_timeout_is_quiet():
    findings = only(lint("""
        class Client:
            async def call(self, net, ep):
                return await net.request(net.process, ep, 1)
    """, CLIENT_PATH), "PROTO006")
    assert findings == []


# ---------------------------------------------------------------- PROTO007

def test_proto007_flags_request_num_without_epoch():
    findings = only(lint("""
        from dataclasses import dataclass

        @dataclass
        class AllocRequest:
            request_num: int
    """), "PROTO007")
    assert len(findings) == 1
    assert "no epoch fence" in findings[0].message


def test_proto007_flags_handler_that_never_dedups():
    findings = only(lint("""
        from dataclasses import dataclass

        class Token:
            ALLOC = 1

        @dataclass
        class AllocRequest:
            request_num: int
            epoch: int

        class Role:
            def start(self, net):
                net.register(Token.ALLOC, self._on_alloc)

            def _on_alloc(self, req, reply):
                reply.send(self.allocate(req.epoch))

        class Client:
            async def call(self, net, addr):
                return await net.request(
                    net.process, Endpoint(addr, Token.ALLOC),
                    AllocRequest(1, 2))
    """), "PROTO007")
    assert [f.detail for f in findings] == ["AllocRequest->_on_alloc"]
    assert "re-executed" in findings[0].message


def test_proto007_dedup_reading_handler_is_quiet():
    findings = only(lint("""
        from dataclasses import dataclass

        class Token:
            ALLOC = 1

        @dataclass
        class AllocRequest:
            request_num: int
            epoch: int

        class Role:
            def start(self, net):
                net.register(Token.ALLOC, self._on_alloc)

            def _on_alloc(self, req, reply):
                cached = self.dedup.get((req.epoch, req.request_num))
                if cached is not None:
                    reply.send(cached)
                    return
                reply.send(self.allocate(req.epoch))

        class Client:
            async def call(self, net, addr):
                return await net.request(
                    net.process, Endpoint(addr, Token.ALLOC),
                    AllocRequest(1, 2))
    """), "PROTO007")
    assert findings == []


# ---------------------------------------------------------------- PROTO008

def test_proto008_flags_unguarded_request_in_long_loop():
    findings = only(lint("""
        class Puller:
            async def run(self, net, ep):
                while True:
                    r = await net.request(net.process, ep, 1)
                    self.apply(r)
    """), "PROTO008")
    assert [f.detail for f in findings] == ["unguarded-await"]
    assert "reply-error" in findings[0].message


def test_proto008_try_inside_the_loop_is_quiet():
    findings = only(lint("""
        class Puller:
            async def run(self, net, ep):
                while True:
                    try:
                        r = await net.request(net.process, ep, 1)
                    except FDBError:
                        continue
                    self.apply(r)
    """), "PROTO008")
    assert findings == []


def test_proto008_try_outside_the_loop_is_quiet():
    # the real storage fetch-loop shape: the try that converts "actor dies"
    # into a handled exit sits OUTSIDE the while — still guarded
    findings = only(lint("""
        class Fetcher:
            async def fetch(self, net, ep):
                try:
                    while self.alive:
                        r = await net.request(net.process, ep, 1)
                        self.apply(r)
                except FDBError:
                    return
    """), "PROTO008")
    assert findings == []


# ------------------------------------------------- token census (satellite)

def _census():
    from foundationdb_tpu.server.coordination import CoordToken
    from foundationdb_tpu.server.interfaces import Token
    toks = {f"Token.{k}": v for k, v in vars(Token).items()
            if not k.startswith("_") and isinstance(v, int)}
    toks.update({f"CoordToken.{k}": v for k, v in vars(CoordToken).items()
                 if not k.startswith("_") and isinstance(v, int)})
    return toks


# ints retired by removed endpoints; never rebind them (a stale peer built
# before the removal would route its frames into the new handler)
BURNED = {4, 12, 15, 43, 97, 98}


def test_token_values_are_unique_across_the_routing_namespace():
    toks = _census()
    values = list(toks.values())
    dupes = {v: [k for k, v2 in toks.items() if v2 == v]
             for v in values if values.count(v) > 1}
    assert dupes == {}, f"duplicate token ints: {dupes}"


def test_token_values_stay_dense_and_off_the_burned_list():
    toks = _census()
    values = set(toks.values())
    assert not values & BURNED, "a retired token int was rebound"
    # density: the table is a small dense namespace (role-decade blocks),
    # not scattered magic numbers — new tokens extend a decade, and the
    # burned ints sit inside the allocated range (retired, not future)
    assert all(0 < v < 100 for v in values)
    assert all(b < max(values) for b in BURNED)


def test_token_name_reverse_lookup():
    from foundationdb_tpu.server.interfaces import Token, token_name
    assert token_name(Token.TLOG_COMMIT) == "TLOG_COMMIT"
    assert token_name(60) == "GENERATION_READ"  # CoordToken covered too
    assert token_name(12345) == "token:12345"
    toks = _census()
    # every bound value must round-trip to exactly its own name
    for name, value in toks.items():
        assert token_name(value) == name.split(".", 1)[1]


# ---------------------------------------------------------- output / CLI

def test_protolint_inline_suppression_tag():
    findings = lint("""
        class Client:
            async def call(self, net, ep):
                return await net.request(net.process, ep, 1, timeout=None)  # protolint: ignore[PROTO006]
    """, CLIENT_PATH)
    assert findings == []


def test_github_format_annotates_proto_findings():
    findings = only(lint("""
        class Client:
            async def call(self, net, ep):
                return await net.request(net.process, ep, 1, timeout=None)
    """, CLIENT_PATH), "PROTO006")
    out = flowlint.format_github(findings)
    assert out.startswith("::")
    assert "file=foundationdb_tpu/client/snippet.py" in out
    assert "PROTO006" in out


def test_cli_family_flag_selects_proto_rules(capsys):
    assert flowlint_main(["--family", "proto", "--list-rules"]) == 0
    codes = [line.split()[0] for line in
             capsys.readouterr().out.strip().splitlines()]
    assert codes == [f"PROTO00{i}" for i in range(1, 9)]


def test_family_scoped_baseline_runs_ignore_proto_entries(tmp_path):
    """A dev-only run must not report the proto grandfathers stale (and
    vice versa) — the family filter in apply_baseline."""
    baseline = flowlint.Baseline(entries=[
        {"rule": "PROTO006", "path": "p.py", "symbol": "f",
         "detail": "timeout=None", "reason": "doc"}])
    new, stale = flowlint.apply_baseline([], baseline, families={"dev"})
    assert new == [] and stale == []
    new, stale = flowlint.apply_baseline([], baseline, families={"proto"})
    assert [e["rule"] for e in stale] == ["PROTO006"]


# ------------------------------------------------------------- enforcement

def test_eight_proto_rules_active():
    codes = [r.code for r in flowlint.active_rules("proto")]
    assert codes == [f"PROTO00{i}" for i in range(1, 9)]


def test_package_and_scripts_clean_under_proto_family():
    """THE enforcement test for this PR: the proto family over the full
    default target set (package + scripts/) reports zero non-baselined
    findings and zero stale entries."""
    findings = flowlint.analyze_paths(flowlint.default_targets(),
                                      flowlint.active_rules("proto"))
    baseline = flowlint.load_baseline(flowlint.default_baseline_path())
    new, stale = flowlint.apply_baseline(findings, baseline,
                                         families={"proto"})
    assert new == [], "new violations:\n" + flowlint.format_text(new)
    assert stale == [], f"stale baseline entries: {stale}"


def test_proto_baseline_entries_are_documented():
    baseline = flowlint.load_baseline(flowlint.default_baseline_path())
    proto = [e for e in baseline.entries if e["rule"].startswith("PROTO")]
    for entry in proto:
        reason = entry.get("reason", "")
        assert reason and not reason.startswith("FIXME"), (
            f"undocumented baseline entry: {entry}")
