"""Contention management: hotspot sketch, throttle computation, informed
backoff (docs/contention.md).

Covers the subsystem's seams in isolation: the resolver-side sketch (decay,
merge, top-k determinism, bounded eviction), the ratekeeper's throttle-list
computation, the wire roundtrip of the new structs (including backward
compatibility of the extended RateInfoReply), and the client's decorrelated-
jitter + server-advised retry schedule under sim determinism.
"""

import pytest

from foundationdb_tpu.client.transaction import Transaction
from foundationdb_tpu.core.eventloop import EventLoop
from foundationdb_tpu.server import ratekeeper as rk
from foundationdb_tpu.server.hotspot import (
    HotRange, HotRangeSketch, HotRangesReply, ThrottleEntry, overlaps)
from foundationdb_tpu.utils import wire
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom

R_HOT = (b"hot", b"hot\x00")
R_COLD = (b"cold", b"cold\x00")


# ---------------------------------------------------------------------------
# sketch
# ---------------------------------------------------------------------------

def test_sketch_decay_halves_per_half_life():
    s = HotRangeSketch(half_life=2.0, max_buckets=16)
    s.record([R_HOT], now=0.0, weight=8.0)
    r0 = s.rate(*R_HOT, now=0.0)
    assert r0 > 0.0
    assert s.rate(*R_HOT, now=2.0) == pytest.approx(r0 / 2)
    assert s.rate(*R_HOT, now=4.0) == pytest.approx(r0 / 4)
    assert s.rate(b"never", b"seen", now=0.0) == 0.0


def test_sketch_rate_tracks_steady_conflict_rate():
    """At a steady R conflicts/sec the decayed estimate converges to ~R
    (the C * ln2 / half_life normalization)."""
    s = HotRangeSketch(half_life=2.0, max_buckets=16)
    for i in range(400):
        s.record([R_HOT], now=i * 0.01, weight=1.0)  # 100 conflicts/sec
    est = s.rate(*R_HOT, now=4.0)
    assert 70.0 < est < 130.0, est


def test_sketch_merge_sums_decayed_mass():
    a = HotRangeSketch(half_life=2.0, max_buckets=16)
    b = HotRangeSketch(half_life=2.0, max_buckets=16)
    a.record([R_HOT], now=1.0, weight=4.0)
    b.record([R_HOT], now=1.0, weight=4.0)
    b.record([R_COLD], now=1.0, weight=2.0)
    a.merge(b, now=1.0)
    # merged mass = 4 + 4 = 8; rate = mass * ln2 / half_life
    assert a.rate(*R_HOT, now=1.0) == pytest.approx(8.0 * 0.6931472 / 2.0,
                                                    rel=1e-5)
    assert a.rate(*R_COLD, now=1.0) > 0.0
    assert len(a) == 2


def test_sketch_top_k_deterministic_order():
    """Equal-rate ranges order by (begin, end) — the snapshot never flaps."""
    s = HotRangeSketch(half_life=2.0, max_buckets=16)
    for key in (b"b", b"a", b"c"):
        s.record([(key, key + b"\x00")], now=0.0, weight=3.0)
    s.record([R_HOT], now=0.0, weight=9.0)
    top = s.top_k(3, now=0.0)
    assert [t.begin for t in top] == [b"hot", b"a", b"b"]
    assert top[0].rate > top[1].rate == top[2].rate
    # and the same content always yields the same list
    s2 = HotRangeSketch(half_life=2.0, max_buckets=16)
    for key in (b"c", b"a", b"b"):  # insertion order must not matter
        s2.record([(key, key + b"\x00")], now=0.0, weight=3.0)
    s2.record([R_HOT], now=0.0, weight=9.0)
    assert s2.top_k(3, now=0.0) == top


def test_sketch_bounded_eviction_keeps_hottest():
    s = HotRangeSketch(half_life=2.0, max_buckets=4)
    s.record([R_HOT], now=0.0, weight=100.0)
    for i in range(50):
        s.record([(b"t%03d" % i, b"t%03d\x00" % i)], now=float(i) * 0.01)
    assert len(s) <= 4
    assert s.rate(*R_HOT, now=0.5) > 0.0, "hottest bucket was evicted"


def test_sketch_prune_drops_dead_buckets():
    s = HotRangeSketch(half_life=1.0, max_buckets=16)
    s.record([R_HOT], now=0.0)
    s.record([R_COLD], now=0.0, weight=1000.0)
    s.prune(now=15.0)  # R_HOT decayed to ~3e-5, R_COLD still ~0.03
    assert len(s) == 1
    assert s.rate(*R_COLD, now=15.0) > 0.0


def test_overlaps_half_open_and_infinite_end():
    assert overlaps(b"a", b"b", b"a", b"b")
    assert overlaps(b"a", b"c", b"b", b"d")
    assert not overlaps(b"a", b"b", b"b", b"c")  # half-open: no touch
    assert overlaps(b"x", b"y", b"w", None)  # None = +infinity
    assert not overlaps(b"a", b"b", b"c", None)


# ---------------------------------------------------------------------------
# wire
# ---------------------------------------------------------------------------

def test_throttle_structs_roundtrip():
    h = HotRangesReply(
        ranges=[HotRange(begin=b"k1", end=b"k2", rate=12.5)], total_rate=13.0)
    assert wire.loads(wire.dumps(h)) == h
    t = ThrottleEntry(begin=b"a", end=b"b", release_tps=10.0, backoff=0.5)
    assert wire.loads(wire.dumps(t)) == t
    r = rk.RateInfoReply(tps=500.0, throttles=[t])
    assert wire.loads(wire.dumps(r)) == r


def test_rate_reply_backward_compatible_with_bare_tps_schema():
    """A peer on the pre-contention schema sends RateInfoReply with only the
    tps field; the decoder must fill `throttles` from its default."""
    tid = wire.type_id(rk.RateInfoReply)
    out = bytearray([wire.MAGIC, wire.WIRE_VERSION, ord("R")])
    wire._w_varint(out, tid)
    wire._w_varint(out, 1)  # old schema: one field
    wire._encode_value(out, 100.0)
    got = wire.loads(bytes(out))
    assert got == rk.RateInfoReply(tps=100.0, throttles=[])


# ---------------------------------------------------------------------------
# ratekeeper throttle computation
# ---------------------------------------------------------------------------

def _mk_rk():
    """A Ratekeeper with no cluster behind it (update loop never sampled)."""
    from foundationdb_tpu.core.sim import SimNetwork
    loop = EventLoop()
    net = SimNetwork(loop, DeterministicRandom(3))
    proc = net.new_process("rk:0")
    keeper = rk.Ratekeeper(proc)
    # only the pure computation is under test: stop the sampling/trace
    # actors so no never-awaited coroutine outlives the test
    keeper.shutdown()
    loop.run_until_idle()
    return loop, keeper


def test_compute_throttles_threshold_and_backoff_scaling():
    KNOBS.set("RK_THROTTLE_CONFLICT_RATE", 10.0)
    KNOBS.set("RK_THROTTLE_BACKOFF", 0.2)
    KNOBS.set("RK_THROTTLE_MAX_BACKOFF", 1.0)
    _loop, keeper = _mk_rk()
    replies = [
        HotRangesReply(ranges=[HotRange(b"a", b"b", 6.0),
                               HotRange(b"c", b"d", 30.0)], total_rate=36.0),
        HotRangesReply(ranges=[HotRange(b"a", b"b", 6.0),
                               HotRange(b"e", b"f", 200.0)], total_rate=206.0),
        None,  # a dead resolver must not break the computation
    ]
    out = keeper._compute_throttles(replies)
    # a+b merged to 12 (throttled), c..d 30, e..f 200; hottest first
    assert [(t.begin, t.end) for t in out] == [(b"e", b"f"), (b"c", b"d"),
                                              (b"a", b"b")]
    by_range = {(t.begin, t.end): t for t in out}
    assert by_range[(b"a", b"b")].backoff == pytest.approx(0.2 * 12 / 10)
    assert by_range[(b"c", b"d")].backoff == pytest.approx(0.2 * 30 / 10)
    assert by_range[(b"e", b"f")].backoff == 1.0  # capped
    assert keeper.stats["hot_total_rate"] == pytest.approx(242.0)
    # determinism: same snapshots -> identical list
    assert keeper._compute_throttles(replies) == out


def test_rate_reply_divides_release_budget_across_proxies():
    KNOBS.set("RK_THROTTLE_CONFLICT_RATE", 10.0)
    KNOBS.set("RK_THROTTLE_RELEASE_TPS", 40.0)
    _loop, keeper = _mk_rk()
    keeper.throttles = keeper._compute_throttles(
        [HotRangesReply(ranges=[HotRange(b"a", b"b", 50.0)], total_rate=50.0)])

    got = []

    class _Reply:
        def send(self, v):
            got.append(v)

    keeper._on_get_rate(4, _Reply())
    r = got[0]
    assert r.tps == pytest.approx(keeper.tps / 4)
    assert len(r.throttles) == 1
    assert r.throttles[0].release_tps == pytest.approx(40.0 / 4)
    # the keeper's own list is not mutated by the per-proxy division
    assert keeper.throttles[0].release_tps == pytest.approx(40.0)


# ---------------------------------------------------------------------------
# client retry schedule (satellite: decorrelated jitter + informed backoff)
# ---------------------------------------------------------------------------

class _FakeDB:
    """Just enough of Database for Transaction.on_error: the loop, the
    deterministic rng, and the real penalty-cache methods."""

    def __init__(self, loop, seed=7):
        from foundationdb_tpu.client.database import Database
        self.loop = loop
        self._rng = DeterministicRandom(seed)
        self._range_penalties = {}
        self._note_throttle = Database._note_throttle.__get__(self)
        self._penalty_wait = Database._penalty_wait.__get__(self)


def _retry_schedule(loop, seed, n=8, error_name="not_committed"):
    db = _FakeDB(loop, seed)
    tr = Transaction(db)
    sleeps = []

    async def drive():
        for _ in range(n):
            t0 = loop.now()
            await tr.on_error(FDBError(error_name))
            sleeps.append(loop.now() - t0)

    loop.run_future(loop.spawn(drive()))
    return sleeps


def test_backoff_is_decorrelated_jitter_with_cap():
    loop = EventLoop()
    sleeps = _retry_schedule(loop, seed=7, n=10)
    base, cap = KNOBS.DEFAULT_BACKOFF, KNOBS.MAX_BACKOFF
    prev = base
    for s in sleeps:
        assert base <= s <= cap + 1e-12, s
        assert s <= max(base, prev * 3) + 1e-12, \
            f"sleep {s} exceeds decorrelated bound {prev * 3}"
        prev = s
    # jitter actually varies (not bare doubling)
    assert len({round(s, 6) for s in sleeps}) > 3


def test_backoff_schedule_is_deterministic_under_sim():
    """Same rng seed -> the exact same retry schedule (pinned)."""
    a = _retry_schedule(EventLoop(), seed=42, n=8)
    b = _retry_schedule(EventLoop(), seed=42, n=8)
    assert a == b
    c = _retry_schedule(EventLoop(), seed=43, n=8)
    assert a != c


def test_backoff_respects_retry_limit():
    loop = EventLoop()
    db = _FakeDB(loop)
    tr = Transaction(db)
    tr.set_option(501, 2)  # retry_limit

    async def drive():
        await tr.on_error(FDBError("not_committed"))
        await tr.on_error(FDBError("not_committed"))
        with pytest.raises(FDBError):
            await tr.on_error(FDBError("not_committed"))

    loop.run_future(loop.spawn(drive()))


def test_on_error_raises_non_retryable():
    loop = EventLoop()
    tr = Transaction(_FakeDB(loop))

    async def drive():
        with pytest.raises(FDBError):
            await tr.on_error(FDBError("operation_failed"))

    loop.run_future(loop.spawn(drive()))


def test_throttled_error_honors_advised_backoff_and_penalty_cache():
    loop = EventLoop()
    db = _FakeDB(loop)
    tr = Transaction(db)
    advised = 0.8
    begin, end = b"hot", b"hot\x00"
    detail = f"{advised} {begin.hex()} {end.hex()}"

    async def drive():
        tr.set(b"hot", b"v")
        t0 = loop.now()
        await tr.on_error(FDBError("transaction_throttled", detail))
        waited = loop.now() - t0
        assert waited >= advised - 1e-9, \
            f"ignored server-advised backoff: {waited}"
        # the penalty landed in the shared cache
        assert db._range_penalties, "no penalty cached"
        # a SECOND transaction writing the same key inherits the penalty
        tr2 = Transaction(db)
        tr2.set(b"hot", b"v2")
        t1 = loop.now()
        await tr2.on_error(FDBError("not_committed"))
        assert loop.now() - t1 >= (advised - waited) - 1e-9
        # a transaction writing elsewhere does NOT
        tr3 = Transaction(db)
        tr3.set(b"elsewhere", b"v")
        t2 = loop.now()
        await tr3.on_error(FDBError("not_committed"))
        assert loop.now() - t2 <= KNOBS.MAX_BACKOFF + 1e-9

    loop.run_future(loop.spawn(drive()))


def test_penalty_cache_prunes_expired_entries():
    loop = EventLoop()
    db = _FakeDB(loop)
    db._range_penalties[(b"a", b"b")] = 0.5  # expires at t=0.5

    async def drive():
        await loop.delay(1.0)
        assert db._penalty_wait([(b"a", b"b")]) == 0.0
        assert not db._range_penalties, "expired penalty not pruned"

    loop.run_future(loop.spawn(drive()))
