"""Test configuration.

Tests run on a virtual 8-device CPU mesh (multi-chip hardware is not available
in CI): JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8 must be set
before jax is imported anywhere, hence the env mutation at module import time.
bench.py and __graft_entry__.py do NOT import this — they run on real TPU.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402
import jax  # noqa: E402

# A PJRT plugin registered at interpreter start (sitecustomize) may have set
# jax_platforms programmatically, which overrides the env var — force CPU
# before any backend initialization so the 8-device mesh is real.
jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: the conflict-engine program is compiled once per
# (shapes, window) and reused across test runs.
jax.config.update("jax_compilation_cache_dir", "/tmp/fdb_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from foundationdb_tpu.utils.knobs import KNOBS  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_knobs():
    KNOBS.reset()
    yield
    KNOBS.reset()
