"""Coordination layer: generation-register quorum state + leader election.

Reference semantics under test (Coordination.actor.cpp,
CoordinatedState.actor.cpp, LeaderElection.actor.cpp): quorum reads return the
latest written value; competing writers serialize (one wins, the loser sees
failure); election converges on one leader with a majority; leases expire when
the leader stops renewing; a minority of dead coordinators is tolerated.
"""

import pytest

from foundationdb_tpu.core.eventloop import EventLoop
from foundationdb_tpu.core.sim import KillType, SimNetwork
from foundationdb_tpu.server.coordination import (
    CoordinatedStateClient, Coordinator, elect_leader, get_leader)
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.rng import DeterministicRandom


def _mk(n_coord=3, seed=1):
    loop = EventLoop()
    net = SimNetwork(loop, DeterministicRandom(seed))
    coords = []
    for i in range(n_coord):
        p = net.new_process(f"coord:{i}")
        Coordinator(p)
        coords.append(p.address)
    return loop, net, coords


def test_coordinated_state_read_write():
    loop, net, coords = _mk()
    client_proc = net.new_process("client:0")
    cs = CoordinatedStateClient(client_proc, coords)
    result = {}

    async def t():
        v0, g0 = await cs.read()
        assert v0 is None
        await cs.write({"epoch": 1, "tlogs": ["a"]})
        v1, g1 = await cs.read()
        result["v"] = v1

    loop.run_future(loop.spawn(t()), max_time=60.0)
    assert result["v"] == {"epoch": 1, "tlogs": ["a"]}


def test_coordinated_state_survives_coordinator_minority_failure():
    loop, net, coords = _mk()
    client_proc = net.new_process("client:0")
    cs = CoordinatedStateClient(client_proc, coords)
    result = {}

    async def t():
        await cs.write({"epoch": 2})
        net.kill(coords[0], KillType.KillProcess)
        v, _ = await cs.read()
        result["v"] = v
        await cs.write({"epoch": 3})
        v2, _ = await cs.read()
        result["v2"] = v2

    loop.run_future(loop.spawn(t()), max_time=60.0)
    assert result["v"] == {"epoch": 2}
    assert result["v2"] == {"epoch": 3}


def test_coordinated_state_majority_failure_blocks():
    loop, net, coords = _mk()
    client_proc = net.new_process("client:0")
    cs = CoordinatedStateClient(client_proc, coords)
    result = {}

    async def t():
        net.kill(coords[0], KillType.KillProcess)
        net.kill(coords[1], KillType.KillProcess)
        try:
            await cs.write({"epoch": 9})
            result["r"] = "wrote"
        except FDBError as e:
            result["r"] = e.name

    loop.run_future(loop.spawn(t()), max_time=60.0)
    assert result["r"] == "coordinators_changed"


def test_competing_writers_serialize():
    loop, net, coords = _mk()
    a = CoordinatedStateClient(net.new_process("writer:a"), coords)
    b = CoordinatedStateClient(net.new_process("writer:b"), coords)
    outcomes = {}

    async def writer(name, cs, value):
        try:
            await cs.write(value)
            outcomes[name] = "ok"
        except FDBError as e:
            outcomes[name] = e.name

    t1 = loop.spawn(writer("a", a, {"who": "a"}))
    t2 = loop.spawn(writer("b", b, {"who": "b"}))
    from foundationdb_tpu.core.future import all_of
    loop.run_future(all_of([t1, t2]), max_time=60.0)
    # both may succeed (serialized one after the other) but the final value
    # must be exactly one of them and reads must agree
    reader = CoordinatedStateClient(net.new_process("reader:0"), coords)
    out = {}

    async def check():
        v, _ = await reader.read()
        out["v"] = v

    loop.run_future(loop.spawn(check()), max_time=60.0)
    assert out["v"] in ({"who": "a"}, {"who": "b"})


def test_leader_election_converges_and_fails_over():
    loop, net, coords = _mk()
    w1 = net.new_process("worker:1")
    w2 = net.new_process("worker:2")
    state = {}

    async def candidate(proc, prio, key):
        await elect_leader(proc, coords, priority=prio, lease_seconds=3.0,
                           poll_interval=0.5)
        state[key] = loop.now()
        # hold the lease by re-electing periodically while alive
        while proc.alive:
            await elect_leader(proc, coords, priority=prio, lease_seconds=3.0,
                               poll_interval=0.5)
            await loop.delay(1.0)

    net.processes["worker:1"].spawn(candidate(w1, 10, "w1_leader"))
    net.processes["worker:2"].spawn(candidate(w2, 5, "w2_leader"))
    client = net.new_process("client:0")
    seen = {}

    async def observe():
        await loop.delay(2.0)
        seen["first"] = await get_leader(client, coords)
        net.kill("worker:1", KillType.KillProcess)
        await loop.delay(8.0)  # lease expires, lower-priority takes over
        seen["second"] = await get_leader(client, coords)

    loop.run_future(loop.spawn(observe()), max_time=120.0)
    assert seen["first"] == "worker:1"  # higher priority wins
    assert seen["second"] == "worker:2"  # failover after lease expiry
