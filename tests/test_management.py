"""Management API: configure/exclude/include as \\xff/conf transactions the
cluster controller acts on (ManagementAPI.actor.cpp:1604; fdbcli commands
fdbcli.actor.cpp:430-518).
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.client import management
from foundationdb_tpu.server.cluster import RecoverableCluster
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    KNOBS.set("DD_INTERVAL_SECONDS", 1.0)
    KNOBS.set("DD_STORAGE_FAILURE_SECONDS", 4.0)
    yield
    KNOBS.reset()


def test_configure_replication_live_change():
    """`configure double` on a single-replica cluster: healing tops every
    team up to 2; `configure single` shrinks back to 1."""
    c = RecoverableCluster(seed=61, n_workers=4, n_proxies=1, n_tlogs=2,
                           n_storage=2, n_replicas=1, n_storage_workers=5)
    db = c.database()

    async def t():
        await db.refresh()
        async def seed(tr):
            for i in range(40):
                tr.set(b"c%02d" % i, b"v%d" % i)
        await db.transact(seed, max_retries=500)

        await management.configure(db, n_replicas=2)
        conf = await management.get_configuration(db)
        assert conf["n_replicas"] == 2
        for _ in range(120):
            await c.loop.delay(0.5)
            cc = c.current_cc()
            if cc and all(len(t_) == 2 for t_ in cc.dbinfo.teams()):
                break
        assert all(len(t_) == 2 for t_ in c.current_cc().dbinfo.teams()), \
            c.current_cc().dbinfo.teams()

        await management.configure(db, n_replicas=1)
        for _ in range(120):
            await c.loop.delay(0.5)
            cc = c.current_cc()
            if cc and all(len(t_) == 1 for t_ in cc.dbinfo.teams()):
                break
        assert all(len(t_) == 1 for t_ in c.current_cc().dbinfo.teams())

        # data still intact
        async def readall(tr):
            return await tr.get_range(b"c", b"d")
        rows = await db.transact(readall, max_retries=500)
        assert len(rows) == 40

    c.run(c.loop.spawn(t()), max_time=300_000.0)


def test_configure_proxies_triggers_recovery():
    c = RecoverableCluster(seed=62, n_workers=5, n_proxies=1, n_tlogs=2,
                           n_storage=1, n_replicas=1)
    db = c.database()

    async def t():
        await db.refresh()
        epoch0 = c.current_cc().dbinfo.epoch
        await management.configure(db, n_proxies=2)
        for _ in range(120):
            await c.loop.delay(0.5)
            cc = c.current_cc()
            if cc and cc.dbinfo.epoch > epoch0 \
                    and len(cc.dbinfo.proxies) == 2:
                break
        info = c.current_cc().dbinfo
        assert len(info.proxies) == 2, info.proxies
        assert info.epoch > epoch0
        # and the cluster still works
        async def w(tr):
            tr.set(b"after-configure", b"1")
        await db.transact(w, max_retries=500)

    c.run(c.loop.spawn(t()), max_time=300_000.0)


def test_exclude_drains_server_and_include_restores():
    """Excluding a storage worker moves every shard off it (like a failure,
    but the server is alive the whole time); include makes it usable
    again."""
    c = RecoverableCluster(seed=63, n_workers=4, n_proxies=1, n_tlogs=2,
                           n_storage=2, n_replicas=2, n_storage_workers=5)
    db = c.database()

    async def t():
        await db.refresh()
        async def seed(tr):
            for i in range(40):
                tr.set(b"e%02d" % i, b"v%d" % i)
        await db.transact(seed, max_retries=500)

        victim = c.current_cc().dbinfo.storages[0][0]
        await management.exclude_servers(db, [victim])
        assert victim in await management.excluded_servers(db)

        for _ in range(160):
            await c.loop.delay(0.5)
            cc = c.current_cc()
            if cc is None:
                continue
            info = cc.dbinfo
            victim_tags = {t for a, t in info.storages if a == victim}
            if not any(t in team for t in victim_tags
                       for team in info.teams()):
                break
        info = c.current_cc().dbinfo
        victim_tags = {t for a, t in info.storages if a == victim}
        for team in info.teams():
            assert not (victim_tags & set(team)), info.teams()
            assert len(team) == 2

        async def readall(tr):
            return await tr.get_range(b"e", b"f")
        rows = await db.transact(readall, max_retries=500)
        assert len(rows) == 40

        await management.include_servers(db, [victim])
        assert victim not in await management.excluded_servers(db)

    c.run(c.loop.spawn(t()), max_time=300_000.0)


def test_fdbcli_management_commands():
    from foundationdb_tpu.tools.fdbcli import FdbCli
    c = RecoverableCluster(seed=64, n_workers=4, n_proxies=1, n_tlogs=2,
                           n_storage=1, n_replicas=1)
    db = c.database()

    async def warm():
        await db.refresh()
    c.run(c.loop.spawn(warm()), max_time=120_000.0)
    cli = FdbCli(c, db)
    out = cli.execute("configure double")
    assert any("changed" in l for l in out), out
    out = cli.execute("configure")
    assert any('"n_replicas": 2' in l for l in out), out
    out = cli.execute("exclude somehost:4500")
    assert any("Excluded" in l for l in out), out
    out = cli.execute("exclude")
    assert out == ["somehost:4500"], out
    out = cli.execute("include all")
    assert any("Included" in l for l in out), out
    out = cli.execute("exclude")
    assert out == [], out
    out = cli.execute("coordinators")
    assert any("coord" in l for l in out), out
    out = cli.execute("configure bogus=1")
    assert any("ERROR" in l for l in out), out
