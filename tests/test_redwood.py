"""Redwood engine unit tests: block codec parity (C vs Python), flush /
compaction life-cycle, crash recovery (torn tails, half-finished
compactions), and the PROTO005-style C-schema pin for the on-disk structs.

The model-check idiom follows tests/test_vstore_parity.py: drive the engine
and a plain dict model with one mutation stream and demand identical reads.
"""

import pytest

from foundationdb_tpu.core.sim import SimFile
from foundationdb_tpu.storage import redwood as R
from foundationdb_tpu.storage.redwood import RedwoodKeyValueStore
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom


@pytest.fixture(autouse=True)
def _tiny_budgets():
    # small enough that a few hundred mutations exercise flush AND multiple
    # compaction levels
    KNOBS.set("REDWOOD_MEMTABLE_BYTES", 512)
    KNOBS.set("REDWOOD_BLOCK_BYTES", 128)
    KNOBS.set("REDWOOD_COMPACTION_FAN_IN", 2)
    yield


class _Files:
    """SimFile surface for the engine: WAL pair + named run files."""

    def __init__(self, seed=0):
        self.rng = DeterministicRandom(seed)
        self.files: dict[str, SimFile] = {}

    def open(self, name):
        if name not in self.files:
            self.files[name] = SimFile(name, self.rng.fork())
        return self.files[name]

    def existing(self):
        return [n for n in self.files if n.startswith("rw.")]

    def store(self) -> RedwoodKeyValueStore:
        return RedwoodKeyValueStore(self.open("wal.0"), self.open("wal.1"),
                                    self.open, self.existing)

    def kill_all(self):
        for f in self.files.values():
            f.on_kill()


# ---------------------------------------------------------------------------
# block codec
# ---------------------------------------------------------------------------

def _random_items(rng, n):
    keys = sorted({bytes(rng.randint(97, 103) for _ in range(
        rng.randint(1, 12))) for _ in range(n)})
    return [(k, bytes(rng.randint(0, 255) for _ in range(rng.randint(0, 20))))
            for k in keys]


def test_block_codec_roundtrip_python():
    rng = DeterministicRandom(1)
    for _ in range(50):
        items = _random_items(rng, rng.randint(0, 30))
        assert R.py_decode_block(R.py_encode_block(items)) == items


def test_block_codec_c_python_parity():
    from foundationdb_tpu import native
    if not (native.available() and hasattr(native.mod,
                                           "redwood_encode_block")):
        pytest.skip("native module without redwood codec")
    rng = DeterministicRandom(2)
    for _ in range(100):
        items = _random_items(rng, rng.randint(0, 30))
        c_img = native.mod.redwood_encode_block(items)
        py_img = R.py_encode_block(items)
        assert c_img == py_img  # byte-identical, not just equivalent
        assert native.mod.redwood_decode_block(py_img) == items
        assert R.py_decode_block(c_img) == items


def test_block_codec_rejects_corruption():
    img = bytearray(R.py_encode_block([(b"a", b"1"), (b"ab", b"2")]))
    img[-1] ^= 0xFF
    with pytest.raises(Exception, match="checksum|corrupt"):
        R.py_decode_block(bytes(img))


# ---------------------------------------------------------------------------
# life-cycle: flush, compaction, model equality
# ---------------------------------------------------------------------------

def _mutate(rng, store, model, n_ops):
    for _ in range(n_ops):
        r = rng.random()
        if r < 0.70:
            k = f"k{rng.randint(0, 200):04d}".encode()
            v = bytes(rng.randint(0, 255) for _ in range(rng.randint(1, 15)))
            store.set(k, v)
            model[k] = v
        elif r < 0.85:
            a, b = sorted((rng.randint(0, 200), rng.randint(0, 200)))
            begin, end = f"k{a:04d}".encode(), f"k{b:04d}".encode()
            store.clear_range(begin, end)
            for k in [k for k in model if begin <= k < end]:
                del model[k]
        else:
            store.commit()
            store.maintain()


def _assert_equal(store, model):
    items = sorted(model.items())
    assert store.get_range(b"", b"\xff" * 8) == items
    assert store.get_range(b"", b"\xff" * 8, reverse=True) == \
        items[::-1]
    assert store.get_range(b"", b"\xff" * 8, limit=5) == items[:5]
    assert store.get_range(b"", b"\xff" * 8, limit=0) == []
    for k in list(model)[:50]:
        assert store.get(k) == model[k]
    assert store.get(b"nonexistent-key") is None


def test_flush_compaction_and_reads_match_model():
    files = _Files(seed=7)
    store = files.store()
    model: dict[bytes, bytes] = {}
    rng = DeterministicRandom(7)
    _mutate(rng, store, model, 600)
    store.commit()
    store.maintain()
    # the tiny budgets must have pushed runs past level 0
    assert any(lv >= 1 for lv in store.level_shape()), store.level_shape()
    _assert_equal(store, model)


def test_recover_after_clean_shutdown():
    files = _Files(seed=8)
    store = files.store()
    model: dict[bytes, bytes] = {}
    rng = DeterministicRandom(8)
    _mutate(rng, store, model, 400)
    store.set_metadata("durableVersion", b"123")
    store.commit()
    store2 = files.store()
    store2.recover()
    _assert_equal(store2, model)
    assert store2.get_metadata("durableVersion") == b"123"


def test_recover_after_kill_preserves_committed_state():
    files = _Files(seed=9)
    store = files.store()
    model: dict[bytes, bytes] = {}
    rng = DeterministicRandom(9)
    _mutate(rng, store, model, 400)
    store.commit()  # everything in `model` is now durable
    # uncommitted suffix: may survive partially (torn tail) — must not
    # corrupt anything, and committed state must be complete
    store.set(b"uncommitted", b"x")
    files.kill_all()
    store2 = files.store()
    store2.recover()
    for k, v in model.items():
        assert store2.get(k) == v, k
    got = dict(store2.get_range(b"", b"\xff" * 8))
    for k in got:
        assert k in model or k == b"uncommitted"


def test_recovery_heals_half_finished_compaction():
    """Crash between the merged run's sync and the source truncation: both
    survive on disk; recovery must keep the merged run, drop + truncate the
    sources, and serve identical data."""
    files = _Files(seed=10)
    store = files.store()
    model: dict[bytes, bytes] = {}
    rng = DeterministicRandom(10)
    # two flushes -> two runs at level 0 (fan-in 2 makes compaction due)
    for round_ in range(2):
        for i in range(40):
            k = f"h{round_}{i:03d}".encode()
            store.set(k, b"v" * 8)
            model[k] = b"v" * 8
        store.commit()
        plan = store.plan_maintenance()
        assert plan is not None and plan.kind == "flush"
        store.apply_maintenance(plan, plan.build())
    assert store.level_shape() == {0: 2}
    plan = store.plan_maintenance()
    assert plan is not None and plan.kind == "compact"
    image = plan.build()
    # simulate the crash: merged run durable, sources NOT truncated
    f = files.open(f"rw.{plan.run_id}")
    f.append(image)
    f.sync()
    store2 = files.store()
    store2.recover()
    assert store2.level_shape() == {1: 1}
    for src in plan.source_ids:
        assert files.files[f"rw.{src}"].read_all() == b""  # healed
    for k, v in model.items():
        assert store2.get(k) == v


def test_torn_run_file_is_ignored_and_truncated():
    """A run that fails its body CRC is dropped and reclaimed at recovery.
    The data still reads back here because the DiskQueue pop is lazy (space
    is reclaimed at file swap, not at pop), so the flushed ops survive in
    the WAL and replay idempotently over the dropped run."""
    files = _Files(seed=11)
    store = files.store()
    for i in range(60):
        store.set(f"t{i:03d}".encode(), b"v" * 8)
    store.commit()
    store.maintain()
    names = store.run_names()
    assert names
    # tear the newest run: recovery must drop it and fall back to the WAL
    torn = files.files[names[0]]
    torn.durable = torn.durable[: len(torn.durable) // 2]
    store2 = files.store()
    store2.recover()
    assert torn.read_all() == b""  # reclaimed
    for i in range(60):
        assert store2.get(f"t{i:03d}".encode()) == b"v" * 8


def test_metadata_only_churn_flushes_and_reclaims_wal():
    """Durable-version bumps with no data writes must not grow the WAL
    forever: the _wal_bytes trigger flushes (possibly an entries-empty run)
    and pops the WAL."""
    files = _Files(seed=12)
    store = files.store()
    store.set(b"seed", b"1")
    store.commit()
    for v in range(400):
        store.set_metadata("durableVersion", str(v).encode())
        store.commit()
        store.maintain()
    assert len(store.queue.live_entries) < 400
    store2 = files.store()
    store2.recover()
    assert store2.get_metadata("durableVersion") == b"399"
    assert store2.get(b"seed") == b"1"


def test_clear_range_shadows_older_runs():
    files = _Files(seed=13)
    store = files.store()
    for i in range(40):
        store.set(f"s{i:03d}".encode(), b"old")
    store.commit()
    store.maintain()  # data now lives in a run
    store.clear_range(b"s010", b"s020")
    store.set(b"s012", b"new")
    store.commit()
    assert store.get(b"s011") is None
    assert store.get(b"s012") == b"new"
    assert store.get(b"s009") == b"old"
    got = store.get_range(b"s005", b"s025")
    keys = [k for k, _ in got]
    assert b"s011" not in keys and b"s012" in keys
    # and the same through a flush of the tombstone + recovery
    store.maintain()
    store2 = files.store()
    store2.recover()
    assert store2.get(b"s011") is None
    assert store2.get(b"s012") == b"new"


# ---------------------------------------------------------------------------
# C-schema pin (PROTO005 discipline for the on-disk structs)
# ---------------------------------------------------------------------------

_EXPECTED_SCHEMAS = {
    "RedwoodBlockHeader": R.BLOCK_HEADER_FIELDS,
    "RedwoodBlockEntry": R.BLOCK_ENTRY_FIELDS,
    "RedwoodRunHeader": R.RUN_HEADER_FIELDS,
    "RedwoodRunIndexEntry": R.RUN_INDEX_FIELDS,
    "RedwoodBloomHeader": R.BLOOM_HEADER_FIELDS,
}


def _c_source():
    import os
    path = os.path.join(os.path.dirname(__file__), "..",
                        "foundationdb_tpu", "native", "fdb_native.c")
    with open(path) as f:
        return f.read()


def test_c_schema_comments_match_python_structs():
    from foundationdb_tpu.analysis.protolint import parse_c_schemas
    schemas = {s.name: s.fields for s in parse_c_schemas(_c_source())
               if s.name in _EXPECTED_SCHEMAS}
    assert schemas == _EXPECTED_SCHEMAS


def test_c_schema_check_detects_drift():
    """Mutation-proving negative case: a renamed field in the C comment must
    make the comparison fail (i.e. the gate above has teeth)."""
    from foundationdb_tpu.analysis.protolint import parse_c_schemas
    mutated = _c_source().replace("payload_bytes: u32", "payload_len: u32")
    assert mutated != _c_source()
    schemas = {s.name: s.fields for s in parse_c_schemas(mutated)
               if s.name in _EXPECTED_SCHEMAS}
    assert schemas != _EXPECTED_SCHEMAS


def test_struct_sizes_are_pinned():
    """Byte sizes are wire format: changing one silently breaks every
    existing store. Pin them."""
    assert R._BLOCK_HEADER.size == 16
    assert R._BLOCK_ENTRY.size == 8
    assert R._RUN_HEADER.size == 52  # v2: + bloom_bytes
    assert R._RUN_INDEX.size == 10
    assert R._BLOOM_HEADER.size == 24
