"""Redundancy healing: a permanently failed storage server's shards are
re-replicated onto a replacement (teamTracker DataDistribution.actor.cpp:1373,
storageServerTracker :1730), and the cluster then passes a full replica
consistency check.
"""

import pytest

from foundationdb_tpu.core.sim import KillType
from foundationdb_tpu.testing.workloads import (
    AttritionWorkload, ConsistencyCheckWorkload, CycleWorkload, run_spec)
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield
    KNOBS.reset()


def test_storage_loss_heals_and_stays_consistent():
    """Kill one storage worker FOREVER mid-run: DD must re-replicate its
    shards onto a replacement; the consistency check compares all replicas
    row-for-row at the end (with the dead worker still dead)."""
    from foundationdb_tpu.server.cluster import RecoverableCluster
    from foundationdb_tpu.utils.rng import DeterministicRandom

    KNOBS.set("DD_STORAGE_FAILURE_SECONDS", 4.0)
    KNOBS.set("DD_INTERVAL_SECONDS", 1.0)
    c = RecoverableCluster(seed=71, n_workers=4, n_proxies=2, n_tlogs=2,
                           n_storage=2, n_replicas=2, n_storage_workers=5)
    db = c.database()

    async def t():
        await db.refresh()
        # seed data across the keyspace
        async def seed(tr):
            for i in range(60):
                tr.set(b"%02x-key" % i, b"val%d" % i)
        await db.transact(seed, max_retries=500)

        # kill a storage worker permanently
        victim = c.storage_worker_procs[0].address
        c.net.kill(victim, KillType.KillProcess)

        # keep writing while the heal runs
        for rnd in range(60):
            async def w(tr, rnd=rnd):
                tr.set(b"live/%03d" % rnd, b"x")
            await db.transact(w, max_retries=500)
            await c.loop.delay(0.3)
            info = c.current_cc()
            if info is None:
                continue
            dead_tags = {t for a, t in info.dbinfo.storages if a == victim}
            teams = info.dbinfo.teams()
            if dead_tags and not any(t in team for t in dead_tags
                                     for team in teams):
                break
        info = c.current_cc().dbinfo
        dead_tags = {t for a, t in info.storages if a == victim}
        for team in info.teams():
            assert not (dead_tags & set(team)), \
                f"dead tag still serving: {info.teams()}"
            assert len(team) == 2, f"replication not restored: {team}"

        # every row readable; replicas identical
        async def readall(tr):
            return await tr.get_range(b"", b"\xff")
        rows = await db.transact(readall, max_retries=500)
        keys = {k for k, _v in rows}
        for i in range(60):
            assert b"%02x-key" % i in keys
        w = ConsistencyCheckWorkload()
        w.init(c, DeterministicRandom(1), stop_at=0)
        await w.check(db)

    c.run(c.loop.spawn(t()), max_time=240_000.0)


def test_cycle_with_storage_attrition_heals():
    """The fault-cocktail spec with HARD storage kills (replication 2):
    serializability holds and replicas agree after healing."""
    KNOBS.set("DD_STORAGE_FAILURE_SECONDS", 4.0)
    KNOBS.set("DD_INTERVAL_SECONDS", 1.0)
    r = run_spec(909, workloads=[CycleWorkload(), AttritionWorkload(),
                                 ConsistencyCheckWorkload()],
                 duration=45.0, buggify=False,
                 n_replicas=2, n_storage_workers=5)
    assert r.rotations > 0
