"""Fitness-based recruitment + preemption (ClusterController.actor.cpp:383
getWorkerForRoleInDatacenter, :799 betterMasterExists).
"""

import pytest

from foundationdb_tpu.server.cluster import RecoverableCluster
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    KNOBS.set("CC_PREEMPT_INTERVAL_SECONDS", 2.0)
    yield
    KNOBS.reset()


def test_preemption_migrates_roles_to_better_worker():
    """Boot with only transaction-class txn workers (degraded placement for
    proxies/master); when a stateless-class worker joins, betterMasterExists
    triggers ONE recovery that moves the stateless-kind roles onto it."""
    c = RecoverableCluster(seed=81, n_workers=3, n_proxies=1, n_resolvers=1,
                           n_tlogs=2, n_storage=1, n_replicas=1)
    # degrade every txn worker to transaction class (they keep both
    # capabilities, so the cluster still recovers — on poor fitness)
    for p in c.worker_procs:
        p.worker.process_class = "transaction"
    db = c.database()

    async def t():
        await db.refresh()
        info0 = c.current_cc().dbinfo
        assert info0.master in [p.address for p in c.worker_procs]

        # a better (stateless-class) worker joins
        c.add_worker("newbie:0", ["stateless"], process_class="stateless")
        for _ in range(60):
            await c.loop.delay(1.0)
            cc = c.current_cc()
            if cc and cc.dbinfo.epoch > info0.epoch \
                    and cc.dbinfo.master == "newbie:0":
                break
        info = c.current_cc().dbinfo
        assert info.master == "newbie:0", info.master
        assert "newbie:0" in info.proxies, info.proxies
        # and it still works
        async def w(tr):
            tr.set(b"after-preempt", b"1")
        await db.transact(w, max_retries=500)
        # no churn: epoch advanced a bounded amount (one preemption +
        # possibly one displacement-triggered recovery)
        assert info.epoch <= info0.epoch + 3, info.epoch

    c.run(c.loop.spawn(t()), max_time=300_000.0)


def test_recruitment_prefers_best_class():
    """With a mixed worker pool from the start, the stateless-kind roles
    land on stateless-class workers and tlogs on transaction-class ones."""
    c = RecoverableCluster(seed=82, n_workers=2, n_proxies=1, n_resolvers=1,
                           n_tlogs=1, n_storage=1, n_replicas=1)
    # make worker:0 transaction class and worker:1 stateless class
    c.worker_procs[0].worker.process_class = "transaction"
    c.worker_procs[1].worker.process_class = "stateless"
    db = c.database()

    async def t():
        await db.refresh()
        # allow preemption cycles to settle placement if the initial
        # recovery raced the class registrations
        for _ in range(60):
            await c.loop.delay(1.0)
            cc = c.current_cc()
            if (cc and cc.dbinfo.master == c.worker_procs[1].address
                    and cc.dbinfo.log_epochs[-1].addrs
                    == [c.worker_procs[0].address]):
                break
        info = c.current_cc().dbinfo
        assert info.master == c.worker_procs[1].address, info.master
        tlogs = info.log_epochs[-1].addrs
        assert tlogs == [c.worker_procs[0].address], tlogs

    c.run(c.loop.spawn(t()), max_time=300_000.0)
