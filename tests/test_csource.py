"""csource front-end: tokenizer fidelity, statement structure, dominance,
goto-ladder resolution — pinned on fixtures AND on the real fdb_native.c.

The NAT rules (test_natlint.py) are only as sound as the shapes this module
extracts, so the round-trip tests here are the foundation: every function in
the real extension must be found with its parameters and labels, and the
ladder/dominance queries must answer exactly as the rule semantics assume.
"""

from __future__ import annotations

import os
import textwrap

from foundationdb_tpu.analysis import csource

_C_SRC = os.path.join(os.path.dirname(__file__), "..", "foundationdb_tpu",
                      "native", "fdb_native.c")


def _parse_one(body: str) -> csource.CFunction:
    src = "static int f(PyObject *o, size_t n) {\n%s\n}\n" % textwrap.dedent(
        body)
    fns = csource.parse_functions(src)
    assert len(fns) == 1 and fns[0].name == "f"
    return fns[0]


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

def test_tokenizer_kinds_and_lines():
    src = ('/* block\n   comment */\n'
           '#define X \\\n    1\n'
           'int a = 10; // trailing\n'
           'char *s = "q\\"uo";\n')
    toks = csource.tokenize(src)
    kinds = [(t.kind, t.line) for t in toks]
    assert ("comment", 1) in kinds          # block comment starts on line 1
    assert ("pp", 3) in kinds               # continuation folded into one pp
    idents = [t for t in toks if t.kind == "ident"]
    assert [t.text for t in idents][:3] == ["int", "a", "char"]
    nums = [t for t in toks if t.kind == "num"]
    assert nums[0].text == "10" and nums[0].line == 5
    strings = [t for t in toks if t.kind == "string"]
    assert strings == [csource.Token("string", '"q\\"uo"', 6)]


def test_tokenizer_two_char_punct_stays_joined():
    toks = csource.code_tokens(csource.tokenize("a->b != c && d <<= 1;"))
    texts = [t.text for t in toks]
    assert "->" in texts and "!=" in texts and "&&" in texts
    # `<<=` is not in the 2-char table: it splits as `<<` `=`, which still
    # keeps the lone-`=` invariant natlint's _split_assign relies on
    assert "<<" in texts


def test_preprocessor_braces_do_not_unbalance_functions():
    src = ("#define GUARD(x) do { if (!(x)) return -1; } while (0)\n"
           "static int g(void) { GUARD(1); return 0; }\n")
    fns = csource.parse_functions(src)
    assert [f.name for f in fns] == ["g"]


def test_suppressions_cover_comment_line_and_next():
    src = ("int a;\n"
           "/* natlint: ignore[NAT004, NAT007] */\n"
           "int b;\n"
           "int c; /* natlint: ignore[all] */\n")
    supp = csource.suppressions(csource.tokenize(src))
    assert supp[2] == {"NAT004", "NAT007"}
    assert supp[3] == {"NAT004", "NAT007"}   # line below the comment
    assert "all" in supp[4]


# ---------------------------------------------------------------------------
# statement structure and dominance
# ---------------------------------------------------------------------------

def test_if_else_structure_and_orelse_blocks():
    fn = _parse_one("""
        if (n > 4) {
            o = NULL;
        } else {
            n = 0;
        }
        return 0;
    """)
    iff = fn.body[0]
    assert iff.kind == "if" and iff.text == "n > 4"
    assert [s.kind for s in iff.body] == ["simple"]
    assert [s.kind for s in iff.orelse] == ["simple"]
    # then- and else-branches get distinct block paths
    assert iff.body[0].block != iff.orelse[0].block


def test_dominance_is_one_sided_at_joins():
    fn = _parse_one("""
        int a = 1;
        if (n) {
            int b = 2;
        }
        int c = 3;
    """)
    a, iff, c = fn.body
    b = iff.body[0]
    assert fn.dominates(a, iff) and fn.dominates(a, b) and fn.dominates(a, c)
    assert fn.dominates(iff, b)
    assert not fn.dominates(b, c)   # branch statement never covers the join
    assert not fn.dominates(c, a)   # order respected


def test_loop_body_is_dominated_by_loop_header():
    fn = _parse_one("""
        while (n--) {
            o = NULL;
        }
        return 0;
    """)
    loop = fn.body[0]
    assert loop.is_loop
    assert fn.dominates(loop, loop.body[0])
    assert not fn.dominates(loop.body[0], fn.body[1])


def test_goto_ladder_flattens_and_chases_chained_labels():
    fn = _parse_one("""
        if (!o) goto err_a;
        return 0;
    err_a:
        n = 1;
        goto err_b;
    err_b:
        if (n) {
            n = 2;
        }
        return -1;
    """)
    ladder = fn.ladder("err_a")
    texts = [s.text for s in ladder]
    assert "n = 1" in texts
    assert "n = 2" in texts          # bodies are flattened
    assert ladder[-1].kind == "return"
    assert ladder[-1].text.replace(" ", "") == "-1"
    # cycle guard: a self-referential chain terminates
    fn2 = _parse_one("""
    loop_a:
        n = 1;
        goto loop_a;
    """)
    assert all(s.kind != "return" for s in fn2.ladder("loop_a"))


def test_exits_enumerates_returns_and_gotos_with_terminals():
    fn = _parse_one("""
        if (!o) goto fail;
        return 0;
    fail:
        return -1;
    """)
    exits = fn.exits()
    kinds = sorted((e.kind, t.text.replace(" ", "") if t else None)
                   for e, _, t in exits)
    assert kinds == [("goto", "-1"), ("return", "-1"), ("return", "0")]
    goto_exit = next(e for e in exits if e[0].kind == "goto")
    assert goto_exit[2] is not None
    assert goto_exit[2].text.replace(" ", "") == "-1"


def test_bare_gil_macros_parse_without_semicolons():
    fn = _parse_one("""
        Py_BEGIN_ALLOW_THREADS
        n = 0;
        Py_END_ALLOW_THREADS
        return 0;
    """)
    texts = [s.text for s in fn.body]
    assert texts[0] == "Py_BEGIN_ALLOW_THREADS"
    assert texts[2] == "Py_END_ALLOW_THREADS"


def test_params_parsed_with_pointer_types():
    src = "static int h(const uint8_t *p, Py_ssize_t len, PyObject *o) {\n" \
          "    return 0;\n}\n"
    fn = csource.parse_functions(src)[0]
    names = [p.name for p in fn.params]
    assert names == ["p", "len", "o"]
    assert "*" in fn.params[0].type and "uint8_t" in fn.params[0].type
    assert "PyObject" in fn.params[2].type


# ---------------------------------------------------------------------------
# round-trip on the real extension source
# ---------------------------------------------------------------------------

def test_real_file_round_trip():
    with open(_C_SRC, encoding="utf-8") as f:
        src = f.read()
    fns = csource.parse_functions(src)
    names = {fn.name for fn in fns}
    # the dispatch surface build_native.sh import-checks must all be found
    for expected in ("py_crc32c", "py_encode_keys_into",
                     "py_redwood_encode_block", "py_redwood_decode_block",
                     "py_encode_conflict_ranges", "crc32c_sw",
                     "PyInit_fdb_native"):
        assert expected in names, f"parser lost {expected}"
    assert len(fns) >= 60  # the file is large; wholesale loss would show

    # goto ladders natlint's NAT002 depends on resolve to their returns
    dec = next(fn for fn in fns if fn.name == "py_redwood_decode_block")
    assert "corrupt_list" in dec.by_label and "corrupt" in dec.by_label
    ladder = dec.ladder("corrupt_list")
    assert ladder and ladder[-1].kind == "return"
    assert any("Py_DECREF ( out )" in s.text for s in ladder)

    enc = next(fn for fn in fns if fn.name == "py_encode_conflict_ranges")
    assert "done" in enc.by_label
    assert any("Py_XDECREF" in s.text for s in enc.ladder("done"))


def test_real_file_statements_carry_every_brace_balanced():
    """The parser consumed the whole file: the last function's last
    statement line is near the end of the source, not stuck mid-file after
    an unbalanced construct."""
    with open(_C_SRC, encoding="utf-8") as f:
        src = f.read()
    total_lines = src.count("\n")
    fns = csource.parse_functions(src)
    last_line = max(s.line for fn in fns for s in fn.flat)
    assert last_line > total_lines - 40
