"""Storage replication: teams, read load-balancing/failover, consistency.

Reference: fdbserver/DataDistribution.actor.cpp:515 (DDTeamCollection server
teams), fdbrpc/LoadBalance.actor.h:159 (replica selection + failover),
fdbserver/workloads/ConsistencyCheck.actor.cpp (replica comparison).
Replication rides the log: the proxy tags every mutation with ALL team
members' tags, so each replica pulls its own copy.
"""

import pytest

from foundationdb_tpu.core.sim import KillType
from foundationdb_tpu.server.cluster import RecoverableCluster
from foundationdb_tpu.testing import (
    AttritionWorkload, ConsistencyCheckWorkload, CycleWorkload,
    RandomCloggingWorkload, run_spec)
from foundationdb_tpu.utils.knobs import KNOBS


@pytest.fixture(autouse=True)
def _oracle_backend():
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    yield


def test_reads_survive_replica_kill():
    """With a 2-replica team, killing one storage server permanently must
    not lose reads or writes: the client fails over to the surviving team
    member."""
    c = RecoverableCluster(seed=41, n_workers=4, n_proxies=1, n_tlogs=2,
                           n_storage=2, n_replicas=2, n_storage_workers=4)
    db = c.database()

    async def t():
        await db.refresh()
        async def setup(tr):
            for i in range(12):
                tr.set(bytes([20 * i]) + b"/r", b"v%02d" % i)
        await db.transact(setup)

        # kill one replica of shard 0 FOR GOOD (no reboot)
        info = c.current_cc().dbinfo
        addr_of_tag = {t: a for a, t in info.storages}
        victim = addr_of_tag[info.shard_tags[0][0]]
        c.net.kill(victim)

        async def read_all(tr):
            rows = await tr.get_range(b"", b"\xff")
            return [(k, v) for k, v in rows if k.endswith(b"/r")]
        rows = await db.transact(read_all, max_retries=300)
        assert len(rows) == 12, f"lost rows after replica kill: {len(rows)}"

        # writes still commit and are readable (the survivor keeps pulling)
        async def more(tr):
            tr.set(b"\x01after", b"yes")
        await db.transact(more, max_retries=300)
        async def readback(tr):
            return await tr.get(b"\x01after")
        assert await db.transact(readback, max_retries=300) == b"yes"

    c.run(c.loop.spawn(t()), max_time=30_000.0)


def test_replica_consistency_after_fault_cocktail():
    """Cycle + clogging + attrition against a replicated cluster; after
    quiescing, every shard's replicas must hold identical data."""
    r = run_spec(88, workloads=[CycleWorkload(), RandomCloggingWorkload(),
                                AttritionWorkload(interval=10.0),
                                ConsistencyCheckWorkload()],
                 duration=40.0, n_replicas=2, n_storage=2)
    assert r.rotations > 0


def test_consistency_check_detects_divergence():
    """The checker itself must FAIL when replicas genuinely diverge (inject
    a rogue write into one replica's versioned map directly)."""
    c = RecoverableCluster(seed=43, n_workers=4, n_proxies=1, n_tlogs=1,
                           n_storage=1, n_replicas=2, n_storage_workers=2)
    db = c.database()

    async def t():
        await db.refresh()
        async def setup(tr):
            tr.set(b"k", b"v")
        await db.transact(setup)
        info = c.current_cc().dbinfo
        addr_of_tag = {t: a for a, t in info.storages}
        tag0 = info.shard_tags[0][0]
        proc = c.net.processes[addr_of_tag[tag0]]
        ss = proc.worker.roles[f"storage:{tag0}"]
        from foundationdb_tpu.utils.types import Mutation, MutationType
        ss.data.apply(ss.version.get(), Mutation(
            MutationType.SET_VALUE, b"rogue", b"divergent"))

        w = ConsistencyCheckWorkload()
        w.init(c, c.rng.fork(), 0)
        try:
            await w.check(db)
            raise AssertionError("divergence not detected")
        except AssertionError as e:
            assert "diverges" in str(e)

    c.run(c.loop.spawn(t()), max_time=30_000.0)
