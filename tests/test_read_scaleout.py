"""Read scale-out unit tests: the versioned hot-key cache's
invalidation-at-version contract, the storage server's fetched-version
watermark fencing, and hedged reads settling on the first replica to answer.

Reference: fdbserver/StorageCache.actor.cpp (version-tagged serving),
storageserver.actor.cpp fetchKeys (local history begins at the splice's
snapshot version — serving below it would fabricate an empty past), and
fdbrpc/LoadBalance.actor.h:159 (backup requests: first response wins,
the loser is ignored, correctness never depends on which one answered).
"""

from __future__ import annotations

import pytest

from foundationdb_tpu.core.eventloop import EventLoop
from foundationdb_tpu.core.sim import Endpoint, SimNetwork
from foundationdb_tpu.server.interfaces import (
    AddShardRequest, GetKeyValuesReply, GetValueRequest, TLogPeekReply,
    Token)
from foundationdb_tpu.server.readcache import VersionedReadCache
from foundationdb_tpu.server.storage import StorageServer
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.knobs import KNOBS
from foundationdb_tpu.utils.rng import DeterministicRandom
from foundationdb_tpu.utils.types import Mutation, MutationType


@pytest.fixture(autouse=True)
def _reset_knobs():
    yield
    KNOBS.reset()


# ---------------------------------------------------------------------------
# VersionedReadCache: the version-tag contract, pure
# ---------------------------------------------------------------------------

def _hot_cache(**kw) -> VersionedReadCache:
    """A cache whose hot set is forced by hand (no sketch warm-up)."""
    kw.setdefault("max_entries", 8)
    kw.setdefault("sample", 1)
    kw.setdefault("hot_rate", 1.0)
    rc = VersionedReadCache(**kw)
    rc.hot_ranges = [(b"hot/", b"hot0")]
    return rc


def _set(k, v):
    return Mutation(MutationType.SET_VALUE, k, v)


def _clear_range(b, e):
    return Mutation(MutationType.CLEAR_RANGE, b, e)


class TestVersionedReadCache:
    def test_hit_only_at_or_above_valid_from(self):
        """The tag proves exactness for v >= valid_from and NOTHING below:
        a read at an older version must fall through to MVCC (the cached
        value may postdate it)."""
        rc = _hot_cache()
        rc.populate(b"hot/a", b"v7", latest_version=700)
        assert rc.lookup(b"hot/a", 700) == (True, b"v7")
        assert rc.lookup(b"hot/a", 900) == (True, b"v7")
        hit, _ = rc.lookup(b"hot/a", 699)
        assert not hit, "served a value tagged ABOVE the read version"

    def test_point_write_invalidates_at_its_version(self):
        """A committed mutation drops the entry in the same tick it is
        applied, so no read at any version >= the write can hit the stale
        value; a re-populate then tags at the post-write version."""
        rc = _hot_cache()
        rc.populate(b"hot/a", b"old", latest_version=700)
        rc.invalidate([_set(b"hot/a", b"new")])
        assert rc.invalidations == 1
        assert rc.lookup(b"hot/a", 800) == (False, None)
        rc.populate(b"hot/a", b"new", latest_version=800)
        assert rc.lookup(b"hot/a", 800) == (True, b"new")
        hit, _ = rc.lookup(b"hot/a", 750)
        assert not hit, "pre-write version must not see the post-write value"

    def test_clear_range_invalidates_only_touched_keys(self):
        rc = _hot_cache()
        rc.populate(b"hot/a", b"1", latest_version=10)
        rc.populate(b"hot/b", b"2", latest_version=10)
        rc.populate(b"hot/z", b"3", latest_version=10)
        rc.invalidate([_clear_range(b"hot/a", b"hot/c")])
        assert rc.invalidations == 2
        assert rc.lookup(b"hot/a", 20) == (False, None)
        assert rc.lookup(b"hot/b", 20) == (False, None)
        assert rc.lookup(b"hot/z", 20) == (True, b"3")

    def test_untouched_keys_survive_other_writes(self):
        rc = _hot_cache()
        rc.populate(b"hot/a", b"1", latest_version=10)
        rc.invalidate([_set(b"hot/other", b"x")])
        assert rc.invalidations == 0
        assert rc.lookup(b"hot/a", 50) == (True, b"1")

    def test_clear_drops_everything(self):
        """Rollback / fetchKeys splice rewrite history out from under the
        tags: the whole table goes."""
        rc = _hot_cache()
        rc.populate(b"hot/a", b"1", latest_version=10)
        rc.populate(b"hot/b", b"2", latest_version=10)
        rc.clear()
        assert rc.invalidations == 2
        assert rc.entries == {}

    def test_populate_refuses_cold_keys_and_bounds_entries(self):
        rc = _hot_cache(max_entries=2)
        rc.populate(b"cold/x", b"v", latest_version=1)
        assert rc.entries == {}, "cold key must not be cached"
        rc.populate(b"hot/a", b"1", latest_version=1)
        rc.populate(b"hot/b", b"2", latest_version=1)
        rc.populate(b"hot/c", b"3", latest_version=1)  # evicts FIFO head
        assert len(rc.entries) == 2 and rc.evictions == 1
        assert rc.lookup(b"hot/a", 5) == (False, None)
        assert rc.lookup(b"hot/c", 5) == (True, b"3")

    def test_none_value_is_cacheable(self):
        """Absence is a value too: a hot key that does not exist hits as
        None instead of re-walking the MVCC map every probe."""
        rc = _hot_cache()
        rc.populate(b"hot/missing", None, latest_version=30)
        assert rc.lookup(b"hot/missing", 40) == (True, None)


# ---------------------------------------------------------------------------
# Watermark fencing on a live storage server (scripted TLog harness)
# ---------------------------------------------------------------------------

class _ScriptedTLog:
    """A fake TLog process serving a fixed message list (the
    test_storage_safety harness, trimmed to what fencing needs)."""

    def __init__(self, process, messages, end, kc):
        self.process = process
        self.messages = messages
        self.end = end
        self.kc = kc
        process.register(Token.TLOG_PEEK, self._on_peek)
        process.register(Token.TLOG_POP, lambda req, reply: reply.send(None))

    def _on_peek(self, req, reply):
        msgs = [(v, list(muts)) for v, muts in self.messages
                if v >= req.begin]
        reply.send(TLogPeekReply(messages=msgs, end=self.end, popped=0,
                                 known_committed_version=self.kc))


def _fencing_harness():
    """One storage server on [a, b) fed by a scripted log, plus a source
    process ready to serve a fetchKeys snapshot of [m, n)."""
    KNOBS.set("MAX_READ_TRANSACTION_LIFE_VERSIONS", 10)
    loop = EventLoop()
    net = SimNetwork(loop, DeterministicRandom(11))
    tlog_proc = net.new_process("tlog:0")
    msgs = [(v, [_set(b"a%03d" % v, b"v")]) for v in range(1, 51)]
    tlog = _ScriptedTLog(tlog_proc, msgs, end=51, kc=50)

    src_proc = net.new_process("src:0")

    def on_get_kv(req, reply):
        reply.send(GetKeyValuesReply(data=[(b"m00", b"s")], more=False,
                                     version=req.version))
    src_proc.register(Token.STORAGE_GET_KEY_VALUES, on_get_kv)

    ss_proc = net.new_process("ss:0")
    ss = StorageServer(ss_proc, tag=0, tlog_addrs=["tlog:0"],
                       shard_ranges=[(b"a", b"b")])
    client = net.new_process("client:0")
    return loop, net, tlog, ss, client


def test_fetched_watermark_fences_reads_below_snapshot():
    """After a fetchKeys splice at snapshot version c0, the spliced range's
    local history STARTS at c0: a read below it must get wrong_shard_server
    (re-resolve onto a replica that lived through those versions) and bump
    the WatermarkRejects ledger, while reads at/above c0 serve normally."""
    loop, net, tlog, ss, client = _fencing_harness()

    async def t():
        await loop.delay(2.0)
        c0 = await net.request(
            client, Endpoint("ss:0", Token.STORAGE_ADD_SHARD),
            AddShardRequest(begin=b"m", end=b"n", source="src:0",
                            fence_version=40))
        assert c0 == 50, c0
        assert ss._watermarks == [(b"m", b"n", 50)]

        async def read(key, version):
            return await net.request(
                client, Endpoint("ss:0", Token.STORAGE_GET_VALUE),
                GetValueRequest(key=key, version=version))

        # at/above the snapshot: the spliced row serves
        assert (await read(b"m00", 50)).value == b"s"
        # below it: fenced, and the ledger counts the reject
        before = ss.counters.as_dict()["WatermarkRejects"]
        with pytest.raises(FDBError) as ei:
            await read(b"m00", 49)
        assert ei.value.name == "wrong_shard_server"
        assert ss.counters.as_dict()["WatermarkRejects"] == before + 1
        # the ORIGINAL shard has full local history: no fence applies to a
        # below-c0 read there (45 is inside the MVCC window, floor is 40)
        assert (await read(b"a045", 45)).value == b"v"

    loop.run_future(loop.spawn(t()), max_time=600.0)


def test_watermark_pruned_once_mvcc_floor_passes():
    """A watermark at/below the MVCC floor can never fire again (those
    versions already throw transaction_too_old): durability advancing past
    it must prune the fence so the serve path stops paying for it."""
    loop, net, tlog, ss, client = _fencing_harness()

    async def t():
        await loop.delay(2.0)
        c0 = await net.request(
            client, Endpoint("ss:0", Token.STORAGE_ADD_SHARD),
            AddShardRequest(begin=b"m", end=b"n", source="src:0",
                            fence_version=40))
        assert c0 == 50 and ss._watermarks
        # extend the log well past c0 + the read-life window and let
        # durability advance: the floor passes 50, the fence goes
        tlog.messages.extend(
            (v, [_set(b"a%03d" % v, b"v")]) for v in range(51, 151))
        tlog.end = 151
        tlog.kc = 150
        await loop.delay(5.0)
        assert ss.data.oldest_version >= 50
        assert ss._watermarks == [], ss._watermarks
        # reads below the old fence now fail as too-old, not wrong-shard
        with pytest.raises(FDBError) as ei:
            await net.request(
                client, Endpoint("ss:0", Token.STORAGE_GET_VALUE),
                GetValueRequest(key=b"m00", version=49))
        assert ei.value.name == "transaction_too_old"

    loop.run_future(loop.spawn(t()), max_time=600.0)


# ---------------------------------------------------------------------------
# Hedged reads: first replica to settle wins, ledger records it
# ---------------------------------------------------------------------------

def test_hedge_settles_first_wins_and_ledger_records_it():
    """With one replica of a 2-replica team clogged, the first read sent
    there must be rescued by a backup request to the healthy replica: the
    hedge's reply settles the read (correct value, no stall) and the
    client's lb ledger records both the hedge and the win."""
    KNOBS.set("CONFLICT_BACKEND", "oracle")
    from foundationdb_tpu.server.cluster import RecoverableCluster
    c = RecoverableCluster(seed=31, n_workers=4, n_proxies=1, n_tlogs=1,
                           n_storage=1, n_replicas=2, n_storage_workers=2)
    db = c.database()

    async def t():
        await db.refresh()

        async def setup(tr):
            for i in range(8):
                tr.set(b"hw%02d" % i, b"v%02d" % i)
        await db.transact(setup)

        team, _end = db.locations.locate(b"hw00")
        assert len(team) == 2, team
        # clog the link to one replica for the whole test: any read routed
        # there first can only finish through its backup request, so every
        # completed read that touched team[0] is a settled-by-hedge proof
        c.net.clog_pair(db.process.address, team[0], 600.0)

        for i in range(12):
            tr = db.create_transaction()
            v = await tr.get(b"hw%02d" % (i % 8))
            assert v == b"v%02d" % (i % 8)

    c.run(c.loop.spawn(t()), max_time=30_000.0)
    snap = db.lb_snapshot()
    assert snap["hedges"] >= 1, snap
    assert snap["hedge_wins"] >= 1, snap
