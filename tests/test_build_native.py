"""Tier-1 compile-smoke for the native extension: scripts/build_native.sh
builds fdb_native.c from scratch in a temp dir and import-checks the
dispatch surface (crc32c, bulk key encoding, the redwood block codec).
Skips cleanly (exit 75, EX_TEMPFAIL) on hosts without a C compiler — the
pure-Python fallbacks are the supported path there."""

import os
import subprocess

import pytest

_SCRIPT = os.path.join(os.path.dirname(__file__), "..", "scripts",
                       "build_native.sh")


def test_native_extension_compiles_and_imports():
    proc = subprocess.run(["sh", _SCRIPT], capture_output=True, text=True,
                          timeout=300)
    if proc.returncode == 75:
        pytest.skip("no C compiler on PATH")
    assert proc.returncode == 0, proc.stderr
    assert "build_native: OK" in proc.stdout
