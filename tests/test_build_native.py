"""Tier-1 compile-smoke for the native extension: scripts/build_native.sh
builds fdb_native.c from scratch in a temp dir and import-checks the
dispatch surface (crc32c, bulk key encoding, the redwood block codec).
Skips cleanly (exit 75, EX_TEMPFAIL) on hosts without a C compiler — the
pure-Python fallbacks are the supported path there.

The --sanitize mode is the runtime half of natlint (docs/natlint.md): it
rebuilds the extension under ASan/UBSan and re-runs the three parity
fuzzes (VStore read path, redwood block codec, transport framing) against
the instrumented build, so memory errors the static rules can't prove are
still caught in tier-1."""

import os
import subprocess

import pytest

_SCRIPT = os.path.join(os.path.dirname(__file__), "..", "scripts",
                       "build_native.sh")


def test_native_extension_compiles_and_imports():
    proc = subprocess.run(["sh", _SCRIPT], capture_output=True, text=True,
                          timeout=300)
    if proc.returncode == 75:
        pytest.skip("no C compiler on PATH")
    assert proc.returncode == 0, proc.stderr
    assert "build_native: OK" in proc.stdout


def test_parity_fuzzes_clean_under_sanitizers():
    proc = subprocess.run(["sh", _SCRIPT, "--sanitize=address,undefined"],
                          capture_output=True, text=True, timeout=600)
    if proc.returncode == 75:
        pytest.skip("no C compiler or sanitizer runtime on this host")
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out
    # every fuzz family must have actually run — a silently-skipped fuzz
    # would report "clean" while covering nothing
    for marker in ("vstore parity OK", "redwood codec parity OK",
                   "transport framing fuzz OK", "redwood read path fuzz OK",
                   "no sanitizer reports"):
        assert marker in out, out
