"""flowlint: the analyzer's own tests + tier-1 enforcement over the package.

Three layers:
  1. Per-rule good/bad snippet fixtures — each rule must flag its exemplar
     bug class and stay quiet on the disciplined version.
  2. The round-5 ADVICE regressions — the linter must catch the PRE-fix
     shape of every hand-found bug (resolver drain-gate wedge, FDBFuture
     race), and the fixed behavior is pinned directly (read timeouts,
     CRC-32C fallback, blob-store backoff, drain-gate cancel survival).
  3. Enforcement: the analyzer runs over the real foundationdb_tpu package
     and must report ZERO non-baselined findings, with every baseline entry
     documented — so the analyzer is exercised and the discipline is
     enforced by the same tier-1 run.
"""

from __future__ import annotations

import json
import os
import textwrap
import threading

import pytest

import foundationdb_tpu
from foundationdb_tpu.analysis import flowlint
from foundationdb_tpu.analysis.__main__ import main as flowlint_main
from foundationdb_tpu.core.eventloop import EventLoop
from foundationdb_tpu.core.future import Future, ready_future
from foundationdb_tpu.core.notified import NotifiedVersion
from foundationdb_tpu.utils.errors import FDBError

SERVER_PATH = "foundationdb_tpu/server/snippet.py"
OTHER_PATH = "foundationdb_tpu/layers/snippet.py"


def lint(source: str, path: str = SERVER_PATH):
    return flowlint.analyze_source(textwrap.dedent(source), path)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------- FLOW001

def test_flow001_flags_wall_clock_in_sim_coroutine():
    findings = lint("""
        import time
        import random

        class Role:
            async def tick(self):
                start = time.time()
                await self.step()
                time.sleep(0.1)
                return random.random() + start
    """)
    assert [f.rule for f in findings] == ["FLOW001"] * 3
    assert {f.detail for f in findings} == {
        "time.time", "time.sleep", "random.random"}
    assert all(f.symbol == "Role.tick" for f in findings)


def test_flow001_resolves_import_aliases():
    findings = lint("""
        import time as t
        from datetime import datetime

        class Role:
            async def tick(self):
                await self.step()
                return t.monotonic(), datetime.now()
    """)
    assert {f.detail for f in findings} == {
        "time.monotonic", "datetime.datetime.now"}


def test_flow001_quiet_outside_coroutines_and_outside_sim_dirs():
    src = """
        import time

        def wall_clock():
            return time.time()   # sync helper: RealEventLoop territory

        class Role:
            async def tick(self):
                await self.step()
                return self.loop.now()
    """
    assert lint(src) == []
    # same nondeterminism in a non-sim-visible subpackage is not flagged
    bad = """
        import time

        class Tool:
            async def run(self):
                await self.step()
                return time.time()
    """
    assert lint(bad, OTHER_PATH) == []
    assert rules_of(lint(bad, SERVER_PATH)) == ["FLOW001"]


def test_flow001_inline_suppression():
    findings = lint("""
        import time

        class Role:
            async def tick(self):
                await self.step()
                return time.time()  # flowlint: ignore[FLOW001]
    """)
    assert findings == []


def test_inline_suppression_ignore_all():
    findings = lint("""
        import time
        import random

        class Role:
            async def tick(self):
                await self.step()
                return time.time() + random.random()  # flowlint: ignore[all]
    """)
    assert findings == []


def test_inline_suppression_multi_code_list():
    src = """
        import time

        class Role:
            async def refresh(self):
                await self.step()

            async def tick(self):
                await self.step()
                self.refresh()  # kick
                return time.time()  # marker
    """
    noisy = lint(src)
    assert rules_of(noisy) == ["FLOW001", "FLOW005"]
    # a comma-separated code list suppresses any of its codes on that line
    suppressed = lint(src
                      .replace("self.refresh()  # kick",
                               "self.refresh()  "
                               "# flowlint: ignore[FLOW005,FLOW001]")
                      .replace("return time.time()  # marker",
                               "return time.time()  "
                               "# flowlint: ignore[FLOW001,FLOW002]"))
    assert suppressed == []
    # codes that don't match the line's finding suppress nothing
    wrong_code = lint(src.replace(
        "return time.time()  # marker",
        "return time.time()  # flowlint: ignore[FLOW002,FLOW004]"))
    assert rules_of(wrong_code) == ["FLOW001", "FLOW005"]


# ---------------------------------------------------------------- FLOW002

PREFIX_DRAIN_GROUP = """
    class Resolver:
        async def _drain_group(self, seq, entries):
            try:
                await self.loop.run_blocking(self.drain)
            except Exception:
                raise
            await self._drained_seq.when_at_least(seq - 1)
            try:
                for entry in entries:
                    self.finish(entry)
            finally:
                self._drained_seq.set(seq)
"""


def test_flow002_flags_prefix_resolver_drain_gate():
    """Round-5 ADVICE resolver.py:148 regression: the pre-fix _drain_group
    settled the sequencing gate in a finally that did NOT cover the two
    awaits before it — the linter must flag exactly that shape."""
    findings = lint(PREFIX_DRAIN_GROUP)
    assert [f.rule for f in findings] == ["FLOW002"]
    assert findings[0].detail == "self._drained_seq.set"
    assert findings[0].symbol == "Resolver._drain_group"


def test_flow002_quiet_when_finally_covers_all_awaits():
    """The fixed shape: one try/finally around the whole coroutine body."""
    findings = lint("""
        class Resolver:
            async def _drain_group(self, seq, entries):
                try:
                    await self.loop.run_blocking(self.drain)
                    await self._drained_seq.when_at_least(seq - 1)
                    for entry in entries:
                        self.finish(entry)
                finally:
                    self._drained_seq.set(seq)
    """)
    assert findings == []


def test_flow002_quiet_for_reply_promises_and_pre_await_settles():
    findings = lint("""
        class Role:
            async def serve(self, req, reply):
                self.gate.set(1)          # before any await: always runs
                data = await self.read(req)
                reply.send(data)          # transport breaks owed replies
    """)
    assert findings == []


def test_flow002_quiet_inside_nested_callbacks():
    findings = lint("""
        class Role:
            async def run(self, seq):
                await self.work()
                self.gate.when_at_least(seq - 1).add_callback(
                    lambda _f: self.gate.set(seq))
    """)
    assert [f.rule for f in findings] == []


# ---------------------------------------------------------------- FLOW003

PREFIX_FDBFUTURE = """
    import threading

    class FDBFuture:
        def __init__(self):
            self._event = threading.Event()
            self._callbacks = []
            self._error = None

        def _resolve_from(self, f):
            self._error = f
            self._event.set()
            for cb in self._callbacks:
                cb(self)

        def set_callback(self, cb):
            if self._event.is_set():
                cb(self)
            else:
                self._callbacks.append(cb)

        def cancel(self):
            self._error = "cancelled"
            self._event.set()

        def destroy(self):
            self._callbacks = []
"""


def test_flow003_flags_prefix_fdbfuture_race():
    """Round-5 ADVICE fdb_c.py:116 regression: a cross-thread class
    (threading.Event marker) mutating shared attrs from several methods
    with no lock at all."""
    findings = lint(PREFIX_FDBFUTURE, "foundationdb_tpu/bindings/snippet.py")
    assert rules_of(findings) == ["FLOW003"]
    assert {f.detail for f in findings} == {"_error", "_callbacks"}


def test_flow003_quiet_when_all_mutations_locked():
    findings = lint("""
        import threading

        class FDBFuture:
            def __init__(self):
                self._event = threading.Event()
                self._mutex = threading.Lock()
                self._callbacks = []
                self._error = None

            def _resolve_from(self, f):
                with self._mutex:
                    self._error = f
                    cbs, self._callbacks = self._callbacks, []
                self._event.set()
                for cb in cbs:
                    cb(self)

            def cancel(self):
                with self._mutex:
                    self._error = "cancelled"
    """, "foundationdb_tpu/bindings/snippet.py")
    assert findings == []


def test_flow003_flags_mixed_locked_unlocked_sites():
    findings = lint("""
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, v):
                with self._lock:
                    self._items.append(v)

            def drop_all(self):
                self._items.clear()
    """, "foundationdb_tpu/bindings/snippet.py")
    assert [f.rule for f in findings] == ["FLOW003"]
    assert findings[0].symbol == "Store.drop_all"


def test_flow003_quiet_without_threading_import():
    findings = lint("""
        class Plain:
            def __init__(self):
                self._x = 0

            def bump(self):
                self._x += 1

            def reset(self):
                self._x = 0
    """, "foundationdb_tpu/bindings/snippet.py")
    assert findings == []


# ---------------------------------------------------------------- FLOW004

def test_flow004_flags_bare_except_and_swallowed_base_exception():
    findings = lint("""
        class Role:
            async def a(self):
                try:
                    await self.step()
                except:
                    pass

            async def b(self):
                try:
                    await self.step()
                except BaseException:
                    self.log()
    """)
    assert [f.rule for f in findings] == ["FLOW004", "FLOW004"]
    assert {f.detail for f in findings} == {"bare-except", "BaseException"}


def test_flow004_quiet_when_cancellation_reraised():
    findings = lint("""
        class Role:
            async def a(self):
                try:
                    await self.step()
                except BaseException:
                    self.cleanup()
                    raise

            async def b(self):
                try:
                    await self.step()
                except FDBError as e:
                    if e.name == "operation_cancelled":
                        raise
                    self.err = e
                except BaseException as e:
                    self.err = e
    """)
    assert findings == []


# ---------------------------------------------------------------- FLOW005

def test_flow005_flags_dropped_coroutine_and_gate_future():
    findings = lint("""
        class Role:
            async def refresh(self):
                await self.step()

            def kick(self):
                self.refresh()

            def wait_wrong(self):
                self.version.when_at_least(5)
    """)
    assert [f.rule for f in findings] == ["FLOW005", "FLOW005"]
    assert {f.detail for f in findings} == {"refresh", "when_at_least"}


def test_flow005_quiet_for_await_spawn_and_unrelated_names():
    findings = lint("""
        class Index:
            async def set(self, tr, k, v):
                await tr.get(k)
                tr.set(k, v)       # sync method of another object: fine

        class Role:
            async def refresh(self):
                await self.step()

            async def ok(self):
                await self.refresh()
                self.loop.spawn(self.refresh())
                fut = self.version.when_at_least(5)
                await fut
    """)
    assert findings == []


# ---------------------------------------------------------------- FLOW006

def test_flow006_flags_device_eval_at_import():
    findings = lint("""
        import jax
        import jax.numpy as jnp

        NEG = jnp.int32(-5)
        NDEV = jax.device_count()
    """, "foundationdb_tpu/ops/snippet.py")
    assert [f.rule for f in findings] == ["FLOW006", "FLOW006"]
    assert {f.detail for f in findings} == {
        "jax.numpy.int32", "jax.device_count"}
    assert all(f.symbol == "<module>" for f in findings)


def test_flow006_quiet_for_lazy_eval_and_jit_decorators():
    findings = lint("""
        import functools

        import jax
        import jax.numpy as jnp

        NEG = -(1 << 30)   # plain host int on purpose

        @jax.jit
        def kernel(x):
            return jnp.maximum(x, NEG)

        @functools.partial(jax.jit, static_argnums=0)
        def kernel2(n, x):
            return x + jnp.zeros((n,))
    """, "foundationdb_tpu/ops/snippet.py")
    assert findings == []


# ---------------------------------------------------------------- FLOW007

def test_flow007_flags_unlogged_trace_event_statement():
    findings = lint("""
        from foundationdb_tpu.utils.trace import TraceEvent

        def report(addr, n):
            TraceEvent("RoleMetrics", addr).detail("N", n)
            TraceEvent("RoleUp", addr)
    """)
    assert [f.rule for f in findings] == ["FLOW007", "FLOW007"]


def test_flow007_flags_assigned_event_never_logged():
    findings = lint("""
        from foundationdb_tpu.utils.trace import TraceEvent

        def report(addr, rows):
            ev = TraceEvent("RoleMetrics", addr)
            for k, v in rows:
                ev.detail(k, v)
    """)
    assert [f.rule for f in findings] == ["FLOW007"]
    assert findings[0].detail == "ev"


def test_flow007_quiet_for_logged_and_escaping_events():
    findings = lint("""
        from foundationdb_tpu.utils.trace import TraceEvent

        def direct(addr):
            TraceEvent("RoleMetrics", addr).detail("N", 1).log()

        def accumulated(addr, rows):
            ev = TraceEvent("RoleMetrics", addr)
            for k, v in rows:
                ev.detail(k, v)
            ev.log()

        def escapes(addr):
            ev = TraceEvent("RoleMetrics", addr)
            return ev  # the caller owns the .log() now

        def unrelated(tr):
            tr.set(b"k", b"v")  # fluent-looking but not a TraceEvent
    """)
    assert findings == []


# ------------------------------------------------- ADVICE fix regressions

def test_advice_fix_drain_gate_survives_partial_cancel():
    """resolver.py fix: _advance_drained must advance the gate even when a
    group dies mid-drain, without jumping over a still-running predecessor
    or moving the gate backwards."""
    from foundationdb_tpu.server.resolver import Resolver

    class Shell:
        _drained_seq = NotifiedVersion(0)
    shell = Shell()

    # group 2 and 3 both cancelled while group 1 still runs: the advances
    # chain off when_at_least and fire in order once group 1 lands
    Resolver._advance_drained(shell, 3)
    Resolver._advance_drained(shell, 2)
    assert shell._drained_seq.get() == 0
    Resolver._advance_drained(shell, 1)  # group 1's finally
    assert shell._drained_seq.get() == 3  # chained through 1 -> 2 -> 3

    # idempotent / never backwards
    Resolver._advance_drained(shell, 2)
    assert shell._drained_seq.get() == 3


def test_advice_fix_timeout_option_bounds_reads():
    """transaction.py fix: the timeout option (code 500) must bound the
    READ path, not just GRV/commit — a hung storage read surfaces as the
    retryable timed_out at the deadline."""
    from foundationdb_tpu.client.transaction import Transaction

    loop = EventLoop()

    class GRVReply:
        version = 7

    class StubDB:
        def __init__(self):
            self.loop = loop
            self.hung = Future()  # never resolves

        def _grv(self):
            return ready_future(GRVReply())

        def _read_get(self, key, version):
            return self.hung

    tr = Transaction(StubDB())
    tr.set_option(500, (100).to_bytes(8, "little"))  # 100 ms
    task = loop.spawn(tr.get(b"k"))
    with pytest.raises(FDBError) as err:
        loop.run_future(task)
    assert err.value.name == "timed_out"
    assert loop.now() == pytest.approx(0.1)


def test_advice_fix_crc32c_fallback_is_real_crc32c(monkeypatch):
    """http.py fix: the pure-Python fallback must compute CRC-32C
    (Castagnoli), not zlib's CRC-32 — otherwise a native-enabled writer and
    a pure-Python reader disagree on every checksum and restore breaks."""
    import zlib

    from foundationdb_tpu import native
    from foundationdb_tpu.net import http

    monkeypatch.setattr(native, "available", lambda: False)
    got = http._crc32c(b"123456789")
    assert got == 0xE3069283          # the published CRC-32C test vector
    assert got != zlib.crc32(b"123456789")
    assert http._crc32c(b"") == 0


def test_advice_fix_blobstore_retries_back_off():
    """container.py fix: _request must sleep a bounded exponential backoff
    between attempts instead of hammering the endpoint back-to-back."""
    from foundationdb_tpu.backup.container import BlobStoreBackupContainer
    from foundationdb_tpu.net.http import HTTPError

    sleeps: list[float] = []
    c = BlobStoreBackupContainer("blobstore://127.0.0.1:1", retries=4,
                                 sleep=sleeps.append)

    class DeadConn:
        def request(self, *a, **k):
            raise OSError("connection refused")
    c._conn = DeadConn()

    with pytest.raises(HTTPError):
        c._request("GET", "/backup/x")
    assert sleeps == [0.05, 0.1, 0.2]          # doubling, no sleep before #1
    assert all(s <= BlobStoreBackupContainer.BACKOFF_MAX for s in sleeps)


def test_advice_fix_fdbfuture_callback_never_lost():
    """fdb_c.py fix: a callback registered while the future resolves on
    another thread must fire exactly once (pre-fix it could be appended
    into a list the resolver had already iterated, and never fire)."""
    from foundationdb_tpu.bindings.fdb_c import FDBFuture

    class Resolved:
        _result = b"v"

        def is_error(self):
            return False

    for _ in range(300):
        fut = FDBFuture()
        fired = []
        barrier = threading.Barrier(2)

        def registrar():
            barrier.wait()
            fut.set_callback(lambda f, arg: fired.append(arg), "cb")

        t = threading.Thread(target=registrar)
        t.start()
        barrier.wait()
        fut._resolve_from(Resolved())
        t.join()
        assert fired == ["cb"], "registered callback was lost or double-fired"
        err, present, value = fut.get_value()
        assert (err, present, value) == (0, True, b"v")


def test_advice_fix_fdbfuture_cancel_resolve_race_settles_once():
    from foundationdb_tpu.bindings.fdb_c import FDBFuture

    class Resolved:
        _result = b"v"

        def is_error(self):
            return False

    for _ in range(300):
        fut = FDBFuture()
        fired = []
        fut.set_callback(lambda f, arg: fired.append(arg), "cb")
        barrier = threading.Barrier(2)

        def canceller():
            barrier.wait()
            fut.cancel()

        t = threading.Thread(target=canceller)
        t.start()
        barrier.wait()
        fut._resolve_from(Resolved())
        t.join()
        assert fired == ["cb"], "settle raced into double-firing callbacks"
        assert fut.is_ready()


# ---------------------------------------------------------- output formats

GOLDEN_SNIPPET = """
    import time

    class Role:
        async def tick(self):
            await self.step()
            time.sleep(1)
"""


def test_json_output_golden():
    findings = lint(GOLDEN_SNIPPET)
    got = json.loads(flowlint.format_json(findings))
    assert got == {
        "findings": [
            {
                "rule": "FLOW001",
                "path": "foundationdb_tpu/server/snippet.py",
                "line": 7,
                "symbol": "Role.tick",
                "detail": "time.sleep",
                "message": (
                    "nondeterministic call time.sleep() inside a "
                    "sim-visible coroutine; use the event-loop clock / "
                    "DeterministicRandom"),
            }
        ]
    }


def test_text_output_format():
    findings = lint(GOLDEN_SNIPPET)
    assert flowlint.format_text(findings) == (
        "foundationdb_tpu/server/snippet.py:7: FLOW001 [Role.tick] "
        "nondeterministic call time.sleep() inside a sim-visible coroutine; "
        "use the event-loop clock / DeterministicRandom")


# ------------------------------------------------------------ CLI/baseline

def test_cli_roundtrip_and_baseline_workflow(tmp_path, capsys):
    bad = tmp_path / "foundationdb_tpu" / "server" / "late.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        import time

        class Role:
            async def tick(self):
                await self.step()
                time.sleep(1)
    """))
    baseline = tmp_path / "baseline.json"

    # new violation -> exit 1, JSON findings on stdout
    rc = flowlint_main([str(bad), "--format=json",
                        "--baseline", str(baseline)])
    out = capsys.readouterr().out
    assert rc == 1
    findings = json.loads(out)["findings"]
    assert findings[0]["rule"] == "FLOW001"
    assert findings[0]["path"] == "foundationdb_tpu/server/late.py"

    # --update-baseline grandfathers it (with a FIXME reason stamp)...
    assert flowlint_main([str(bad), "--baseline", str(baseline),
                          "--update-baseline"]) == 0
    data = json.loads(baseline.read_text())
    assert len(data["entries"]) == 1
    assert data["entries"][0]["reason"].startswith("FIXME")

    # ...and the next run is clean against that baseline
    capsys.readouterr()
    assert flowlint_main([str(bad), "--baseline", str(baseline)]) == 0

    # fixing the code makes the entry stale: still exit 0, but warned
    bad.write_text(textwrap.dedent("""
        class Role:
            async def tick(self):
                await self.step()
    """))
    assert flowlint_main([str(bad), "--baseline", str(baseline)]) == 0
    assert "stale baseline entry" in capsys.readouterr().err


def test_baseline_survives_line_shift():
    """Baseline identity is (rule, path, symbol, detail) — inserting lines
    above the finding must neither report it new nor orphan its entry."""
    src = """
        import time

        class Role:
            async def tick(self):
                await self.step()
                return time.time()
    """
    findings = lint(src)
    baseline = flowlint.Baseline(entries=[{
        "rule": f.rule, "path": f.path, "symbol": f.symbol,
        "detail": f.detail, "reason": "doc"} for f in findings])
    shifted = lint("\n\n\n# a comment\nX = 1\n" + textwrap.dedent(src))
    assert [f.line for f in shifted] != [f.line for f in findings]
    new, stale = flowlint.apply_baseline(shifted, baseline)
    assert new == [] and stale == []


def test_baseline_survives_enclosing_function_rename():
    """Renaming the enclosing function changes the exact key; the fuzzy
    (rule, path, detail) tier must still pair finding and entry."""
    src = """
        import time

        class Role:
            async def tick(self):
                await self.step()
                return time.time()
    """
    findings = lint(src)
    baseline = flowlint.Baseline(entries=[{
        "rule": f.rule, "path": f.path, "symbol": f.symbol,
        "detail": f.detail, "reason": "doc"} for f in findings])
    renamed = lint(src.replace("async def tick", "async def tock"))
    assert [f.symbol for f in renamed] == ["Role.tock"]
    new, stale = flowlint.apply_baseline(renamed, baseline)
    assert new == [] and stale == []
    # ...and --update-baseline carries the documented reason across the
    # rename instead of stamping a fresh FIXME
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        out = flowlint.write_baseline(os.path.join(td, "b.json"),
                                      renamed, baseline)
    assert [e["reason"] for e in out.entries] == ["doc"]


def test_baseline_fuzzy_tier_is_count_aware():
    """Two live findings with the same (rule, path, detail) cannot both
    consume one renamed entry: the second one stays NEW."""
    src = """
        import time

        class Role:
            async def a(self):
                await self.step()
                return time.time()

            async def b(self):
                await self.step()
                return time.time()
    """
    findings = lint(src)
    assert len(findings) == 2
    baseline = flowlint.Baseline(entries=[{
        "rule": "FLOW001", "path": SERVER_PATH, "symbol": "Role.renamed",
        "detail": "time.time", "reason": "doc"}])
    new, stale = flowlint.apply_baseline(findings, baseline)
    assert len(new) == 1 and stale == []


def test_update_baseline_preserves_documented_reasons(tmp_path):
    f = flowlint.Finding(rule="FLOW001", path="p.py", line=3, symbol="S.t",
                         detail="time.time", message="m")
    old = flowlint.Baseline(entries=[{
        "rule": "FLOW001", "path": "p.py", "symbol": "S.t",
        "detail": "time.time", "reason": "documented: legacy clock"}])
    out = flowlint.write_baseline(str(tmp_path / "b.json"), [f], old)
    assert out.entries[0]["reason"] == "documented: legacy clock"


# ------------------------------------------------------------- enforcement

def package_dir() -> str:
    return os.path.dirname(os.path.abspath(foundationdb_tpu.__file__))


def test_at_least_six_rules_active():
    codes = [r.code for r in flowlint.active_rules()]
    assert len(codes) == len(set(codes))
    assert len(codes) >= 6


def test_package_is_flowlint_clean():
    """THE enforcement test: the flow family over the full default target
    set (package INCLUDING testing/, plus repo scripts/) reports zero
    non-baselined violations — any new actor-discipline bug fails tier-1
    the moment it is written. (test_devlint.py runs the same gate with
    --family all.)"""
    targets = flowlint.default_targets()
    assert targets[0] == package_dir()
    assert any(t.endswith("scripts") for t in targets[1:])
    findings = flowlint.analyze_paths(targets, flowlint.active_rules("flow"))
    baseline = flowlint.load_baseline(flowlint.default_baseline_path())
    new, stale = flowlint.apply_baseline(findings, baseline,
                                         families={"flow"})
    assert new == [], "new flowlint violations:\n" + flowlint.format_text(new)
    assert stale == [], f"stale baseline entries (run --update-baseline): {stale}"


def test_baseline_entries_are_documented():
    baseline = flowlint.load_baseline(flowlint.default_baseline_path())
    assert baseline.entries, "the grandfathered set should not be empty yet"
    for entry in baseline.entries:
        reason = entry.get("reason", "")
        assert reason and not reason.startswith("FIXME"), (
            f"undocumented baseline entry: {entry}")
