"""Sharded conflict engine: verdicts on an 8-device mesh vs single-device/oracle.

The combine rule (min over shards, MasterProxyServer.actor.cpp:492-504) plus
per-shard write retention can only create false conflicts, never false
commits — so the invariant tested is:

  1. On workloads where every committed verdict is consistent across shards
     (which is all of them: clipping preserves overlap structure exactly,
     since a read range and a write range overlap iff they overlap within at
     least one shard), sharded verdicts == single-device verdicts.
  2. Write history is exactly partitioned: re-checking a read against the
     sharded state gives the same answer as the unsharded state.
"""

from __future__ import annotations

import numpy as np
import pytest

from foundationdb_tpu.ops.batch import COMMITTED, CONFLICT, TxnConflictInfo
from foundationdb_tpu.ops.conflict import DeviceConflictSet
from foundationdb_tpu.ops.conflict_oracle import OracleConflictSet
from foundationdb_tpu.parallel.sharded_conflict import (
    ShardedDeviceConflictSet, make_resolver_mesh, shard_cut_keys)
from foundationdb_tpu.utils.rng import DeterministicRandom


def _random_batches(seed, n_batches, txns_per_batch, key_space=200, max_len=3):
    rng = DeterministicRandom(seed)

    def rkey():
        n = rng.randint(1, max_len + 1)
        return bytes(rng.randint(0, key_space) % 256 for _ in range(n))

    def rrange():
        a, b = sorted([rkey(), rkey()])
        if a == b:
            b = a + b"\x00"
        return (a, b)

    batches = []
    version = 100
    for _ in range(n_batches):
        txns = []
        for _ in range(txns_per_batch):
            snap = version - rng.randint(0, 50)
            txns.append(TxnConflictInfo(
                read_snapshot=snap,
                read_ranges=[rrange() for _ in range(rng.randint(0, 3))],
                write_ranges=[rrange() for _ in range(rng.randint(0, 3))],
            ))
        batches.append((txns, version))
        version += rng.randint(1, 30)
    return batches


def test_shard_cut_keys_shape():
    cuts = shard_cut_keys(8)
    assert cuts.shape[0] == 9
    assert cuts[0].sum() == 0
    assert (cuts[8] == 0xFFFFFFFF).all()
    # strictly increasing first limbs
    assert (np.diff(cuts[:, 0].astype(np.uint64)) > 0)[: 7].all()


def _clip(rng_pair, lo, hi):
    b, e = rng_pair
    b2, e2 = max(b, lo), min(e, hi) if hi is not None else e
    return (b2, e2) if b2 < e2 else None


def _sharded_oracle_detect(oracles, cuts, txns, version):
    """Expected sharded verdicts: N host oracles fed shard-clipped ranges,
    combined with min (the proxy rule, MasterProxyServer.actor.cpp:492-504).
    Every oracle sees every transaction (clipped-to-empty ranges removed),
    matching the device program where clipped ranges become inert."""
    from foundationdb_tpu.ops.batch import TxnConflictInfo

    n = len(oracles)
    verdicts = []
    for d in range(n):
        lo = cuts[d]
        hi = cuts[d + 1] if d + 1 < n else None
        sub = []
        for t in txns:
            reads = [r for r in (_clip(p, lo, hi) for p in t.read_ranges) if r]
            writes = [w for w in (_clip(p, lo, hi) for p in t.write_ranges) if w]
            # too-old fires on every shard for txns with any read range
            # anywhere (has_reads is shard-local on device only through
            # rvalid, which clipping does not change)
            sub.append(TxnConflictInfo(
                read_snapshot=t.read_snapshot, read_ranges=reads,
                write_ranges=writes,
                ))
        verdicts.append(oracles[d].detect(sub, version))
    combined = [min(v[t] for v in verdicts) for t in range(len(txns))]
    return combined


@pytest.mark.parametrize("seed", [1, 2, 7])
def test_sharded_matches_clipped_oracles(seed):
    """Exact parity: device sharded verdicts == N shard-clipped host oracles
    with min-combine. Also: no false commits vs the single-device engine
    (sharded COMMITTED implies single-device COMMITTED; per-shard write
    retention can only add conflicts, Resolver.actor.cpp semantics)."""
    from foundationdb_tpu.parallel.sharded_conflict import shard_cut_bytes

    mesh = make_resolver_mesh(8)
    n = mesh.devices.size
    cuts = shard_cut_bytes(n)
    sharded = ShardedDeviceConflictSet(
        mesh=mesh, capacity=256, txns=16, reads_per_txn=4, writes_per_txn=4)
    single = DeviceConflictSet(
        capacity=256, txns=16, reads_per_txn=4, writes_per_txn=4)
    oracles = [OracleConflictSet() for _ in range(n)]
    for txns, version in _random_batches(seed, n_batches=12, txns_per_batch=10):
        got = sharded.detect(txns, version)
        want = _sharded_oracle_detect(oracles, cuts, txns, version)
        assert got == want
        base = single.detect(txns, version)
        for g, b in zip(got, base):
            if g == COMMITTED:
                assert b == COMMITTED  # no false commits


def test_sharded_cross_shard_range():
    """A single write range spanning every shard must conflict a later read."""
    mesh = make_resolver_mesh(8)
    cs = ShardedDeviceConflictSet(
        mesh=mesh, capacity=64, txns=4, reads_per_txn=2, writes_per_txn=2)
    whole = (b"\x00", b"\xff\xff")
    assert cs.detect([TxnConflictInfo(read_snapshot=0, write_ranges=[whole])],
                     10) == [COMMITTED]
    # stale read anywhere in the space conflicts
    for k in [b"\x01", b"\x40zz", b"\x80", b"\xc0\x01", b"\xfe"]:
        got = cs.detect(
            [TxnConflictInfo(read_snapshot=5,
                             read_ranges=[(k, k + b"\x00")],
                             write_ranges=[])], 20)
        assert got == [CONFLICT], k
    # fresh read commits
    assert cs.detect([TxnConflictInfo(read_snapshot=25,
                                      read_ranges=[(b"\x40", b"\x41")])],
                     30) == [COMMITTED]


def test_sharded_clear():
    mesh = make_resolver_mesh(8)
    cs = ShardedDeviceConflictSet(
        mesh=mesh, capacity=64, txns=4, reads_per_txn=2, writes_per_txn=2)
    cs.detect([TxnConflictInfo(read_snapshot=0, write_ranges=[(b"a", b"b")])], 10)
    cs.clear(oldest_version=100)
    assert cs.detect(
        [TxnConflictInfo(read_snapshot=100, read_ranges=[(b"a", b"b")])],
        110) == [COMMITTED]


class _SafetyTracker:
    """Independent no-false-commit checker: replays the ENGINE's own
    decisions (committed writes enter history; aborted writes do not), then
    asserts every engine-committed txn really had no overlapping committed
    write above its snapshot. Valid even when the engine conflicts
    conservatively (e.g. after a resolutionBalancing cut move)."""

    def __init__(self):
        self.writes: list[tuple[bytes, bytes, int]] = []  # (b, e, version)

    def check_and_apply(self, txns, statuses, version):
        for t, s in zip(txns, statuses):
            if s != COMMITTED:
                continue
            for rb, re in t.read_ranges:
                for wb, we, wv in self.writes:
                    if wv > t.read_snapshot and rb < we and wb < re:
                        raise AssertionError(
                            f"false commit: read [{rb!r},{re!r}) snap "
                            f"{t.read_snapshot} vs write [{wb!r},{we!r})@{wv}")
        for t, s in zip(txns, statuses):
            if s == COMMITTED:
                for wb, we in t.write_ranges:
                    self.writes.append((wb, we, version))


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_decision_parity_across_device_counts(n_devices):
    """The resolver may be handed any mesh width (CONFLICT_NUM_SHARDS):
    verdicts at EVERY width must match the N-clipped-oracle min-combine
    model, and a 1-wide mesh must agree with the single-device evaluator
    exactly (no cuts -> no clipping -> no retention divergence)."""
    from foundationdb_tpu.parallel.sharded_conflict import shard_cut_bytes

    mesh = make_resolver_mesh(n_devices)
    cuts = shard_cut_bytes(n_devices)
    sharded = ShardedDeviceConflictSet(
        mesh=mesh, capacity=256, txns=16, reads_per_txn=4, writes_per_txn=4)
    single = DeviceConflictSet(
        capacity=256, txns=16, reads_per_txn=4, writes_per_txn=4)
    oracles = [OracleConflictSet() for _ in range(n_devices)]
    for txns, version in _random_batches(
            seed=3, n_batches=10, txns_per_batch=10):
        got = sharded.detect(txns, version)
        want = _sharded_oracle_detect(oracles, cuts, txns, version)
        assert got == want
        base = single.detect(txns, version)
        if n_devices == 1:
            assert got == base
        else:
            for g, b in zip(got, base):
                if g == COMMITTED:
                    assert b == COMMITTED  # no false commits at any width


def test_safe_false_conflict_at_shard_cut():
    """The documented divergence between sharded and single-resolver
    semantics, pinned as a deterministic case: a txn aborted by a conflict
    on shard 0 still has its shard-1 write retained THERE (shards don't
    exchange abort decisions mid-batch), so a later txn in the same batch
    reading that range gets a conservative intra-batch CONFLICT where the
    single-device engine commits. Safe (false conflict), never the reverse
    (false commit)."""
    mesh = make_resolver_mesh(8)
    sharded = ShardedDeviceConflictSet(
        mesh=mesh, capacity=64, txns=4, reads_per_txn=2, writes_per_txn=2)
    single = DeviceConflictSet(
        capacity=64, txns=4, reads_per_txn=2, writes_per_txn=2)
    # seed history: commit a write on shard 0 at version 10
    seedw = [TxnConflictInfo(read_snapshot=0,
                             write_ranges=[(b"\x10", b"\x11")])]
    assert sharded.detect(seedw, 10) == [COMMITTED]
    assert single.detect(seedw, 10) == [COMMITTED]
    # txn0: stale read of that range (-> CONFLICT, decided on shard 0) plus
    # a write on shard 1 (first byte 0x30 >= cut_1 = 0x20); txn1: fresh read
    # of txn0's shard-1 write range
    batch = [
        TxnConflictInfo(read_snapshot=5,
                        read_ranges=[(b"\x10", b"\x11")],
                        write_ranges=[(b"\x30", b"\x31")]),
        TxnConflictInfo(read_snapshot=10,
                        read_ranges=[(b"\x30", b"\x31")]),
    ]
    assert single.detect(batch, 20) == [CONFLICT, COMMITTED]
    got = sharded.detect(batch, 20)
    assert got[0] == CONFLICT
    # shard 1 never learns txn0 aborted: its retained write forces the
    # conservative verdict on txn1
    assert got[1] == CONFLICT


def test_conflict_config_validation():
    """validate_conflict_config (worker/resolver boot): unknown backend and
    malformed shard counts fail closed with invalid_option, like
    validate_storage_engine."""
    from foundationdb_tpu.ops.batch import validate_conflict_config
    from foundationdb_tpu.utils.errors import FDBError

    validate_conflict_config("sharded", 0)
    validate_conflict_config("oracle", 8)
    for bad in ("skiplist", "", "SHARDED"):
        with pytest.raises(FDBError) as ei:
            validate_conflict_config(bad, 0)
        assert ei.value.name == "invalid_option"
    for bad_n in (-1, 2.5, "4", True):
        with pytest.raises(FDBError):
            validate_conflict_config("sharded", bad_n)


def test_num_shards_over_device_count_is_rejected():
    """CONFLICT_NUM_SHARDS beyond the attached device count must fail at
    role boot (resolver), not at first dispatch."""
    from foundationdb_tpu.server.resolver import new_conflict_set
    from foundationdb_tpu.utils.errors import FDBError
    from foundationdb_tpu.utils.knobs import KNOBS

    KNOBS.overrides(CONFLICT_BACKEND="sharded", CONFLICT_NUM_SHARDS=99,
                    CONFLICT_CPU_FALLBACK="jax")
    try:
        with pytest.raises(FDBError) as ei:
            new_conflict_set()
        assert ei.value.name == "invalid_option"
    finally:
        KNOBS.overrides(CONFLICT_BACKEND="oracle", CONFLICT_NUM_SHARDS=0,
                        CONFLICT_CPU_FALLBACK="host")


def test_rebalance_from_conflicts_schedules_cuts():
    """Conflict-mass recut (the balance loop's planner): skewed hot-range
    mass must schedule new cuts that are applied at the NEXT batch, and the
    engine stays safe across the move. Mass concentrated on one prefix
    cannot be split and must be declined."""
    mesh = make_resolver_mesh(4)
    cs = ShardedDeviceConflictSet(
        mesh=mesh, capacity=128, txns=8, reads_per_txn=2, writes_per_txn=2)
    before = list(cs.cut_bytes)
    # all conflict mass on three prefixes inside shard 0
    hot = [(b"\x01", b"\x02", 50.0), (b"\x02", b"\x03", 30.0),
           (b"\x03", b"\x04", 20.0)]
    assert cs.rebalance_from_conflicts(hot) is True
    assert cs.cut_bytes == before  # scheduled, not yet applied
    tracker = _SafetyTracker()
    version = 100
    txns = [TxnConflictInfo(read_snapshot=90,
                            read_ranges=[(b"\x01a", b"\x01b")],
                            write_ranges=[(b"\x02a", b"\x02b")])]
    statuses = cs.detect(txns, version)
    tracker.check_and_apply(txns, statuses, version)
    assert cs.cut_bytes != before  # applied at the batch boundary
    assert cs.rebalances >= 1
    # post-move decisions stay safe and fresh reads commit
    got = cs.detect([TxnConflictInfo(read_snapshot=version,
                                     read_ranges=[(b"\x02a", b"\x02b")])],
                    version + 10)
    assert got == [COMMITTED]
    # degenerate: every unit of mass on ONE prefix -> cannot split
    assert cs.rebalance_from_conflicts(
        [(b"\x05", b"\x05\x01", 100.0)]) is False


def test_rebalance_moves_cuts_and_stays_safe():
    """A skewed workload (all load in one shard) must trigger
    resolutionBalancing; decisions afterwards may be conservative but never
    a false commit, and fresh reads still work."""
    from foundationdb_tpu.utils.knobs import KNOBS
    KNOBS.set("RESOLUTION_BALANCE_CHECK_BATCHES", 4)
    KNOBS.set("RESOLUTION_BALANCE_MIN_SAMPLES", 64)
    try:
        mesh = make_resolver_mesh(8)
        cs = ShardedDeviceConflictSet(
            mesh=mesh, capacity=256, txns=8, reads_per_txn=2, writes_per_txn=2)
        tracker = _SafetyTracker()
        rng = DeterministicRandom(42)
        version = 100
        # every key begins with 0x03... -> all load lands in shard 0
        for _ in range(40):
            txns = []
            for _ in range(8):
                a = bytes([3]) + bytes([rng.randint(0, 255) % 256 for _ in range(2)])
                b = a + b"\x00"
                txns.append(TxnConflictInfo(
                    read_snapshot=version - rng.randint(0, 50),
                    read_ranges=[(a, b)], write_ranges=[(a, b)]))
            version += 10
            statuses = cs.detect(txns, version)
            tracker.check_and_apply(txns, statuses, version)
        assert cs.rebalances >= 1, "skewed load never rebalanced"
        assert cs.cut_bytes[1] != b"\x20\x00\x00\x00", "cuts unchanged"
        # fresh reads after the move still commit
        got = cs.detect([TxnConflictInfo(read_snapshot=version,
                                         read_ranges=[(b"\x03xx", b"\x03xy")])],
                        version + 10)
        assert got == [COMMITTED]
    finally:
        KNOBS.set("RESOLUTION_BALANCE_CHECK_BATCHES", 64)
        KNOBS.set("RESOLUTION_BALANCE_MIN_SAMPLES", 2048)
