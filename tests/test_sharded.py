"""Sharded conflict engine: verdicts on an 8-device mesh vs single-device/oracle.

The combine rule (min over shards, MasterProxyServer.actor.cpp:492-504) plus
per-shard write retention can only create false conflicts, never false
commits — so the invariant tested is:

  1. On workloads where every committed verdict is consistent across shards
     (which is all of them: clipping preserves overlap structure exactly,
     since a read range and a write range overlap iff they overlap within at
     least one shard), sharded verdicts == single-device verdicts.
  2. Write history is exactly partitioned: re-checking a read against the
     sharded state gives the same answer as the unsharded state.
"""

from __future__ import annotations

import numpy as np
import pytest

from foundationdb_tpu.ops.batch import COMMITTED, CONFLICT, TxnConflictInfo
from foundationdb_tpu.ops.conflict import DeviceConflictSet
from foundationdb_tpu.ops.conflict_oracle import OracleConflictSet
from foundationdb_tpu.parallel.sharded_conflict import (
    ShardedDeviceConflictSet, make_resolver_mesh, shard_cut_keys)
from foundationdb_tpu.utils.rng import DeterministicRandom


def _random_batches(seed, n_batches, txns_per_batch, key_space=200, max_len=3):
    rng = DeterministicRandom(seed)

    def rkey():
        n = rng.randint(1, max_len + 1)
        return bytes(rng.randint(0, key_space) % 256 for _ in range(n))

    def rrange():
        a, b = sorted([rkey(), rkey()])
        if a == b:
            b = a + b"\x00"
        return (a, b)

    batches = []
    version = 100
    for _ in range(n_batches):
        txns = []
        for _ in range(txns_per_batch):
            snap = version - rng.randint(0, 50)
            txns.append(TxnConflictInfo(
                read_snapshot=snap,
                read_ranges=[rrange() for _ in range(rng.randint(0, 3))],
                write_ranges=[rrange() for _ in range(rng.randint(0, 3))],
            ))
        batches.append((txns, version))
        version += rng.randint(1, 30)
    return batches


def test_shard_cut_keys_shape():
    cuts = shard_cut_keys(8)
    assert cuts.shape[0] == 9
    assert cuts[0].sum() == 0
    assert (cuts[8] == 0xFFFFFFFF).all()
    # strictly increasing first limbs
    assert (np.diff(cuts[:, 0].astype(np.uint64)) > 0)[: 7].all()


def _clip(rng_pair, lo, hi):
    b, e = rng_pair
    b2, e2 = max(b, lo), min(e, hi) if hi is not None else e
    return (b2, e2) if b2 < e2 else None


def _sharded_oracle_detect(oracles, cuts, txns, version):
    """Expected sharded verdicts: N host oracles fed shard-clipped ranges,
    combined with min (the proxy rule, MasterProxyServer.actor.cpp:492-504).
    Every oracle sees every transaction (clipped-to-empty ranges removed),
    matching the device program where clipped ranges become inert."""
    from foundationdb_tpu.ops.batch import TxnConflictInfo

    n = len(oracles)
    verdicts = []
    for d in range(n):
        lo = cuts[d]
        hi = cuts[d + 1] if d + 1 < n else None
        sub = []
        for t in txns:
            reads = [r for r in (_clip(p, lo, hi) for p in t.read_ranges) if r]
            writes = [w for w in (_clip(p, lo, hi) for p in t.write_ranges) if w]
            # too-old fires on every shard for txns with any read range
            # anywhere (has_reads is shard-local on device only through
            # rvalid, which clipping does not change)
            sub.append(TxnConflictInfo(
                read_snapshot=t.read_snapshot, read_ranges=reads,
                write_ranges=writes,
                ))
        verdicts.append(oracles[d].detect(sub, version))
    combined = [min(v[t] for v in verdicts) for t in range(len(txns))]
    return combined


@pytest.mark.parametrize("seed", [1, 2, 7])
def test_sharded_matches_clipped_oracles(seed):
    """Exact parity: device sharded verdicts == N shard-clipped host oracles
    with min-combine. Also: no false commits vs the single-device engine
    (sharded COMMITTED implies single-device COMMITTED; per-shard write
    retention can only add conflicts, Resolver.actor.cpp semantics)."""
    from foundationdb_tpu.parallel.sharded_conflict import shard_cut_bytes

    mesh = make_resolver_mesh(8)
    n = mesh.devices.size
    cuts = shard_cut_bytes(n)
    sharded = ShardedDeviceConflictSet(
        mesh=mesh, capacity=256, txns=16, reads_per_txn=4, writes_per_txn=4)
    single = DeviceConflictSet(
        capacity=256, txns=16, reads_per_txn=4, writes_per_txn=4)
    oracles = [OracleConflictSet() for _ in range(n)]
    for txns, version in _random_batches(seed, n_batches=12, txns_per_batch=10):
        got = sharded.detect(txns, version)
        want = _sharded_oracle_detect(oracles, cuts, txns, version)
        assert got == want
        base = single.detect(txns, version)
        for g, b in zip(got, base):
            if g == COMMITTED:
                assert b == COMMITTED  # no false commits


def test_sharded_cross_shard_range():
    """A single write range spanning every shard must conflict a later read."""
    mesh = make_resolver_mesh(8)
    cs = ShardedDeviceConflictSet(
        mesh=mesh, capacity=64, txns=4, reads_per_txn=2, writes_per_txn=2)
    whole = (b"\x00", b"\xff\xff")
    assert cs.detect([TxnConflictInfo(read_snapshot=0, write_ranges=[whole])],
                     10) == [COMMITTED]
    # stale read anywhere in the space conflicts
    for k in [b"\x01", b"\x40zz", b"\x80", b"\xc0\x01", b"\xfe"]:
        got = cs.detect(
            [TxnConflictInfo(read_snapshot=5,
                             read_ranges=[(k, k + b"\x00")],
                             write_ranges=[])], 20)
        assert got == [CONFLICT], k
    # fresh read commits
    assert cs.detect([TxnConflictInfo(read_snapshot=25,
                                      read_ranges=[(b"\x40", b"\x41")])],
                     30) == [COMMITTED]


def test_sharded_clear():
    mesh = make_resolver_mesh(8)
    cs = ShardedDeviceConflictSet(
        mesh=mesh, capacity=64, txns=4, reads_per_txn=2, writes_per_txn=2)
    cs.detect([TxnConflictInfo(read_snapshot=0, write_ranges=[(b"a", b"b")])], 10)
    cs.clear(oldest_version=100)
    assert cs.detect(
        [TxnConflictInfo(read_snapshot=100, read_ranges=[(b"a", b"b")])],
        110) == [COMMITTED]


class _SafetyTracker:
    """Independent no-false-commit checker: replays the ENGINE's own
    decisions (committed writes enter history; aborted writes do not), then
    asserts every engine-committed txn really had no overlapping committed
    write above its snapshot. Valid even when the engine conflicts
    conservatively (e.g. after a resolutionBalancing cut move)."""

    def __init__(self):
        self.writes: list[tuple[bytes, bytes, int]] = []  # (b, e, version)

    def check_and_apply(self, txns, statuses, version):
        for t, s in zip(txns, statuses):
            if s != COMMITTED:
                continue
            for rb, re in t.read_ranges:
                for wb, we, wv in self.writes:
                    if wv > t.read_snapshot and rb < we and wb < re:
                        raise AssertionError(
                            f"false commit: read [{rb!r},{re!r}) snap "
                            f"{t.read_snapshot} vs write [{wb!r},{we!r})@{wv}")
        for t, s in zip(txns, statuses):
            if s == COMMITTED:
                for wb, we in t.write_ranges:
                    self.writes.append((wb, we, version))


def test_rebalance_moves_cuts_and_stays_safe():
    """A skewed workload (all load in one shard) must trigger
    resolutionBalancing; decisions afterwards may be conservative but never
    a false commit, and fresh reads still work."""
    from foundationdb_tpu.utils.knobs import KNOBS
    KNOBS.set("RESOLUTION_BALANCE_CHECK_BATCHES", 4)
    KNOBS.set("RESOLUTION_BALANCE_MIN_SAMPLES", 64)
    try:
        mesh = make_resolver_mesh(8)
        cs = ShardedDeviceConflictSet(
            mesh=mesh, capacity=256, txns=8, reads_per_txn=2, writes_per_txn=2)
        tracker = _SafetyTracker()
        rng = DeterministicRandom(42)
        version = 100
        # every key begins with 0x03... -> all load lands in shard 0
        for _ in range(40):
            txns = []
            for _ in range(8):
                a = bytes([3]) + bytes([rng.randint(0, 255) % 256 for _ in range(2)])
                b = a + b"\x00"
                txns.append(TxnConflictInfo(
                    read_snapshot=version - rng.randint(0, 50),
                    read_ranges=[(a, b)], write_ranges=[(a, b)]))
            version += 10
            statuses = cs.detect(txns, version)
            tracker.check_and_apply(txns, statuses, version)
        assert cs.rebalances >= 1, "skewed load never rebalanced"
        assert cs.cut_bytes[1] != b"\x20\x00\x00\x00", "cuts unchanged"
        # fresh reads after the move still commit
        got = cs.detect([TxnConflictInfo(read_snapshot=version,
                                         read_ranges=[(b"\x03xx", b"\x03xy")])],
                        version + 10)
        assert got == [COMMITTED]
    finally:
        KNOBS.set("RESOLUTION_BALANCE_CHECK_BATCHES", 64)
        KNOBS.set("RESOLUTION_BALANCE_MIN_SAMPLES", 2048)
