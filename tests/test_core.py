"""Tests for the deterministic runtime core (futures, loop, simulator)."""

import pytest

from foundationdb_tpu.core.eventloop import EventLoop, TaskPriority
from foundationdb_tpu.core.future import Future, Promise, PromiseStream, all_of, any_of
from foundationdb_tpu.core.sim import Endpoint, KillType, SimNetwork
from foundationdb_tpu.utils.errors import FDBError
from foundationdb_tpu.utils.rng import DeterministicRandom


def test_future_basic():
    p = Promise()
    assert not p.future.is_ready()
    p.send(42)
    assert p.future.get() == 42


def test_future_error():
    p = Promise()
    p.send_error(FDBError("not_committed"))
    with pytest.raises(FDBError):
        p.future.get()


def test_broken_promise():
    p = Promise()
    p.break_promise()
    assert p.future.is_error()


def test_actor_await_and_delay():
    loop = EventLoop()
    log = []

    async def actor():
        log.append(("start", loop.now()))
        await loop.delay(1.5)
        log.append(("after", loop.now()))
        return "done"

    t = loop.spawn(actor())
    assert loop.run_future(t) == "done"
    assert log == [("start", 0.0), ("after", 1.5)]


def test_virtual_time_ordering_and_priority():
    loop = EventLoop()
    order = []
    loop._schedule(1.0, TaskPriority.Low, lambda: order.append("low"))
    loop._schedule(1.0, TaskPriority.TLogCommit, lambda: order.append("high"))
    loop._schedule(0.5, TaskPriority.Low, lambda: order.append("early"))
    loop.run_until_idle()
    assert order == ["early", "high", "low"]


def test_actor_cancellation():
    loop = EventLoop()
    witness = []

    async def actor():
        try:
            await loop.delay(100.0)
        except FDBError as e:
            witness.append(e.name)
            raise

    t = loop.spawn(actor())
    loop._schedule(1.0, TaskPriority.DefaultDelay, t.cancel)
    with pytest.raises(FDBError):
        loop.run_future(t)
    assert witness == ["operation_cancelled"]


def test_actor_can_swallow_cancel_and_await_cleanup():
    loop = EventLoop()
    done = []

    async def actor():
        try:
            await loop.delay(100.0)
        except FDBError:
            await loop.delay(0.5)  # cleanup await after swallowing the cancel
            done.append(loop.now())
            return "cleaned"

    t = loop.spawn(actor())
    loop._schedule(1.0, TaskPriority.DefaultDelay, t.cancel)
    assert loop.run_future(t, max_time=50.0) == "cleaned"
    assert done and done[0] == pytest.approx(1.5)


def test_run_future_timeout_does_not_lose_events():
    loop = EventLoop()
    p = Promise()
    fired = []
    loop._schedule(12.0, TaskPriority.DefaultDelay, lambda: fired.append(True))
    with pytest.raises(FDBError, match="timed_out"):
        loop.run_future(p.future, max_time=10.0)
    loop.run_until_idle()
    assert fired == [True]  # the popped t=12 event was restored and ran


def test_completed_actors_do_not_accumulate_on_process():
    loop = EventLoop()
    from foundationdb_tpu.core.sim import SimNetwork
    net = SimNetwork(loop, DeterministicRandom(1))
    p = net.new_process("s:1")

    async def quick():
        await loop.delay(0.001)

    for _ in range(50):
        p.spawn(quick())
    loop.run_until_idle()
    assert p.actors == []


def test_promise_stream():
    loop = EventLoop()
    stream = PromiseStream()
    got = []

    async def consumer():
        for _ in range(3):
            got.append(await stream.pop())

    t = loop.spawn(consumer())
    stream.send(1)
    stream.send(2)
    loop._schedule(0.5, TaskPriority.DefaultDelay, lambda: stream.send(3))
    loop.run_future(t)
    assert got == [1, 2, 3]


def test_all_of_any_of():
    p1, p2 = Promise(), Promise()
    a = all_of([p1.future, p2.future])
    n = any_of([p1.future, p2.future])
    p2.send("b")
    assert n.get() == (1, "b")
    assert not a.is_ready()
    p1.send("a")
    assert a.get() == ["a", "b"]


def test_timeout():
    loop = EventLoop()
    p = Promise()
    f = loop.timeout(p.future, 2.0)
    loop.run_until_idle()
    assert f.is_error()
    with pytest.raises(FDBError, match="timed_out"):
        f.get()


def _mk_net(seed=1):
    loop = EventLoop()
    net = SimNetwork(loop, DeterministicRandom(seed))
    return loop, net


def test_sim_rpc_roundtrip():
    loop, net = _mk_net()
    server = net.new_process("server:1")
    client = net.new_process("client:1")
    server.register(100, lambda payload, reply: reply.send(payload * 2))

    async def run():
        return await net.request(client, Endpoint("server:1", 100), 21)

    t = client.spawn(run())
    assert loop.run_future(t) == 42
    assert loop.now() > 0.0  # latency was applied


def test_sim_rpc_to_dead_process_is_broken_promise():
    loop, net = _mk_net()
    server = net.new_process("server:1")
    client = net.new_process("client:1")
    server.register(100, lambda payload, reply: reply.send(1))
    net.kill("server:1")

    async def run():
        await net.request(client, Endpoint("server:1", 100), None)

    t = client.spawn(run())
    with pytest.raises(FDBError, match="broken_promise"):
        loop.run_future(t)


def test_sim_kill_mid_request_breaks_promise():
    loop, net = _mk_net()
    server = net.new_process("server:1")
    client = net.new_process("client:1")
    # Handler never replies; the kill must break the owed promise.
    server.register(100, lambda payload, reply: None)

    async def run():
        await net.request(client, Endpoint("server:1", 100), None)

    t = client.spawn(run())
    loop._schedule(1.0, TaskPriority.DefaultDelay, lambda: net.kill("server:1"))
    with pytest.raises(FDBError, match="broken_promise"):
        loop.run_future(t)


def test_sim_partition_drops_packets():
    loop, net = _mk_net()
    server = net.new_process("server:1")
    client = net.new_process("client:1")
    server.register(100, lambda payload, reply: reply.send(1))
    net.partition("client:1", "server:1")

    async def run():
        # a partitioned request surfaces request_maybe_delivered through the
        # built-in RPC timeout (SIM_RPC_TIMEOUT_SECONDS) — dropped packets
        # may never hang an actor forever
        return await net.request(client, Endpoint("server:1", 100), None)

    t = client.spawn(run())
    with pytest.raises(FDBError, match="request_maybe_delivered"):
        loop.run_future(t)
    net.heal()


def test_sim_reboot_runs_boot_fn_and_kills_actors():
    loop, net = _mk_net()
    p = net.new_process("server:1")
    boots = []
    p.boot_fn = lambda proc: boots.append(loop.now())

    async def forever():
        await loop.delay(1e9)

    p.spawn(forever())
    net.kill("server:1", KillType.RebootProcess)
    loop.run_until_idle(max_time=10.0)
    assert p.alive and p.reboots == 1 and len(boots) == 1


def test_sim_file_loses_unsynced_writes_on_kill():
    loop, net = _mk_net(seed=3)
    p = net.new_process("server:1")
    f = net.open_file(p, "wal")
    f.append(b"a")
    f.sync()
    f.append(b"b")
    f.append(b"c")
    net.kill("server:1", KillType.RebootProcess)
    loop.run_until_idle(max_time=10.0)
    data = f.durable
    # synced prefix always survives; unsynced tail is a prefix of b"bc"
    assert data.startswith(b"a")
    assert data in (b"a", b"ab", b"abc")


def test_determinism_same_seed_same_trace():
    def run(seed):
        loop, net = _mk_net(seed)
        server = net.new_process("server:1")
        client = net.new_process("client:1")
        server.register(7, lambda x, r: r.send(x + 1))
        results = []

        async def driver():
            for i in range(20):
                v = await net.request(client, Endpoint("server:1", 7), i)
                results.append((round(loop.now(), 9), v))

        t = client.spawn(driver())
        loop.run_future(t)
        return results

    assert run(5) == run(5)
    assert run(5) != run(6)  # latency schedule differs by seed


def test_unobserved_actor_error_is_loud():
    """Flow contract: an actor error nobody awaits must crash the run loop
    (flow/flow.h SAV error delivery traces SevError), so a background role
    actor can never die silently."""
    loop = EventLoop()

    async def bad():
        raise FDBError("io_error")

    loop.spawn(bad(), "background")
    with pytest.raises(FDBError, match="io_error"):
        loop.run_until_idle(max_time=1.0)


def test_observed_actor_error_is_quiet():
    loop = EventLoop()

    async def bad():
        raise FDBError("io_error")

    task = loop.spawn(bad(), "background")
    with pytest.raises(FDBError, match="io_error"):
        loop.run_future(task)  # the caller observes it; no double report


def test_cancelled_actor_is_not_reported():
    loop, = (EventLoop(),)

    async def forever():
        await loop.delay(100.0)

    task = loop.spawn(forever(), "victim")
    loop.run_until_idle(max_time=0.1)
    task.cancel()
    loop.run_until_idle(max_time=1.0)  # must not raise
    assert task.is_error()
