"""Real transport: TCP FlowTransport + a multi-OS-process cluster smoke test.

Reference: fdbrpc/FlowTransport.actor.cpp (:200-308 wire format, peers,
token dispatch). The same role and client code that runs under the
deterministic simulator here runs across real processes over TCP — the
deployment path VERDICT round 1 called out as missing ("a database you
cannot deploy is a test harness").
"""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

from foundationdb_tpu.utils.knobs import KNOBS


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_transport_request_reply_loopback():
    """Token-routed request/reply between two transports in one process."""
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop

    loop = RealEventLoop()
    a = NetTransport(loop, f"127.0.0.1:{free_port()}")
    b = NetTransport(loop, f"127.0.0.1:{free_port()}")
    a.start()
    b.start()
    try:
        b.process.register(42, lambda payload, reply: reply.send(payload * 2))

        async def call():
            return await a.request(a.process, Endpoint(b.address, 42), 21)
        assert loop.run_future(loop.spawn(call()), max_time=10.0) == 42

        # unknown token -> broken_promise (TOKEN_IGNORE path)
        async def bad():
            try:
                await a.request(a.process, Endpoint(b.address, 999), None)
                return "no error"
            except Exception as e:
                return getattr(e, "name", str(e))
        assert loop.run_future(loop.spawn(bad()), max_time=10.0) == "broken_promise"
    finally:
        a.close()
        b.close()
    # teardown contract: close() cancels and reaps every task the transport
    # spawned (reply readers, sends) — a leftover pending task would warn
    # "Task was destroyed but it is pending!" at loop GC
    assert not a._tasks and not b._tasks


def test_transport_error_detail_survives_the_wire():
    """A handler's FDBError detail must reach the remote caller intact:
    transaction_throttled carries the advised backoff + hot range there,
    and a client that loses it degrades to blind-jitter retry. Bare-name
    errors keep the old single-string wire shape."""
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
    from foundationdb_tpu.utils.errors import FDBError

    loop = RealEventLoop()
    a = NetTransport(loop, f"127.0.0.1:{free_port()}")
    b = NetTransport(loop, f"127.0.0.1:{free_port()}")
    a.start()
    b.start()
    try:
        def throttler(payload, reply):
            reply.send_error(FDBError("transaction_throttled",
                                      "0.5 6b3030 6b303100"))
        b.process.register(43, throttler)

        def plain(payload, reply):
            reply.send_error(FDBError("not_committed"))
        b.process.register(44, plain)

        async def call(token):
            try:
                await a.request(a.process, Endpoint(b.address, token), None)
                return None
            except FDBError as e:
                return e
        e = loop.run_future(loop.spawn(call(43)), max_time=10.0)
        assert e.name == "transaction_throttled"
        assert e.detail == "0.5 6b3030 6b303100"
        e = loop.run_future(loop.spawn(call(44)), max_time=10.0)
        assert e.name == "not_committed"
        assert e.detail == ""
    finally:
        a.close()
        b.close()


def test_multiprocess_cluster_serves_gets_and_commits(tmp_path):
    """Boot a real multi-OS-process cluster (txn subsystem in one server
    process, storage in another) and run transactions against it from this
    process through the ordinary client API."""
    from foundationdb_tpu.client.database import Database, LocationCache
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
    from foundationdb_tpu.server.interfaces import Token

    p_txn = f"127.0.0.1:{free_port()}"
    p_storage = f"127.0.0.1:{free_port()}"

    txn_spec = {
        "listen": p_txn,
        "data_dir": str(tmp_path / "txn"),
        "knobs": {"CONFLICT_BACKEND": "oracle"},
        "roles": [
            {"role": "master", "args": {}},
            {"role": "resolver", "args": {}},
            {"role": "tlog", "args": {}},
            {"role": "proxy", "args": {
                "proxy_id": 0,
                "master": {"address": p_txn,
                           "token": Token.MASTER_GET_COMMIT_VERSION},
                "resolvers": {"boundaries": [b"".hex()],
                              "endpoints": [{"address": p_txn,
                                             "token": Token.RESOLVER_RESOLVE}]},
                "tlogs": [{"address": p_txn, "token": Token.TLOG_COMMIT}],
                "shards": {"boundaries": [b"".hex()], "tags": [[0]]},
            }},
        ],
    }
    storage_spec = {
        "listen": p_storage,
        "data_dir": str(tmp_path / "storage"),
        "knobs": {"CONFLICT_BACKEND": "oracle"},
        "roles": [
            {"role": "storage", "args": {"tag": 0, "tlog_addrs": [p_txn]}},
        ],
    }

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=os.getcwd())
    procs = []
    try:
        for spec in (txn_spec, storage_spec):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "foundationdb_tpu.net.server_main",
                 json.dumps(spec)],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env))
        for p in procs:
            line = p.stdout.readline().decode()
            assert line.startswith("ready"), line

        loop = RealEventLoop()
        client = NetTransport(loop, f"127.0.0.1:{free_port()}")
        client.start()
        db = Database(client.process, proxies=[p_txn],
                      locations=LocationCache([b""], [[p_storage]]))

        async def workload():
            async def setup(tr):
                tr.set(b"hello", b"multiprocess")
                tr.set(b"k2", b"v2")
            await db.transact(setup, max_retries=50)

            async def read(tr):
                v = await tr.get(b"hello")
                rows = await tr.get_range(b"", b"\xff")
                return v, rows
            return await db.transact(read, max_retries=50)

        v, rows = loop.run_future(loop.spawn(workload()), max_time=60.0)
        assert v == b"multiprocess"
        assert (b"hello", b"multiprocess") in rows and (b"k2", b"v2") in rows
        client.close()
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


def test_multiprocess_restart_recovers_wire_wal(tmp_path):
    """SIGKILL the txn-subsystem process and restart it on the same data_dir:
    the TLog recovers its wire-encoded disk queue, the master/resolver fence
    version allocation past the recovered version (server_main's
    '@recover:local_tlog'), new commits land, old data survives. Also fires
    hostile bytes (garbage, bad crc, pickle) at the live port first — decode
    failures must drop the connection, not the server."""
    import signal

    from foundationdb_tpu.client.database import Database, LocationCache
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
    from foundationdb_tpu.server.interfaces import Token

    p_txn = f"127.0.0.1:{free_port()}"
    p_storage = f"127.0.0.1:{free_port()}"
    txn_spec = {
        "listen": p_txn, "data_dir": str(tmp_path / "txn"),
        "knobs": {"CONFLICT_BACKEND": "oracle"},
        "roles": [
            {"role": "master",
             "args": {"recovery_version": "@recover:local_tlog"}},
            {"role": "resolver",
             "args": {"recovery_version": "@recover:local_tlog"}},
            {"role": "tlog", "args": {}},
            {"role": "proxy", "args": {
                "proxy_id": 0,
                "master": {"address": p_txn,
                           "token": Token.MASTER_GET_COMMIT_VERSION},
                "resolvers": {"boundaries": [b"".hex()],
                              "endpoints": [{"address": p_txn,
                                             "token": Token.RESOLVER_RESOLVE}]},
                "tlogs": [{"address": p_txn, "token": Token.TLOG_COMMIT}],
                "shards": {"boundaries": [b"".hex()], "tags": [[0]]},
            }},
        ],
    }
    storage_spec = {
        "listen": p_storage, "data_dir": str(tmp_path / "storage"),
        "knobs": {"CONFLICT_BACKEND": "oracle"},
        "roles": [{"role": "storage",
                   "args": {"tag": 0, "tlog_addrs": [p_txn]}}],
    }
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=os.getcwd())

    def boot(spec):
        p = subprocess.Popen(
            [sys.executable, "-m", "foundationdb_tpu.net.server_main",
             json.dumps(spec)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env)
        line = p.stdout.readline().decode()
        assert line.startswith("ready"), line
        return p

    txn_p = boot(txn_spec)
    sto_p = boot(storage_spec)
    try:
        loop = RealEventLoop()
        client = NetTransport(loop, f"127.0.0.1:{free_port()}")
        client.start()
        db = Database(client.process, proxies=[p_txn],
                      locations=LocationCache([b""], [[p_storage]]))

        def run(coro, t=90.0):
            return loop.run_future(loop.spawn(coro), max_time=t)

        async def write_kv(k, v):
            async def body(tr):
                tr.set(k, v)
            await db.transact(body, max_retries=50)

        async def read_k(k):
            async def body(tr):
                return await tr.get(k)
            return await db.transact(body, max_retries=50)

        run(write_kv(b"before", b"alive"))

        # hostile bytes at the live port: server must keep serving
        import struct as _struct

        from foundationdb_tpu.net import native_transport as _nt
        from foundationdb_tpu.net.transport import _CONNECT as _connect
        host, port = p_txn.rsplit(":", 1)
        body = b"\x80\x04junkpickle"
        frame = _struct.pack(">IQQBI", len(body), 10, 1, 0,
                             _nt.crc32c(body)) + body
        for blob in (b"\x00" * 64, _connect + b"\xff" * 200,
                     _connect + frame):
            s = socket.create_connection((host, int(port)))
            s.sendall(blob)
            s.close()
        run(write_kv(b"hostile", b"survived"))

        txn_p.send_signal(signal.SIGKILL)
        txn_p.wait(timeout=10)
        time.sleep(0.5)
        txn_p = boot(txn_spec)
        run(write_kv(b"after", b"recovered"))
        assert run(read_k(b"after")) == b"recovered"
        assert run(read_k(b"before")) == b"alive"
        client.close()
    finally:
        for p in (txn_p, sto_p):
            p.terminate()
        for p in (txn_p, sto_p):
            p.wait(timeout=10)


def test_networktest_tool_measures_the_wire():
    """networktest (fdbserver -r networktest): parallel request streams over
    the real transport report throughput + latency percentiles."""
    import socket

    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
    from foundationdb_tpu.tools.networktest import run_load, start_receiver

    def free_addr():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        a = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()
        return a

    loop = RealEventLoop()
    srv = NetTransport(loop, free_addr())
    cli = NetTransport(loop, free_addr())
    srv.start()
    cli.start()
    start_receiver(srv.process)

    async def go():
        return await run_load(cli, cli.process, srv.address, streams=8,
                              payload_bytes=128, seconds=1.0)
    report = loop.run_future(loop.spawn(go()), max_time=30.0)
    assert report["requests"] > 50, report
    # generous bound: this asserts the tool MEASURES, not that this CI box
    # is fast — a loaded single-core host can be slow legitimately
    assert report["p50_ms"] is not None and report["p50_ms"] < 500
    assert report["mbit_per_sec"] > 0
    cli.close()
    srv.close()


def test_framing_fuzz_rejects_garbage_without_wedging():
    """Framing robustness: random bodies, truncated frames, corrupted CRC,
    unknown-kind bytes, and missing connect magic thrown at a live listener
    must all be rejected cleanly — the server never hangs or crashes, and
    still answers a well-formed request afterwards."""
    import asyncio
    import random

    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.net import native_transport as nt
    from foundationdb_tpu.net import transport as T
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
    from foundationdb_tpu.utils import wire

    loop = RealEventLoop()
    srv = NetTransport(loop, f"127.0.0.1:{free_port()}")
    cli = NetTransport(loop, f"127.0.0.1:{free_port()}")
    srv.start()
    cli.start()
    try:
        srv.process.register(7, lambda payload, reply: reply.send(payload))
        rng = random.Random(0xF0D8)
        good_body = wire.dumps("ping")

        def fuzz_bytes(trial: int) -> bytes:
            noise = bytes(rng.randrange(256)
                          for _ in range(rng.randrange(1, 64)))
            shape = trial % 5
            if shape == 0:  # pure noise: not even a coherent header
                return noise
            if shape == 1:  # truncated: header promises more body than sent
                return T._HEADER.pack(1000, 7, 1, T._REQUEST,
                                      nt.crc32c(noise)) + noise
            if shape == 2:  # corrupted CRC on a well-formed frame
                return T._HEADER.pack(len(good_body), 7, 1, T._REQUEST,
                                      nt.crc32c(good_body) ^ 0xDEAD
                                      ) + good_body
            if shape == 3:  # valid CRC, undecodable body
                return T._HEADER.pack(len(noise), 7, 1, T._REQUEST,
                                      nt.crc32c(noise)) + noise
            # shape 4: unknown frame-kind byte with a decodable body
            return T._HEADER.pack(len(good_body), 7, 1, 9,
                                  nt.crc32c(good_body)) + good_body

        async def fuzz():
            # raw asyncio (not loop.spawn): the fuzz client speaks bytes,
            # not the package's Future protocol
            host, port = srv.address.rsplit(":", 1)
            for trial in range(25):
                reader, writer = await asyncio.open_connection(host,
                                                               int(port))
                if trial % 7 != 0:  # sometimes skip the connect magic too
                    writer.write(T._CONNECT)
                writer.write(fuzz_bytes(trial))
                try:
                    await writer.drain()
                except OSError:
                    pass  # server already dropped us: that IS the rejection
                writer.close()

        loop.aio.run_until_complete(asyncio.wait_for(fuzz(), 25.0))

        # the listener must still be alive and routing after all that
        async def call():
            return await cli.request(cli.process,
                                     Endpoint(srv.address, 7), "alive")

        assert loop.run_future(loop.spawn(call()), max_time=10.0) == "alive"
    finally:
        srv.close()
        cli.close()


def test_read_frame_roundtrip_and_crc_reject():
    """_frame/_read_frame are inverses, and one flipped body byte is a
    ConnectionError (checksum), not a mis-delivered payload."""
    import asyncio

    import pytest

    from foundationdb_tpu.net import transport as T
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
    from foundationdb_tpu.utils import wire

    loop = RealEventLoop()
    t = NetTransport(loop, "127.0.0.1:1")  # never started: pure framing
    frame = t._frame(7, 3, T._REPLY, wire.dumps(["hello", 7]))

    def feed(data: bytes):
        async def go():
            r = asyncio.StreamReader()
            r.feed_data(data)
            r.feed_eof()
            return await t._read_frame(r)
        return loop.run_future(loop.spawn(go()), max_time=5.0)

    assert feed(frame) == (7, 3, T._REPLY, ["hello", 7])
    corrupted = frame[:-1] + bytes([frame[-1] ^ 1])
    with pytest.raises(ConnectionError):
        feed(corrupted)
    truncated = frame[: len(frame) - 3]
    with pytest.raises(asyncio.IncompleteReadError):
        feed(truncated)


def test_fail_pending_names_endpoint_and_cause():
    """The broken_promise a failed send produces must carry the token NAME,
    the peer address, and the causing exception — a bare "connect/encode
    failed" in a log of thousands of requests is uncorrelatable."""
    from foundationdb_tpu.core.future import Promise
    from foundationdb_tpu.core.sim import Endpoint
    from foundationdb_tpu.net.transport import NetTransport, RealEventLoop
    from foundationdb_tpu.server.interfaces import Token

    loop = RealEventLoop()
    t = NetTransport(loop, "127.0.0.1:2")  # never started: no I/O here
    reply = Promise()
    t._pending[9] = (reply, "10.0.0.8:4500", None)
    t._fail_pending(9, "connect/encode failed",
                    dest=Endpoint("10.0.0.8:4500", Token.TLOG_COMMIT),
                    cause=OSError("connection refused"))
    fut = reply.future
    assert fut.is_ready() and fut.is_error()
    err = fut._result
    assert err.name == "broken_promise"
    assert "TLOG_COMMIT" in err.detail
    assert "10.0.0.8:4500" in err.detail
    assert "OSError" in err.detail and "connection refused" in err.detail
    assert 9 not in t._pending
