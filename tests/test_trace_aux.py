"""Auxiliary observability: rolling trace files, rate suppression,
g_traceBatch txn timelines, latency bands, AsyncVar/AsyncTrigger.

Reference: flow/Trace.cpp (rolling + suppression), flow/Trace.h g_traceBatch,
flow/Stats.h LatencyBands, flow/genericactors.actor.h AsyncVar/AsyncTrigger.
"""

from __future__ import annotations

import json
import os

import pytest

from foundationdb_tpu.utils import trace as T


@pytest.fixture(autouse=True)
def _clean_trace():
    yield
    T.set_sink(None)
    T.disable_suppression()


def test_rolling_trace_file(tmp_path):
    path = str(tmp_path / "trace.log")
    rt = T.RollingTraceFile(path, roll_bytes=500, keep=3)
    T.set_sink(rt.write)
    for i in range(100):
        T.TraceEvent("RollMe").detail("I", i).log()
    rt.close()
    rolls = [f for f in os.listdir(tmp_path) if f.startswith("trace.log.")]
    assert rolls, "never rolled"
    assert len(rolls) <= 3
    # every kept file parses as JSON lines
    for name in rolls + ["trace.log"]:
        for line in open(tmp_path / name):
            json.loads(line)


def test_rolling_trace_file_keep_chain(tmp_path):
    """Explicit rolls shift path.1 -> path.2 -> ... and drop past `keep`;
    the newest roll always holds the newest content."""
    path = str(tmp_path / "trace.log")
    rt = T.RollingTraceFile(path, roll_bytes=10**9, keep=2)
    T.set_sink(rt.write)
    for gen in range(4):
        T.TraceEvent("Gen").detail("N", gen).log()
        rt.roll()
    rt.close()
    names = sorted(f for f in os.listdir(tmp_path)
                   if f.startswith("trace.log."))
    assert names == ["trace.log.1", "trace.log.2"]  # 3rd+ oldest dropped
    newest = [json.loads(line) for line in open(tmp_path / "trace.log.1")]
    assert newest[-1]["N"] == 3
    older = [json.loads(line) for line in open(tmp_path / "trace.log.2")]
    assert older[-1]["N"] == 2


def test_suppression_flush_on_quiet():
    """A chatty type that goes quiet still surfaces its final window's
    Dropped count via flush_suppressed()."""
    got: list[dict] = []
    T.set_sink(got.append)
    T.enable_suppression(limit=3, interval=10_000.0)
    for _ in range(10):
        T.TraceEvent("Chatty").log()
    assert not [e for e in got if e["Type"] == "TraceEventsSuppressed"]
    T.flush_suppressed()
    sup = [e for e in got if e["Type"] == "TraceEventsSuppressed"]
    assert len(sup) == 1
    assert sup[0]["OfType"] == "Chatty" and sup[0]["Dropped"] == 7
    # flushed windows reset: a second flush reports nothing new
    T.flush_suppressed()
    assert len([e for e in got if e["Type"] == "TraceEventsSuppressed"]) == 1


def test_sampling_profiler_catches_a_hot_loop():
    import time as wall

    from foundationdb_tpu.utils.profiler import SamplingProfiler

    def hot_spin(deadline):
        x = 0
        while wall.perf_counter() < deadline:
            x += 1
        return x

    p = SamplingProfiler(interval=0.001)
    p.start()
    hot_spin(wall.perf_counter() + 0.25)
    report = p.stop()
    assert p.total_samples > 0 and report
    hottest = p.hottest_functions(top=5)
    assert any("hot_spin" in label for label, _n in hottest), hottest
    got: list[dict] = []
    T.set_sink(got.append)
    p.trace_report(who="test")
    assert any(e["Type"] == "ProfilerSample" and "hot_spin" in e["Where"]
               for e in got)


def test_latency_bands_exact_edges():
    """Band assignment at the boundaries: a sample exactly ON an upper
    bound lands in that bound's band (bisect_left semantics)."""
    lb = T.LatencyBands("Edges")
    first, last = T.LatencyBands.BANDS[0], T.LatencyBands.BANDS[-1]
    lb.add(0.0)          # below everything -> first band
    lb.add(first)        # exactly the first bound -> still le_first
    lb.add(last)         # exactly the last bound -> le_last, not gt
    lb.add(last + 1e-9)  # just past it -> overflow bucket
    got: list[dict] = []
    T.set_sink(got.append)
    lb.trace()
    ev = got[0]
    assert ev[f"le_{first}"] == 2
    assert ev[f"le_{last}"] == 1
    assert ev["gt_last"] == 1
    assert ev["Total"] == 4 and ev["Max"] == round(last + 1e-9, 6)


def test_suppression_limits_and_reports(tmp_path):
    got: list[dict] = []
    T.set_sink(got.append)
    T.enable_suppression(limit=5, interval=1000.0)
    for _ in range(50):
        T.TraceEvent("Chatty").log()
    T.TraceEvent("Rare").log()
    # errors always pass
    for _ in range(10):
        T.TraceEvent("Bad", severity=T.SevError).log()
    chatty = [e for e in got if e["Type"] == "Chatty"]
    assert len(chatty) == 5
    assert len([e for e in got if e["Type"] == "Rare"]) == 1
    assert len([e for e in got if e["Type"] == "Bad"]) == 10


def test_trace_batch_timeline():
    tb = T.TraceBatch()
    tb.add_event("CommitDebug", "txn1", "Native.commit.Before")
    tb.add_event("CommitDebug", "txn2", "Native.commit.Before")
    tb.add_event("CommitDebug", "txn1", "Proxy.commitBatch.AfterResolution")
    tl = tb.timeline("txn1")
    assert [e["Location"] for e in tl] == [
        "Native.commit.Before", "Proxy.commitBatch.AfterResolution"]
    got: list[dict] = []
    T.set_sink(got.append)
    tb.dump()
    assert len(got) == 3 and tb.timeline("txn1") == []


def test_latency_bands():
    lb = T.LatencyBands("X")
    for s in (0.0005, 0.003, 0.003, 0.2, 9.0):
        lb.add(s)
    got: list[dict] = []
    T.set_sink(got.append)
    lb.trace()
    ev = got[0]
    assert ev["Type"] == "XLatencyBands"
    assert ev["Total"] == 5
    assert ev["le_0.001"] == 1
    assert ev["le_0.005"] == 2
    assert ev["gt_last"] == 1


def test_async_var_and_trigger():
    from foundationdb_tpu.core.eventloop import EventLoop
    from foundationdb_tpu.core.notified import AsyncTrigger, AsyncVar

    loop = EventLoop()
    av = AsyncVar(1)
    trig = AsyncTrigger()
    seen = []

    async def watcher():
        seen.append(await av.on_change())
        await trig.on_trigger()
        seen.append("triggered")

    async def driver():
        av.set(1)  # no-op: equal value must not fire
        await loop.delay(0.01)
        av.set(2)
        await loop.delay(0.01)
        trig.trigger()
        await loop.delay(0.01)
        trig.trigger()  # no waiter: forgotten, not queued

    t1 = loop.spawn(watcher(), name="w")
    t2 = loop.spawn(driver(), name="d")
    loop.run_future(t2, max_time=10.0)
    assert seen == [2, "triggered"]
    assert av.get() == 2


def test_proxy_emits_bands_and_probes():
    """The live proxy records commit/GRV latency bands and CommitDebug
    timeline probes."""
    from foundationdb_tpu.server.cluster import SimCluster
    from foundationdb_tpu.utils.knobs import KNOBS

    KNOBS.set("CONFLICT_BACKEND", "oracle")
    c = SimCluster(seed=2, n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=1)
    db = c.database()

    async def t():
        for i in range(5):
            tr = db.create_transaction()
            await tr.get(b"k%d" % i)  # forces a GRV
            tr.set(b"k%d" % i, b"v")
            await tr.commit()
    c.run(c.loop.spawn(t()), max_time=600.0)
    p = c.proxies[0]
    assert p.commit_bands.total >= 5
    assert p.grv_bands.total >= 1
    probes = [e for e in T.g_trace_batch._events
              if e["Type"] == "CommitDebug"]
    assert any(e["Location"] == "Proxy.commitBatch.AfterLogPush"
               for e in probes)
    T.g_trace_batch.dump()
    KNOBS.reset()


def test_sim_validation_oracles():
    """sim_validation (fdbrpc/sim_validation.cpp pattern): the external-
    consistency oracle observes real multi-proxy runs, and violations
    assert."""
    from foundationdb_tpu.core import sim_validation as sv
    from foundationdb_tpu.server.cluster import SimCluster
    from foundationdb_tpu.utils.knobs import KNOBS

    KNOBS.set("CONFLICT_BACKEND", "oracle")
    c = SimCluster(seed=6, n_proxies=2, n_resolvers=1, n_tlogs=1, n_storage=1)
    oracle = sv.of(c.net)
    assert oracle.enabled
    # a second simulated cluster in the same interpreter gets its OWN oracle
    # (state is per-SimNetwork, not module-global)
    c2 = SimCluster(seed=7, n_proxies=1, n_resolvers=1, n_tlogs=1, n_storage=1)
    assert sv.of(c2.net) is not oracle

    async def t():
        for i in range(10):
            tr = db.create_transaction()
            await tr.get(b"s%d" % i)
            tr.set(b"s%d" % i, b"v")
            await tr.commit()
    db = c.database()
    c.run(c.loop.spawn(t()), max_time=600.0)
    assert oracle.debug_grv_floor() > 0  # acks were observed
    assert sv.of(c2.net).debug_grv_floor() == 0  # and c2's saw none of them

    # a violating sequence asserts (the oracle has teeth)
    oracle.debug_advance_max_committed(10**15, "pA/b1")
    with pytest.raises(AssertionError):
        oracle.debug_advance_max_committed(10**15, "pB/b9")
    with pytest.raises(AssertionError):
        oracle.debug_check_read_version(1, 10**15, "pA")
    KNOBS.reset()
